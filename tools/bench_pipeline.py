"""Serial vs async phase-2 scheduling: dispatch-gap histograms + wall.

The async actor/learner pipeline (``search/pipeline.py``,
``--async-pipeline on``) exists to drive the idle time BETWEEN device
dispatches to ~0: in the serial scheduler every round pays host-side
TPE math (``tools/bench_tpe.py`` measures ~3-5 ms/trial on the real
30-D space), policy decode + tensor upload, and an fsync'd trial-log
persist while the device waits.  This bench runs the SAME seeded search
twice — serial (``FAA_PIPELINE_TRACE=1`` arms the dispatch trace on the
historical scheduler) and async — and reports, per arm:

- the dispatch-gap histogram (p50/p99 inter-dispatch idle, log-bucket
  counts) and the device busy fraction during phase 2,
- end-to-end ``search_secs`` (phase-2 wall) and the async speedup,
- the host ask/tell latency rows for the configured trial batch (the
  overlap headroom the pipeline hides), and
- contention + compile-cache stamps (every number on this host is a
  1-core CPU plumbing number; the cache keeps the first dispatch from
  reading as a 7 s "busy" window in both arms).

Phase 1 is trained once in a warmup run and its fold checkpoint is
copied into every arm's save dir, so the comparison is pure phase-2
scheduling.  Arms run as PAIRED ALTERNATING rounds (serial,async /
async,serial / ...) and the report takes per-arm MEDIANS — the same
1-core A/B discipline as ``tools/bench_router.py``: fixed-order arms
on this host read the allocator's ±2-3% slow drift as signal, and the
alternation + medians cancel it.  ``single_core_caveat`` is stamped in
the JSON line: every wall ratio here is a plumbing number (all threads
share one core); the transferable evidence is the gap histogram.
Honors ``FAA_BENCH_REQUIRE_QUIET=1`` (refuses on a contended host,
exit 3).

    python tools/bench_pipeline.py --num-search 32 --trial-batch 4
    make bench-pipeline
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _conf(batch: int, epoch: int):
    from fast_autoaugment_tpu.core.config import Config

    return Config({
        "model": {"type": "wresnet10_1"},
        "dataset": "synthetic",
        "aug": "default",
        "cutout": 8,
        "batch": batch,
        "epoch": epoch,
        "lr": 0.05,
        "lr_schedule": {"type": "cosine"},
        "optimizer": {"type": "sgd", "decay": 1e-4, "clip": 5.0,
                      "momentum": 0.9, "nesterov": True},
    })


_CKPT_COPY_SUFFIXES = ("", ".meta.json")


def _copy_fold_ckpt(src_dir: str, dst_dir: str, name: str) -> None:
    os.makedirs(dst_dir, exist_ok=True)
    for suffix in _CKPT_COPY_SUFFIXES:
        src = os.path.join(src_dir, name + suffix)
        if os.path.exists(src):
            shutil.copy2(src, os.path.join(dst_dir, name + suffix))


def _median(xs):
    xs = sorted(x for x in xs if x is not None)
    n = len(xs)
    if n == 0:
        return None
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def run_pipeline_bench(args, workdir: str) -> dict:
    import jax

    from fast_autoaugment_tpu.search.driver import (
        _fold_ckpt_path,
        search_policies,
    )

    conf = _conf(args.batch, 1)
    cache_dir = os.path.join(workdir, "compile_cache")
    common = dict(
        dataroot=workdir, cv_num=1, cv_ratio=args.cv_ratio,
        num_policy=args.num_policy, num_op=args.num_op,
        num_top=5, trial_batch=args.trial_batch, seed=args.seed,
        compile_cache=cache_dir,
    )
    devices = jax.device_count()

    # warmup: train the shared phase-1 fold + fill the compile cache
    # (one round of trials compiles the TTA step into the cache, so no
    # measured round's first dispatch is a compile window)
    warm_dir = os.path.join(workdir, "warm")
    search_policies(conf, save_dir=warm_dir,
                    num_search=max(1, args.trial_batch), **common)
    ckpt_name = os.path.basename(_fold_ckpt_path(warm_dir, conf, 0,
                                                 args.cv_ratio))

    def _one_arm(tag: str, async_on: bool) -> dict:
        save_dir = os.path.join(workdir, tag)
        _copy_fold_ckpt(warm_dir, save_dir, ckpt_name)
        if not async_on:
            os.environ["FAA_PIPELINE_TRACE"] = "1"
        try:
            t0 = time.time()
            result = search_policies(
                conf, save_dir=save_dir, num_search=args.num_search,
                async_pipeline="on" if async_on else "off",
                pipeline_actors=args.actors,
                pipeline_queue_depth=args.queue_depth, **common)
            wall = time.time() - t0
        finally:
            os.environ.pop("FAA_PIPELINE_TRACE", None)
        pipe = result.get("pipeline") or {}
        gaps = pipe.get("dispatch_gaps") or {}
        return {
            "mode": "async" if async_on else "serial",
            "search_secs": round(wall, 3),
            "phase2_secs": round(
                result["device_secs_phase2"] / max(1, devices), 3),
            "device_busy_frac": pipe.get("device_busy_frac"),
            "gap_p50_ms": gaps.get("gap_p50_ms"),
            "gap_p99_ms": gaps.get("gap_p99_ms"),
            "gap_total_secs": gaps.get("gap_total_secs"),
            "num_gaps": gaps.get("num_gaps"),
            "num_dispatches": gaps.get("num_dispatches"),
            "tell_reorders": pipe.get("tell_reorders"),
            "num_sub_policies": result.get("num_sub_policies"),
        }

    # paired alternating arm order + per-arm medians: the 1-core A/B
    # discipline (bench_router.py) — fixed-order arms read ±2-3%
    # allocator drift as signal on this host
    rounds: list[dict] = []
    for i in range(max(1, args.pairs)):
        order = (("serial", "async") if i % 2 == 0
                 else ("async", "serial"))
        for name in order:
            rounds.append(_one_arm(f"{name}{i}", name == "async"))

    arms = {}
    for name in ("serial", "async"):
        rows = [r for r in rounds if r["mode"] == name]
        arms[name] = {
            "rounds": len(rows),
            "phase2_secs_median": _median([r["phase2_secs"] for r in rows]),
            "search_secs_median": _median([r["search_secs"] for r in rows]),
            "device_busy_frac_median": _median(
                [r["device_busy_frac"] for r in rows]),
            "gap_p50_ms_median": _median([r["gap_p50_ms"] for r in rows]),
            "gap_p99_ms_median": _median([r["gap_p99_ms"] for r in rows]),
            "gap_total_secs_median": _median(
                [r["gap_total_secs"] for r in rows]),
            "num_dispatches": rows[-1]["num_dispatches"],
            "tell_reorders_total": sum(r["tell_reorders"] or 0
                                       for r in rows),
        }
    arms["async"].update(actors=args.actors, queue_depth=args.queue_depth)
    s_med = arms["serial"]["phase2_secs_median"]
    a_med = arms["async"]["phase2_secs_median"]
    speedup = (s_med / a_med) if (s_med and a_med) else None
    return {
        "bench": "pipeline",
        "devices": devices,
        "num_search": args.num_search,
        "trial_batch": args.trial_batch,
        "num_policy": args.num_policy,
        "num_op": args.num_op,
        "pairs": args.pairs,
        "serial": arms["serial"],
        "async": arms["async"],
        "rounds": rounds,
        "phase2_speedup": round(speedup, 3) if speedup else None,
        # every process here shares ONE core: wall ratios measure
        # scheduling plumbing, not device overlap — the transferable
        # evidence is the gap histogram (docs/BENCHMARKS.md)
        "single_core_caveat": True,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-search", type=int, default=24)
    p.add_argument("--trial-batch", type=int, default=4)
    p.add_argument("--num-policy", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--cv-ratio", type=float, default=0.4)
    p.add_argument("--actors", type=int, default=1)
    p.add_argument("--queue-depth", type=int, default=1)
    p.add_argument("--pairs", type=int, default=2,
                   help="paired alternating (serial,async) rounds; "
                        "per-arm medians reported")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh tempdir, removed "
                        "on success)")
    p.add_argument("--out", default=None, help="also write the JSON line here")
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )
    from bench_tpe import bench_ask_tell_latency

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="faa_bench_pipeline_")
    made_temp = args.workdir is None
    record = run_pipeline_bench(args, workdir)
    # unified provenance block (bench.telemetry_stamp): contention +
    # compile cache + registry counters in the shared schema
    record.update(telemetry_stamp(contention=contention))
    # the overlap headroom the async arm hides: host ask/tell latency
    # at this bench's trial batch (same JSON line, per the bench_tpe
    # citation contract)
    record["tpe_latency"] = bench_ask_tell_latency(
        ks=(args.trial_batch,), reps=20)

    for arm in ("serial", "async"):
        a = record[arm]
        print(f"{arm} (medians over {a['rounds']} alternating rounds): "
              f"phase2 {a['phase2_secs_median']}s, busy_frac "
              f"{a['device_busy_frac_median']}, gap p50 "
              f"{a['gap_p50_ms_median']}ms p99 {a['gap_p99_ms_median']}ms "
              f"({a['num_dispatches']} dispatches/round)")
    print(f"phase2_speedup (median/median): {record['phase2_speedup']}x "
          "[single_core_caveat: wall on this host is plumbing, the gap "
          "histogram is the evidence]")
    busy = record["async"]["device_busy_frac_median"] or 0.0
    ok = busy >= 0.9 or (record["phase2_speedup"] or 0.0) >= 1.5
    print("acceptance (median busy_frac >= 0.9 during phase 2 OR >= 1.5x "
          f"phase-2 speedup): {'PASS' if ok else 'FAIL'}")

    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    if made_temp:
        shutil.rmtree(workdir, ignore_errors=True)
    return record


if __name__ == "__main__":
    main()
