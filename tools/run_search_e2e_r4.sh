#!/bin/bash
# Round-4 defaults-safety validation (VERDICT round 3, next-steps 3+4).
#
# Runs the full 3-phase search on the pose-varying glyph task with CLI
# DEFAULTS — no guard flags at all.  Round 3's validated recipe
# (audit floor 0.95, fold-quality gate on, 200-epoch phase 1) is now
# the default configuration (search_cli.py + the conf), so a user
# typing the documented command line gets the validated behavior, not
# the round-2 failure mode.  Phase 3 runs >=8 seeds per mode and the
# artifact records per-seed values, std and a paired t-test.
#
#   bash tools/run_search_e2e_r4.sh [dataset] [save_dir] [seeds]
set -euo pipefail
cd "$(dirname "$0")/.."

DATASET="${1:-synthetic_shapes_pose300}"
SAVE="${2:-search_e2e_r4_defaults}"
SEEDS="${3:-10}"

# clean CPU env (the dead-tunnel PJRT plugin wedges any interpreter
# that keeps PALLAS_AXON_POOL_IPS; tests/conftest.py)
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m fast_autoaugment_tpu.launch.search_cli \
    -c confs/wresnet10x1_shapes_hard.yaml \
    --dataroot ./data \
    --save-dir "$SAVE" \
    --seed 1 \
    --num-result-per-cv "$SEEDS" \
    "dataset=$DATASET" \
    2>&1 | tee "$SAVE.log"

# stage the committable summary artifacts (the run dir is gitignored;
# tests/test_defaults_artifact.py reads the committed copies)
git add -f "$SAVE/search_result.json" "$SAVE/final_policy.json" \
    "$SAVE/audit.json" "$SAVE/search_trials.json" "$SAVE.log" 2>/dev/null || true
echo "[e2e-r4] summary artifacts staged; commit them to activate" \
     "tests/test_defaults_artifact.py"
