"""Capture jax.profiler traces of the two hot steps on real hardware.

VERDICT r3, next-step 1: while the chip is up, capture a profiler trace
of (a) the fused WRN-40-2 train step at the headline config and (b) the
compiled TTA evaluation step, so the op-level cost structure of the
augmentation engine and the model are on record even if the tunnel dies
again.  Runs a few warm steps, then traces a handful under
``jax.profiler.trace``; the xplane protobufs land in ``--out`` (default
``docs/tpu_trace_r4/``) together with a small ``summary.json`` (wall
times + per-step cost-analysis FLOPs) that is committable even when the
raw trace is too big for git.

    python tools/profile_tpu.py [--out docs/tpu_trace_r4] [--steps 5]

Run on the plain (TPU) environment; falls back to CPU gracefully but
the numbers are then only plumbing evidence (marked in the summary).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="docs/tpu_trace_r4")
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch", type=int, default=128)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.ops.schedules import build_schedule
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor
    from fast_autoaugment_tpu.search.tta import make_tta_step
    from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step

    platform = jax.devices()[0].platform
    mesh = make_mesh()
    batch = args.batch * mesh.size
    model = get_model({"type": "wresnet40_2", "precision": "bf16"}, 10)
    optimizer = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
         "nesterov": True},
        build_schedule({"lr": 0.1, "epoch": 200,
                        "lr_schedule": {"type": "cosine"}},
                       steps_per_epoch=50000 // batch),
    )
    rng = jax.random.PRNGKey(0)
    state = create_train_state(model, optimizer, rng,
                               jnp.zeros((2, 32, 32, 3), jnp.float32),
                               use_ema=False)
    train_step = make_train_step(model, optimizer, num_classes=10,
                                 cutout_length=16, use_policy=True)
    tta_step = make_tta_step(model, num_policy=5, cutout_length=16)

    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    host = np.random.default_rng(0)
    b = shard_batch(mesh, {
        "x": host.integers(0, 256, (batch, 32, 32, 3), dtype=np.uint8),
        "y": host.integers(0, 10, (batch,), np.int32),
        "m": np.ones(batch, np.float32),
    })

    summary: dict = {"platform": platform, "batch": batch,
                     "devices": mesh.size, "steps_traced": args.steps}

    # AOT-compile ONCE; the same executable serves the warm timing, the
    # traced steps and the FLOPs cost analysis (a second independent
    # compile would double the dominant fixed cost of this tool on TPU
    # and risk the ambush stage timeout)
    t0 = time.perf_counter()
    train_exec = train_step.lower(state, b["x"], b["y"], policy, rng).compile()
    summary["train_step_compile_s"] = round(time.perf_counter() - t0, 1)

    def timed(tag, fn):
        fn()  # warm (tta_step compiles here on its first call)
        jax.effects_barrier()
        t0 = time.perf_counter()
        for _ in range(args.steps):
            fn()
        jax.effects_barrier()
        summary[f"{tag}_ms_per_step"] = round(
            (time.perf_counter() - t0) / args.steps * 1e3, 3)

    def run_train():
        nonlocal state
        state, _ = train_exec(state, b["x"], b["y"], policy, rng)
        jax.block_until_ready(state.params)

    def run_tta():
        out = tta_step(state.params, state.batch_stats, b["x"], b["y"],
                       b["m"], policy, rng)
        jax.block_until_ready(out["cnt"])

    os.makedirs(args.out, exist_ok=True)
    timed("train_step_warm", run_train)
    timed("tta_step_warm", run_tta)
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            run_train()
        for _ in range(args.steps):
            run_tta()

    # flops from the already-compiled executable (per-device, SPMD)
    try:
        cost = train_exec.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        summary["train_step_flops"] = float(cost.get("flops", 0.0))
    except Exception as e:  # noqa: BLE001 — backend-dependent
        summary["train_step_flops"] = None
        summary["cost_analysis_error"] = str(e)

    trace_files = []
    for root, _dirs, files in os.walk(args.out):
        for f in files:
            fp = os.path.join(root, f)
            trace_files.append(
                {"file": os.path.relpath(fp, args.out),
                 "bytes": os.path.getsize(fp)})
    summary["trace_files"] = trace_files

    with open(os.path.join(args.out, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
