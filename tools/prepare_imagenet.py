"""Prepare a local ILSVRC2012 tree for the framework.

Subcommands (composable; reference ``imagenet.py:6-19,164-245``
capabilities):

  download:   fetch + md5-verify + extract one release archive
              (train expands its per-class inner tars; supports
              --url mirrors incl. file://)
  val-reorg:  move the flat ``val/`` images into per-wnid folders using
              the devkit's meta.mat + ground-truth list
  listfile:   generate ``train_cls.txt`` / ``val_cls.txt`` (CLS-LOC
              format) so dataset loading skips the os.walk
  meta:       print the parsed synset table (sanity check)

    python tools/prepare_imagenet.py download --split devkit --root /data
    python tools/prepare_imagenet.py val-reorg --root /data/imagenet \
        --devkit /data/ILSVRC2012_devkit_t12
    python tools/prepare_imagenet.py listfile --root /data/imagenet --split train
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_autoaugment_tpu.data.imagenet_tools import (  # noqa: E402
    download_and_extract,
    parse_devkit,
    prepare_val_folder,
    write_listfile,
)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    pd = sub.add_parser("download", help="fetch+verify+extract an archive")
    pd.add_argument("--root", required=True)
    pd.add_argument("--split", default="devkit",
                    choices=["train", "val", "devkit"])
    pd.add_argument("--url", default=None, help="mirror override (file:// ok)")
    pd.add_argument("--md5", default=None,
                    help="checksum override; empty string disables the check")

    pv = sub.add_parser("val-reorg", help="flat val/ -> per-wnid folders")
    pv.add_argument("--root", required=True, help="imagenet root (contains val/)")
    pv.add_argument("--devkit", required=True, help="ILSVRC2012_devkit_t12 dir")

    pl = sub.add_parser("listfile", help="generate <split>_cls.txt")
    pl.add_argument("--root", required=True)
    pl.add_argument("--split", default="train", choices=["train", "val"])

    pm = sub.add_parser("meta", help="print parsed devkit synsets")
    pm.add_argument("--devkit", required=True)

    args = p.parse_args(argv)
    if args.cmd == "download":
        dest = download_and_extract(args.split, args.root,
                                    url=args.url, md5=args.md5)
        print(f"extracted {args.split} -> {dest}")
    elif args.cmd == "val-reorg":
        n = prepare_val_folder(os.path.join(args.root, "val"), args.devkit)
        print(f"moved {n} val images into wnid folders")
    elif args.cmd == "listfile":
        out = os.path.join(args.root, f"{args.split}_cls.txt")
        n = write_listfile(os.path.join(args.root, args.split), out)
        print(f"wrote {n} entries to {out}")
    else:
        wnid_to_classes, val_wnids = parse_devkit(args.devkit)
        print(f"{len(wnid_to_classes)} leaf synsets, {len(val_wnids)} val labels")
        for wnid, classes in sorted(wnid_to_classes.items())[:5]:
            print(f"  {wnid}: {', '.join(classes)}")


if __name__ == "__main__":
    main()
