# Makes tools/ importable as a package so `python -m tools.faalint`
# works from the repo root.  Standalone script entry points
# (`python tools/lint_robustness.py`, `python tools/bench_*.py`) are
# unaffected.
