#!/usr/bin/env python
"""Routed-fleet vs direct-replica serving bench (``make bench-router``).

Spawns a real serving plane — N ``serve_cli`` replicas announcing
themselves into a shared ``--port-dir``, two policies resident per
replica (the default + one tenancy-warmed), and a ``router_cli`` front
door over them — then measures closed-loop HTTP load through two arms:

- **direct**: clients against ONE replica (the single-replica
  baseline);
- **routed**: the same traffic through the router, mixed across both
  policy digests (digest-affinity routing decides the landing
  replica).

Arms run as PAIRED ALTERNATING rounds (direct,routed / routed,direct /
...) and the report takes per-arm MEDIANS — on this 1-core host the
client loop, every replica and the router all contend for the same
core, so absolute numbers are plumbing-level and ordering effects are
first-order (docs/BENCHMARKS.md measurement notes); the alternation +
medians cancel the slow drift, and the contention stamp records the
conditions.  The JSON line carries both arms' rps/p50/p99 medians, the
routed/direct throughput ratio, the router's own topology + affinity
accounting, and the unified telemetry stamp.

    python tools/bench_router.py [--replicas 3] [--pairs 3]
        [--seconds-per-arm 2] [--image 8] [--shapes 1,8]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

#: two deterministic single-sub policies (exact dispatch — the fast
#: shape); different ops so their digests (and served bytes) differ
POLICY_A = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
POLICY_B = [[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]


def _http(host, port, method, path, body=None, headers=None, timeout=30.0):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def wait_ready(host, port, proc, timeout=180.0, path="/readyz"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"process died before ready: rc={proc.returncode}")
        try:
            status, _h, _b = _http(host, port, "GET", path, timeout=5.0)
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.2)
    raise RuntimeError(f"{host}:{port}{path} never went ready "
                      f"within {timeout:.0f}s")


def wait_port_record(port_dir, tag, proc, timeout=180.0) -> int:
    path = os.path.join(port_dir, f"{tag}.json")
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {tag} died before binding: rc={proc.returncode}")
        try:
            with open(path) as fh:
                return int(json.load(fh)["port"])
        except (OSError, ValueError, KeyError):
            time.sleep(0.2)
    raise RuntimeError(f"replica {tag} never wrote its port record")


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if n == 0:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--pairs", type=int, default=3,
                   help="paired alternating rounds per arm (medians "
                        "reported)")
    p.add_argument("--seconds-per-arm", type=float, default=2.0)
    p.add_argument("--image", type=int, default=8)
    p.add_argument("--shapes", default="1,8")
    p.add_argument("--imgs-per-request", type=int, default=4)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--startup-timeout", type=float, default=180.0)
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )
    from bench_serve import run_router_load

    contention = refuse_or_flag_contention(host_contention_stamp())

    import numpy as np

    from fast_autoaugment_tpu.policies.archive import policy_to_tensor
    from fast_autoaugment_tpu.serve.policy_server import policy_digest

    digest_a = policy_digest(policy_to_tensor(
        [[(op, float(pr), float(lv)) for op, pr, lv in sub]
         for sub in POLICY_A]))
    digest_b = policy_digest(policy_to_tensor(
        [[(op, float(pr), float(lv)) for op, pr, lv in sub]
         for sub in POLICY_B]))

    procs: list[subprocess.Popen] = []
    out = {}
    with tempfile.TemporaryDirectory(prefix="bench_router_") as tmp:
        port_dir = os.path.join(tmp, "replicas")
        policy_dir = os.path.join(tmp, "policies")
        os.makedirs(policy_dir)
        path_a = os.path.join(policy_dir, "a.json")
        path_b = os.path.join(policy_dir, "b.json")
        with open(path_a, "w") as fh:
            json.dump(POLICY_A, fh)
        with open(path_b, "w") as fh:
            json.dump(POLICY_B, fh)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            # ---- the replica fleet (default policy A, tenancy for B)
            replica_ports = []
            for i in range(args.replicas):
                env_i = dict(env, FAA_HOST_ID=str(i))
                procs.append(subprocess.Popen([
                    sys.executable, "-m",
                    "fast_autoaugment_tpu.serve.serve_cli",
                    "--policy", path_a, "--image", str(args.image),
                    "--shapes", args.shapes,
                    "--max-wait-ms", str(args.max_wait_ms),
                    "--tenant-capacity", "2",
                    "--policy-dir", policy_dir,
                    "--port", "0", "--port-dir", port_dir,
                    "--host-tag", f"replica{i}",
                ], env=env_i, cwd=_REPO))
            for i in range(args.replicas):
                port = wait_port_record(port_dir, f"replica{i}", procs[i],
                                        args.startup_timeout)
                wait_ready("127.0.0.1", port, procs[i],
                           args.startup_timeout)
                replica_ports.append(port)
                # pre-warm policy B so mixed traffic is warm everywhere
                status, _h, body = _http(
                    "127.0.0.1", port, "POST", "/tenants/warm",
                    body=json.dumps({"policy": path_b}).encode(),
                    timeout=args.startup_timeout)
                if status != 200:
                    raise RuntimeError(
                        f"tenant warm failed on replica{i}: "
                        f"{status} {body[:200]!r}")

            # ---- the router over the fleet
            router_pf = os.path.join(tmp, "router.port")
            router = subprocess.Popen([
                sys.executable, "-m",
                "fast_autoaugment_tpu.serve.router_cli",
                "--port-dir", port_dir, "--port", "0",
                "--port-file", router_pf, "--poll-interval", "0.2",
            ], env=env, cwd=_REPO)
            procs.append(router)
            t0 = time.monotonic()
            while not os.path.exists(router_pf) \
                    and time.monotonic() - t0 < args.startup_timeout:
                time.sleep(0.1)
            with open(router_pf) as fh:
                router_port = int(fh.read().strip())
            wait_ready("127.0.0.1", router_port, router,
                       args.startup_timeout)

            rng = np.random.default_rng(0)
            pool = rng.integers(
                0, 256, (max(64, 2 * args.imgs_per_request), args.image,
                         args.image, 3),
                dtype=np.uint8).astype(np.float32)
            digests = [digest_a, digest_b]

            def run_arm(name: str) -> dict:
                target = (f"127.0.0.1:{router_port}" if name == "routed"
                          else f"127.0.0.1:{replica_ports[0]}")
                row = run_router_load(
                    target, pool, args.seconds_per_arm,
                    args.imgs_per_request, digests, args.concurrency)
                row["arm"] = name
                return row

            # paired alternating arm order + medians: the 1-core A/B
            # discipline (ordering effects are first-order here)
            rounds = []
            for i in range(max(1, args.pairs)):
                order = (("direct", "routed") if i % 2 == 0
                         else ("routed", "direct"))
                for name in order:
                    rounds.append(run_arm(name))

            meds = {}
            for name in ("direct", "routed"):
                rows = [r for r in rounds if r["arm"] == name]
                meds[name] = {
                    "rps_median": round(_median(
                        [r["rps"] for r in rows]), 1),
                    "p50_ms_median": round(_median(
                        [r["latency_ms"]["p50"] for r in rows]), 3),
                    "p99_ms_median": round(_median(
                        [r["latency_ms"]["p99"] for r in rows]), 3),
                    "requests_ok": sum(r["requests_ok"] for r in rows),
                    "requests_failed": sum(r["requests_failed"]
                                           for r in rows),
                }
            ratio = (meds["routed"]["rps_median"]
                     / meds["direct"]["rps_median"]
                     if meds["direct"]["rps_median"] else None)
            _s, _h, stats_body = _http("127.0.0.1", router_port, "GET",
                                       "/stats", timeout=10.0)
            topology = json.loads(stats_body)
            out = {
                "metric": "serve_router_paired_rps",
                "replicas": args.replicas,
                "pairs": args.pairs,
                "seconds_per_arm": args.seconds_per_arm,
                "image": args.image,
                "imgs_per_request": args.imgs_per_request,
                "concurrency": args.concurrency,
                "digests": digests,
                "arms": meds,
                "routed_over_direct_rps": (round(ratio, 3)
                                           if ratio else None),
                "affinity": topology.get("affinity"),
                "router_topology": topology,
                "rounds": rounds,
                # the 1-core caveat, stamped not implied: every process
                # shares one core, so routed/direct ratios here measure
                # PLUMBING overhead, not fleet scaling — multi-host
                # replicas are where routed ~ N x direct appears
                "single_core_caveat": True,
                **telemetry_stamp(contention=contention),
            }
        finally:
            for proc in reversed(procs):
                if proc.poll() is None:
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except ProcessLookupError:
                        pass
            deadline = time.monotonic() + 30.0
            for proc in procs:
                left = max(0.5, deadline - time.monotonic())
                try:
                    proc.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)

    print(json.dumps(out))
    ok = bool(out) and out["arms"]["routed"]["requests_ok"] > 0 \
        and out["arms"]["direct"]["requests_ok"] > 0
    return 0 if ok else 4


if __name__ == "__main__":
    raise SystemExit(main())
