"""Cold/warm compile-tax benchmark: two processes, one shared cache
(``make bench-compile``).

The acceptance measurement for the persistent compile cache
(docs/BENCHMARKS.md "Compile cost & cache"): the SAME child workload —
build the real jitted train step (``make_train_step``) for ``--model``
and run it to completion once, i.e. time-to-first-train-step — runs in
two fresh processes sharing one ``FAA_COMPILE_CACHE`` dir.  The first
(cold) process pays the full XLA lowering; the second (warm) process
deserializes the executables.  One JSON line stamps both processes'
``compile_cache`` blocks (the warm one proves ``hits > 0, misses ==
0``), the first-step walls, and the speedup.

    python tools/bench_compile.py [--model wresnet40_2] [--batch 8]
        [--cache-dir DIR (default: a fresh temp dir)]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def child_main(args) -> int:
    """One process's workload: build the real train step, reach the
    first completed step, print the evidence as one JSON line."""
    t0 = time.perf_counter()
    from fast_autoaugment_tpu.core.compilecache import (
        compile_cache_stats,
        configure_compile_cache,
    )

    configure_compile_cache(None)  # FAA_COMPILE_CACHE from the parent

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.policies.archive import (
        load_policy,
        policy_to_tensor,
    )
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_train_step,
    )

    model = get_model({"type": args.model}, 10)
    optimizer = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
         "nesterov": True}, lambda s: 0.05)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, args.image, args.image, 3), jnp.float32)
    state = create_train_state(model, optimizer, rng, sample, use_ema=False)
    step = make_train_step(model, optimizer, num_classes=10,
                           cutout_length=16, use_policy=True)
    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    host = np.random.default_rng(0)
    x = jnp.asarray(host.integers(0, 256,
                                  (args.batch, args.image, args.image, 3),
                                  dtype=np.uint8))
    y = jnp.asarray(host.integers(0, 10, (args.batch,), np.int32))
    # phase split: tracing/lowering is Python work NO cache can skip;
    # compile() is the 23-55 s XLA tax the persistent cache kills
    # (warm = executable deserialization); exec is the step itself
    t_step = time.perf_counter()
    lowered = step.lower(state, x, y, policy, rng)
    t_lower = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter()
    state, metrics = compiled(state, x, y, policy, rng)
    jax.block_until_ready(state.params)
    now = time.perf_counter()
    print(json.dumps({
        "first_step_sec": round(now - t_step, 3),
        "trace_lower_sec": round(t_lower - t_step, 3),
        "compile_sec": round(t_compile - t_lower, 3),
        "exec_sec": round(now - t_compile, 3),
        "proc_to_first_step_sec": round(now - t0, 3),
        "compile_cache": compile_cache_stats(),
        "backend": jax.devices()[0].platform,
    }))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=os.environ.get(
        "FAA_BENCH_CC_MODEL", "wresnet40_2"))
    p.add_argument("--batch", type=int, default=int(os.environ.get(
        "FAA_BENCH_CC_BATCH", 8)))
    p.add_argument("--image", type=int, default=32)
    p.add_argument("--cache-dir", default=None,
                   help="shared cache dir (default: fresh temp dir — a "
                        "guaranteed-cold first process)")
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.child:
        return child_main(args)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())
    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="faa_compile_cache_")

    def run(tag: str) -> dict:
        env = dict(os.environ)
        env["FAA_COMPILE_CACHE"] = cache_dir
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never probe the tunnel
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--model", args.model, "--batch", str(args.batch),
               "--image", str(args.image)]
        t0 = time.perf_counter()
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=1800)
        wall = time.perf_counter() - t0
        if r.returncode != 0:
            raise RuntimeError(
                f"{tag} child failed rc={r.returncode}: {r.stderr[-1500:]}")
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        rec["process_wall_sec"] = round(wall, 3)
        print(f"[bench_compile] {tag}: compile={rec['compile_sec']}s "
              f"(trace {rec['trace_lower_sec']}s, exec {rec['exec_sec']}s) "
              f"first_step={rec['first_step_sec']}s "
              f"to-first-step={rec['proc_to_first_step_sec']}s "
              f"(hits={rec['compile_cache']['hits']} "
              f"misses={rec['compile_cache']['misses']})", file=sys.stderr)
        return rec

    cold = run("cold")
    warm = run("warm")
    out = {
        # the headline is the COMPILE tax (the 23-55 s BENCH_r02-r05
        # number): warm = executable deserialization, the piece the
        # persistent cache kills.  Tracing/lowering is Python work no
        # cache can skip; the per-phase walls ride in cold/warm.
        "metric": "warm_process_compile_sec",
        "value": warm["compile_sec"],
        "unit": "seconds",
        "model": args.model,
        "batch": args.batch,
        "cache_dir": cache_dir,
        "cold": cold,
        "warm": warm,
        "speedup_compile": (
            round(cold["compile_sec"] / warm["compile_sec"], 1)
            if warm["compile_sec"] else None),
        "speedup_first_step": (
            round(cold["first_step_sec"] / warm["first_step_sec"], 1)
            if warm["first_step_sec"] else None),
        # the acceptance bits, spelled out: the warm process observed
        # cache hits and zero misses, and its compile took seconds
        "warm_hits": warm["compile_cache"]["hits"],
        "warm_misses": warm["compile_cache"]["misses"],
        "backend": warm.get("backend"),
        # unified provenance block (bench.telemetry_stamp) — the
        # supervisor process compiles nothing, so its own compile_cache
        # block is empty; the cold/warm children carry the real stamps
        **telemetry_stamp(contention=contention),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
