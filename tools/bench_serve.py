"""AOT policy-serving benchmark: p50/p99 latency + imgs/s at fixed
offered QPS (``make bench-serve``).

Drives the real serving pair — :class:`AotPolicyApplier` (AOT-compiled
padded-shape executables) behind :class:`PolicyServer` (batch
coalescing) — with an OPEN-LOOP arrival process at ``--qps``: requests
are submitted on a fixed schedule regardless of completion (the
heavy-traffic model; a closed loop would hide queueing collapse).  One
JSON line reports:

- ``latency_ms``: p50/p90/p99/max submit-to-scatter per request;
- ``images_per_sec``: achieved serving throughput over the run;
- ``aot_compile_sec`` per shape + the unified ``compile_cache`` block
  (with ``FAA_COMPILE_CACHE`` set, a re-run deserializes the
  executables — the warm-start story applied to serving);
- the standard contention + shadow-watchdog stamps, plus a per-run
  ``bitwise_match`` re-verification that exact-dispatch served outputs
  equal direct ``apply_policy`` application.

    python tools/bench_serve.py [--qps 200] [--seconds 5] [--image 32]
        [--dispatch auto] [--shapes 1,8,32,128]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthetic_policy(num_sub: int, num_op: int):
    """Deterministic multi-sub policy shaped like a search result (ops
    cycle through the searchable table, probs/levels spread)."""
    import numpy as np

    rows = []
    for i in range(num_sub):
        rows.append([[(i * num_op + j) % 15, 0.4 + 0.1 * (i % 5),
                      0.2 + 0.15 * ((i + j) % 5)]
                     for j in range(num_op)])
    return np.asarray(rows, np.float32)


def verify_bitwise(applier, images, keys) -> bool:
    """Exact-dispatch acceptance: served == direct apply_policy, bitwise
    (grouped dispatch is checked against its own batch kernel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.ops.augment import (
        apply_policy,
        apply_policy_batch_grouped,
    )

    got = applier.apply(images, keys)
    if applier.dispatch == "exact":
        ref = np.stack([
            np.asarray(apply_policy(
                jnp.asarray(images[i], jnp.float32),
                applier.policy, jnp.asarray(keys[i])))
            for i in range(images.shape[0])])
    else:
        from fast_autoaugment_tpu.serve.policy_server import pick_shape

        s = pick_shape(applier.shapes, images.shape[0])
        padded = np.zeros((s,) + images.shape[1:], np.float32)
        padded[:images.shape[0]] = images
        ref = np.asarray(apply_policy_batch_grouped(
            jnp.asarray(padded), applier.policy, jnp.asarray(keys),
            groups=applier.groups))[:images.shape[0]]
    return bool(np.array_equal(got, ref))


def run_offered_load(server, images_pool, qps: float, seconds: float,
                     imgs_per_request: int):
    """Open-loop offered load: submit on schedule, collect latencies."""
    import numpy as np

    n_requests = max(1, int(qps * seconds))
    interval = 1.0 / qps
    pending = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        lo = (i * imgs_per_request) % (images_pool.shape[0]
                                       - imgs_per_request + 1)
        pending.append(server.submit(images_pool[lo:lo + imgs_per_request]))
    for p in pending:
        server.result(p, timeout=120.0)
    t_end = max(p.t_done for p in pending)
    lat_ms = np.asarray([p.latency() * 1e3 for p in pending])
    total_imgs = sum(p.n for p in pending)
    return {
        "requests": n_requests,
        "qps_offered": round(qps, 1),
        "qps_achieved": round(n_requests / (t_end - t0), 1),
        "images_per_sec": round(total_imgs / (t_end - t0), 1),
        "latency_ms": {
            "p50": round(float(np.percentile(lat_ms, 50)), 3),
            "p90": round(float(np.percentile(lat_ms, 90)), 3),
            "p99": round(float(np.percentile(lat_ms, 99)), 3),
            "max": round(float(lat_ms.max()), 3),
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--policy", default=None,
                   help="final_policy.json / archive name (default: a "
                        "deterministic synthetic --num-sub policy)")
    p.add_argument("--num-sub", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--image", type=int, default=32)
    p.add_argument("--shapes", default="1,8,32,128")
    p.add_argument("--dispatch", default="auto",
                   choices=("auto", "exact", "grouped"))
    p.add_argument("--groups", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--qps", type=float, default=200.0)
    p.add_argument("--seconds", type=float, default=5.0)
    p.add_argument("--imgs-per-request", type=int, default=1)
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        watchdog_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())

    import jax
    import numpy as np

    from fast_autoaugment_tpu.core.compilecache import (
        compile_cache_stats,
        configure_compile_cache,
    )
    from fast_autoaugment_tpu.serve.policy_server import (
        AotPolicyApplier,
        PolicyServer,
    )

    # honor an inherited FAA_COMPILE_CACHE: a second bench run then
    # deserializes the AOT executables instead of re-lowering them
    configure_compile_cache(None)

    if args.policy:
        from fast_autoaugment_tpu.serve.serve_cli import build_policy_tensor

        policy = build_policy_tensor(args.policy)
    else:
        policy = synthetic_policy(args.num_sub, args.num_op)
    shapes = tuple(int(s) for s in str(args.shapes).split(",") if s)

    t0 = time.perf_counter()
    applier = AotPolicyApplier(policy, image=args.image, shapes=shapes,
                               dispatch=args.dispatch, groups=args.groups)
    aot_secs = time.perf_counter() - t0

    rng = np.random.default_rng(0)
    pool = rng.integers(
        0, 256, (max(shapes) * 2, args.image, args.image, 3),
        dtype=np.uint8).astype(np.float32)
    # acceptance re-verification on this exact build: served outputs
    # match the direct kernel bit-for-bit
    n_check = min(3, max(shapes))
    check_keys = (np.stack([np.asarray(jax.random.PRNGKey(i), np.uint32)
                            for i in range(n_check)])
                  if applier.dispatch == "exact"
                  else np.asarray(jax.random.PRNGKey(7), np.uint32))
    bitwise = verify_bitwise(applier, pool[:n_check], check_keys)

    server = PolicyServer(applier, max_wait_ms=args.max_wait_ms).start()
    # warm the dispatch path (first calls already AOT-compiled)
    server.augment(pool[:1])
    load = run_offered_load(server, pool, args.qps, args.seconds,
                            args.imgs_per_request)
    stats = server.stats()
    server.stop()

    out = {
        "metric": "serve_policy_latency_ms",
        "backend": jax.devices()[0].platform,
        "policy": args.policy or f"synthetic_{args.num_sub}sub",
        "num_sub": int(policy.shape[0]),
        "image": args.image,
        "dispatch": applier.dispatch,
        "groups": applier.groups,
        "shapes": list(applier.shapes),
        "max_wait_ms": args.max_wait_ms,
        "imgs_per_request": args.imgs_per_request,
        **load,
        "serving": stats,
        "bitwise_match": bitwise,
        "aot_compile_sec_total": round(aot_secs, 3),
        "aot_compile": {str(s): r for s, r in applier.compile_log.items()},
        # unified compile stamp (the block every bench JSON line carries)
        "compile_cache": compile_cache_stats(),
        "contention": contention,
        "watchdog": watchdog_stamp(stats.get("mean_dispatch_ms", 0) and
                                   [stats["mean_dispatch_ms"] / 1e3] or [],
                                   label="serve_dispatch"),
    }
    print(json.dumps(out))
    return 0 if bitwise else 4


if __name__ == "__main__":
    raise SystemExit(main())
