"""AOT policy-serving benchmark: p50/p99 latency + imgs/s at fixed
offered QPS (``make bench-serve``), and the OVERLOAD drill
(``make bench-overload``).

Drives the real serving pair — :class:`AotPolicyApplier` (AOT-compiled
padded-shape executables) behind :class:`PolicyServer` (batch
coalescing) — with an OPEN-LOOP arrival process at ``--qps``: requests
are submitted on a fixed schedule regardless of completion (the
heavy-traffic model; a closed loop would hide queueing collapse).  One
JSON line reports:

- ``latency_ms``: p50/p90/p99/max submit-to-scatter per request;
- ``images_per_sec``: achieved serving throughput over the run;
- ``aot_compile_sec`` per shape + the unified ``compile_cache`` block
  (with ``FAA_COMPILE_CACHE`` set, a re-run deserializes the
  executables — the warm-start story applied to serving);
- ``serve_robustness``: the admission/shed/breaker/reload counters
  (docs/RESILIENCE.md "Serving under overload");
- the standard contention + shadow-watchdog stamps, plus a per-run
  ``bitwise_match`` re-verification that exact-dispatch served outputs
  equal direct ``apply_policy`` application.

``--overload`` sweeps offered QPS PAST capacity (calibrated
closed-loop, then ``--multipliers`` x capacity) twice — shedding ON
(bounded queue + per-request deadline + adaptive-LIFO watermarks) vs
OFF (the unbounded clean-weather config) — and reports per arm:
goodput (admitted requests completing within the deadline, per
second), shed rate, deadline-miss rate of admitted, and p50/p99 of
ADMITTED requests.  The acceptance shape: with shedding on, goodput
holds near the clean-weather plateau while p99-of-admitted stays
bounded; with shedding off, every request "succeeds" into a queue
whose latency has already collapsed past the deadline.

    python tools/bench_serve.py [--qps 200] [--seconds 5] [--image 32]
        [--dispatch auto] [--shapes 1,8,32,128]
    python tools/bench_serve.py --overload [--multipliers 1,2,4]
        [--deadline-ms 100] [--overload-seconds 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthetic_policy(num_sub: int, num_op: int):
    """Deterministic multi-sub policy shaped like a search result (ops
    cycle through the searchable table, probs/levels spread)."""
    import numpy as np

    rows = []
    for i in range(num_sub):
        rows.append([[(i * num_op + j) % 15, 0.4 + 0.1 * (i % 5),
                      0.2 + 0.15 * ((i + j) % 5)]
                     for j in range(num_op)])
    return np.asarray(rows, np.float32)


def verify_bitwise(applier, images, keys) -> bool:
    """Exact-dispatch acceptance: served == direct apply_policy, bitwise
    (grouped dispatch is checked against its own batch kernel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.ops.augment import (
        apply_policy,
        apply_policy_batch_grouped,
    )

    got = applier.apply(images, keys)
    if applier.dispatch == "exact":
        ref = np.stack([
            np.asarray(apply_policy(
                jnp.asarray(images[i], jnp.float32),
                applier.policy, jnp.asarray(keys[i])))
            for i in range(images.shape[0])])
    else:
        from fast_autoaugment_tpu.serve.policy_server import pick_shape

        s = pick_shape(applier.shapes, images.shape[0])
        padded = np.zeros((s,) + images.shape[1:], np.float32)
        padded[:images.shape[0]] = images
        ref = np.asarray(apply_policy_batch_grouped(
            jnp.asarray(padded), applier.policy, jnp.asarray(keys),
            groups=applier.groups))[:images.shape[0]]
    return bool(np.array_equal(got, ref))


def run_offered_load(server, images_pool, qps: float, seconds: float,
                     imgs_per_request: int):
    """Open-loop offered load: submit on schedule, collect latencies."""
    import numpy as np

    n_requests = max(1, int(qps * seconds))
    interval = 1.0 / qps
    pending = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        lo = (i * imgs_per_request) % (images_pool.shape[0]
                                       - imgs_per_request + 1)
        pending.append(server.submit(images_pool[lo:lo + imgs_per_request]))
    for p in pending:
        server.result(p, timeout=120.0)
    t_end = max(p.t_done for p in pending)
    lat_ms = np.asarray([p.latency() * 1e3 for p in pending])
    total_imgs = sum(p.n for p in pending)
    return {
        "requests": n_requests,
        "qps_offered": round(qps, 1),
        "qps_achieved": round(n_requests / (t_end - t0), 1),
        "images_per_sec": round(total_imgs / (t_end - t0), 1),
        "latency_ms": {
            "p50": round(float(np.percentile(lat_ms, 50)), 3),
            "p90": round(float(np.percentile(lat_ms, 90)), 3),
            "p99": round(float(np.percentile(lat_ms, 99)), 3),
            "max": round(float(lat_ms.max()), 3),
        },
    }


def _robustness_stamp(stats: dict) -> dict:
    """The flat serve-robustness block every bench JSON line carries
    (admitted/shed/expired/breaker_fires/reloads — BENCH rounds track
    them alongside latency)."""
    adm = stats.get("admission", {})
    brk = stats.get("breaker", {})
    # mean coalesced batch over DISPATCHED work only: images_served /
    # dispatches counts requests the coalescer actually batched — shed
    # and expired requests never reach a dispatch, so an offered-load
    # denominator would understate batch efficiency under overload
    disp = stats.get("dispatches", 0)
    mean_coalesced = (round(stats.get("images_served", 0) / disp, 2)
                      if disp else None)
    return {
        "mean_coalesced_batch": mean_coalesced,
        "admitted": adm.get("admitted", 0),
        "shed_overload": adm.get("shed_overload", 0),
        "shed_breaker": adm.get("shed_breaker", 0),
        "expired": adm.get("expired", 0),
        "deadline_misses": adm.get("deadline_misses", 0),
        "lifo_takes": adm.get("lifo_takes", 0),
        "breaker_fires": brk.get("fires", 0),
        "breaker_state": brk.get("state", "disabled"),
        "reloads": stats.get("reloads", 0),
    }


def _parse_addr(url: str) -> tuple[str, int]:
    from urllib.parse import urlparse

    u = urlparse(url if "//" in url else f"http://{url}")
    return u.hostname or "127.0.0.1", int(u.port or 80)


def _http(host: str, port: int, method: str, path: str, body=None,
          headers=None, timeout: float = 30.0):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def run_router_load(router_url: str, images_pool, seconds: float,
                    imgs_per_request: int, digests: list[str],
                    concurrency: int = 4) -> dict:
    """Closed-loop HTTP load through a serving-plane ROUTER: N client
    threads alternate requests across `digests` (mixed multi-policy
    traffic), HONOR ``Retry-After`` on 429/503 instead of hot
    retrying, and collect end-to-end latencies.  The result stamps the
    router's own topology + affinity accounting (``GET /stats``) so
    the JSON line records WHICH fleet served the numbers."""
    import io
    import threading

    import numpy as np

    from fast_autoaugment_tpu.serve import wire

    host, port = _parse_addr(router_url)
    buf = io.BytesIO()
    np.savez(buf, images=images_pool[:imgs_per_request].astype(np.uint8))
    body = buf.getvalue()
    lat_lock = threading.Lock()
    lats: list[float] = []
    outcomes = {"ok": 0, "retried": 0, "failed": 0}
    stop_at = time.perf_counter() + seconds
    # keep-alive clients: each thread reuses pooled connections instead
    # of paying a TCP handshake per request (wire.ConnectionPool)
    pool = wire.ConnectionPool(timeout_s=30.0,
                               max_idle_per_key=max(1, concurrency))

    def client(idx: int):
        k = idx
        while time.perf_counter() < stop_at:
            headers = {}
            if digests:
                headers["X-FAA-Policy-Digest"] = digests[k % len(digests)]
            k += 1
            t0 = time.perf_counter()
            try:
                status, rheaders, _data = pool.request(
                    host, port, "POST", "/augment", body, headers)
            except OSError:
                with lat_lock:
                    outcomes["failed"] += 1
                continue
            if status in (429, 503):
                # the Retry-After contract: back off what the plane
                # asked for, never hot-retry
                try:
                    ra = float(rheaders.get("Retry-After", "1") or 1)
                except ValueError:
                    ra = 1.0
                with lat_lock:
                    outcomes["retried"] += 1
                time.sleep(min(ra, 2.0))
                continue
            wall = time.perf_counter() - t0
            with lat_lock:
                if status == 200:
                    outcomes["ok"] += 1
                    lats.append(wall)
                else:
                    outcomes["failed"] += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(max(1, concurrency))]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=seconds + 60.0)
    wall = time.perf_counter() - t_start
    lat_ms = np.asarray(lats) * 1e3 if lats else np.asarray([0.0])
    conn_stats = pool.stats()
    pool.close_all()
    row = {
        "requests_ok": outcomes["ok"],
        "client_connections": conn_stats,
        "requests_retried": outcomes["retried"],
        "requests_failed": outcomes["failed"],
        "rps": round(outcomes["ok"] / wall, 1) if wall > 0 else 0.0,
        "latency_ms": {
            "p50": round(float(np.percentile(lat_ms, 50)), 3),
            "p99": round(float(np.percentile(lat_ms, 99)), 3),
            "max": round(float(lat_ms.max()), 3),
        },
    }
    # the router-topology stamp: which replicas, what rotation, what
    # affinity hit rate produced these numbers
    try:
        status, _h, data = _http(host, port, "GET", "/stats", timeout=10.0)
        if status == 200:
            row["router_topology"] = json.loads(data)
    except (OSError, ValueError):
        row["router_topology"] = None
    return row


def calibrate_capacity(make_server, images_pool, imgs_per_request: int,
                       seconds: float = 0.75) -> float:
    """Closed-loop capacity estimate: keep ``2 x max_batch`` requests
    in flight for `seconds`, return achieved requests/s — the
    saturation throughput the overload multipliers scale from.

    A 429 (typed overload rejection) is honored the way a production
    client honors it: BACK OFF ``retry_after_s`` before re-offering.
    The old immediate hot retry hammered the admission path in a tight
    loop, inflating the replica's shed counters during calibration and
    biasing the measured capacity downward (admission-path contention
    on this 1-core host)."""
    from fast_autoaugment_tpu.serve.policy_server import (
        ServerOverloadedError,
    )

    server = make_server()
    try:
        n_window = max(2, 2 * server.max_batch)
        done = 0
        t0 = time.perf_counter()
        inflight = []
        while time.perf_counter() - t0 < seconds:
            while len(inflight) < n_window:
                lo = done % (images_pool.shape[0] - imgs_per_request + 1)
                try:
                    inflight.append(server.submit(
                        images_pool[lo:lo + imgs_per_request]))
                except ServerOverloadedError as e:
                    # honor Retry-After instead of re-offering hot
                    time.sleep(min(e.retry_after_s, 0.25))
                    continue
                done += 1
            server.result(inflight.pop(0), timeout=60.0)
        for p in inflight:
            server.result(p, timeout=60.0)
        wall = time.perf_counter() - t0
        return done / wall
    finally:
        server.stop()


def run_overload_arm(server, images_pool, qps: float, seconds: float,
                     imgs_per_request: int, deadline_ms: float,
                     shed: bool) -> dict:
    """One overload arm: open-loop offered load at `qps`, submissions
    never block (typed rejections counted as shed), goodput = admitted
    requests completing WITHIN the deadline."""
    import numpy as np

    from fast_autoaugment_tpu.serve.policy_server import ServeError

    n_requests = max(1, int(qps * seconds))
    interval = 1.0 / qps
    admitted, shed_n = [], 0
    t0 = time.perf_counter()
    for i in range(n_requests):
        sched = t0 + i * interval
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        lo = (i * imgs_per_request) % (images_pool.shape[0]
                                       - imgs_per_request + 1)
        try:
            # shedding-on stamps the deadline; the off arm submits the
            # clean-weather way (no deadline, unbounded queue)
            admitted.append(server.submit(
                images_pool[lo:lo + imgs_per_request],
                deadline_ms=deadline_ms if shed else None))
        except ServeError:
            shed_n += 1
    good_lat, completed_lat, miss_n = [], [], 0
    for p in admitted:
        try:
            server.result(p, timeout=120.0)
        except ServeError:
            miss_n += 1  # shed in queue (deadline) or failed
            continue
        except TimeoutError:
            miss_n += 1
            continue
        lat = p.latency()
        completed_lat.append(lat)
        if lat * 1e3 <= deadline_ms:
            good_lat.append(lat)
        else:
            miss_n += 1  # completed, but past the deadline budget
    wall = (max((p.t_done for p in admitted), default=time.perf_counter())
            - t0)
    # percentiles over requests that were admitted AND served — a shed
    # request's t_done is its error delivery, not a service latency
    lat_ms = (np.asarray(completed_lat) * 1e3 if completed_lat
              else np.asarray([0.0]))
    return {
        "shedding": "on" if shed else "off",
        "qps_offered": round(qps, 1),
        "requests_offered": n_requests,
        "admitted": len(admitted),
        "shed": shed_n,
        "shed_rate": round(shed_n / n_requests, 4),
        "goodput_rps": round(len(good_lat) / wall, 1) if wall > 0 else 0.0,
        "deadline_miss_rate": (round(miss_n / len(admitted), 4)
                               if admitted else 0.0),
        "admitted_latency_ms": {
            "p50": round(float(np.percentile(lat_ms, 50)), 3),
            "p99": round(float(np.percentile(lat_ms, 99)), 3),
            "max": round(float(lat_ms.max()), 3),
        },
    }


def run_overload(args, applier, pool) -> dict:
    """The full overload sweep: calibrate capacity, then every
    multiplier x capacity with shedding on and off."""
    from fast_autoaugment_tpu.serve.policy_server import PolicyServer

    # the drill serves ONE request per dispatch (requests carry
    # --overload-imgs-per-request images, default 32): with full
    # coalescing of 1-image requests this host's submit loop cannot
    # offer more than the device serves and nothing ever queues — the
    # drill is about queue behavior, not batching efficiency
    imgs_per_request = max(1, args.overload_imgs_per_request)
    max_batch = max(imgs_per_request, args.overload_max_batch)

    def make_server(shed: bool = False):
        if shed:
            return PolicyServer(
                applier, max_batch=max_batch,
                max_wait_ms=args.max_wait_ms,
                queue_depth=args.overload_queue_depth,
                default_deadline_ms=args.deadline_ms,
                lifo_depth=max(2, args.overload_queue_depth // 2),
                lifo_age_ms=args.deadline_ms / 2).start()
        return PolicyServer(applier, max_batch=max_batch,
                            max_wait_ms=args.max_wait_ms).start()

    capacity = calibrate_capacity(lambda: make_server(False), pool,
                                  imgs_per_request)
    multipliers = [float(m) for m in str(args.multipliers).split(",") if m]
    rows = []
    last_stats = {}
    for shed in (True, False):
        for m in multipliers:
            server = make_server(shed)
            try:
                row = run_overload_arm(
                    server, pool, m * capacity, args.overload_seconds,
                    imgs_per_request, args.deadline_ms, shed)
            finally:
                stats = server.stats()
                server.stop()
            row["multiplier"] = m
            row["serve_robustness"] = _robustness_stamp(stats)
            rows.append(row)
            last_stats = stats
    return {
        "capacity_qps": round(capacity, 1),
        "deadline_ms": args.deadline_ms,
        "imgs_per_request": imgs_per_request,
        "overload_queue_depth": args.overload_queue_depth,
        "arms": rows,
        "serving": last_stats,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--policy", default=None,
                   help="final_policy.json / archive name (default: a "
                        "deterministic synthetic --num-sub policy)")
    p.add_argument("--num-sub", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--image", type=int, default=32)
    p.add_argument("--shapes", default="1,8,32,128")
    p.add_argument("--dispatch", default="auto",
                   choices=("auto", "exact", "grouped"))
    p.add_argument("--groups", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--qps", type=float, default=200.0)
    p.add_argument("--seconds", type=float, default=5.0)
    p.add_argument("--imgs-per-request", type=int, default=1)
    # --------------------------------------------------- router mode
    p.add_argument("--router", default=None, metavar="URL",
                   help="measure THROUGH a serving-plane router "
                        "(router_cli) instead of an in-process server: "
                        "closed-loop HTTP clients honoring Retry-After, "
                        "with the router topology + affinity stamp in "
                        "the JSON line (docs/SERVING.md)")
    p.add_argument("--router-digests", default="",
                   help="comma-separated policy digests to alternate "
                        "across requests (mixed multi-policy traffic); "
                        "empty = no digest header (default policy)")
    p.add_argument("--router-concurrency", type=int, default=4,
                   help="closed-loop client threads in --router mode")
    # ------------------------------------------------- overload drill
    p.add_argument("--overload", action="store_true",
                   help="sweep offered QPS past calibrated capacity, "
                        "shedding on vs off (make bench-overload)")
    p.add_argument("--multipliers", default="1,2,4",
                   help="offered-QPS multipliers over calibrated capacity")
    p.add_argument("--deadline-ms", type=float, default=100.0,
                   help="per-request deadline budget in the overload "
                        "drill (shed + goodput reference)")
    p.add_argument("--overload-seconds", type=float, default=2.0,
                   help="seconds of offered load per overload arm")
    p.add_argument("--overload-queue-depth", type=int, default=64,
                   help="bounded queue depth for the shedding-on arms")
    p.add_argument("--overload-max-batch", type=int, default=1,
                   help="coalescer cap during the drill (defaults to the "
                        "per-request image count = one request per "
                        "dispatch, so offered load can actually exceed "
                        "served capacity on a small host)")
    p.add_argument("--overload-imgs-per-request", type=int, default=32,
                   help="images per request in the drill: enough device "
                        "work per dispatch that the open-loop generator "
                        "can out-offer the served rate")
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())

    if args.router:
        # host-only HTTP client mode: the plane (router + replicas) is
        # already up; this process never imports jax
        import numpy as np

        rng = np.random.default_rng(0)
        pool = rng.integers(
            0, 256, (max(64, args.imgs_per_request * 2), args.image,
                     args.image, 3), dtype=np.uint8).astype(np.float32)
        digests = [d for d in str(args.router_digests).split(",") if d]
        load = run_router_load(args.router, pool, args.seconds,
                               args.imgs_per_request, digests,
                               args.router_concurrency)
        out = {
            "metric": "serve_router_latency_ms",
            "router": args.router,
            "image": args.image,
            "imgs_per_request": args.imgs_per_request,
            "digests": digests,
            "seconds": args.seconds,
            **load,
            **telemetry_stamp(contention=contention),
        }
        print(json.dumps(out))
        return 0

    import jax
    import numpy as np

    from fast_autoaugment_tpu.core.compilecache import configure_compile_cache
    from fast_autoaugment_tpu.serve.policy_server import (
        AotPolicyApplier,
        PolicyServer,
    )

    # honor an inherited FAA_COMPILE_CACHE: a second bench run then
    # deserializes the AOT executables instead of re-lowering them
    configure_compile_cache(None)

    if args.policy:
        from fast_autoaugment_tpu.serve.serve_cli import build_policy_tensor

        policy = build_policy_tensor(args.policy)
    else:
        policy = synthetic_policy(args.num_sub, args.num_op)
    shapes = tuple(int(s) for s in str(args.shapes).split(",") if s)

    t0 = time.perf_counter()
    applier = AotPolicyApplier(policy, image=args.image, shapes=shapes,
                               dispatch=args.dispatch, groups=args.groups)
    aot_secs = time.perf_counter() - t0

    rng = np.random.default_rng(0)
    pool = rng.integers(
        0, 256, (max(shapes) * 2, args.image, args.image, 3),
        dtype=np.uint8).astype(np.float32)
    # acceptance re-verification on this exact build: served outputs
    # match the direct kernel bit-for-bit
    n_check = min(3, max(shapes))
    check_keys = (np.stack([np.asarray(jax.random.PRNGKey(i), np.uint32)
                            for i in range(n_check)])
                  if applier.dispatch == "exact"
                  else np.asarray(jax.random.PRNGKey(7), np.uint32))
    bitwise = verify_bitwise(applier, pool[:n_check], check_keys)

    if args.overload:
        # warm the dispatch path once, then run the sweep
        warm = PolicyServer(applier, max_wait_ms=args.max_wait_ms).start()
        warm.augment(pool[:1])
        warm.stop()
        sweep = run_overload(args, applier, pool)
        out = {
            "metric": "serve_overload_goodput",
            "backend": jax.devices()[0].platform,
            "policy": args.policy or f"synthetic_{args.num_sub}sub",
            "num_sub": int(policy.shape[0]),
            "image": args.image,
            "dispatch": applier.dispatch,
            "shapes": list(applier.shapes),
            "max_wait_ms": args.max_wait_ms,
            "imgs_per_request": args.imgs_per_request,
            **sweep,
            "bitwise_match": bitwise,
            "aot_compile_sec_total": round(aot_secs, 3),
            # unified provenance block (bench.telemetry_stamp)
            **telemetry_stamp(contention=contention),
        }
        print(json.dumps(out))
        return 0 if bitwise else 4

    server = PolicyServer(applier, max_wait_ms=args.max_wait_ms).start()
    # warm the dispatch path (first calls already AOT-compiled)
    server.augment(pool[:1])
    load = run_offered_load(server, pool, args.qps, args.seconds,
                            args.imgs_per_request)
    stats = server.stats()
    server.stop()

    out = {
        "metric": "serve_policy_latency_ms",
        "backend": jax.devices()[0].platform,
        "policy": args.policy or f"synthetic_{args.num_sub}sub",
        "num_sub": int(policy.shape[0]),
        "image": args.image,
        "dispatch": applier.dispatch,
        "groups": applier.groups,
        "shapes": list(applier.shapes),
        "max_wait_ms": args.max_wait_ms,
        "imgs_per_request": args.imgs_per_request,
        **load,
        "serving": stats,
        "serve_robustness": _robustness_stamp(stats),
        "bitwise_match": bitwise,
        "aot_compile_sec_total": round(aot_secs, 3),
        "aot_compile": {str(s): r for s, r in applier.compile_log.items()},
        # unified provenance block (bench.telemetry_stamp): contention +
        # shadow watchdog + compile cache + registry counters
        **telemetry_stamp(stats.get("mean_dispatch_ms", 0) and
                          [stats["mean_dispatch_ms"] / 1e3] or [],
                          label="serve_dispatch", contention=contention),
    }
    print(json.dumps(out))
    return 0 if bitwise else 4


if __name__ == "__main__":
    raise SystemExit(main())
