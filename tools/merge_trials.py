"""Merge per-host search trial logs for the --folds scatter flow.

Each host runs ``search_cli --folds k --save-dir <its own dir>``; this
tool merges their ``search_trials.json`` files (and copies fold
checkpoints when present) into one save-dir, after which rerunning
``search_cli`` there resumes instantly and emits the combined final
policy set:

    python tools/merge_trials.py --into merged_dir host0_dir host1_dir ...
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--into", required=True, help="destination save-dir")
    p.add_argument("sources", nargs="+", help="per-host save-dirs")
    args = p.parse_args(argv)

    os.makedirs(args.into, exist_ok=True)
    dest_trials_path = os.path.join(args.into, "search_trials.json")
    merged: dict = {}
    if os.path.exists(dest_trials_path):
        with open(dest_trials_path) as fh:
            merged = json.load(fh)

    # a fold's trial rewards were produced against THAT host's fold
    # checkpoint — trials and checkpoint must travel together, or resumed
    # TPE runs would mix rewards from two differently-initialized models.
    # Folds already held by the destination count as won by the
    # destination: a source checkpoint must never be installed for them,
    # even when the destination lacks its own checkpoint file.
    fold_source: dict[str, str] = {fold: args.into for fold in merged}
    for src in args.sources:
        trials_path = os.path.join(src, "search_trials.json")
        if os.path.exists(trials_path):
            with open(trials_path) as fh:
                for fold, trials in json.load(fh).items():
                    # keep whichever side has MORE trials for a fold
                    if len(trials) > len(merged.get(fold, [])):
                        merged[fold] = trials
                        fold_source[fold] = src

    for src in args.sources:
        for ckpt in glob.glob(os.path.join(src, "*.msgpack*")):
            name = os.path.basename(ckpt)
            if name.endswith(".tmp"):
                continue
            dst = os.path.join(args.into, name)
            if os.path.abspath(ckpt) == os.path.abspath(dst):
                continue
            owner = next(
                (s for fold, s in fold_source.items() if f"fold{fold}_" in name), None
            )
            if owner is not None:
                # fold checkpoint: always take it from the host whose
                # trials won the merge for that fold
                if owner == src:
                    shutil.copy2(ckpt, dst)
            elif not os.path.exists(dst):
                shutil.copy2(ckpt, dst)

    with open(dest_trials_path, "w") as fh:
        json.dump(merged, fh)
    print(
        f"merged {len(args.sources)} dirs -> {args.into}: folds "
        f"{sorted(merged, key=int)} with "
        f"{[len(merged[k]) for k in sorted(merged, key=int)]} trials"
    )


if __name__ == "__main__":
    main()
