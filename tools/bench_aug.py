"""Micro-benchmark for the on-device augmentation engine.

Times each augmentation op (vmapped over a batch), the full policy
application, and the complete CIFAR train-time stack — the pieces that
replace the reference's 8-worker PIL pipeline (``data.py:214-224``).
Run on TPU (plain env) or CPU mesh for relative numbers:

    python tools/bench_aug.py [--batch 128] [--steps 20]

Prints a per-op table plus the policy/stack totals; useful for deciding
whether any op deserves a Pallas kernel (so far XLA fusion has been
sufficient — the full 493-sub-policy stack is a small fraction of a
WRN-40-2 train step).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args(argv)

    # loadavg/process provenance, shared with bench.py: a busy-host
    # capture must be visible in the output itself, and
    # FAA_BENCH_REQUIRE_QUIET=1 refuses instead (VERDICT r5 weak 1)
    import json

    from bench import host_contention_stamp, refuse_or_flag_contention

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.ops import augment as A
    from fast_autoaugment_tpu.ops.preprocess import cifar_train_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor

    images = jnp.asarray(
        np.random.default_rng(0).integers(
            0, 256, (args.batch, args.size, args.size, 3), dtype=np.uint8
        ),
        jnp.float32,
    )
    key = jax.random.PRNGKey(0)

    def timed(fn, *fn_args):
        out = fn(*fn_args)
        jax.block_until_ready(out)  # compile
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = fn(*fn_args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps * 1e3  # ms

    print(f"backend={jax.devices()[0].platform} batch={args.batch} "
          f"size={args.size} steps={args.steps}")
    print(f"{'op':<16} {'ms/batch':>10} {'us/image':>10}")
    for idx, name in enumerate(A.OP_NAMES):
        fn = jax.jit(
            lambda imgs, k, i=idx: jax.vmap(
                lambda im, kk: A.apply_op(im, jnp.int32(i), jnp.float32(0.7), kk)
            )(imgs, jax.random.split(k, imgs.shape[0]))
        )
        ms = timed(fn, images, key)
        print(f"{name:<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")

    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    fn = jax.jit(lambda imgs, k: A.apply_policy_batch(imgs, policy, k))
    ms = timed(fn, images, key)
    print(f"{'policy(493)':<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")

    fn = jax.jit(lambda imgs, k: cifar_train_batch(imgs, k, policy=policy,
                                                   cutout_length=16))
    ms = timed(fn, images, key)
    print(f"{'full stack':<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")


if __name__ == "__main__":
    main()
