"""Micro-benchmark for the on-device augmentation engine.

Times each augmentation op (vmapped over a batch), the full policy
application under BOTH dispatch modes (``exact``: per-image vmapped
``lax.switch``, which XLA lowers to executing all 19 op branches per
image; ``grouped``: scalar-dispatch kernels at each ``--groups`` value),
and the complete CIFAR train-time stack — the pieces that replace the
reference's 8-worker PIL pipeline (``data.py:214-224``).  Run on TPU
(plain env) or CPU mesh for relative numbers:

    python tools/bench_aug.py [--batch 128] [--steps 20] [--groups 4,8,16]

Prints a per-op table plus the dispatch-mode table, and emits ONE JSON
line with ``aug_images_per_sec`` per (mode, G) and the per-mode compile
seconds (the grouped program's branch fan-in differs from the
select-all lowering, so compile time is a first-class metric here).
Use ``--skip-ops`` to bench only the dispatch modes.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def full_19op_policy(num_ops_per_sub: int = 2):
    """A policy touching every registered op: sub-policy i applies ops
    (i, i+1 mod 19) at prob 0.5 — the full-branch-fan-in shape the
    acceptance bench runs (every `lax.switch` branch is live)."""
    import numpy as np

    from fast_autoaugment_tpu.ops.augment import NUM_OPS

    rows = []
    for i in range(NUM_OPS):
        rows.append([[(i + j) % NUM_OPS, 0.5, 0.5 + 0.4 * (j % 2)]
                     for j in range(num_ops_per_sub)])
    return np.asarray(rows, np.float32)


def bench_dispatch_modes(images, key, policy, groups, steps, timed):
    """``aug_images_per_sec`` + compile seconds per (mode, G)."""
    import jax

    from fast_autoaugment_tpu.ops import augment as A

    batch = int(images.shape[0])
    out: dict = {}

    def measure(tag, fn):
        t0 = time.perf_counter()
        first = fn(images, key)
        jax.block_until_ready(first)
        compile_sec = time.perf_counter() - t0
        ms = timed(fn, images, key)
        out[tag] = {
            "images_per_sec": round(batch / (ms / 1e3), 1),
            "ms_per_batch": round(ms, 3),
            "compile_sec": round(compile_sec, 3),
        }
        print(f"{tag:<16} {ms:>10.3f} {ms / batch * 1e3:>10.1f} "
              f"{out[tag]['images_per_sec']:>12.1f} {compile_sec:>10.2f}")

    print(f"{'dispatch':<16} {'ms/batch':>10} {'us/image':>10} "
          f"{'images/sec':>12} {'compile_s':>10}")
    measure("exact", jax.jit(
        lambda imgs, k: A.apply_policy_batch(imgs, policy, k)))
    for g in groups:
        measure(f"grouped_g{g}", jax.jit(
            lambda imgs, k, g=g: A.apply_policy_batch_grouped(
                imgs, policy, k, groups=g)))
    best = max((v["images_per_sec"] for t, v in out.items()
                if t.startswith("grouped")), default=None)
    if best and out["exact"]["images_per_sec"]:
        out["speedup_grouped_best_vs_exact"] = round(
            best / out["exact"]["images_per_sec"], 2)
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--groups", default="4,8,16",
                   help="comma-separated grouped-dispatch chunk counts")
    p.add_argument("--skip-ops", action="store_true",
                   help="skip the per-op table (dispatch modes only)")
    args = p.parse_args(argv)
    groups = [int(g) for g in str(args.groups).split(",") if g]

    # loadavg/process provenance, shared with bench.py: a busy-host
    # capture must be visible in the output itself, and
    # FAA_BENCH_REQUIRE_QUIET=1 refuses instead (VERDICT r5 weak 1)
    import json

    from bench import (
        arm_compile_cache_from_env,
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())
    print(f"contention: {json.dumps(contention)}")
    arm_compile_cache_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.ops import augment as A
    from fast_autoaugment_tpu.ops.preprocess import cifar_train_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor

    images = jnp.asarray(
        np.random.default_rng(0).integers(
            0, 256, (args.batch, args.size, args.size, 3), dtype=np.uint8
        ),
        jnp.float32,
    )
    key = jax.random.PRNGKey(0)

    def timed(fn, *fn_args):
        out = fn(*fn_args)
        jax.block_until_ready(out)  # compile
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = fn(*fn_args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps * 1e3  # ms

    print(f"backend={jax.devices()[0].platform} batch={args.batch} "
          f"size={args.size} steps={args.steps}")
    if not args.skip_ops:
        print(f"{'op':<16} {'ms/batch':>10} {'us/image':>10}")
        for idx, name in enumerate(A.OP_NAMES):
            fn = jax.jit(
                lambda imgs, k, i=idx: jax.vmap(
                    lambda im, kk: A.apply_op(im, jnp.int32(i), jnp.float32(0.7), kk)
                )(imgs, jax.random.split(k, imgs.shape[0]))
            )
            ms = timed(fn, images, key)
            print(f"{name:<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")

    # dispatch modes on the full-19-op policy (every branch live): the
    # acceptance shape for the grouped >= 3x exact criterion
    policy19 = jnp.asarray(full_19op_policy())
    modes = bench_dispatch_modes(images, key, policy19, groups, args.steps,
                                 timed)

    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    fn = jax.jit(lambda imgs, k: A.apply_policy_batch(imgs, policy, k))
    ms = timed(fn, images, key)
    print(f"{'policy(493)':<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")
    policy493 = {"exact_ms_per_batch": round(ms, 3)}
    g0 = groups[0] if groups else 8
    fn = jax.jit(lambda imgs, k: A.apply_policy_batch_grouped(
        imgs, policy, k, groups=g0))
    ms_g = timed(fn, images, key)
    print(f"{'policy(493) g' + str(g0):<16} {ms_g:>10.3f} "
          f"{ms_g / args.batch * 1e3:>10.1f}")
    policy493[f"grouped_g{g0}_ms_per_batch"] = round(ms_g, 3)

    fn = jax.jit(lambda imgs, k: cifar_train_batch(imgs, k, policy=policy,
                                                   cutout_length=16))
    ms = timed(fn, images, key)
    print(f"{'full stack':<16} {ms:>10.3f} {ms / args.batch * 1e3:>10.1f}")
    stack = {"exact_ms_per_batch": round(ms, 3)}
    fn = jax.jit(lambda imgs, k: cifar_train_batch(
        imgs, k, policy=policy, cutout_length=16, aug_dispatch="grouped",
        aug_groups=g0))
    ms_g = timed(fn, images, key)
    print(f"{'full stack g' + str(g0):<16} {ms_g:>10.3f} "
          f"{ms_g / args.batch * 1e3:>10.1f}")
    stack[f"grouped_g{g0}_ms_per_batch"] = round(ms_g, 3)

    # unified provenance block (bench.telemetry_stamp): schema_version
    # + contention + shadow watchdog + compile cache + registry counters
    # — the per-(mode, G) compile_sec entries above remain raw timings
    print(json.dumps({
        "metric": "aug_images_per_sec",
        "unit": "images/sec",
        "backend": jax.devices()[0].platform,
        "batch": args.batch,
        "size": args.size,
        "steps": args.steps,
        "policy": "full19 (every op branch live, 2 ops/sub)",
        "modes": modes,
        "policy_493": policy493,
        "full_stack": stack,
        **telemetry_stamp([ms / 1e3], label="train_aug_stack",
                          contention=contention),
    }))


if __name__ == "__main__":
    main()
