#!/bin/bash
# Opportunistic TPU capture loop (VERDICT round 3 next-step 1; hardened
# per VERDICT round 4 weak 2: heartbeat + append-only logging so
# "armed" is verifiable post-hoc even if the loop dies with the round).
#
# The TPU tunnel flaps for whole rounds; the official perf record needs
# a real-chip number the moment one is reachable.  This loop probes the
# chip cheaply and, as soon as a probe answers, fires the capture
# ladder in order of value-per-minute:
#
#   1. python bench.py                  -> docs/bench_tpu_latest.json
#   2. python tools/bench_tta.py        -> docs/tta_bench_tpu.json
#      (TTA/eval-shape throughput: de-risks the CPU->TPU conversion in
#      the search-cost certification, which otherwise borrows the
#      train-shape rate)
#   3. python tools/bench_aug.py        -> docs/aug_bench_tpu.txt
#   4. python tools/profile_tpu.py      -> docs/tpu_trace_r5/
#   5. bash tools/run_search_refscale.sh full   -> search_refscale/
#      (reference-scale search, certifies the <1 TPU-hour claim)
#
# Each stage commits its artifact immediately (path-scoped commits so a
# mid-ladder tunnel death still leaves evidence in git), records a
# marker in .ambush/ and is skipped on later revivals once captured.
#
# Evidence trail (VERDICT r4 weak 2 — round 4's loop left no trace):
#   - .ambush/heartbeat.log: one appended line per probe cycle;
#   - the heartbeat log is force-committed every $HEARTBEAT_COMMIT_EVERY
#     cycles, so git history itself proves the loop stayed armed;
#   - all stdout/stderr appends to tpu_ambush.log via exec (the caller
#     cannot truncate it by redirect mistake).
#
#   nohup bash tools/tpu_ambush.sh & disown
set -u
cd "$(dirname "$0")/.."
mkdir -p .ambush
# kernel-managed mutual exclusion: flock is atomic, and the lock
# auto-releases on ANY exit (kill -9 included) — no staleness
# heuristics, pid files, or cleanup-trap races
exec 9>.ambush/lock
if ! flock -n 9; then
    echo "[ambush] another instance holds the lock — exiting" >> tpu_ambush.log
    exit 0
fi
# append-only logging owned by the script itself, not the caller
exec >> tpu_ambush.log 2>&1

PROBE_TIMEOUT="${AMBUSH_PROBE_TIMEOUT:-150}"
SLEEP_SECS="${AMBUSH_SLEEP_SECS:-300}"
HEARTBEAT_COMMIT_EVERY="${AMBUSH_HEARTBEAT_COMMIT_EVERY:-20}"

log() { echo "[ambush $(date -u +%H:%M:%S)] $*"; }

probe() {
    timeout "$PROBE_TIMEOUT" python -c \
        "import jax; d = jax.devices()[0]; assert d.platform != 'cpu', d" \
        >/dev/null 2>&1
}

commit_paths() {  # commit_paths <msg> <path...>
    local msg="$1"; shift
    for _ in 1 2 3 4 5; do
        if git add -f "$@" && git commit -m "$msg" -- "$@"; then
            return 0
        fi
        sleep 15   # index.lock contention with the foreground session
    done
    log "commit failed for: $*"
    return 1
}

log "armed: pid $$, probe timeout ${PROBE_TIMEOUT}s, sleep ${SLEEP_SECS}s"
CYCLE=0
while true; do
    if [ -e .ambush/done ]; then
        log "all stages captured — exiting"
        exit 0
    fi
    CYCLE=$((CYCLE + 1))
    if probe; then ALIVE=ALIVE; else ALIVE=dead; fi
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) cycle=$CYCLE probe=$ALIVE" \
        >> .ambush/heartbeat.log
    if [ $((CYCLE % HEARTBEAT_COMMIT_EVERY)) -eq 0 ]; then
        commit_paths "ambush heartbeat: armed through cycle $CYCLE ($(date -u +%H:%M)Z)" \
            .ambush/heartbeat.log
    fi
    if [ "$ALIVE" != ALIVE ]; then
        sleep "$SLEEP_SECS"
        continue
    fi
    log "TPU probe ALIVE (cycle $CYCLE)"

    if [ ! -e .ambush/bench ]; then
        log "stage 1: bench.py"
        if FAA_BENCH_PROBE_TIMEOUT=60 FAA_BENCH_RETRY_WINDOW=120 \
                timeout 2400 python bench.py > .ambush/bench_out.json 2>.ambush/bench.log \
                && grep -vq cpu-fallback .ambush/bench_out.json \
                && [ -s docs/bench_tpu_latest.json ]; then
            touch .ambush/bench
            commit_paths "TPU bench captured opportunistically: persist docs/bench_tpu_latest.json" \
                docs/bench_tpu_latest.json .ambush/heartbeat.log
        else
            log "bench failed (tunnel died mid-run?)"; tail -3 .ambush/bench.log
        fi
    fi

    if [ -e .ambush/bench ] && [ ! -e .ambush/tta ]; then
        log "stage 2: TTA/eval-shape throughput"
        if timeout 1800 python tools/bench_tta.py --out docs/tta_bench_tpu.json \
                > .ambush/tta.log 2>&1 \
                && grep -vq '"backend": "cpu"' docs/tta_bench_tpu.json; then
            touch .ambush/tta
            commit_paths "TTA-shape TPU throughput sample: measured CPU->TPU trial-cost conversion" \
                docs/tta_bench_tpu.json
        else
            log "tta bench failed"; tail -3 .ambush/tta.log
        fi
    fi

    if [ -e .ambush/bench ] && [ ! -e .ambush/aug ]; then
        log "stage 3: aug op-cost table on TPU"
        if timeout 1800 python tools/bench_aug.py --batch 128 --steps 20 \
                > docs/aug_bench_tpu.txt 2>.ambush/aug.log \
                && grep -q "full stack" docs/aug_bench_tpu.txt; then
            touch .ambush/aug
            commit_paths "TPU re-profile of the augmentation engine: per-op cost table" \
                docs/aug_bench_tpu.txt
        else
            log "aug bench failed"; tail -3 .ambush/aug.log
        fi
    fi

    if [ -e .ambush/bench ] && [ ! -e .ambush/trace ]; then
        log "stage 4: jax.profiler traces of train + TTA steps"
        if timeout 2400 python tools/profile_tpu.py --out docs/tpu_trace_r5 \
                >> .ambush/trace.log 2>&1 \
                && [ -s docs/tpu_trace_r5/summary.json ]; then
            touch .ambush/trace
            # commit the summary always; the raw xplane only when small
            TRACE_PATHS="docs/tpu_trace_r5/summary.json"
            if [ "$(du -sk docs/tpu_trace_r5 | cut -f1)" -lt 2048 ]; then
                TRACE_PATHS="docs/tpu_trace_r5"
            fi
            commit_paths "jax.profiler traces of the train and TTA steps on TPU" \
                $TRACE_PATHS
        else
            log "trace capture failed"; tail -3 .ambush/trace.log
        fi
    fi

    if [ -e .ambush/bench ] && [ ! -e .ambush/refscale ]; then
        log "stage 5: reference-scale search on TPU"
        if timeout 21600 bash tools/run_search_refscale.sh full; then
            touch .ambush/refscale
            commit_paths "Reference-scale search on TPU: 5 folds x 200 trials at production shape" \
                search_refscale/search_result.json search_refscale/audit.json \
                search_refscale/final_policy.json search_refscale.log
        else
            log "refscale search failed or timed out"
        fi
    fi

    if [ -e .ambush/bench ] && [ -e .ambush/tta ] && [ -e .ambush/aug ] \
            && [ -e .ambush/trace ] && [ -e .ambush/refscale ]; then
        touch .ambush/done
    fi
    sleep "$SLEEP_SECS"
done
