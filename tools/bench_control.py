#!/usr/bin/env python
"""Closed-loop control-plane bench (``make bench-control``).

Measures the two numbers docs/CONTROL.md promises: DETECT->PROMOTE
LATENCY (injected drift at the serve dispatch seam -> the journaled
``promote`` event) and ROLLOVER GOODPUT (served requests/s while the
canary rollout + fleet-wide promotion are in flight, vs the same
fleet's steady-state goodput).

Per round a real 3-replica plane comes up — ``serve_cli
--traffic-stats --telemetry`` replicas announcing into a shared
``--port-dir`` — and one of two arms runs:

- **steady**: closed-loop traffic, no drift, no controller;
- **rollover**: the same traffic with ``FAA_FAULT
  drift@dispatch=N,shift=S`` armed in every replica and a
  ``control_cli`` (drill mode: pre-built candidate, so the measured
  latency is the CONTROL PLANE's, not a search wall) that detects,
  canaries and promotes mid-run.

Arms run as PAIRED ALTERNATING rounds with per-arm MEDIANS (the
1-core A/B discipline: fixed-order arms read allocator drift as
signal) and the JSON line carries the latency breakdown
(shift->detect, detect->promote), both arms' goodput, the zero-drop
verdict, the unified telemetry stamp and the ``single_core_caveat`` —
every process here shares one core, so the goodput ratio measures
PLUMBING overhead, not fleet behavior at scale.

    python tools/bench_control.py [--pairs 2] [--seconds-per-arm 12]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

#: baseline / candidate single-sub policies (exact dispatch, distinct
#: digests — the canary comparator must be able to tell them apart)
POLICY_A = [[["Rotate", 0.5, 0.4], ["Invert", 0.2, 0.0]]]
POLICY_B = [[["ShearX", 0.9, 0.1], ["Solarize", 0.3, 0.7]]]

DRIFT_DISPATCH = 40      # the fault's dispatch coordinate
DRIFT_SHIFT = 60.0       # injected pixel shift (sigmas >> cusum h)


def _read_journal_events(tel_dir: str, etypes: set[str]) -> list[dict]:
    out = []
    for path in sorted(glob.glob(
            os.path.join(tel_dir, "**", "journal-*.jsonl"),
            recursive=True)):
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("type") in etypes:
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: r.get("t_wall") or 0)
    return out


def _drive_traffic(ports, seconds, imgs_per_request, image,
                   until_fn=None, check_every: int = 32):
    """Round-robin closed-loop client over the replica ports; returns
    (per-request (t_wall_done, ok, latency_s) rows, elapsed_s).

    `until_fn` (rollover arm) is polled every `check_every` requests
    once `seconds` has passed: traffic CONTINUES until it returns True
    (the promote landed) or the hard bound — the rollover arm must
    cover the whole detect->promote window, however long the AOT
    reloads take on this host."""
    import io

    import numpy as np

    from bench_router import _http

    rng = np.random.default_rng(0)
    pool = rng.integers(0, 256, (64, image, image, 3),
                        dtype=np.uint8).astype(np.float32)
    rows = []
    i = 0
    t0 = time.monotonic()
    t_end = t0 + seconds
    t_hard = t0 + max(seconds, 150.0)
    while True:
        now = time.monotonic()
        if until_fn is None:
            if now >= t_end:
                break
        elif now >= t_hard:
            break
        elif now >= t_end and i % check_every == 0 and until_fn():
            break
        batch = pool[(i * imgs_per_request) % 48:
                     (i * imgs_per_request) % 48 + imgs_per_request]
        buf = io.BytesIO()
        np.savez(buf, images=batch)
        port = ports[i % len(ports)]
        t_req = time.monotonic()
        try:
            status, _h, _b = _http("127.0.0.1", port, "POST", "/augment",
                                   body=buf.getvalue(), timeout=30.0)
            ok = status == 200
        except OSError:
            ok = False
        rows.append((time.time(), ok, time.monotonic() - t_req))
        i += 1
    return rows, time.monotonic() - t0


def run_round(arm: str, args, compile_cache: str) -> dict:
    from bench_router import wait_port_record, wait_ready

    procs: list[subprocess.Popen] = []
    with tempfile.TemporaryDirectory(prefix=f"bench_control_{arm}_") as tmp:
        tel_dir = os.path.join(tmp, "telemetry")
        port_dir = os.path.join(tmp, "replicas")
        path_a = os.path.join(tmp, "a.json")
        path_b = os.path.join(tmp, "b.json")
        with open(path_a, "w") as fh:
            json.dump(POLICY_A, fh)
        with open(path_b, "w") as fh:
            json.dump(POLICY_B, fh)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FAA_COMPILE_CACHE=compile_cache)
        env.pop("FAA_TELEMETRY", None)
        if arm == "rollover":
            env["FAA_FAULT"] = (f"drift@dispatch={DRIFT_DISPATCH},"
                                f"shift={DRIFT_SHIFT:g}")
        try:
            ports = []
            for i in range(args.replicas):
                env_i = dict(env, FAA_HOST_ID=str(i))
                procs.append(subprocess.Popen([
                    sys.executable, "-m",
                    "fast_autoaugment_tpu.serve.serve_cli",
                    "--policy", path_a, "--image", str(args.image),
                    "--shapes", args.shapes,
                    "--max-wait-ms", "2",
                    "--traffic-stats",
                    "--telemetry", tel_dir,
                    "--compile-cache", compile_cache,
                    "--port", "0", "--port-dir", port_dir,
                    "--host-tag", f"replica{i}",
                ], env=env_i, cwd=_REPO))
            for i in range(args.replicas):
                port = wait_port_record(port_dir, f"replica{i}", procs[i],
                                        args.startup_timeout)
                wait_ready("127.0.0.1", port, procs[i],
                           args.startup_timeout)
                ports.append(port)

            ctl = None
            stats_file = os.path.join(tmp, "control_stats.json")
            if arm == "rollover":
                ctl_env = dict(env)
                ctl_env.pop("FAA_FAULT", None)  # the fault is serve-side
                ctl = subprocess.Popen([
                    sys.executable, "-m",
                    "fast_autoaugment_tpu.launch.control_cli",
                    "--telemetry", tel_dir, "--port-dir", port_dir,
                    "--baseline-policy", path_a,
                    "--candidate-policy", path_b,
                    "--baseline-samples", "10",
                    "--cusum-h", "4", "--gate-polls", "2",
                    "--quality-margin", "1.0",
                    "--poll-interval", "0.2",
                    "--reload-timeout", str(args.startup_timeout),
                    "--stats-file", stats_file,
                ], env=ctl_env, cwd=_REPO)
                procs.append(ctl)

            until_fn = None
            if arm == "rollover":
                def until_fn():
                    return any(
                        e["type"] == "promote" for e in
                        _read_journal_events(tel_dir, {"promote"}))

            rows, elapsed = _drive_traffic(
                ports, args.seconds_per_arm, args.imgs_per_request,
                args.image, until_fn=until_fn)

            row: dict = {"arm": arm}
            oks = [r for r in rows if r[1]]
            lats = sorted(r[2] for r in oks)
            row["requests_ok"] = len(oks)
            row["requests_failed"] = len(rows) - len(oks)
            row["elapsed_s"] = round(elapsed, 2)
            row["rps"] = round(len(oks) / elapsed, 1)
            if lats:
                row["p50_ms"] = round(lats[len(lats) // 2] * 1e3, 3)
                row["p99_ms"] = round(
                    lats[min(len(lats) - 1,
                             int(0.99 * len(lats)))] * 1e3, 3)
            if arm == "rollover":
                evs = _read_journal_events(
                    tel_dir, {"drift", "canary", "promote", "rollback",
                              "dispatch"})
                drift = next((e for e in evs if e["type"] == "drift"),
                             None)
                promote = next((e for e in evs
                                if e["type"] == "promote"), None)
                rollout = next((e for e in evs
                                if e["type"] == "canary"
                                and e.get("action") == "rollout"), None)
                # the shift lands at a known dispatch event: the first
                # journal dispatch whose input_mean jumped past half
                # the injected shift over the pre-shift level
                shifted = None
                pre = [e for e in evs if e["type"] == "dispatch"
                       and isinstance(e.get("input_mean"), (int, float))]
                if pre:
                    base = pre[0]["input_mean"]
                    shifted = next(
                        (e for e in pre
                         if e["input_mean"] - base > DRIFT_SHIFT / 2),
                        None)
                row["promoted"] = promote is not None
                if shifted and drift:
                    row["shift_to_detect_s"] = round(
                        drift["t_wall"] - shifted["t_wall"], 3)
                if drift and promote:
                    row["detect_to_promote_s"] = round(
                        promote["t_wall"] - drift["t_wall"], 3)
                if rollout and promote:
                    window = [r for r in rows
                              if rollout["t_wall"] <= r[0]
                              <= promote["t_wall"]]
                    w_ok = [r for r in window if r[1]]
                    span = max(promote["t_wall"] - rollout["t_wall"],
                               1e-9)
                    row["rollover_window_s"] = round(span, 3)
                    row["rollover_rps"] = round(len(w_ok) / span, 1)
                    row["rollover_failed"] = len(window) - len(w_ok)
            return row
        finally:
            for proc in reversed(procs):
                if proc.poll() is None:
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except ProcessLookupError:
                        pass
            deadline = time.monotonic() + 30.0
            for proc in procs:
                left = max(0.5, deadline - time.monotonic())
                try:
                    proc.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--pairs", type=int, default=2,
                   help="paired alternating rounds per arm (medians "
                        "reported)")
    p.add_argument("--seconds-per-arm", type=float, default=14.0)
    p.add_argument("--image", type=int, default=8)
    p.add_argument("--shapes", default="1,8")
    p.add_argument("--imgs-per-request", type=int, default=4)
    p.add_argument("--startup-timeout", type=float, default=240.0)
    args = p.parse_args(argv)

    from bench import (
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )
    from bench_router import _median

    contention = refuse_or_flag_contention(host_contention_stamp())

    rounds = []
    with tempfile.TemporaryDirectory(prefix="bench_control_cc_") as cc:
        for i in range(max(1, args.pairs)):
            order = (("steady", "rollover") if i % 2 == 0
                     else ("rollover", "steady"))
            for arm in order:
                rounds.append(run_round(arm, args, cc))

    meds = {}
    for arm in ("steady", "rollover"):
        sel = [r for r in rounds if r["arm"] == arm]
        meds[arm] = {
            "rps_median": round(_median([r["rps"] for r in sel]), 1),
            "p50_ms_median": round(_median(
                [r.get("p50_ms", 0.0) for r in sel]), 3),
            "p99_ms_median": round(_median(
                [r.get("p99_ms", 0.0) for r in sel]), 3),
            "requests_ok": sum(r["requests_ok"] for r in sel),
            "requests_failed": sum(r["requests_failed"] for r in sel),
        }
    roll = [r for r in rounds if r["arm"] == "rollover"]
    promoted = all(r.get("promoted") for r in roll)
    out = {
        "metric": "control_detect_to_promote",
        "replicas": args.replicas,
        "pairs": args.pairs,
        "seconds_per_arm": args.seconds_per_arm,
        "drift_dispatch": DRIFT_DISPATCH,
        "drift_shift": DRIFT_SHIFT,
        "arms": meds,
        "all_rounds_promoted": promoted,
        "shift_to_detect_s_median": _median(
            [r["shift_to_detect_s"] for r in roll
             if "shift_to_detect_s" in r]),
        "detect_to_promote_s_median": _median(
            [r["detect_to_promote_s"] for r in roll
             if "detect_to_promote_s" in r]),
        "rollover_rps_median": _median(
            [r["rollover_rps"] for r in roll if "rollover_rps" in r]),
        "rollover_dropped_total": sum(
            r.get("rollover_failed", 0) for r in roll),
        "rollover_over_steady_rps": (
            round(_median([r["rollover_rps"] for r in roll
                           if "rollover_rps" in r])
                  / meds["steady"]["rps_median"], 3)
            if meds["steady"]["rps_median"]
            and any("rollover_rps" in r for r in roll) else None),
        "rounds": rounds,
        # every replica, the controller and the client share ONE core:
        # ratios here are plumbing overhead, not fleet behavior
        "single_core_caveat": True,
        **telemetry_stamp(contention=contention),
    }
    print(json.dumps(out))
    ok = promoted and out["rollover_dropped_total"] == 0 \
        and meds["steady"]["requests_ok"] > 0
    return 0 if ok else 4


if __name__ == "__main__":
    raise SystemExit(main())
