"""Real-data reproduction fire-drill (``make reproduce``).

The repo's accuracy evidence is synthetic-only because this build
environment is zero-egress (no CIFAR pickle, SVHN .mat or reference
.pth exists on disk).  This tool is the one-command path that fires the
moment data and hardware appear (VERDICT r3, next-step 8):

1. fetch CIFAR-10 (and optionally SVHN/CIFAR-100) with the same
   integrity-gated transfer the ImageNet machinery uses
   (``imagenet_tools.fetch``: md5-verified, .part + atomic rename,
   resumable) — skipping gracefully when the network is unreachable;
2. train WRN-40-2 with the shipped ``fa_reduced_cifar10`` policy
   archive at the reference's headline config
   (``confs/wresnet40x2_cifar.yaml``; reference README.md:20 — FAA 3.6
   / published checkpoint 3.52 top-1 error);
3. evaluate any published reference ``.pth`` checkpoints present under
   ``--ckpt-dir`` through the import + only-eval manifest
   (``tools/reproduce_checkpoints.py``).

    python tools/reproduce.py --dataroot ./data [--datasets cifar10,svhn]
        [--ckpt-dir ./ckpts] [--epochs N] [--dry-run]

Exit code 0 on a graceful offline skip (nothing fetched, nothing to
do), so CI can run the drill unconditionally.
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.error

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_autoaugment_tpu.data.imagenet_tools import extract_tar, fetch  # noqa: E402

# public dataset mirrors + md5s (same values torchvision pins;
# reference data.py:114-134 downloads through torchvision)
DATA_TABLE: dict[str, list[dict]] = {
    "cifar10": [{
        "url": "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
        "md5": "c58f30108f718f92721af3b95e74349a",
        "extract": True,
    }],
    "cifar100": [{
        "url": "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
        "md5": "eb9058c3a382ffc7106e4002c42a8d85",
        "extract": True,
    }],
    "svhn": [
        {"url": "http://ufldl.stanford.edu/housenumbers/train_32x32.mat",
         "md5": "e26dedcc434d2e4c54c9b2d4a06d8373", "extract": False},
        {"url": "http://ufldl.stanford.edu/housenumbers/test_32x32.mat",
         "md5": "eb5a983be6a315427106f1b164d9cef3", "extract": False},
        {"url": "http://ufldl.stanford.edu/housenumbers/extra_32x32.mat",
         "md5": "a93ce644f1a588dc4d68dda5feec44a7", "extract": False},
    ],
}


def fetch_datasets(dataroot: str, names: list[str]) -> list[str]:
    """Fetch + verify + extract each dataset; returns those available
    locally afterwards.  Network failures skip (offline is normal)."""
    ready = []
    for name in names:
        ok = True
        for item in DATA_TABLE[name]:
            try:
                path = fetch(item["url"], dataroot, md5=item["md5"])
            except (urllib.error.URLError, OSError, IOError) as e:
                print(f"[reproduce] {name}: fetch failed ({e}) — skipping "
                      "(offline build environment?)")
                ok = False
                break
            if item["extract"]:
                extract_tar(path, dataroot)
        if ok:
            ready.append(name)
    return ready


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dataroot", default="./data")
    p.add_argument("--datasets", default="cifar10")
    p.add_argument("--ckpt-dir", default="./ckpts")
    p.add_argument("--save", default="ckpt/reproduce_wresnet40x2.msgpack")
    p.add_argument("--epochs", type=int, default=None,
                   help="override conf epoch (smoke runs)")
    p.add_argument("--dry-run", action="store_true",
                   help="fetch/verify only; no training or eval")
    args = p.parse_args(argv)

    names = [n.strip() for n in args.datasets.split(",") if n.strip()]
    unknown = [n for n in names if n not in DATA_TABLE]
    if unknown:
        p.error(f"unknown datasets {unknown}; choose from {sorted(DATA_TABLE)}")
    ready = fetch_datasets(args.dataroot, names)
    print(f"[reproduce] datasets ready: {ready or 'none'}")

    did_anything = False
    if "cifar10" in ready and not args.dry_run:
        from fast_autoaugment_tpu.core.config import load_config
        from fast_autoaugment_tpu.train.trainer import train_and_eval

        overrides = [f"epoch={args.epochs}"] if args.epochs else []
        conf = load_config("confs/wresnet40x2_cifar.yaml", overrides=overrides)
        print("[reproduce] training WRN-40-2 + fa_reduced_cifar10 "
              f"({conf['epoch']} epochs) -> {args.save}")
        os.makedirs(os.path.dirname(args.save) or ".", exist_ok=True)
        res = train_and_eval(conf, args.dataroot, test_ratio=0.0,
                             save_path=args.save, metric="test")
        top1 = res.get("top1_test", 0.0)
        print(f"[reproduce] WRN-40-2 cifar10 top1_test={top1:.4f} "
              f"(error {100 * (1 - top1):.2f}%; reference FAA 3.6, "
              "published ckpt 3.52 — README.md:20)")
        did_anything = True

    if os.path.isdir(args.ckpt_dir) and not args.dry_run:
        present = [f for f in os.listdir(args.ckpt_dir) if f.endswith(".pth")]
        if present and ready:
            import tools.reproduce_checkpoints as rc

            print(f"[reproduce] evaluating {len(present)} published checkpoints")
            rc_code = rc.main(["--ckpt-dir", args.ckpt_dir,
                               "--dataroot", args.dataroot])
            if rc_code:
                return rc_code  # failed reproduction must fail the drill
            did_anything = True

    if not did_anything:
        print("[reproduce] nothing to do (no data fetched, no checkpoints "
              "present) — graceful skip")
    return 0


if __name__ == "__main__":
    sys.exit(main())
