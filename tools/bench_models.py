"""Model-zoo training-throughput benchmark: img/s + MFU per family.

Runs the SAME fused production train step `bench.py` measures (policy
augmentation + fwd/bwd + optimizer, bf16 activations) across model
families on whatever backend the environment provides — the real TPU
chip in the build container, or the virtual CPU mesh for plumbing runs.
Complements `bench.py` (single headline config) with the zoo-wide view:
the reference's cost table spans WRN/Shake-Shake/PyramidNet/ResNet/
EfficientNet (reference ``README.md:16-41``), so the TPU story should
too.

    python tools/bench_models.py [--models wresnet40_2,resnet50]
        [--steps 15] [--out docs/model_bench.md]

Each entry prints a JSON line and, with --out, the table is appended
as markdown. CIFAR families run at 32px / their conf batch; ImageNet
families at 224px with a reduced batch so a single chip holds them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (model conf, dataset family, batch/device, policy archive)
ZOO = {
    "wresnet40_2": ({"type": "wresnet40_2"}, "cifar", 128, "fa_reduced_cifar10"),
    "wresnet28_10": ({"type": "wresnet28_10"}, "cifar", 128, "fa_reduced_cifar10"),
    "shake26_2x32d": ({"type": "shakeshake26_2x32d"}, "cifar", 128, "fa_reduced_cifar10"),
    "shake26_2x96d": ({"type": "shakeshake26_2x96d"}, "cifar", 128, "fa_reduced_cifar10"),
    "pyramid272": (
        {"type": "pyramid", "depth": 272, "alpha": 200, "bottleneck": True},
        "cifar", 64, "fa_reduced_cifar10",
    ),
    "resnet50": ({"type": "resnet50"}, "imagenet", 64, "fa_resnet50_rimagenet"),
    "resnet200": ({"type": "resnet200"}, "imagenet", 16, "fa_resnet50_rimagenet"),
    "efficientnet_b0": (
        {"type": "efficientnet-b0"}, "imagenet", 64, "fa_resnet50_rimagenet",
    ),
}


def bench_one(name, steps, warmup):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor
    from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step

    from bench import _chip_peak_flops, _step_flops  # reuse headline helpers

    model_conf, family, batch, archive = ZOO[name]
    mesh = make_mesh()
    global_batch = batch * mesh.size
    size = 224 if family == "imagenet" else 32
    num_classes = 120 if family == "imagenet" else 10

    model = get_model(dict(model_conf, precision="bf16"), num_classes)
    optimizer = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
         "nesterov": True},
        lambda step: 0.1,
    )
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, size, size, 3), jnp.float32)
    state = create_train_state(model, optimizer, rng, sample, use_ema=False)

    if family == "imagenet":
        from fast_autoaugment_tpu.ops.preprocess_imagenet import imagenet_train_batch

        augment_fn = lambda images, pol, key: imagenet_train_batch(  # noqa: E731
            images, key, pol, cutout_length=0
        )
    else:
        augment_fn = None  # default CIFAR stack, cutout 16
    train_step = make_train_step(
        model, optimizer, num_classes=num_classes, cutout_length=16,
        use_policy=True, augment_fn=augment_fn,
    )

    host = np.random.default_rng(0)
    images = host.integers(0, 256, (global_batch, size, size, 3), dtype=np.uint8)
    labels = host.integers(0, num_classes, (global_batch,), np.int32).astype(np.int32)
    policy = jnp.asarray(policy_to_tensor(load_policy(archive)))
    batch_sharded = shard_batch(mesh, {"x": images, "y": labels})

    t0 = time.perf_counter()
    step_exec = train_step.lower(
        state, batch_sharded["x"], batch_sharded["y"], policy, rng
    ).compile()
    compile_s = time.perf_counter() - t0
    for _ in range(warmup):
        state, _ = step_exec(state, batch_sharded["x"], batch_sharded["y"], policy, rng)
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, _ = step_exec(state, batch_sharded["x"], batch_sharded["y"], policy, rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    ips = steps * global_batch / dt / mesh.size
    flops = _step_flops(step_exec)
    peak = _chip_peak_flops(jax.devices()[0])
    mfu = round(flops * (steps / dt) / peak, 4) if flops and peak else None
    from bench import watchdog_stamp  # hang-vs-straggler provenance

    return {
        "model": name, "family": family, "batch_per_device": batch,
        "image_size": size, "images_per_sec_per_chip": round(ips, 1),
        "mfu": mfu, "step_flops": flops, "compile_s": round(compile_s, 1),
        "devices": mesh.size,
        "watchdog": watchdog_stamp([dt / steps], label=name),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", default=",".join(ZOO))
    p.add_argument("--steps", type=int, default=15)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    from bench import (  # dead-tunnel guard + load provenance (bench.py)
        _ensure_live_backend,
        arm_compile_cache_from_env,
        host_contention_stamp,
        refuse_or_flag_contention,
        telemetry_stamp,
    )

    contention = refuse_or_flag_contention(host_contention_stamp())
    _ensure_live_backend(
        reexec_argv=[sys.executable, os.path.abspath(__file__), *sys.argv[1:]]
    )
    arm_compile_cache_from_env()
    cpu_fallback = bool(os.environ.get("FAA_BENCH_CPU_FALLBACK"))
    if cpu_fallback:
        # plumbing heartbeat only (mirrors bench.py's shrunk fallback):
        # clamp the sweep so a 1-core CPU run stays bounded, and keep
        # only the 32px families unless the user picked models explicitly
        args.steps = min(args.steps, 2)
        args.warmup = min(args.warmup, 1)
        if args.models == p.get_default("models"):
            args.models = "wresnet40_2"

    rows = []
    for name in args.models.split(","):
        name = name.strip()
        if name not in ZOO:
            print(f"[bench_models] unknown model {name!r}; skipping", file=sys.stderr)
            continue
        print(f"[bench_models] {name}: compiling + measuring...", file=sys.stderr)
        try:
            row = bench_one(name, args.steps, args.warmup)
        except Exception as e:  # noqa: BLE001 — keep sweeping on OOM etc.
            print(f"[bench_models] {name} FAILED: {e}", file=sys.stderr)
            row = {"model": name, "error": str(e).splitlines()[0][:200]}
        if cpu_fallback:
            row["backend"] = "cpu-fallback"  # never masquerades as TPU
        # unified provenance block (bench.telemetry_stamp) — the
        # per-model watchdog stamp bench_one computed rides through
        row.update(telemetry_stamp(contention=contention,
                                   watchdog=row.get("watchdog")))
        rows.append(row)
        print(json.dumps(row), flush=True)

    if args.out:
        lines = [
            "| model | family | batch | img/s/chip | MFU | compile (s) |",
            "|---|---|---|---|---|---|",
        ]
        for r in rows:
            if "error" in r:
                lines.append(f"| {r['model']} | — | — | FAILED | — | — |")
            else:
                lines.append(
                    f"| {r['model']} | {r['family']} | {r['batch_per_device']} "
                    f"| {r['images_per_sec_per_chip']} | {r['mfu']} "
                    f"| {r['compile_s']} |"
                )
        with open(args.out, "a") as fh:
            fh.write("\n".join(lines) + "\n")
    return rows


if __name__ == "__main__":
    main()
