"""Headline benchmark: WRN-40-2 CIFAR-10 training throughput per chip.

Measures the full production train step — on-device fa_reduced_cifar10
policy augmentation (493 sub-policies as a tensor), random crop/flip,
normalize, cutout-16, forward/backward with global-batch BN, non-BN
weight decay, grad clip, SGD-nesterov, cosine+warmup LR — at the
reference's headline config (``confs/wresnet40x2_cifar.yaml``: batch
128 per device).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference pipeline (PyTorch + 8 PIL CPU workers per GPU)
sustains roughly 1500 images/s/GPU on a V100-class device for WRN-40-2
CIFAR-10 (its 3.5 GPU-hour / 200-epoch budget on this config implies
the low thousands; no exact number is published — README.md:16).
vs_baseline = value / 1500.
"""

import json
import os
import sys
import time

import numpy as np

REFERENCE_IMAGES_PER_SEC = 1500.0
BATCH_PER_DEVICE = max(1, int(os.environ.get("FAA_BENCH_BATCH", 128)))
# floors: warmup 0 would put the multi-minute first compile inside the
# timed loop and silently wreck the headline number
WARMUP_STEPS = max(1, int(os.environ.get("FAA_BENCH_WARMUP", 5)))
MEASURE_STEPS = max(1, int(os.environ.get("FAA_BENCH_STEPS", 30)))


def _log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.ops.schedules import build_schedule
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor
    from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step

    mesh = make_mesh()
    n_dev = mesh.size
    global_batch = BATCH_PER_DEVICE * n_dev

    conf = {
        "lr": 0.1, "epoch": 200,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 5}},
    }
    # bf16 activations (f32 params/BN) — the TPU-first precision choice
    model = get_model({"type": "wresnet40_2", "precision": "bf16"}, 10)
    optimizer = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9, "nesterov": True},
        build_schedule(conf, steps_per_epoch=50000 // global_batch,
                       world_lr_scale=float(n_dev)),
    )
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, 32, 32, 3), jnp.float32)
    state = create_train_state(model, optimizer, rng, sample, use_ema=False)
    train_step = make_train_step(
        model, optimizer, num_classes=10, cutout_length=16, use_policy=True
    )

    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    images = np.random.default_rng(0).integers(
        0, 256, (global_batch, 32, 32, 3), dtype=np.uint8
    )
    labels = np.random.default_rng(1).integers(0, 10, (global_batch,), np.int32)
    batch = shard_batch(mesh, {"x": images, "y": labels})

    _log(f"devices={n_dev} global_batch={global_batch}; compiling train step "
         "(first TPU compile can take minutes)")
    t_compile = time.perf_counter()
    for i in range(WARMUP_STEPS):
        state, metrics = train_step(state, batch["x"], batch["y"], policy, rng)
        if i == 0:
            jax.block_until_ready(state.params)
            _log(f"compile+first step: {time.perf_counter() - t_compile:.1f}s")
    jax.block_until_ready(state.params)
    _log(f"warmup done; measuring {MEASURE_STEPS} steps")

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = train_step(state, batch["x"], batch["y"], policy, rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    images_per_sec_per_chip = MEASURE_STEPS * global_batch / dt / n_dev
    print(
        json.dumps(
            {
                "metric": "wrn40x2_cifar10_train_images_per_sec_per_chip",
                "value": round(images_per_sec_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(images_per_sec_per_chip / REFERENCE_IMAGES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
