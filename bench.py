"""Headline benchmark: WRN-40-2 CIFAR-10 training throughput per chip.

Measures the full production train step — on-device fa_reduced_cifar10
policy augmentation (493 sub-policies as a tensor), random crop/flip,
normalize, cutout-16, forward/backward with global-batch BN, non-BN
weight decay, grad clip, SGD-nesterov, cosine+warmup LR — at the
reference's headline config (``confs/wresnet40x2_cifar.yaml``: batch
128 per device).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"images_per_sec_hostfeed", "contention", "tta_trials_per_sec", ...}.
Every artifact is loadavg-stamped at capture start (`contention`) and
carries the phase-2 scheduler throughput at candidate-batch K in
{1, 4, 16} (`tta_trials_per_sec`; see bench_tta_scheduler).

Baseline: the reference pipeline (PyTorch + 8 PIL CPU workers per GPU)
sustains roughly 1500 images/s/GPU on a V100-class device for WRN-40-2
CIFAR-10 (its 3.5 GPU-hour / 200-epoch budget on this config implies
the low thousands; no exact number is published — README.md:16).
vs_baseline = value / 1500 (a bracket); `mfu` — model FLOPs utilization
from the compiled step's XLA cost analysis against the chip's peak —
is the defensible headline on TPU.

Two throughput numbers are measured:
- `value` (headline): device-resident batch, steady-state step rate —
  pure device throughput of the fused train step;
- `images_per_sec_hostfeed`: fresh batches flow through the real host
  pipeline (`train_batches` + background `prefetch`) every step, i.e.
  end-to-end including the host feed path.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_IMAGES_PER_SEC = 1500.0
BATCH_PER_DEVICE = max(1, int(os.environ.get("FAA_BENCH_BATCH", 128)))
# floors: warmup 0 would put the multi-minute first compile inside the
# timed loop and silently wreck the headline number
WARMUP_STEPS = max(1, int(os.environ.get("FAA_BENCH_WARMUP", 5)))
MEASURE_STEPS = max(1, int(os.environ.get("FAA_BENCH_STEPS", 30)))
#  default: cpu-count-gated (docs/loader_bench.md — depth >1 hurts on a
#  1-core host); override with FAA_BENCH_PREFETCH
_env_depth = os.environ.get("FAA_BENCH_PREFETCH")
PREFETCH_DEPTH = max(1, int(_env_depth)) if _env_depth else None

# peak dense bf16 FLOP/s per chip by generation (public spec sheets);
# MFU is computed against the matching entry, else reported as null
_PEAK_FLOPS_BF16 = {
    "v2": 46e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def _log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def host_contention_stamp() -> dict:
    """Load/contention provenance for a bench artifact.

    VERDICT r5 weak 1: an official round number was captured while the
    host was busy, and nothing in the artifact said so.  Every bench
    JSON now carries the 1/5/15-minute load averages, the core count
    and the process count AT CAPTURE START, plus a ``contended`` verdict
    (pre-existing 1-minute load above 75% of the cores) — so a busy-host
    capture is visible in the artifact itself.  Set
    ``FAA_BENCH_REQUIRE_QUIET=1`` to make the bench REFUSE to run
    (exit 3) instead of merely flagging.
    """
    stamp: dict = {"cpu_count": os.cpu_count()}
    try:
        la1, la5, la15 = os.getloadavg()
        stamp["loadavg_1m"] = round(la1, 2)
        stamp["loadavg_5m"] = round(la5, 2)
        stamp["loadavg_15m"] = round(la15, 2)
    except OSError:  # not available on this platform
        stamp["loadavg_1m"] = stamp["loadavg_5m"] = stamp["loadavg_15m"] = None
    try:
        stamp["process_count"] = sum(
            1 for d in os.listdir("/proc") if d.isdigit())
    except OSError:
        stamp["process_count"] = None
    la1 = stamp["loadavg_1m"]
    stamp["contended"] = bool(
        la1 is not None and la1 > 0.75 * (stamp["cpu_count"] or 1))
    return stamp


def refuse_or_flag_contention(stamp: dict) -> dict:
    """Exit under FAA_BENCH_REQUIRE_QUIET on a busy host, else annotate."""
    if not stamp.get("contended"):
        return stamp
    msg = (f"host is contended at capture start: loadavg_1m="
           f"{stamp['loadavg_1m']} on {stamp['cpu_count']} core(s), "
           f"{stamp['process_count']} processes")
    if os.environ.get("FAA_BENCH_REQUIRE_QUIET"):
        _log(f"REFUSING to bench ({msg}); unset FAA_BENCH_REQUIRE_QUIET "
             "to capture anyway (the artifact would be flagged)")
        sys.exit(3)
    _log(f"WARNING: {msg} — artifact will be flagged contended=true; do "
         "not commit it as an official number")
    stamp["note"] = ("captured under host contention — timings are "
                     "unreliable; not an official number")
    return stamp


def watchdog_stamp(observed_walls, fires: int = 0,
                   label: str = "dispatch") -> dict:
    """Shadow-watchdog provenance for a bench artifact.

    Feeds the bench's observed per-dispatch walls through the REAL
    auto-mode EMA (``core/watchdog.py``) and stamps the deadline a
    ``--watchdog auto`` run would settle at, alongside the fire count
    (0 for an unmonitored bench).  With this next to the contention
    stamp, a BENCH artifact can distinguish a hang (deadline would
    fire) from a straggler (wall above EMA, below deadline) after the
    fact."""
    from fast_autoaugment_tpu.core.watchdog import DispatchWatchdog

    walls = [float(w) for w in observed_walls if w and w > 0]
    stamp = {"watchdog_fires": int(fires)}
    if not walls:
        stamp["watchdog_deadline_sec"] = None
        return stamp
    wd = DispatchWatchdog("auto")
    for w in walls:
        wd.observe(label, w)
    stamp["watchdog_deadline_sec"] = round(wd.deadline(label), 6)
    stamp["watchdog_ema_sec"] = round(wd.ema(label) or 0.0, 6)
    stamp["watchdog_max_observed_sec"] = round(max(walls), 6)
    return stamp


def arm_compile_cache_from_env() -> str | None:
    """Enable the persistent compile cache from an inherited
    ``FAA_COMPILE_CACHE`` (no-op otherwise).  Benches call this BEFORE
    their first compile so a second invocation demonstrates the warm
    start the cache exists for; returns the active dir or None."""
    from fast_autoaugment_tpu.core.compilecache import configure_compile_cache

    return configure_compile_cache(None)


def compile_cache_stamp() -> dict:
    """The unified ``compile_cache`` block every bench JSON line
    carries: persistent-cache dir/hit/miss counts plus per-label
    first-call (compile) seconds through the seam — ONE schema across
    ``bench.py`` and the ``tools/bench_*.py`` siblings (the comparable
    record the ad-hoc per-tool ``compile_*_sec`` keys never were)."""
    from fast_autoaugment_tpu.core.compilecache import compile_cache_stats

    return compile_cache_stats()


#: version of the unified telemetry_stamp() block — bump on any key
#: rename/removal so cross-round bench JSON comparisons can gate on it
TELEMETRY_STAMP_SCHEMA_VERSION = 1


def telemetry_stamp(observed_walls=(), *, fires: int = 0,
                    label: str = "dispatch",
                    contention: dict | None = None,
                    watchdog: dict | None = None) -> dict:
    """THE unified provenance block for a bench JSON line.

    One schema (``schema_version`` + ``contention`` + ``watchdog`` +
    ``compile_cache`` + the telemetry registry's counters) across
    ``bench.py`` and every ``tools/bench_*.py`` sibling — each tool
    used to re-implement its own stamp block from the individual
    helpers, which is exactly how schemas drift.  Splat the result into
    the artifact (``row.update(telemetry_stamp(...))``): the historical
    top-level keys (``contention``/``watchdog``/``compile_cache``) keep
    their names and shapes.

    `observed_walls`/`fires`/`label` feed the shadow-watchdog stamp
    (or pass a pre-built `watchdog` dict — per-row sweeps that already
    stamped a per-config deadline keep it); `contention` reuses a stamp
    captured earlier (benches capture it BEFORE compiling so their own
    load doesn't pollute the 1-minute average) or captures one now."""
    from fast_autoaugment_tpu.core import telemetry

    return {
        "schema_version": TELEMETRY_STAMP_SCHEMA_VERSION,
        "contention": (contention if contention is not None
                       else host_contention_stamp()),
        "watchdog": (watchdog if watchdog is not None
                     else watchdog_stamp(observed_walls, fires=fires,
                                         label=label)),
        "compile_cache": compile_cache_stamp(),
        "telemetry_counters": telemetry.registry().counters_snapshot(),
    }


def vs_baseline(images_per_sec: float, cpu_fallback: bool) -> float | None:
    """Ratio against the reference-pipeline estimate, or None on the CPU
    fallback: comparing a CPU plumbing heartbeat against the TPU-class
    1500 img/s baseline produced misleading artifacts (BENCH_r05.json's
    `vs_baseline: 0.003` was a dead-tunnel CPU number, not a 300x
    regression) — a fallback run has no meaningful baseline ratio."""
    if cpu_fallback:
        return None
    return round(images_per_sec / REFERENCE_IMAGES_PER_SEC, 3)


def _chip_peak_flops(device) -> float | None:
    """Peak bf16 FLOP/s for this chip, or None when unknown/not a TPU."""
    if getattr(device, "platform", "") == "cpu":
        return None  # MFU vs a TPU peak is meaningless on the CPU mesh
    kind = getattr(device, "device_kind", "") or ""
    hints = [kind.lower(), os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()]
    for gen in sorted(_PEAK_FLOPS_BF16, key=len, reverse=True):
        if any(gen in h for h in hints if h):
            return _PEAK_FLOPS_BF16[gen]
    return None


def _step_flops(lowered_compiled) -> float | None:
    """FLOPs of one compiled step from XLA's cost analysis.

    Under SPMD partitioning these are PER-DEVICE flops (the analysis is
    of the partitioned module), so MFU = flops * step_rate / chip_peak
    with no extra device division (verified empirically: a 4-way-sharded
    matmul reports 1/4 the unsharded flops)."""
    try:
        cost = lowered_compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:  # noqa: BLE001 — backend-dependent API
        _log(f"cost_analysis unavailable: {e}")
        return None


def _probe_backend_once(probe_timeout: float) -> int:
    """Device-init probe in a throwaway subprocess; 0 = chip reachable."""
    try:
        return subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=probe_timeout, capture_output=True,
        ).returncode
    except subprocess.TimeoutExpired:
        return -1


def _probe_memo_path() -> str:
    import tempfile

    return os.environ.get(
        "FAA_PROBE_MEMO_PATH",
        os.path.join(tempfile.gettempdir(), "faa_tpu_probe_verdict.json"))


def _read_probe_memo(ttl: float) -> str | None:
    """The memoized probe verdict ('alive'/'dead') if fresher than
    `ttl` seconds, else None.  BENCH_r05's tail burned an 11-minute
    probe-retry window PER TOOL before each CPU fallback; back-to-back
    bench invocations now share one verdict instead of re-paying it."""
    if ttl <= 0:
        return None
    try:
        with open(_probe_memo_path()) as fh:
            rec = json.load(fh)
        if time.time() - float(rec["ts"]) <= ttl:
            return str(rec["verdict"])
    except (OSError, ValueError, KeyError, TypeError):
        pass  # missing/torn/stale memo: probe for real
    return None


def _write_probe_memo(verdict: str) -> None:
    path = _probe_memo_path()
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump({"verdict": verdict, "ts": time.time()}, fh)
        os.replace(tmp, path)  # atomic: concurrent tools never tear it
    except OSError as e:
        _log(f"could not persist probe memo {path}: {e}")


def _ensure_live_backend(reexec_argv=None, fallback_env=None):
    """Fall back to a clean CPU env when the TPU tunnel is dead.

    The ambient sitecustomize registers a single-chip TPU PJRT plugin in
    every interpreter (gated on PALLAS_AXON_POOL_IPS); when the tunnel
    drops, backend discovery hangs forever — even `jax.devices()` under
    JAX_PLATFORMS=cpu.  Both failure modes are observed (round 1: claim
    serialization; round 2: mid-round tunnel drop), so probe device init
    in a throwaway subprocess first and, if it wedges, re-exec the
    calling script (`reexec_argv`, default this bench) into a stripped
    CPU environment with an explicit marker so the reported JSON can
    never masquerade as a TPU number.  Shared by the sibling benchmark
    tools (e.g. tools/bench_models.py), which pass their own argv and
    fallback knobs.

    Tunnel flaps are often transient (round 2 lost its official TPU
    number to one dead probe at capture time), so a failed probe is
    retried every FAA_BENCH_RETRY_SECS (60 s) within a bounded
    FAA_BENCH_RETRY_WINDOW (900 s) before surrendering to the CPU
    fallback (VERDICT round 2, next-step 2).
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return  # nothing registered that could hang
    if os.environ.get("FAA_SKIP_TPU_PROBE"):
        _log("FAA_SKIP_TPU_PROBE set: trusting the chip, skipping the "
             "backend probe entirely")
        return
    probe_timeout = float(os.environ.get("FAA_BENCH_PROBE_TIMEOUT", 240))
    if probe_timeout <= 0:
        return  # probe disabled: trust the chip, skip the extra init
    # short-TTL memoized verdict: BENCH_r05's tail shows EVERY bench
    # round burning the full probe-retry window (11 min) before its CPU
    # fallback — back-to-back invocations share one verdict instead
    memo_ttl = float(os.environ.get("FAA_PROBE_MEMO_TTL", 600))
    memo = _read_probe_memo(memo_ttl)
    if memo == "alive":
        _log("probe memo says the chip was reachable "
             f"<{memo_ttl:.0f}s ago: skipping the probe")
        return
    retry_window = float(os.environ.get("FAA_BENCH_RETRY_WINDOW", 900))
    retry_secs = max(1.0, float(os.environ.get("FAA_BENCH_RETRY_SECS", 60)))
    if memo == "dead":
        _log("probe memo says the tunnel was dead "
             f"<{memo_ttl:.0f}s ago: skipping the "
             f"{retry_window:.0f}s retry window, straight to CPU fallback")
        rc = -2
    else:
        deadline = time.monotonic() + retry_window
        rc = _probe_backend_once(probe_timeout)
        while rc != 0 and time.monotonic() < deadline:
            wait = min(retry_secs, max(0.0, deadline - time.monotonic()))
            _log(f"TPU backend probe failed (rc={rc}); re-probing in "
                 f"{wait:.0f}s "
                 f"(window closes in {deadline - time.monotonic():.0f}s)")
            time.sleep(wait)
            rc = _probe_backend_once(probe_timeout)
        _write_probe_memo("alive" if rc == 0 else "dead")
    if rc == 0:
        return  # chip reachable; run the real benchmark
    _log(f"TPU backend probe failed (rc={rc}) for the whole retry window; "
         "re-exec on clean CPU env")
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["FAA_BENCH_CPU_FALLBACK"] = "1"
    for k, v in (fallback_env or {}).items():
        env.setdefault(k, v)
    if reexec_argv is None:
        reexec_argv = [sys.executable, os.path.abspath(__file__)]
    os.execvpe(reexec_argv[0], reexec_argv, env)


def bench_tta_scheduler(ks=(1, 4, 16), trials_per_k=None) -> dict:
    """Phase-2 scheduler throughput: TTA trials/sec at candidate-batch K.

    Runs a faithful miniature of `search/driver.py` phase 2 — real
    in-tree TPE proposals (`ask(K)`/`tell_batch`), real policy
    decode/tensorize, the real compiled TTA step (`make_tta_step`,
    candidate axis vmapped for K>1), and the real per-round fsync
    trial-log persist — at a deliberately tiny probe shape
    (`FAA_BENCH_TTA_MODEL` @ `FAA_BENCH_TTA_IMG` px, batch
    `FAA_BENCH_TTA_BATCH`, 1 TTA draw) so the FIXED per-trial costs the
    batched scheduler amortizes (dispatch, host sync, fsync persist,
    proposal overhead) are visible next to the device math.  K=1 is the
    sequential scheduler code path (`suggest`/`tell`, one program per
    trial); K>1 evaluates K trials per device program.

    On a TPU the same amortization applies to a device that finishes
    the math orders of magnitude faster, PLUS the K*P*B batch actually
    fills the MXU — so the CPU-measured speedup is a LOWER bound on the
    scheduling win, not a chip throughput claim.  The headline train
    bench above stays the chip-throughput number.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.policies.archive import (
        policy_decoder,
        policy_to_tensor,
    )
    from fast_autoaugment_tpu.search.driver import (
        make_search_space,
        write_json_atomic,
    )
    from fast_autoaugment_tpu.search.tpe import TPE
    from fast_autoaugment_tpu.search.tta import (
        eval_tta,
        eval_tta_batched,
        make_tta_step,
    )

    model_type = os.environ.get("FAA_BENCH_TTA_MODEL", "wresnet10_1")
    img = int(os.environ.get("FAA_BENCH_TTA_IMG", 8))
    batch = int(os.environ.get("FAA_BENCH_TTA_BATCH", 1))
    num_policy, num_op, n_sub = 1, 1, 1
    if trials_per_k is None:
        trials_per_k = max(
            max(ks), int(os.environ.get("FAA_BENCH_TTA_TRIALS", 192)))
    repeats = max(1, int(os.environ.get("FAA_BENCH_TTA_REPEATS", 3)))

    model = get_model({"type": model_type}, 10)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (batch, img, img, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (batch,), np.int32)
    mask = np.ones(batch, np.float32)
    batches = [{"x": jnp.asarray(images), "y": jnp.asarray(labels),
                "m": jnp.asarray(mask)}]
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, img, img, 3), jnp.float32),
        train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    space = make_search_space(n_sub, num_op)
    tmpdir = tempfile.mkdtemp(prefix="faa_tta_bench_")
    trials_path = os.path.join(tmpdir, "search_trials.json")
    key_fold = jax.random.PRNGKey(7)

    def run_rounds(k, n_trials, step):
        """The phase-2 inner loop at candidate-batch k; returns seconds."""
        tpe = TPE(space, seed=0, n_startup=5)
        trial_log = []
        t0 = time.perf_counter()
        done = 0
        while done < n_trials:
            if k == 1:
                proposal = tpe.suggest()
                policy_t = jnp.asarray(policy_to_tensor(
                    policy_decoder(proposal, n_sub, num_op)))
                m = eval_tta(step, params, batch_stats, batches, policy_t,
                             jax.random.fold_in(key_fold, done))
                tpe.tell(proposal, m["top1_valid"])
                trial_log.append((proposal, m["top1_valid"]))
            else:
                proposals = tpe.ask(k)
                policies_t = jnp.asarray(np.stack([
                    np.asarray(policy_to_tensor(
                        policy_decoder(p, n_sub, num_op)), np.float32)
                    for p in proposals
                ]))
                keys = jnp.stack([jax.random.fold_in(key_fold, done + i)
                                  for i in range(k)])
                ms = eval_tta_batched(step, params, batch_stats, batches,
                                      policies_t, keys)
                rewards = [m["top1_valid"] for m in ms]
                tpe.tell_batch(proposals, rewards)
                trial_log.extend(zip(proposals, rewards))
            # the driver's per-round durability write (fsync + rename)
            write_json_atomic(trials_path, {"0": trial_log})
            done += k
        return time.perf_counter() - t0, done

    out = {"probe": {"model": model_type, "image": img, "batch": batch,
                     "num_policy": num_policy, "num_sub": n_sub,
                     "trials_per_k": trials_per_k},
           "trials_per_sec": {}}
    for k in ks:
        t_c = time.perf_counter()
        step = make_tta_step(model, num_policy=num_policy, cutout_length=0,
                             num_candidates=None if k == 1 else k)
        # warm-up round: compile lands here, outside the timed loop
        run_rounds(k, k, step)
        compile_s = time.perf_counter() - t_c
        # best of `repeats`: the least-contended window is the honest
        # scheduler rate on a shared host (the stamp records the load)
        rate, done = 0.0, 0
        for _ in range(repeats):
            dt, done = run_rounds(k, trials_per_k, step)
            rate = max(rate, done / dt)
        out["trials_per_sec"][str(k)] = round(rate, 2)
        _log(f"tta scheduler K={k}: {rate:.1f} trials/s best-of-{repeats} "
             f"({done} trials/repeat; compile+warm {compile_s:.1f}s)")
    base = out["trials_per_sec"].get("1")
    top = out["trials_per_sec"].get(str(max(ks)))
    if base and top:
        out["speedup_max_k_vs_1"] = round(top / base, 2)
    return out


def bench_fold_stack(num_folds=5, steps=None) -> dict:
    """Phase-1 scheduler throughput: fold-train steps/sec at
    ``--fold-stack {0, K}``.

    Runs a faithful miniature of phase-1 fold pretraining — the real
    jitted train step (`make_train_step`) vs the real fold-stacked step
    (`make_stacked_train_step`, K whole learner replicas vmapped into
    one program) on K independent states — at a tiny probe shape
    (`FAA_BENCH_FS_MODEL` @ `FAA_BENCH_FS_IMG` px, batch
    `FAA_BENCH_FS_BATCH`) so the per-step FIXED costs the stacked
    scheduler amortizes (K per-fold program dispatches per step -> one)
    are visible next to the device math.  The unit is FOLD-steps/sec:
    one stacked call counts K.  On a TPU the same amortization applies
    PLUS the K-model batch actually fills the MXU — the CPU number is a
    lower bound on the scheduling win, exactly as `bench_tta_scheduler`
    is for phase 2.
    """
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_stacked_train_step,
        make_train_step,
        stack_states,
    )

    model_type = os.environ.get("FAA_BENCH_FS_MODEL", "wresnet10_1")
    img = int(os.environ.get("FAA_BENCH_FS_IMG", 8))
    batch = int(os.environ.get("FAA_BENCH_FS_BATCH", 4))
    if steps is None:
        steps = max(1, int(os.environ.get("FAA_BENCH_FS_STEPS", 30)))
    repeats = max(1, int(os.environ.get("FAA_BENCH_FS_REPEATS", 3)))

    model = get_model({"type": model_type}, 10)
    opt_conf = {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
                "nesterov": True}
    sample = jnp.zeros((2, img, img, 3), jnp.float32)
    kw = dict(num_classes=10, cutout_length=0, use_policy=False)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (num_folds, batch, img, img, 3),
                          dtype=np.uint8)
    labels = rng.integers(0, 10, (num_folds, batch), np.int32)
    pol = jnp.zeros((1, 1, 3), jnp.float32)
    keys = jnp.stack([jax.random.PRNGKey(k) for k in range(num_folds)])
    active = jnp.ones((num_folds,), jnp.float32)

    def fresh_states():
        opt = build_optimizer(opt_conf, lambda s: 0.05)
        return [create_train_state(model, opt, jax.random.PRNGKey(k), sample,
                                   use_ema=False) for k in range(num_folds)]

    out = {"probe": {"model": model_type, "image": img, "batch": batch,
                     "num_folds": num_folds, "steps": steps},
           "steps_per_sec": {}}

    # sequential: one program per (fold, step) — today's phase-1 loop
    opt = build_optimizer(opt_conf, lambda s: 0.05)
    seq_step = make_train_step(model, opt, **kw)
    states = fresh_states()
    xs = [jnp.asarray(images[k]) for k in range(num_folds)]
    ys = [jnp.asarray(labels[k]) for k in range(num_folds)]
    for k in range(num_folds):  # compile + warm outside the timed loop
        states[k], _ = seq_step(states[k], xs[k], ys[k], pol, keys[k])
    jax.block_until_ready(states[0].params)
    rate = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            for k in range(num_folds):
                states[k], _ = seq_step(states[k], xs[k], ys[k], pol, keys[k])
        jax.block_until_ready(states[0].params)
        rate = max(rate, steps * num_folds / (time.perf_counter() - t0))
    out["steps_per_sec"]["0"] = round(rate, 2)
    _log(f"fold-stack K=0 (sequential): {rate:.1f} fold-steps/s "
         f"best-of-{repeats}")

    # stacked: K folds per program — the --fold-stack K scheduler
    opt = build_optimizer(opt_conf, lambda s: 0.05)
    st_step = make_stacked_train_step(model, opt, **kw)
    stacked = stack_states(fresh_states())
    xst, yst = jnp.asarray(images), jnp.asarray(labels)
    stacked, _ = st_step(stacked, xst, yst, pol, keys, active)
    jax.block_until_ready(stacked.params)
    rate = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            stacked, _ = st_step(stacked, xst, yst, pol, keys, active)
        jax.block_until_ready(stacked.params)
        rate = max(rate, steps * num_folds / (time.perf_counter() - t0))
    out["steps_per_sec"][str(num_folds)] = round(rate, 2)
    _log(f"fold-stack K={num_folds} (stacked): {rate:.1f} fold-steps/s "
         f"best-of-{repeats}")
    base = out["steps_per_sec"]["0"]
    top = out["steps_per_sec"][str(num_folds)]
    if base and top:
        out["speedup_stacked_vs_sequential"] = round(top / base, 2)
    return out


def _dispatch_probe_model():
    """Conv-free probe for `bench_step_dispatch`: dense + batch-norm.

    The dispatch bench measures the per-step FIXED costs (host gather,
    device_put, program launch, metric-sum dispatches) that multi-step
    fusion removes, so the probe's device math must be small enough not
    to drown them — AND must avoid convolutions, whose BACKWARD pass
    inside an XLA:CPU while loop hits a ~3-4x slow kernel path that
    would turn the CPU measurement into a conv-kernel artifact instead
    of a dispatch measurement (`train/steps.py::default_dispatch_unroll`
    documents the pathology; TPU scans of conv models are the standard
    pjit-trainer shape and unaffected).  Set FAA_BENCH_SD_MODEL to a
    registry model (e.g. wresnet10_1) to measure a CNN probe instead —
    on CPU that number understates the win for exactly this reason.
    """
    import flax.linen as nn
    import jax.numpy as jnp

    class DispatchProbe(nn.Module):
        features: int = 32

        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(self.features)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             momentum=0.9)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    return DispatchProbe()


def bench_step_dispatch(ns=(1, 8, 32), steps=None,
                        telemetry_compare: bool = False) -> dict:
    """Train-step dispatch throughput: `train_steps_per_sec` at
    ``--steps-per-dispatch N`` with the device cache vs the host feed.

    Runs a faithful miniature of the trainer's inner loop — the real
    jitted step (`make_train_step`) fed fresh host batches through
    `train_batches` + `shard_batch`, with the trainer's per-step
    metric-sum accumulation (one fancy-gather + H2D copy + dispatch +
    metric adds per step, today's path) — against the real multi-step
    program (`make_multistep_train_step` over a `DeviceCache`: one
    int32 index matrix + ONE dispatch + one metric add per N steps).
    The probe model is deliberately dispatch-bound and conv-free
    (see `_dispatch_probe_model`; FAA_BENCH_SD_MODEL overrides), at
    `FAA_BENCH_SD_IMG` px / batch `FAA_BENCH_SD_BATCH`.  On a TPU the
    same amortization applies on top of device math the MXU finishes
    faster — the CPU number measures the scheduling win, not chip
    throughput, exactly as `bench_fold_stack` does for fold stacking.
    Per-(N, cache) compile seconds ride in the JSON line.
    """
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.core.metrics import Accumulator
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import (
        DeviceCache,
        train_batches,
        train_index_matrix,
    )
    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.parallel.mesh import (
        make_mesh,
        place_index_matrix,
        replicated,
        shard_batch,
    )
    from fast_autoaugment_tpu.train.steps import (
        create_train_state,
        make_multistep_train_step,
        make_train_step,
        make_train_step_body,
    )

    model_type = os.environ.get("FAA_BENCH_SD_MODEL", "linear")
    img = int(os.environ.get("FAA_BENCH_SD_IMG", 8))
    batch = int(os.environ.get("FAA_BENCH_SD_BATCH", 4))
    if steps is None:
        # divisible by every N so all configs run the same step count
        steps = max(max(ns), int(os.environ.get("FAA_BENCH_SD_STEPS", 192)))
        steps -= steps % max(ns)
    repeats = max(1, int(os.environ.get("FAA_BENCH_SD_REPEATS", 3)))

    mesh = make_mesh()
    model = (_dispatch_probe_model() if model_type == "linear"
             else get_model({"type": model_type}, 10))
    # conv-free probe: the rolled scan is the fast CPU shape (and the
    # TPU production shape); registry CNN probes take the trainer's
    # default_dispatch_unroll (full unroll on CPU — conv-backward-in-
    # loop pathology, _dispatch_probe_model docstring)
    unroll = 1 if model_type == "linear" else None
    opt_conf = {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9,
                "nesterov": True}
    kw = dict(num_classes=10, cutout_length=0, use_policy=False)
    sample = jnp.zeros((2, img, img, 3), jnp.float32)
    rng = np.random.default_rng(0)
    n_examples = max(256, 2 * batch)
    ds = ArrayDataset(
        rng.integers(0, 256, (n_examples, img, img, 3), dtype=np.uint8),
        rng.integers(0, 10, (n_examples,), np.int32), 10)
    rep = replicated(mesh)
    pol = jax.device_put(jnp.zeros((1, 1, 3), jnp.float32), rep)
    key = jax.device_put(jax.random.PRNGKey(0), rep)

    def fresh_state():
        opt = build_optimizer(opt_conf, lambda s: 0.05)
        state = create_train_state(model, opt, jax.random.PRNGKey(0), sample,
                                   use_ema=False)
        # mesh-commit: uncommitted state + committed cache knocks every
        # dispatch off the C++ fast path (make_multistep_train_step)
        return jax.device_put(state, rep)

    out = {"probe": {"model": model_type, "image": img, "batch": batch,
                     "steps": steps, "scan_unroll": unroll or "default"},
           "train_steps_per_sec": {}, "compile_sec": {}}

    def host_epoch(state, step_fn, n_steps):
        acc = Accumulator()
        done = 0
        while done < n_steps:  # cycle fresh epochs until n_steps consumed
            for b in train_batches(ds, None, batch, epoch=done):
                b = shard_batch(mesh, {"x": b[0], "y": b[1]})
                state, metrics = step_fn(state, b["x"], b["y"], pol, key)
                acc.add_dict(metrics)
                done += 1
                if done >= n_steps:
                    break
        return state

    # host-fed N=1: today's loop — gather + device_put + dispatch per step
    opt = build_optimizer(opt_conf, lambda s: 0.05)
    seq_step = make_train_step(model, opt, **kw)
    t_c = time.perf_counter()
    state = host_epoch(fresh_state(), seq_step, 1)  # compile + warm
    jax.block_until_ready(state.params)
    out["compile_sec"]["hostfeed_n1"] = round(time.perf_counter() - t_c, 2)
    rate = 0.0
    for _ in range(repeats):
        state = fresh_state()
        t0 = time.perf_counter()
        state = host_epoch(state, seq_step, steps)
        jax.block_until_ready(state.params)
        rate = max(rate, steps / (time.perf_counter() - t0))
    out["train_steps_per_sec"]["hostfeed_n1"] = round(rate, 2)
    _log(f"step dispatch host-fed N=1: {rate:.1f} steps/s best-of-{repeats}")

    # device cache at each N: one dispatch per N steps, index-fed
    opt = build_optimizer(opt_conf, lambda s: 0.05)
    body = make_train_step_body(model, opt, **kw)
    cache = DeviceCache(ds, mesh)
    for n in ns:
        multi = make_multistep_train_step(body, steps_per_dispatch=n,
                                          unroll=unroll)

        def cache_epoch(state, n_steps, n=n, multi=multi):
            acc = Accumulator()
            done = 0
            while done < n_steps:
                mat = train_index_matrix(np.arange(n_examples), batch,
                                         epoch=done)
                for lo in range(0, len(mat) - len(mat) % n, n):
                    idx = place_index_matrix(mesh, mat[lo:lo + n])
                    state, metrics = multi(state, cache.images, cache.labels,
                                           idx, pol, key)
                    acc.add_dict(metrics)
                    done += n
                    if done >= n_steps:
                        break
            return state

        t_c = time.perf_counter()
        state = cache_epoch(fresh_state(), n)  # compile + warm
        jax.block_until_ready(state.params)
        out["compile_sec"][f"cache_n{n}"] = round(time.perf_counter() - t_c, 2)
        rate = 0.0
        for _ in range(repeats):
            state = fresh_state()
            t0 = time.perf_counter()
            state = cache_epoch(state, steps)
            jax.block_until_ready(state.params)
            rate = max(rate, steps / (time.perf_counter() - t0))
        out["train_steps_per_sec"][f"cache_n{n}"] = round(rate, 2)
        _log(f"step dispatch cache N={n}: {rate:.1f} steps/s "
             f"best-of-{repeats}")

    base = out["train_steps_per_sec"].get("hostfeed_n1")
    top = out["train_steps_per_sec"].get(f"cache_n{max(ns)}")
    if base and top:
        out["speedup_cache_max_n_vs_hostfeed"] = round(top / base, 2)

    # telemetry on-vs-off comparison row (the observability acceptance
    # bound): the SAME cache_nN loop with telemetry fully armed —
    # journal into a scratch dir, one span (registry histogram +
    # rate-bounded JSONL event) per dispatch, exactly the per-dispatch
    # cost the trainer's _monitored_dispatch seam pays with --telemetry
    # on — measured as PAIRED ALTERNATING epochs (off, on, off, on, …)
    # with per-arm medians: this host's run-to-run drift (~±2-3%) would
    # otherwise swamp a microsecond-scale per-dispatch delta.  Overhead
    # must stay <= 1% steps/s (docs/OBSERVABILITY.md "Overhead").
    if telemetry_compare:
        import shutil
        import statistics
        import tempfile

        from fast_autoaugment_tpu.core import telemetry

        was_on = telemetry.journal_active()
        tmp = None
        if not was_on:
            tmp = tempfile.mkdtemp(prefix="faa-bench-telemetry-")
            telemetry.enable_telemetry(tmp)  # full default config
        pairs = max(5, repeats)
        out["telemetry_comparison"] = {"pairs": pairs, "steps": steps}
        try:
            for n in ns:
                multi = make_multistep_train_step(
                    body, steps_per_dispatch=n, unroll=unroll)

                def one_epoch(state, n_steps, with_span, n=n, multi=multi):
                    acc = Accumulator()
                    done = 0
                    while done < n_steps:
                        mat = train_index_matrix(np.arange(n_examples),
                                                 batch, epoch=done)
                        for lo in range(0, len(mat) - len(mat) % n, n):
                            idx = place_index_matrix(mesh, mat[lo:lo + n])
                            if with_span:
                                with telemetry.span("train_dispatch",
                                                    step=done):
                                    state, metrics = multi(
                                        state, cache.images, cache.labels,
                                        idx, pol, key)
                            else:
                                state, metrics = multi(
                                    state, cache.images, cache.labels,
                                    idx, pol, key)
                            acc.add_dict(metrics)
                            done += n
                            if done >= n_steps:
                                break
                    return state

                state = one_epoch(fresh_state(), n, True)  # warm
                jax.block_until_ready(state.params)
                rates = {False: [], True: []}
                for p in range(pairs):
                    # alternate the within-pair order: process state
                    # (allocator, caches) drifts monotonically, so a
                    # fixed off-then-on order reads that drift as
                    # telemetry overhead
                    order = (False, True) if p % 2 == 0 else (True, False)
                    for with_span in order:
                        state = fresh_state()
                        t0 = time.perf_counter()
                        state = one_epoch(state, steps, with_span)
                        jax.block_until_ready(state.params)
                        rates[with_span].append(
                            steps / (time.perf_counter() - t0))
                off = statistics.median(rates[False])
                on = statistics.median(rates[True])
                out["telemetry_comparison"][f"cache_n{n}"] = {
                    "steps_per_sec_off": round(off, 2),
                    "steps_per_sec_on": round(on, 2),
                    "overhead_frac": round(1.0 - on / off, 4),
                }
                _log(f"step dispatch cache N={n} telemetry off/on "
                     f"(median of {pairs} alternating pairs): "
                     f"{off:.1f} / {on:.1f} steps/s "
                     f"({(1.0 - on / off) * 100:+.2f}%)")
        finally:
            if not was_on:
                telemetry._disable_for_tests()  # detach the scratch journal
                if tmp:
                    shutil.rmtree(tmp, ignore_errors=True)
    # per-config shadow-watchdog stamp from the implied per-dispatch
    # wall (a cache_nN dispatch advances N steps)
    out["watchdog"] = {
        cfg: watchdog_stamp([int(cfg.rsplit("n", 1)[1]) / rate], label=cfg)
        for cfg, rate in out["train_steps_per_sec"].items() if rate
    }
    return out


def main():
    # stamp BEFORE any compile ramps our own load into the 1-min average
    contention = refuse_or_flag_contention(host_contention_stamp())
    _ensure_live_backend(
        reexec_argv=[sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
        # plumbing heartbeat only — keep the CPU run small
        fallback_env={
            "FAA_BENCH_BATCH": "32",
            "FAA_BENCH_STEPS": "3",
            "FAA_BENCH_WARMUP": "1",
        },
    )
    arm_compile_cache_from_env()
    if "--dispatch-only" in sys.argv:
        # `make bench-dispatch`: just the step-dispatch/device-cache
        # sweep, one JSON line (same stamp discipline as the headline),
        # plus the telemetry on-vs-off comparison row (the <=1% overhead
        # bound — docs/OBSERVABILITY.md)
        sd = bench_step_dispatch(telemetry_compare=True)
        row = {
            "metric": "train_steps_per_sec",
            "train_steps_per_sec": sd["train_steps_per_sec"],
            "telemetry_comparison": sd.get("telemetry_comparison"),
            "compile_sec": sd["compile_sec"],
            "probe": sd["probe"],
            "speedup_cache_max_n_vs_hostfeed": sd.get(
                "speedup_cache_max_n_vs_hostfeed"),
            "backend": ("cpu-fallback"
                        if os.environ.get("FAA_BENCH_CPU_FALLBACK")
                        else __import__("jax").devices()[0].platform),
        }
        row.update(telemetry_stamp(contention=contention))
        # per-config shadow-watchdog detail (telemetry_stamp carries the
        # single-label stamp; the sweep's per-(N, cache) table rides on)
        row["watchdog"] = sd.get("watchdog")
        print(json.dumps(row))
        return
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.models import get_model
    from fast_autoaugment_tpu.ops.optim import build_optimizer
    from fast_autoaugment_tpu.ops.schedules import build_schedule
    from fast_autoaugment_tpu.parallel.mesh import make_mesh, shard_batch
    from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor
    from fast_autoaugment_tpu.train.steps import create_train_state, make_train_step

    mesh = make_mesh()
    n_dev = mesh.size
    global_batch = BATCH_PER_DEVICE * n_dev

    conf = {
        "lr": 0.1, "epoch": 200,
        "lr_schedule": {"type": "cosine", "warmup": {"multiplier": 2, "epoch": 5}},
    }
    # bf16 activations (f32 params/BN) — the TPU-first precision choice
    model = get_model({"type": "wresnet40_2", "precision": "bf16"}, 10)
    optimizer = build_optimizer(
        {"type": "sgd", "decay": 2e-4, "clip": 5.0, "momentum": 0.9, "nesterov": True},
        build_schedule(conf, steps_per_epoch=50000 // global_batch,
                       world_lr_scale=float(n_dev)),
    )
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, 32, 32, 3), jnp.float32)
    state = create_train_state(model, optimizer, rng, sample, use_ema=False)
    train_step = make_train_step(
        model, optimizer, num_classes=10, cutout_length=16, use_policy=True
    )

    policy = jnp.asarray(policy_to_tensor(load_policy("fa_reduced_cifar10")))
    images = np.random.default_rng(0).integers(
        0, 256, (global_batch, 32, 32, 3), dtype=np.uint8
    )
    labels = np.random.default_rng(1).integers(0, 10, (global_batch,), np.int32)
    batch = shard_batch(mesh, {"x": images, "y": labels})

    _log(f"devices={n_dev} global_batch={global_batch}; compiling train step "
         "(first TPU compile can take minutes)")
    # AOT-compile ONCE: the same executable serves warmup, the timed
    # loop and the FLOPs cost analysis (a second lower().compile() just
    # for cost_analysis would double the multi-minute TPU compile)
    t_compile = time.perf_counter()
    step_exec = train_step.lower(state, batch["x"], batch["y"], policy, rng).compile()
    compile_train_step_sec = time.perf_counter() - t_compile
    _log(f"compile: {compile_train_step_sec:.1f}s")
    for _ in range(WARMUP_STEPS):
        state, metrics = step_exec(state, batch["x"], batch["y"], policy, rng)
    jax.block_until_ready(state.params)
    _log(f"warmup done; measuring {MEASURE_STEPS} steps")

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step_exec(state, batch["x"], batch["y"], policy, rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    images_per_sec_per_chip = MEASURE_STEPS * global_batch / dt / n_dev

    # per-step spread (BENCH_r05 reported a 3-step mean with no
    # sample-size signal): a second pass timing each step individually
    # (block per step — slightly pessimistic vs the pipelined headline,
    # but the variance is the point, not the mean)
    step_times = []
    for _ in range(MEASURE_STEPS):
        t_s = time.perf_counter()
        state, metrics = step_exec(state, batch["x"], batch["y"], policy, rng)
        jax.block_until_ready(state.params)
        step_times.append(time.perf_counter() - t_s)
    step_time_stddev = float(np.std(step_times, ddof=1)) if len(step_times) > 1 else 0.0

    # MFU: per-device FLOPs of the whole fused step (aug+fwd/bwd+opt)
    # x step rate / chip peak (VERDICT round 1, weak 2)
    flops = _step_flops(step_exec)
    peak = _chip_peak_flops(jax.devices()[0])
    mfu = None
    if flops and peak:
        mfu = round(flops * (MEASURE_STEPS / dt) / peak, 4)
        _log(f"per-device step flops={flops:.3e} peak={peak:.0e} mfu={mfu}")

    # end-to-end: fresh host batches through the production pipeline
    # (train_batches + threaded prefetch) — includes the host feed path
    from fast_autoaugment_tpu.data.datasets import ArrayDataset
    from fast_autoaugment_tpu.data.pipeline import prefetch, train_batches

    host_rng = np.random.default_rng(2)
    n_examples = max(global_batch * (MEASURE_STEPS + 2), global_batch)
    ds = ArrayDataset(
        host_rng.integers(0, 256, (n_examples, 32, 32, 3), dtype=np.uint8),
        host_rng.integers(0, 10, (n_examples,), dtype=np.int32), 10,
    )
    from fast_autoaugment_tpu.parallel.mesh import shard_transform

    it = prefetch(
        train_batches(ds, None, global_batch, epoch=1), depth=PREFETCH_DEPTH,
        transform=shard_transform(mesh),
    )
    b = next(it)  # warm the pipeline + any reshape paths
    state, _ = step_exec(state, b["x"], b["y"], policy, rng)
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    hf_steps = 0
    for b in it:
        state, _ = step_exec(state, b["x"], b["y"], policy, rng)
        hf_steps += 1
        if hf_steps >= MEASURE_STEPS:
            break
    jax.block_until_ready(state.params)
    dt_hf = time.perf_counter() - t0
    hostfeed = hf_steps * global_batch / dt_hf / n_dev if hf_steps else None
    # release the worker and its buffered device-resident batches NOW,
    # not when main() returns (the generator holds up to `depth` batches
    # in HBM otherwise)
    it.close()

    out = {
        "metric": "wrn40x2_cifar10_train_images_per_sec_per_chip",
        "value": round(images_per_sec_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": vs_baseline(
            images_per_sec_per_chip,
            bool(os.environ.get("FAA_BENCH_CPU_FALLBACK"))),
        "mfu": mfu,
        "images_per_sec_hostfeed": round(hostfeed, 1) if hostfeed else None,
        # first-class: it was measured and logged ("compile: 55.2s") but
        # dropped from the JSON line — the multi-minute first TPU compile
        # is a real cost the artifact should carry
        "compile_train_step_sec": round(compile_train_step_sec, 1),
        # sample-size + spread provenance (BENCH_r05 carried a 3-step
        # mean with neither): how many steps the mean covers and how
        # noisy the individually-timed steps were
        "steps_measured": MEASURE_STEPS,
        "step_time_stddev_sec": round(step_time_stddev, 6),
        "batch_per_device": BATCH_PER_DEVICE,
        "devices": n_dev,
    }
    # unified provenance block (schema_version + contention + shadow
    # watchdog + compile cache + telemetry counters) — ONE helper across
    # bench.py and every tools/bench_*.py sibling (docs/OBSERVABILITY.md)
    out.update(telemetry_stamp(step_times, label="train_step",
                               contention=contention))

    # search-scheduler throughput: trials/sec at --trial-batch K
    # (FAA_BENCH_TTA=0 skips; see bench_tta_scheduler docstring)
    if os.environ.get("FAA_BENCH_TTA", "1") != "0":
        try:
            tta = bench_tta_scheduler()
            out["tta_trials_per_sec"] = tta["trials_per_sec"]
            out["tta_bench"] = {k: v for k, v in tta.items()
                                if k != "trials_per_sec"}
        except Exception as e:  # noqa: BLE001 — never sink the headline
            _log(f"tta scheduler bench failed: {e}")
            out["tta_trials_per_sec"] = None

    # phase-1 scheduler throughput: fold-train steps/sec at
    # --fold-stack {0, K} (FAA_BENCH_FOLD_STACK=0 skips) — tracks the
    # fold-stacking win the way tta_trials_per_sec tracks trial batching
    if os.environ.get("FAA_BENCH_FOLD_STACK", "1") != "0":
        try:
            fs = bench_fold_stack()
            out["fold_stack_steps_per_sec"] = fs["steps_per_sec"]
            out["fold_stack_bench"] = {k: v for k, v in fs.items()
                                       if k != "steps_per_sec"}
        except Exception as e:  # noqa: BLE001 — never sink the headline
            _log(f"fold-stack bench failed: {e}")
            out["fold_stack_steps_per_sec"] = None

    # step-dispatch throughput: train steps/sec at --steps-per-dispatch
    # N with/without the device cache (FAA_BENCH_STEP_DISPATCH=0 skips)
    # — tracks the host-loop-removal win the way fold_stack_steps_per_
    # sec tracks fold stacking
    if os.environ.get("FAA_BENCH_STEP_DISPATCH", "1") != "0":
        try:
            sd = bench_step_dispatch()
            out["train_steps_per_sec"] = sd["train_steps_per_sec"]
            out["step_dispatch_bench"] = {k: v for k, v in sd.items()
                                          if k != "train_steps_per_sec"}
        except Exception as e:  # noqa: BLE001 — never sink the headline
            _log(f"step dispatch bench failed: {e}")
            out["train_steps_per_sec"] = None
    latest_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "docs", "bench_tpu_latest.json")
    if os.environ.get("FAA_BENCH_CPU_FALLBACK"):
        out["backend"] = "cpu-fallback"
        out["note"] = (
            "TPU tunnel unreachable for the whole bench retry window; this "
            "is a CPU plumbing number. `last_tpu` carries the most recent "
            "successful TPU measurement (docs/bench_tpu_latest.json)."
        )
        # cite the persisted last-good TPU measurement so the official
        # record never regresses to CPU-only evidence (VERDICT round 2)
        try:
            with open(latest_path) as fh:
                out["last_tpu"] = json.load(fh)
        except (OSError, ValueError):  # missing OR truncated/corrupt
            out["last_tpu"] = None
    else:
        platform = getattr(jax.devices()[0], "platform", "unknown")
        out["backend"] = platform
        if platform != "cpu":
            # persist this successful hardware measurement for future
            # fallback runs to cite (checked in alongside the round docs)
            import datetime

            try:
                tmp_path = latest_path + ".tmp"
                with open(tmp_path, "w") as fh:
                    json.dump({
                        "captured_at": datetime.datetime.now(
                            datetime.timezone.utc).isoformat(timespec="seconds"),
                        **out,
                    }, fh, indent=1)
                os.replace(tmp_path, latest_path)  # atomic: no torn reads
            except OSError as e:
                _log(f"could not persist {latest_path}: {e}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
