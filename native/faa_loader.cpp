// Native host-side data loader for fast-autoaugment-tpu.
//
// The reference feeds its GPUs with 8 torch DataLoader worker
// PROCESSES per GPU running PIL (reference data.py:214-224) — the
// native muscle is inside Pillow/libjpeg and the worker pool.  This
// library is the in-tree equivalent for the TPU host: a C++ thread
// pool that reads JPEG files, decodes them with libjpeg, crops to a
// caller-provided box and bilinearly resizes into a caller-owned
// contiguous uint8 batch buffer — one syscall layer, no Python in the
// loop, no per-image numpy allocations.
//
// It is a throughput engine, not the semantic reference: the PIL path
// in data/pipeline.py remains the golden-parity decoder (PIL resizes
// bicubic; this resizes bilinear).  The Python wrapper
// (data/native_loader.py) falls back to PIL transparently.
//
// C API (ctypes-friendly):
//   faa_decode_resize_batch(paths, n, boxes, target, out, threads)
//     paths:  array of n C strings
//     boxes:  n*4 float32 (x0, y0, x1, y1) crop boxes in source pixels,
//             or NULL for full image
//     target: output side length S
//     out:    n * S * S * 3 uint8 buffer (RGB, HWC)
//     return: number of images that FAILED to decode (0 == all good);
//             failed slots are zero-filled.
//   faa_gather_u8(src, index, n, item_bytes, out, threads)
//     parallel batch gather: out[i] = src[index[i]] for item_bytes each.

#include <cstddef>
#include <cstdio>
// jpeglib.h needs size_t/FILE declared before inclusion
#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Decode a JPEG file to RGB8. Returns true on success.
bool decode_jpeg(const char* path, std::vector<uint8_t>* pixels, int* width,
                 int* height) {
  FILE* fh = std::fopen(path, "rb");
  if (!fh) return false;

  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(fh);
    return false;
  }

  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fh);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);

  *width = cinfo.output_width;
  *height = cinfo.output_height;
  pixels->resize(size_t(*width) * *height * 3);
  const size_t stride = size_t(*width) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pixels->data() + size_t(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(fh);
  return true;
}

// PIL-style separable triangle (bilinear-with-antialias) resample of the
// crop box [x0, y0, x1, y1) of src into a target x target RGB output.
// When downscaling, the filter support scales with the decimation ratio
// (Pillow's convolution resampling); at scale <= 1 this degenerates to
// classic 2-tap bilinear.  Horizontal pass into a float32 intermediate,
// then vertical pass.
struct FilterTaps {
  std::vector<int> first;      // first source index per output pixel
  std::vector<int> count;      // tap count per output pixel
  std::vector<float> weights;  // taps, max_count stride
  int max_count = 0;
};

FilterTaps build_triangle_taps(float in0, float in1, int in_size, int out_size) {
  FilterTaps taps;
  const float scale = (in1 - in0) / out_size;
  const float support = std::max(1.0f, scale);  // triangle filter support
  taps.max_count = int(std::ceil(support)) * 2 + 1;
  taps.first.resize(out_size);
  taps.count.resize(out_size);
  taps.weights.assign(size_t(out_size) * taps.max_count, 0.0f);
  for (int i = 0; i < out_size; ++i) {
    const float center = in0 + (i + 0.5f) * scale;
    int lo = std::max(int(std::floor(center - support + 0.5f)), 0);
    int hi = std::min(int(std::floor(center + support + 0.5f)), in_size);
    hi = std::max(hi, lo + 1);
    float total = 0.0f;
    float* w = taps.weights.data() + size_t(i) * taps.max_count;
    for (int j = lo; j < hi; ++j) {
      const float x = std::fabs((j + 0.5f - center) / support);
      const float weight = x < 1.0f ? 1.0f - x : 0.0f;
      w[j - lo] = weight;
      total += weight;
    }
    if (total > 0.0f) {
      for (int j = 0; j < hi - lo; ++j) w[j] /= total;
    }
    taps.first[i] = lo;
    taps.count[i] = hi - lo;
  }
  return taps;
}

void resize_box_bilinear(const uint8_t* src, int src_w, int src_h, float x0,
                         float y0, float x1, float y1, int target,
                         uint8_t* out) {
  const FilterTaps tx = build_triangle_taps(x0, x1, src_w, target);
  const FilterTaps ty = build_triangle_taps(y0, y1, src_h, target);

  // only rows inside the crop's vertical filter support are ever read by
  // the vertical pass — skip the rest (crops can be a small fraction of a
  // large source image)
  int row_lo = src_h, row_hi = 0;
  for (int oy = 0; oy < target; ++oy) {
    row_lo = std::min(row_lo, ty.first[oy]);
    row_hi = std::max(row_hi, ty.first[oy] + ty.count[oy]);
  }

  // horizontal pass: [row_hi - row_lo, target, 3] float
  std::vector<float> tmp(size_t(row_hi - row_lo) * target * 3);
  for (int y = row_lo; y < row_hi; ++y) {
    const uint8_t* row = src + size_t(y) * src_w * 3;
    float* trow = tmp.data() + size_t(y - row_lo) * target * 3;
    for (int ox = 0; ox < target; ++ox) {
      const float* w = tx.weights.data() + size_t(ox) * tx.max_count;
      float acc[3] = {0, 0, 0};
      const int lo = tx.first[ox];
      for (int j = 0; j < tx.count[ox]; ++j) {
        const uint8_t* px = row + size_t(lo + j) * 3;
        acc[0] += w[j] * px[0];
        acc[1] += w[j] * px[1];
        acc[2] += w[j] * px[2];
      }
      trow[ox * 3 + 0] = acc[0];
      trow[ox * 3 + 1] = acc[1];
      trow[ox * 3 + 2] = acc[2];
    }
  }
  // vertical pass
  for (int oy = 0; oy < target; ++oy) {
    const float* w = ty.weights.data() + size_t(oy) * ty.max_count;
    const int lo = ty.first[oy] - row_lo;
    for (int ox = 0; ox < target; ++ox) {
      float acc[3] = {0, 0, 0};
      for (int j = 0; j < ty.count[oy]; ++j) {
        const float* px = tmp.data() + (size_t(lo + j) * target + ox) * 3;
        acc[0] += w[j] * px[0];
        acc[1] += w[j] * px[1];
        acc[2] += w[j] * px[2];
      }
      uint8_t* dst = out + (size_t(oy) * target + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        dst[c] = uint8_t(std::lround(std::clamp(acc[c], 0.0f, 255.0f)));
      }
    }
  }
}

void parallel_for(int n, int threads, const std::function<void(int)>& fn) {
  if (threads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next{0};
  std::vector<std::thread> pool;
  const int workers = std::min(threads, n);
  pool.reserve(workers);
  for (int t = 0; t < workers; ++t) {
    pool.emplace_back([&] {
      for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

int faa_decode_resize_batch(const char** paths, int n, const float* boxes,
                            int target, uint8_t* out, int threads) {
  std::atomic<int> failures{0};
  const size_t item = size_t(target) * target * 3;
  parallel_for(n, threads, [&](int i) {
    std::vector<uint8_t> pixels;
    int w = 0, h = 0;
    uint8_t* dst = out + size_t(i) * item;
    if (!decode_jpeg(paths[i], &pixels, &w, &h)) {
      std::memset(dst, 0, item);
      failures.fetch_add(1);
      return;
    }
    float x0 = 0, y0 = 0, x1 = float(w), y1 = float(h);
    if (boxes) {
      x0 = boxes[i * 4 + 0];
      y0 = boxes[i * 4 + 1];
      x1 = boxes[i * 4 + 2];
      y1 = boxes[i * 4 + 3];
    }
    resize_box_bilinear(pixels.data(), w, h, x0, y0, x1, y1, target, dst);
  });
  return failures.load();
}

void faa_gather_u8(const uint8_t* src, const int64_t* index, int n,
                   int64_t item_bytes, uint8_t* out, int threads) {
  parallel_for(n, threads, [&](int i) {
    std::memcpy(out + size_t(i) * item_bytes,
                src + size_t(index[i]) * item_bytes, size_t(item_bytes));
  });
}

int faa_image_size(const char* path, int* width, int* height) {
  std::vector<uint8_t> pixels;  // header-only would be cheaper; fine for now
  FILE* fh = std::fopen(path, "rb");
  if (!fh) return 1;
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(fh);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fh);
  jpeg_read_header(&cinfo, TRUE);
  *width = cinfo.image_width;
  *height = cinfo.image_height;
  jpeg_destroy_decompress(&cinfo);
  std::fclose(fh);
  return 0;
}

}  // extern "C"
