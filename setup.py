from setuptools import find_packages, setup

setup(
    name="fast-autoaugment-tpu",
    version="0.1.0",
    description="TPU-native Fast AutoAugment: policy search by density matching in JAX/Flax",
    packages=find_packages(include=["fast_autoaugment_tpu*"]),
    package_data={"fast_autoaugment_tpu.policies": ["data/*.json"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy", "pyyaml", "msgpack"],
    entry_points={
        "console_scripts": [
            "faa-train=fast_autoaugment_tpu.launch.train_cli:main",
            "faa-search=fast_autoaugment_tpu.launch.search_cli:main",
            "faa-fleet=fast_autoaugment_tpu.launch.fleet:main",
        ]
    },
)
