"""Persistent-compile-cache wiring + the instrumented compile seam.

Every process in this stack used to re-pay 23-55 s of XLA compile
(BENCH_r02-r05) before its first real step, and the PR 5/6 resilience
machinery multiplies that tax: every exit-77 resume, fleet retry and
reclaimed work unit is a FRESH process that recompiled everything from
scratch.  This module kills the recurrence with two pieces:

1. **Persistent compilation cache** (``--compile-cache {off,DIR}``,
   env ``FAA_COMPILE_CACHE``): JAX's on-disk executable cache
   (``jax_compilation_cache_dir``) is pointed at a shared directory so
   a relaunched process DESERIALIZES the executables its predecessor
   compiled instead of re-lowering them — the pjit compilation-cache
   discipline of the TPUv4 pjit trainers (PAPERS.md: *Scalable Training
   of Language Models using JAX pjit and TPUv4*).  ``off`` (the
   default) is bit-for-bit the historical behavior: nothing is read or
   written, and the cache never changes numerics either way — only
   where executables come from.

2. **The compile seam** (:func:`seam_jit` / :func:`aot_compile`): every
   jit entry point in ``train/``, ``search/`` and ``serve/`` routes
   through one wrapper (the ``compile_step_with_plan`` pattern,
   SNIPPETS [3]) that times each first-call lowering, classifies it
   hit/miss against the persistent cache's monitoring events, and
   aggregates the evidence so ``search_result.json``, the bench JSON
   lines and the resilience resume path can PROVE a warm process
   reached its first step in seconds (``compile_cache{dir, hits,
   misses, first_step_secs}``).  Rule R5 in ``tools/lint_robustness.py``
   keeps future hot paths on the seam.

The hit/miss counters come from JAX's own monitoring events
(``/jax/compilation_cache/cache_{hits,misses}``), so they count every
XLA module the process compiles — including the small auxiliary ones
(``convert_element_type`` etc.) outside any seam label.  Per-label
classification snapshots the counters around the label's first call;
the repo's dispatch discipline is single-threaded per step factory, so
the deltas attribute cleanly in practice (a concurrent compile would
merely make a verdict pessimistic, never silently wrong the other way).

The watchdog coupling (``core/watchdog.py``): once this process has
OBSERVED cache hits and no misses (:func:`process_is_warm`), the
watchdog shrinks its generous first-call compile allowance — a warm
process must not be able to hide a genuine multi-minute hang behind a
compile grace window it no longer needs.
"""

from __future__ import annotations

import functools
import os
import threading
import time
import warnings
from typing import Any, Callable

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = [
    "ENV_VAR",
    "resolve_compile_cache",
    "configure_compile_cache",
    "enable_compile_cache",
    "seam_jit",
    "instrument_jitted",
    "aot_compile",
    "compile_cache_stats",
    "cache_dir",
    "process_is_warm",
]

logger = get_logger("faa_tpu.compilecache")

#: env handoff: the CLIs export the resolved dir here so every child
#: process (fleet-launched hosts, exit-77 relaunches, subprocess e2e
#: reruns) inherits the shared cache without re-plumbing flags
ENV_VAR = "FAA_COMPILE_CACHE"

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_dir: str | None = None
# hit/miss live in the process-wide telemetry registry (one source of
# truth: compile_cache_stats, /metrics and the bench stamps all read
# the same counters; pinned by tests/test_telemetry.py)
_HITS = telemetry.registry().counter(
    "faa_compile_cache_hits_total",
    "persistent-compile-cache modules deserialized instead of compiled")
_MISSES = telemetry.registry().counter(
    "faa_compile_cache_misses_total",
    "persistent-compile-cache modules compiled fresh")
# per-seam-label first-call evidence:
# {label: {"sec": float, "hit": n, "miss": n, "uncached": n, "none": n}}
_labels: dict[str, dict] = {}
_listener_registered = False


def _listener(event: str, **_kwargs: Any) -> None:
    if event == _HIT_EVENT:
        _HITS.inc()
    elif event == _MISS_EVENT:
        _MISSES.inc()


def resolve_compile_cache(spec: str | None = None) -> str | None:
    """``--compile-cache {off,DIR}`` (or None) -> cache dir or None.

    An unset/``off`` spec falls back to the :data:`ENV_VAR` environment
    handoff — that is how fleet-launched hosts and exit-77 relaunches
    inherit the shared dir without carrying the flag.  ``off`` in the
    environment disables too.
    """
    spec = ("" if spec is None else str(spec)).strip()
    if spec.lower() in ("", "off"):
        env = os.environ.get(ENV_VAR, "").strip()
        if env.lower() in ("", "off"):
            return None
        return env
    return spec


def enable_compile_cache(directory: str) -> str:
    """Point JAX's persistent compilation cache at `directory`.

    Creates the dir, drops the min-compile-time/min-entry-size floors
    (JAX's 1 s default would silently skip exactly the small dev/test
    compiles the warm-start tests pin), registers the hit/miss event
    listener, and exports :data:`ENV_VAR` for child processes.
    Idempotent; re-enabling with a different dir re-points the cache
    (logged — the stats keep accumulating process-wide).
    """
    global _dir, _listener_registered
    import jax
    from jax.experimental.compilation_cache import compilation_cache as jax_cc

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    jax_cc.set_cache_dir(directory)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("FAA_COMPILE_CACHE_MIN_COMPILE_SECS", "0")))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if _dir is None or _dir != directory:
        # the cache-used verdict is a one-shot per-process latch inside
        # jax: a process that compiled ANYTHING before the dir was set
        # has latched "disabled" — reset so enabling mid-process works
        # (the trainers/driver configure after import-time jits)
        jax_cc.reset_cache()
    with _lock:
        if not _listener_registered:
            jax.monitoring.register_event_listener(_listener)
            _listener_registered = True
        if _dir is not None and _dir != directory:
            logger.warning("compile cache re-pointed %s -> %s", _dir, directory)
        _dir = directory
    os.environ[ENV_VAR] = directory
    logger.info("persistent compile cache enabled at %s", directory)
    return directory


def configure_compile_cache(spec: str | None = None) -> str | None:
    """Resolve `spec` (flag value, ``None`` = env only) and enable the
    cache when it names a directory.  Returns the active dir or None."""
    directory = resolve_compile_cache(spec)
    if directory:
        return enable_compile_cache(directory)
    return None


def cache_dir() -> str | None:
    """The active persistent-cache directory, or None when disabled."""
    return _dir


def process_is_warm() -> bool:
    """True once this process has PROVEN the cache warm: enabled, at
    least one observed hit, and not a single miss.  The watchdog uses
    this to shrink its first-call compile allowance
    (``core/watchdog.py``) — a miss anywhere means cold compiles may
    still be coming and the generous window stays."""
    return _dir is not None and _HITS.value > 0 and _MISSES.value == 0


def _snapshot() -> tuple[int, int]:
    return int(_HITS.value), int(_MISSES.value)


def _classify(h0: int, m0: int) -> str:
    """Verdict for a compile window bounded by the (h0, m0) snapshot:
    ``uncached`` (cache off), ``miss`` (any module compiled fresh),
    ``hit`` (every module deserialized), ``none`` (no cache event — the
    in-process tracing cache already held the executable)."""
    if _dir is None:
        return "uncached"
    dh, dm = int(_HITS.value) - h0, int(_MISSES.value) - m0
    if dm > 0:
        return "miss"
    if dh > 0:
        return "hit"
    return "none"


def _record(label: str, sec: float, verdict: str) -> None:
    with _lock:
        rec = _labels.setdefault(
            label, {"sec": 0.0, "hit": 0, "miss": 0, "uncached": 0, "none": 0})
        rec["sec"] += float(sec)
        rec[verdict] += 1
    # journal evidence (no-op with telemetry off): when/where this
    # process paid its compile tax, and whether the cache absorbed it
    telemetry.emit("compile", label, sec=round(float(sec), 6),
                   verdict=verdict, cache_dir=_dir)
    if sec >= 1.0:
        logger.info("compile seam %r: first call %.1fs (%s)",
                    label, sec, verdict)


class _SeamWrapped:
    """A jitted callable instrumented at its first invocation.

    Transparent otherwise: ``lower``/``_cache_size``/every other
    attribute delegates to the wrapped jit object (``bench.py`` AOT-
    lowers through ``.lower``; ``search/census.py`` probes
    ``_cache_size``), and post-first-call invocations are a single
    attribute load + call on top of the C++ fast dispatch path.
    """

    def __init__(self, jitted: Callable, label: str):
        self._jitted = jitted
        self._seam_label = label
        self._first_done = False
        functools.update_wrapper(self, jitted, updated=())

    def __call__(self, *args: Any, **kwargs: Any):
        if self._first_done:
            return self._jitted(*args, **kwargs)
        h0, m0 = _snapshot()
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        sec = time.perf_counter() - t0
        self._first_done = True
        _record(self._seam_label, sec, _classify(h0, m0))
        return out

    def __getattr__(self, name: str):
        return getattr(self._jitted, name)


def instrument_jitted(jitted: Callable, *, label: str) -> Callable:
    """Wrap an ALREADY-jitted callable in the compile seam."""
    return _SeamWrapped(jitted, label)


def seam_jit(fn: Callable, *, label: str, **jit_kwargs: Any) -> Callable:
    """``jax.jit`` through the compile seam — THE way train/search/serve
    build jitted entry points (lint rule R5 flags direct ``jax.jit``
    there).  `label` names the entry point in the stats; reuse the
    watchdog's dispatch labels where one exists so the two evidence
    streams line up."""
    import jax

    return _SeamWrapped(jax.jit(fn, **jit_kwargs), label)


def aot_compile(fn: Callable, *, label: str, example_args: tuple,
                jit_kwargs: dict | None = None,
                donate_argnums: tuple | None = None) -> tuple[Any, dict]:
    """``jax.jit(fn).lower(*example_args).compile()`` through the seam.

    The ahead-of-time half of the seam (the serving path's executables,
    the Anakin dispatch-only execution style — PAPERS.md *Podracer
    architectures*): compile cost lands HERE, at load time, and the
    serving loop only ever dispatches.  `example_args` are arrays or
    ``jax.ShapeDtypeStruct`` specs.  Returns ``(compiled_executable,
    {"sec", "verdict"})``; with the persistent cache enabled and warm,
    the verdict is ``hit`` and `sec` is deserialization, not lowering.

    `donate_argnums` compiles a DONATING executable: the named input
    buffers alias the outputs, so the device never holds input and
    output live at once — the zero-allocation serving dispatch
    (docs/BENCHMARKS.md "Serving data plane").  A donated input must
    never be read after dispatch; backends without donation support
    (CPU) ignore the aliasing and stay bitwise-identical, which is
    what lets the donation tests pin donated == undonated output.
    """
    import jax

    kw = dict(jit_kwargs or {})
    if donate_argnums is not None:
        kw["donate_argnums"] = tuple(donate_argnums)
    h0, m0 = _snapshot()
    t0 = time.perf_counter()
    with warnings.catch_warnings():
        # CPU/backends without donation warn-and-ignore per executable;
        # the fallback is part of the contract (bitwise tests), not news
        warnings.filterwarnings(
            "ignore", message=".*[Dd]onation.*not implemented.*")
        compiled = jax.jit(fn, **kw).lower(*example_args).compile()
    sec = time.perf_counter() - t0
    verdict = _classify(h0, m0)
    _record(label, sec, verdict)
    return compiled, {"sec": round(sec, 3), "verdict": verdict}


def compile_cache_stats() -> dict:
    """The artifact stamp: ``compile_cache{dir, enabled, hits, misses,
    first_step_secs, labels}``.

    ``hits``/``misses`` are the process-wide persistent-cache event
    counts; ``first_step_secs`` is the total first-call seconds paid
    through the seam — the compile tax this process actually spent
    before its steps/evals/serves ran.  Stamped into
    ``search_result.json``, every bench JSON line, the trainer result,
    and logged on the resilience resume path.
    """
    with _lock:
        labels = {
            lb: {"sec": round(r["sec"], 3), "hit": r["hit"],
                 "miss": r["miss"], "uncached": r["uncached"],
                 "none": r["none"]}
            for lb, r in sorted(_labels.items())
        }
        first_step = round(sum(r["sec"] for r in _labels.values()), 3)
    return {
        "dir": _dir,
        "enabled": _dir is not None,
        # sourced from the telemetry registry — the same counters a
        # /metrics scrape exports (equality pinned by tests)
        "hits": int(_HITS.value),
        "misses": int(_MISSES.value),
        "first_step_secs": first_step,
        "labels": labels,
    }


def _reset_stats_for_tests() -> None:
    """Zero the counters/labels (NOT the cache config) — test isolation
    only; the listener stays registered."""
    _HITS._reset()
    _MISSES._reset()
    with _lock:
        _labels.clear()


def _disable_for_tests() -> None:
    """Detach the cache dir (config side too) — test isolation only."""
    global _dir
    import jax
    from jax.experimental.compilation_cache import compilation_cache as jax_cc

    enabled = _dir is not None
    with _lock:
        _dir = None
    jax.config.update("jax_compilation_cache_dir", None)
    if enabled:
        jax_cc.reset_cache()  # clear the process latch too
    os.environ.pop(ENV_VAR, None)
