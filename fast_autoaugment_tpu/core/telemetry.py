"""Unified telemetry: metrics registry, span seam, flight-recorder journal.

The paper's headline claim is a COST claim (policy search in device-
hours, not accuracy alone), and every prior PR grew its own private
accounting for one slice of that cost: ``DispatchTrace`` gap histograms
lived only inside the async pipeline, the watchdog kept EMAs, the
compile seam kept hit/miss counters, the policy server kept a dozen
robustness integers, and each bench re-stamped its own provenance
block.  Podracer-style actor/learner systems and MPMD pipeline trainers
(PAPERS.md) treat per-stage occupancy timelines and counters as the
first-class EVIDENCE for their scaling claims — this module is that
substrate, shared by train/search/serve/fleet:

1. **Metrics registry** (:class:`MetricsRegistry`, process-wide
   :func:`registry`): thread-safe counters, gauges and fixed-bucket
   histograms with Prometheus-style names and label sets.  Always on —
   it is in-memory integers, numerics-free, and costs a dict lookup
   plus a lock per update.  ``search_result.json``, serve ``/stats``
   and the bench stamps read the SAME counters the hot paths bump
   (one source of truth; equality pinned by tests).  Export surfaces:
   :meth:`MetricsRegistry.prometheus_text` behind ``GET /metrics``
   (``serve_cli`` and ``--telemetry-port`` on the train/search CLIs).

2. **Span seam** (:func:`span` / :func:`record_dispatch`): ONE way to
   time a device dispatch window.  The trainer's dispatch chunks, eval
   replays, TTA/audit rounds and serve dispatches all route here — the
   registry gets a ``faa_dispatch_seconds`` histogram observation, the
   journal (when armed) gets a typed ``dispatch`` event, and the async
   pipeline's ``DispatchTrace`` keeps receiving the same ``(t0, t1)``
   windows it always did (its gap/busy math is unchanged).

3. **Flight-recorder journal** (:class:`FlightRecorder`): an append-only
   JSONL stream of typed events (:data:`EVENT_TYPES` — ``dispatch``,
   ``compile``, ``checkpoint``, ``lease``, ``trial``, ``shed``,
   ``breaker_fire``, ``watchdog_fire``, ``reload``, ``preempt``,
   ``phase``, ``mark``) with BOTH wall and monotonic timestamps,
   host/attempt identity (``FAA_HOST_ID``/``FAA_ATTEMPT`` — the fleet's
   supervisor exports), pid/tid, and bounded size via segment rotation
   (oldest segments deleted — a flight recorder, not an archive).
   ``tools/trace_export.py`` renders the journal into a Chrome
   trace-event ``trace.json`` (per-thread dispatch lanes, phase-1/2
   overlap lanes, shed/breaker markers); ``tools/faa_status.py``
   aggregates journals + fleet heartbeats into one fleet table.

Defaults are bit-for-bit: the journal and every exporter sit behind
``--telemetry {off,DIR}`` / ``FAA_TELEMETRY`` (off = no file I/O, no
new artifact keys, :func:`emit` is a None check), and the registry
never touches numerics.  Overhead with telemetry fully ON is bounded
and measured (``make bench-dispatch`` comparison row): a fixed
~26-39 µs per DISPATCH on this host — ≤1% steps/s for any dispatch
wall ≥ ~3 ms, i.e. every real model configuration; the conv-free
2 kHz dispatch stress probe pays 7.6% by design
(docs/OBSERVABILITY.md "Overhead" — rate-budgeted journal slices,
interval-buffered flushing, cached metric fast path).

Lint rule R8 (``tools/lint_robustness.py``) keeps raw
``time.time()``/``time.perf_counter()`` out of the train/search/serve
hot paths: timestamps come from :func:`wall`/:func:`mono` and timing
windows from :func:`span`, so every measurement stays recordable here.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time

from fast_autoaugment_tpu.core import fsfault
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = [
    "ENV_VAR",
    "EVENT_TYPES",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "FlightRecorder",
    "registry",
    "wall",
    "mono",
    "span",
    "record_dispatch",
    "emit",
    "resolve_telemetry",
    "configure_telemetry",
    "enable_telemetry",
    "telemetry_dir",
    "journal_active",
    "journal_flush",
    "start_metrics_server",
]

logger = get_logger("faa_tpu.telemetry")

#: env handoff, mirroring FAA_COMPILE_CACHE: the CLIs export the
#: resolved journal dir so fleet-launched hosts, exit-77 relaunches and
#: subprocess drills inherit the shared telemetry dir without flags
ENV_VAR = "FAA_TELEMETRY"

#: the journal's closed event taxonomy (docs/OBSERVABILITY.md) — a typo
#: in an event type must fail loudly, not fork a private schema
EVENT_TYPES = frozenset({
    "dispatch",       # one device dispatch window (the span seam)
    "compile",        # a first-call compile/lowering through the seam
    "checkpoint",     # save/load/corrupt on the checkpoint chain
    "lease",          # workqueue claim/renew-lost/reclaim/release
    "trial",          # one phase-2 trial told to the TPE
    "shed",           # serving admission/deadline/overload shed
    "breaker_fire",   # a circuit breaker transitioned to OPEN
    "watchdog_fire",  # a dispatch watchdog deadline expired
    "reload",         # serving hot policy reload
    "preempt",        # a preemption/hang was honored (exit-77 path)
    "phase",          # a phase window (phase-1 fold train, phase-2 fold)
    "mark",           # free-form marker (tools, tests)
    "round",          # fleet-search round transport: publish/claim/return/apply
    "rotation",       # a router ejected / re-admitted a serving replica
    "tenant",         # multi-policy tenancy admit/evict/warm (serve LRU)
    "scale_up",       # autoscaler grew the replica fleet (evidence inline)
    "scale_down",     # autoscaler shrank the replica fleet
    # closed-loop control plane (control/, docs/CONTROL.md): the four
    # stage transitions of the drift->promote loop, each carrying its
    # metric evidence inline exactly like the autoscaler's decisions
    "drift",          # a seeded statistical test tripped on served traffic
    "research",       # a warm-started top-up search produced a candidate
    "canary",         # canary rollout start/verify on a replica subset
    "promote",        # the delta gate promoted the candidate fleet-wide
    "rollback",       # the delta gate rolled the canary subset back
    "fsfault",        # the FAA_FSFAULT seam injected a shared-FS fault
    # trace-driven game days (gameday/, docs/GAMEDAYS.md): the scenario
    # runner's lifecycle marks and the verdict engine's rows, each
    # carrying its evidence inline like the decision events above
    "scenario",       # game-day lifecycle: start/progress/phase/end
    "verdict",        # one verdict predicate's pass/fail + evidence
})


# --------------------------------------------------------------------------
# clock seam — the one place train/search/serve hot paths read clocks
# (lint R8).  Wall time anchors cross-host comparison; monotonic time
# anchors durations (immune to NTP steps).
# --------------------------------------------------------------------------


def wall() -> float:
    """Wall-clock seconds (``time.time``) through the telemetry seam.

    The ``FAA_FSFAULT skew@host=H,offset=±S`` verb lands HERE: a
    matched host sees (and stamps) wall time offset by S seconds —
    the deterministic stand-in for NTP drift across a fleet.  Unset
    (the default), the consult is one cached None check."""
    t = time.time()
    plan = fsfault.active_plan()
    return t + plan.wall_offset if plan is not None else t


def mono() -> float:
    """Monotonic seconds (``time.perf_counter``) through the seam."""
    return time.perf_counter()


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default latency buckets (seconds) — one fixed schema shared by every
#: dispatch-shaped histogram so cross-run artifacts stay comparable
DEFAULT_BUCKETS_SEC = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       30.0, 120.0)


class Counter:
    """Monotonically non-decreasing counter (thread-safe)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Last-writer-wins instantaneous value (thread-safe)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bucket histogram (cumulative-on-read, Prometheus-style).

    The bucket schema is FIXED at first registration — a second
    registration of the same name with different buckets raises, so one
    metric can never carry two incomparable schemas across the repo.
    """

    __slots__ = ("name", "labels", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, labels: tuple, buckets: tuple):
        self.name = name
        self.labels = labels
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)  # C-speed bucket pick
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum = 0
        buckets = {}
        for edge, c in zip(self.buckets, counts):
            cum += c
            buckets[f"{edge:g}"] = cum
        buckets["+Inf"] = total
        return {"count": total, "sum": round(s, 6), "buckets": buckets}

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


def _label_key(labels: dict) -> tuple:
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


class MetricsRegistry:
    """Process-wide metric store: get-or-create counters/gauges/
    histograms keyed by ``(name, labels)``.

    One name has exactly ONE kind (and, for histograms, one bucket
    schema) — re-registering with a conflicting kind/schema raises.
    ``snapshot()`` is the artifact-stamp view; ``prometheus_text()`` is
    the scrape view (text exposition format 0.0.4).
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> ("counter"|"gauge"|"histogram", help, buckets|None)
        self._meta: dict[str, tuple] = {}
        # (name, label_key) -> metric object
        self._metrics: dict[tuple, object] = {}

    def _get(self, kind: str, name: str, help: str, labels: dict,
             buckets: tuple | None = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        lk = _label_key(labels)
        with self._lock:
            meta = self._meta.get(name)
            if meta is None:
                self._meta[name] = (kind, help, buckets)
            else:
                if meta[0] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {meta[0]}, "
                        f"not {kind}")
                if kind == "histogram" and meta[2] != buckets:
                    raise ValueError(
                        f"histogram {name!r} has a fixed bucket schema "
                        f"{meta[2]}; cannot re-register with {buckets}")
            key = (name, lk)
            m = self._metrics.get(key)
            if m is None:
                if kind == "counter":
                    m = Counter(name, lk)
                elif kind == "gauge":
                    m = Gauge(name, lk)
                else:
                    m = Histogram(name, lk, buckets)
                self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS_SEC,
                  **labels) -> Histogram:
        return self._get("histogram", name, help, labels,
                         buckets=tuple(float(b) for b in buckets))

    # ------------------------------------------------------------ views

    def snapshot(self) -> dict:
        """Artifact-stamp view: plain nested dicts, keys
        ``name{label="v",...}`` (sorted), JSON-ready."""
        with self._lock:
            items = sorted(self._metrics.items())
            meta = dict(self._meta)
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, lk), m in items:
            key = f"{name}{_render_labels(lk)}"
            kind = meta[name][0]
            if kind == "counter":
                out["counters"][key] = m.value
            elif kind == "gauge":
                out["gauges"][key] = round(m.value, 6)
            else:
                out["histograms"][key] = m.snapshot()
        return out

    def counters_snapshot(self) -> dict[str, float]:
        """Flat ``{name{labels}: value}`` of the counters only — the
        compact block the unified bench stamp carries."""
        return dict(self.snapshot()["counters"])

    def prometheus_text(self) -> str:
        """Text exposition (format 0.0.4): ``# HELP``/``# TYPE`` per
        family, one sample line per child, histogram ``_bucket``/
        ``_sum``/``_count`` expansion."""
        with self._lock:
            items = sorted(self._metrics.items())
            meta = dict(self._meta)
        lines: list[str] = []
        seen_head: set[str] = set()
        for (name, lk), m in items:
            kind, help, _buckets = meta[name]
            if name not in seen_head:
                seen_head.add(name)
                if help:
                    lines.append(f"# HELP {name} {help}")
                lines.append(f"# TYPE {name} {kind}")
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_render_labels(lk)} {m.value:g}")
            else:
                snap = m.snapshot()
                for le, cum in snap["buckets"].items():
                    blabels = _render_labels(lk + (("le", le),))
                    lines.append(f"{name}_bucket{blabels} {cum}")
                lbl = _render_labels(lk)
                lines.append(f"{name}_sum{lbl} {snap['sum']:g}")
                lines.append(f"{name}_count{lbl} {snap['count']}")
        return "\n".join(lines) + "\n"

    def _reset_for_tests(self) -> None:
        """Zero every metric (registrations survive) — test isolation."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()


#: THE process-wide registry (tests may build private ones)
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# --------------------------------------------------------------------------
# flight-recorder journal
# --------------------------------------------------------------------------

#: rotation defaults: 4 MiB x 8 segments = ≤32 MiB per process chain —
#: a bounded flight recorder, not an unbounded log
DEFAULT_SEGMENT_BYTES = 4 * 1024 * 1024
DEFAULT_MAX_SEGMENTS = 8
#: flush cadence: events reach disk within this bound (plus the stdio
#: buffer's own overflow flushes).  Flushing per event costs a syscall
#: per dispatch — measured at ~2x the whole emit path — and the
#: flight-recorder contract only needs BOUNDED staleness: a killed
#: process loses at most this window's tail
DEFAULT_FLUSH_INTERVAL_SEC = 0.25
#: per-label journal budget for ``dispatch`` events: above this rate
#: individual slices are suppressed (counted in
#: ``faa_dispatch_events_suppressed_total``) — a serialized JSONL line
#: costs ~10 µs of Python, which a kHz dispatch loop cannot afford, and
#: sub-millisecond slices past ~50/s carry no timeline information a
#: human or Perfetto can use anyway.  The REGISTRY still observes EVERY
#: dispatch (counts and latency percentiles stay exact); only the
#: journal's slice stream is rate-bounded.  <= 0 disables the bound.
DEFAULT_DISPATCH_EVENTS_PER_SEC = 50.0


class FlightRecorder:
    """Append-only JSONL journal with segment rotation.

    One recorder per process writes
    ``journal-<host>-a<attempt>-p<pid>.<seg>.jsonl`` under `directory`;
    when a segment exceeds ``max_segment_bytes`` a new one opens and
    segments beyond ``max_segments`` are deleted oldest-first (the
    flight-recorder bound — recent evidence survives, ancient evidence
    ages out).  Every record carries the event type, label, BOTH clocks
    (``t_wall``/``t_mono`` at emit — their difference aligns monotonic
    spans onto the wall clock per process), host/attempt identity and
    pid/tid/thread name (the Chrome-trace lanes).  Writes are
    lock-serialized and flushed at least every ``flush_interval_s``
    (per-event flushing costs a syscall per dispatch — the measured
    bulk of the emit path), so a killed process loses at most the last
    interval's tail; :meth:`flush` forces the buffer out for readers.
    """

    def __init__(self, directory: str, *,
                 max_segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 flush_interval_s: float = DEFAULT_FLUSH_INTERVAL_SEC,
                 dispatch_events_per_sec: float =
                 DEFAULT_DISPATCH_EVENTS_PER_SEC,
                 host: str | None = None, attempt: int | None = None,
                 tb_bridge: bool = True):
        os.makedirs(directory, exist_ok=True)
        self.directory = os.path.abspath(directory)
        self.host = host or f"host{os.environ.get('FAA_HOST_ID', '0')}"
        self.attempt = int(attempt if attempt is not None
                           else os.environ.get("FAA_ATTEMPT", "1") or 1)
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segments = max(1, int(max_segments))
        self.flush_interval_s = float(flush_interval_s)
        self._last_flush = time.monotonic()
        self.dispatch_events_per_sec = float(dispatch_events_per_sec)
        # per-label 1 s rate window [window_start, count]; racy updates
        # only ever over/under-journal a slice or two — the registry
        # histogram, not the journal, is the exact record
        self._rate: dict[str, list] = {}
        self._prefix = os.path.join(
            self.directory,
            f"journal-{self.host}-a{self.attempt}-p{os.getpid()}")
        self._lock = threading.Lock()
        self._seq = 0
        # serialization fast path: the identity fields are constant per
        # recorder (and per thread), so they are pre-encoded once — the
        # per-event work is two clock reads plus encoding the caller's
        # payload fields (measured: this halves the span-seam cost)
        self._ident_json = (
            f'"host":{json.dumps(self.host)},"attempt":{self.attempt},'
            f'"pid":{os.getpid()}')
        self._thread_local = threading.local()
        self._label_cache: dict[str, str] = {}
        self._seg = 0
        self._segments: list[str] = []
        self._fh = None
        self._bytes = 0
        self._open_segment()
        # TB bridge (utils/tb_events.py): numeric event fields double as
        # TensorBoard scalar curves for free — <dir>/tb/events.out...
        self._tb = None
        if tb_bridge:
            try:
                from fast_autoaugment_tpu.utils.tb_events import TBEventWriter

                self._tb = TBEventWriter(
                    os.path.join(self.directory, "tb"),
                    f"{self.host}.a{self.attempt}")
            except OSError as e:
                logger.warning("telemetry TB bridge disabled: %s", e)

    # ------------------------------------------------------- internals

    def _open_segment(self) -> None:
        path = f"{self._prefix}.{self._seg:03d}.jsonl"
        self._fh = open(path, "a")
        self._segments.append(path)
        self._bytes = 0
        while len(self._segments) > self.max_segments:
            old = self._segments.pop(0)
            try:
                os.remove(old)
            except OSError as e:
                logger.warning("journal rotation: could not drop %s (%s)",
                               old, e)

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._seg += 1
        self._open_segment()

    # ------------------------------------------------------------- API

    @property
    def segments(self) -> list[str]:
        with self._lock:
            return list(self._segments)

    #: record keys callers may not shadow through **fields
    _RESERVED = frozenset({"type", "label", "t_wall", "t_mono", "host",
                           "attempt", "pid", "tid", "thread", "seq"})
    #: one shared encoder: ``json.dumps(..., default=...)`` builds a
    #: fresh JSONEncoder per call — measurable at span-seam frequency
    _ENCODER = json.JSONEncoder(separators=(",", ":"), default=str)

    def _thread_ident(self) -> str:
        ident = getattr(self._thread_local, "ident", None)
        if ident is None:
            th = threading.current_thread()
            ident = (f'"tid":{threading.get_native_id()},'
                     f'"thread":{json.dumps(th.name)}')
            self._thread_local.ident = ident
        return ident

    def _label_json(self, label) -> str:
        s = self._label_cache.get(label)
        if s is None:
            s = json.dumps(label)
            if len(self._label_cache) < 4096:  # labels are low-cardinality
                self._label_cache[label] = s
        return s

    def emit(self, etype: str, label: str | None = None, **fields) -> None:
        """Append one typed event.  Unknown event types raise — the
        taxonomy (:data:`EVENT_TYPES`) is closed by design."""
        if etype not in EVENT_TYPES:
            raise ValueError(
                f"unknown telemetry event type {etype!r} — the taxonomy "
                f"is {sorted(EVENT_TYPES)} (docs/OBSERVABILITY.md)")
        tw = time.time()
        tm = time.perf_counter()
        if fields:
            if not self._RESERVED.isdisjoint(fields):
                raise ValueError(
                    f"event fields may not shadow the record schema: "
                    f"{sorted(self._RESERVED & set(fields))}")
            payload = "," + self._ENCODER.encode(fields)[1:-1]
        else:
            payload = ""
        head = (f'{{"type":"{etype}","label":{self._label_json(label)},'
                f'"t_wall":{tw!r},"t_mono":{tm!r},{self._ident_json},'
                f'{self._thread_ident()}')
        with self._lock:
            seq = self._seq
            self._seq += 1
            line = f'{head},"seq":{seq}{payload}}}\n'
            self._fh.write(line)
            self._bytes += len(line)
            now = time.monotonic()
            if now - self._last_flush >= self.flush_interval_s:
                self._fh.flush()
                self._last_flush = now
            if self._bytes >= self.max_segment_bytes:
                self._rotate_locked()
        if self._tb is not None and etype not in self._TB_SKIP_TYPES \
                and fields:
            self._tb_scalars({"type": etype, "label": label, "seq": seq,
                              **fields})

    def allow_dispatch_event(self, label: str) -> bool:
        """Token check for one ``dispatch`` journal slice: True while
        `label` is under its per-second budget."""
        budget = self.dispatch_events_per_sec
        if budget <= 0:
            return True
        now = time.monotonic()
        st = self._rate.get(label)
        if st is None or now - st[0] >= 1.0:
            self._rate[label] = [now, 1]
            return True
        if st[1] < budget:
            st[1] += 1
            return True
        return False

    def flush(self) -> None:
        """Force buffered events to disk (readers: faa_status and the
        tests call this via :func:`journal_flush`)."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._last_flush = time.monotonic()

    _TB_SKIP = frozenset({"t_wall", "t_mono", "seq", "pid", "tid",
                          "attempt", "t_mono_start", "t_mono_end", "step"})
    #: high-frequency event types the TB bridge skips: dispatch windows
    #: fire per device dispatch (kHz on small programs) and already
    #: live in the faa_dispatch_seconds histogram + the Chrome trace —
    #: a per-dispatch TB scalar write would dominate the span seam cost
    _TB_SKIP_TYPES = frozenset({"dispatch"})

    def _tb_scalars(self, rec: dict) -> None:
        if self._tb is None or rec["type"] in self._TB_SKIP_TYPES:
            return
        step = rec.get("step")
        step = int(step) if isinstance(step, (int, float)) and step >= 0 \
            else rec["seq"]
        tag_base = f"{rec['type']}/{rec.get('label') or 'event'}"
        for k, v in rec.items():
            if k in self._TB_SKIP or k in ("type", "label", "host",
                                           "thread"):
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            try:
                self._tb.add_scalar(f"{tag_base}/{k}", v, step)
            except (OSError, ValueError) as e:
                logger.warning("telemetry TB bridge write failed: %s", e)
                return

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None


# --------------------------------------------------------------------------
# process-wide journal configuration (mirrors core/compilecache.py)
# --------------------------------------------------------------------------

_state_lock = threading.Lock()
_recorder: FlightRecorder | None = None


def resolve_telemetry(spec: str | None = None) -> str | None:
    """``--telemetry {off,DIR}`` (or None) -> journal dir or None.
    Unset/``off`` falls back to the :data:`ENV_VAR` handoff — how fleet
    hosts and exit-77 relaunches inherit the shared dir."""
    spec = ("" if spec is None else str(spec)).strip()
    if spec.lower() in ("", "off"):
        env = os.environ.get(ENV_VAR, "").strip()
        if env.lower() in ("", "off"):
            return None
        return env
    return spec


def enable_telemetry(directory: str, **recorder_kw) -> str:
    """Arm the process journal at `directory` (idempotent; re-enabling
    with a different dir closes the old recorder) and export
    :data:`ENV_VAR` for child processes."""
    global _recorder
    directory = os.path.abspath(directory)
    with _state_lock:
        if _recorder is not None and _recorder.directory == directory:
            return directory
        old, _recorder = _recorder, None
    if old is not None:
        logger.warning("telemetry journal re-pointed %s -> %s",
                       old.directory, directory)
        old.close()
    rec = FlightRecorder(directory, **recorder_kw)
    with _state_lock:
        _recorder = rec
    os.environ[ENV_VAR] = directory
    logger.info("telemetry journal enabled at %s (host=%s attempt=%d)",
                directory, rec.host, rec.attempt)
    return directory


def configure_telemetry(spec: str | None = None, **recorder_kw) -> str | None:
    """Resolve `spec` (flag value; None = env only) and arm the journal
    when it names a directory.  Returns the active dir or None."""
    directory = resolve_telemetry(spec)
    if directory:
        return enable_telemetry(directory, **recorder_kw)
    return None


def telemetry_dir() -> str | None:
    with _state_lock:
        return None if _recorder is None else _recorder.directory


def journal_active() -> bool:
    return _recorder is not None


def emit(etype: str, label: str | None = None, **fields) -> None:
    """Emit one journal event — a cheap no-op while the journal is off
    (the defaults-off hot-path cost is this None check)."""
    rec = _recorder
    if rec is None:
        return
    try:
        rec.emit(etype, label, **fields)
    except ValueError:
        raise  # taxonomy violations are caller bugs — never swallowed
    except OSError as e:
        logger.warning("telemetry emit failed (%s) — event dropped", e)


def journal_flush() -> None:
    """Flush the process journal's buffered events (no-op when off)."""
    rec = _recorder
    if rec is not None:
        rec.flush()


def _disable_for_tests() -> None:
    """Close and detach the journal (env side too) — test isolation."""
    global _recorder
    with _state_lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.close()
    os.environ.pop(ENV_VAR, None)


# --------------------------------------------------------------------------
# the span seam
# --------------------------------------------------------------------------


class _DispatchMeter:
    """One-lock fast path for the span seam's per-label registry
    update.  The seam runs once per device dispatch; the generic
    counter+histogram route costs four function calls and three lock
    acquisitions per window, which measurably taxes sub-millisecond
    dispatches — this object updates the SAME registry-visible metrics
    (``faa_dispatches_total`` / ``faa_dispatch_seconds`` /
    ``faa_dispatch_events_suppressed_total``) behind one lock."""

    __slots__ = ("counter", "hist", "suppressed")

    def __init__(self, label: str):
        self.counter = _REGISTRY.counter(
            "faa_dispatches_total",
            "device dispatches through the span seam", label=label)
        self.hist = _REGISTRY.histogram(
            "faa_dispatch_seconds",
            "per-dispatch wall seconds through the span seam",
            label=label)
        self.suppressed = _REGISTRY.counter(
            "faa_dispatch_events_suppressed_total",
            "journal dispatch slices suppressed by the per-label "
            "rate budget (the registry still observed them)",
            label=label)

    def observe(self, dur: float) -> None:
        h = self.hist
        i = bisect.bisect_left(h.buckets, dur)
        with h._lock:
            h._counts[i] += 1
            h._sum += dur
            h._count += 1
        c = self.counter
        with c._lock:
            c._value += 1.0


_DISPATCH_METRICS: dict[str, _DispatchMeter] = {}


def _dispatch_metrics(label: str) -> _DispatchMeter:
    m = _DISPATCH_METRICS.get(label)
    if m is None:
        m = _DispatchMeter(label)
        _DISPATCH_METRICS[label] = m
    return m


def record_dispatch(label: str, t0_mono: float, t1_mono: float, *,
                    etype: str = "dispatch", **fields) -> None:
    """Record one dispatch window: registry histogram + counter always,
    journal event when armed (rate-bounded per label).  `t0_mono`/
    `t1_mono` are :func:`mono` stamps; the journal record's own
    ``t_wall``/``t_mono`` pair (taken at emit) aligns them onto the
    wall clock for cross-host views."""
    dur = t1_mono - t0_mono
    if dur < 0.0:
        dur = 0.0
    meter = _DISPATCH_METRICS.get(label)
    if meter is None:
        meter = _dispatch_metrics(label)
    meter.observe(dur)
    rec = _recorder
    if rec is not None:
        if rec.allow_dispatch_event(label):
            emit(etype, label, t_mono_start=t0_mono, t_mono_end=t1_mono,
                 dur_sec=round(dur, 9), **fields)
        else:
            meter.suppressed.inc()


class _Span:
    """Class-based context manager (a generator CM costs ~3x more per
    entry, and the span seam runs once per device dispatch)."""

    __slots__ = ("label", "etype", "trace", "fields", "t0")

    def __init__(self, label, etype, trace, fields):
        self.label = label
        self.etype = etype
        self.trace = trace
        self.fields = fields

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if self.trace is not None:
            self.trace(self.t0, t1)
        record_dispatch(self.label, self.t0, t1, etype=self.etype,
                        **self.fields)
        return False


def span(label: str, *, etype: str = "dispatch", trace=None, **fields):
    """Time one dispatch window through the seam (a ``with`` context).

    `trace` (optional ``(t0, t1)`` callable) keeps feeding the async
    pipeline's :class:`~fast_autoaugment_tpu.search.pipeline.
    DispatchTrace` the exact windows it always consumed — the span seam
    GENERALIZES that recorder instead of replacing it."""
    return _Span(label, etype, trace, fields)


def phase_event(label: str, t0_mono: float, t1_mono: float,
                **fields) -> None:
    """One phase window (``phase`` event + ``faa_phase_seconds_total``
    counter) — the overlap-timeline lanes in the trace export."""
    dur = max(0.0, float(t1_mono) - float(t0_mono))
    _REGISTRY.counter("faa_phase_seconds_total",
                      "cumulative wall seconds per phase",
                      label=label).inc(dur)
    if _recorder is not None:
        emit("phase", label, t_mono_start=float(t0_mono),
             t_mono_end=float(t1_mono), dur_sec=round(dur, 9), **fields)


# --------------------------------------------------------------------------
# Prometheus exposition server (train/search CLIs' --telemetry-port;
# serve_cli mounts /metrics on its existing handler instead)
# --------------------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def start_metrics_server(port: int, host: str = "127.0.0.1"):
    """Serve ``GET /metrics`` (read-only registry exposition) on a
    daemon thread.  Returns ``(httpd, bound_port)`` — pass port 0 to
    bind an ephemeral port."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _MetricsHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("metrics http: " + fmt, *args)

        def do_GET(self):
            if self.path in ("/metrics", "/"):
                body = _REGISTRY.prometheus_text().encode()
                ctype = PROMETHEUS_CONTENT_TYPE
                code = 200
            elif self.path == "/healthz":
                body = b'{"ok": true}'
                ctype = "application/json"
                code = 200
            else:
                body = b'{"error": "unknown path"}'
                ctype = "application/json"
                code = 404
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Server(ThreadingHTTPServer):
        daemon_threads = True

    httpd = _Server((host, int(port)), _MetricsHandler)
    th = threading.Thread(target=httpd.serve_forever, daemon=True,
                          name="telemetry-metrics")
    th.start()
    bound = httpd.server_address[1]
    logger.info("telemetry /metrics listening on http://%s:%d", host, bound)
    return httpd, bound
