"""Immutable configuration objects.

The reference uses the ``theconf`` package: a process-global mutable
``Config.get()`` singleton readable from any module (reference
``train.py:20``, ``data.py:53``) merged from a YAML file plus CLI
overrides.  A mutable global is hostile to jit tracing and to running
many differently-configured trials inside one process (the search loop
mutates copies of the config dict per trial, reference
``search.py:62-64``), so here configuration is an explicit, immutable,
hashable object passed to the functions that need it.

- :class:`Config` wraps a nested dict; attribute and item access;
  ``cfg.replace(**dotted)`` returns a new config.
- :func:`load_config` reads a YAML preset (same schema as the reference
  ``confs/*.yaml``) and applies dotted-path CLI overrides.

Hashability means a ``Config`` can be a static argument to
``jax.jit``-compiled functions without further ceremony.
"""

from __future__ import annotations

import json
from typing import Any, Iterator, Mapping

import yaml

__all__ = ["Config", "load_config", "parse_overrides"]


def _freeze(value: Any) -> Any:
    if isinstance(value, Mapping):
        return Config(value)
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


def _thaw(value: Any) -> Any:
    if isinstance(value, Config):
        return {k: _thaw(v) for k, v in value.items()}
    if isinstance(value, tuple):
        return [_thaw(v) for v in value]
    return value


class Config(Mapping):
    """Immutable nested mapping with attribute access.

    >>> c = Config({'model': {'type': 'wresnet40_2'}, 'lr': 0.1})
    >>> c.model.type
    'wresnet40_2'
    >>> c['lr']
    0.1
    >>> c.get('missing', 3)
    3
    >>> c2 = c.replace(**{'model.type': 'resnet50'})
    >>> c2.model.type, c.model.type
    ('resnet50', 'wresnet40_2')
    """

    __slots__ = ("_data", "_hash")

    def __init__(self, data: Mapping | None = None):
        object.__setattr__(self, "_data", {k: _freeze(v) for k, v in (data or {}).items()})
        object.__setattr__(self, "_hash", None)

    # Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: object) -> bool:
        return key in self._data

    # Attribute access -------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self._data[key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key: str, value: Any):
        raise TypeError("Config is immutable; use .replace()")

    # Niceties ---------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        """Dotted-path lookup with default: ``cfg.get('optimizer.clip', 5.0)``."""
        node: Any = self
        for part in key.split("."):
            if isinstance(node, Config) and part in node:
                node = node[part]
            else:
                return default
        return node

    def to_dict(self) -> dict:
        return {k: _thaw(v) for k, v in self._data.items()}

    def replace(self, **dotted: Any) -> "Config":
        """Return a new Config with dotted-path keys replaced.

        Underscores may be used in place of dots only if the key has no
        dots (plain top-level keys).
        """
        data = self.to_dict()
        for path, value in dotted.items():
            node = data
            parts = path.split(".")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = value
        return Config(data)

    def __hash__(self) -> int:
        if self._hash is None:
            object.__setattr__(
                self, "_hash", hash(json.dumps(self.to_dict(), sort_keys=True, default=str))
            )
        return self._hash

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Config) and self.to_dict() == other.to_dict()

    def __repr__(self) -> str:
        return f"Config({self.to_dict()!r})"


def _coerce(text: str) -> Any:
    """Parse a CLI override value with YAML scalar rules ('5' -> 5 etc.)."""
    try:
        return yaml.safe_load(text)
    except yaml.YAMLError:
        return text


def parse_overrides(pairs: list[str]) -> dict:
    """Parse ``["model.type=resnet50", "lr=0.4"]`` into a dotted dict."""
    out: dict[str, Any] = {}
    for pair in pairs:
        if "=" not in pair:
            raise ValueError(f"override must look like key=value, got {pair!r}")
        key, _, value = pair.partition("=")
        out[key.strip()] = _coerce(value.strip())
    return out


def load_config(path: str | None = None, overrides: list[str] | dict | None = None,
                defaults: Mapping | None = None) -> Config:
    """Load a YAML preset and apply dotted CLI overrides.

    Mirrors the reference's ``ConfigArgumentParser`` behavior (YAML via
    ``-c`` + CLI flags override file values) without the global singleton.
    """
    data: dict = dict(defaults or {})
    if path is not None:
        with open(path) as fh:
            data.update(yaml.safe_load(fh) or {})
    cfg = Config(data)
    if overrides:
        if isinstance(overrides, list):
            overrides = parse_overrides(overrides)
        cfg = cfg.replace(**overrides)
    return cfg
