"""Fault-tolerance primitives: typed failures, the preemption contract.

Fast AutoAugment's three-phase pipeline is exactly the long-running
multi-host workload where TPU preemption, torn checkpoints and diverged
trials are routine (PAPERS.md: *Scalable Training of Language Models
using JAX pjit and TPUv4* treats preemption-tolerant checkpoint/restore
as a first-class subsystem; *Podracer architectures* requires workers to
survive individual failures without losing fleet progress).  This
module holds the pieces every layer shares:

- **typed failures** — :class:`CheckpointCorruptError` (digest/size
  mismatch or unreadable payload, raised by ``core/checkpoint.py``) and
  :class:`PreemptedError` (a graceful shutdown request was honored; the
  process should exit :data:`PREEMPTED_EXIT_CODE` so supervisors map it
  to "resume me", not "failed");
- **the preemption flag** — :func:`install_signal_handlers` registers
  SIGTERM/SIGUSR1 handlers that only set a flag; the training loops
  poll :func:`preemption_requested` at dispatch-chunk boundaries (the
  PR-4 boundaries already guarantee resumability there), checkpoint
  with ``preempted: true`` metadata and raise :class:`PreemptedError`;
- **exit-code contract** — exit 77 == preempted-and-checkpointed.  77
  is outside the shell (126+) and signal (128+N) ranges and collides
  with nothing the CLIs emit today; ``launch/fleet.py`` treats it as
  retry-eligible.  The SERVING side of the contract
  (``serve/serve_cli.py``): SIGTERM triggers a graceful drain — stop
  admitting, finish in-flight requests, exit **0** (a drained replica
  is DONE, not failed); a replica that exits because its circuit
  breaker latched open (``--breaker-exit``) uses **77** — "restart me",
  exactly what the fleet supervisor's retry path does;
- **circuit breaking** — :class:`CircuitBreaker` is the generic
  closed/open/half-open state machine the policy server wraps around
  its device dispatches: repeated failures OPEN the circuit (callers
  fail fast with the typed :class:`CircuitOpenError` instead of piling
  onto a wedged backend), a cooldown later ONE probe is admitted
  half-open, and a probe success closes it again.

See docs/RESILIENCE.md for the full failure taxonomy and the
deterministic fault-injection harness (``utils/faultinject.py``) that
drives every recovery path in tests.
"""

from __future__ import annotations

import signal
import threading
import time

from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = [
    "PREEMPTED_EXIT_CODE",
    "CheckpointCorruptError",
    "CircuitBreaker",
    "CircuitOpenError",
    "DispatchHungError",
    "PreemptedError",
    "install_signal_handlers",
    "preemption_requested",
    "request_preemption",
    "clear_preemption",
]

logger = get_logger("faa_tpu.resilience")

#: exit code meaning "preempted: state checkpointed, resume me"
PREEMPTED_EXIT_CODE = 77


class CheckpointCorruptError(RuntimeError):
    """A checkpoint payload failed its integrity check (digest or size
    mismatch against the ``.meta.json`` sidecar, or an unreadable /
    truncated payload).  ``load_checkpoint_chain`` treats this as "walk
    back one link"; bare ``load_checkpoint`` propagates it."""


class PreemptedError(RuntimeError):
    """A SIGTERM/SIGUSR1 shutdown request was honored at a safe
    boundary: state is checkpointed (``preempted: true`` metadata) and
    the process should exit :data:`PREEMPTED_EXIT_CODE`."""

    exit_code = PREEMPTED_EXIT_CODE


class DispatchHungError(RuntimeError):
    """A monitored device dispatch blew past its watchdog deadline
    (``core/watchdog.py``) — the scalar-collective rendezvous deadlock
    class measured in PR 4, or any other wedged XLA dispatch.  The
    in-flight device state is unrecoverable (its buffers are donated to
    the hung computation), so recovery is the PROCESS-restart arm of
    the exit-77 contract: the CLIs map this to
    :data:`PREEMPTED_EXIT_CODE` and the relaunch resumes from the
    newest intact checkpoint-chain link.  A wedged rendezvous costs one
    process restart, not the run."""

    exit_code = PREEMPTED_EXIT_CODE

    def __init__(self, label: str, deadline_sec: float, waited_sec: float):
        super().__init__(
            f"dispatch {label!r} exceeded its watchdog deadline "
            f"({waited_sec:.1f}s waited > {deadline_sec:.1f}s allowed) — "
            "treating the dispatch as hung")
        self.label = label
        self.deadline_sec = deadline_sec
        self.waited_sec = waited_sec


class CircuitOpenError(RuntimeError):
    """The circuit breaker is OPEN: the backend has failed repeatedly
    and callers fail fast instead of queueing onto it.  Carries
    ``retry_after_s`` — the seconds until the breaker next admits a
    half-open probe (the ``Retry-After`` the serving layer returns)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))


class CircuitBreaker:
    """Closed / open / half-open failure containment.

    - **closed**: calls flow; ``threshold`` CONSECUTIVE failures open
      the circuit (one success resets the count);
    - **open**: :meth:`allow` is False for ``cooldown_s`` — callers
      fail fast with :class:`CircuitOpenError` instead of stacking onto
      a backend that is erroring or hanging;
    - **half-open**: after the cooldown exactly ONE probe call is
      admitted; its success closes the circuit, its failure re-opens it
      (a fresh cooldown, :attr:`fires` incremented again).

    ``threshold <= 0`` disables the breaker entirely (:attr:`enabled`
    False, :meth:`allow` always True) — the bit-for-bit default.
    Thread-safe; the serving worker calls :meth:`allow` /
    :meth:`record_success` / :meth:`record_failure` around each
    dispatch while HTTP handler threads read :meth:`is_open` for
    admission fast-fail and ``/readyz``.
    """

    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 name: str = "breaker"):
        self.name = str(name)
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        self.fires = 0  # transitions into OPEN
        self.consecutive_failures = 0
        self._opened_at: float | None = None
        self._probe_inflight = False
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def _cooldown_left(self) -> float:
        if self._opened_at is None:
            return 0.0
        return max(0.0, self.cooldown_s - (time.monotonic() - self._opened_at))

    def is_open(self) -> bool:
        """Non-mutating admission check: True while the circuit is open
        AND still cooling down (half-open probes are admitted by
        :meth:`allow`, not here)."""
        if not self.enabled:
            return False
        with self._lock:
            return self.state == "open" and self._cooldown_left() > 0.0

    def retry_after_s(self) -> float:
        """Seconds until the next half-open probe is admitted."""
        with self._lock:
            return self._cooldown_left()

    def allow(self) -> bool:
        """Whether the caller may dispatch NOW.  Consumes the single
        half-open probe slot when the cooldown has elapsed; the probe's
        record_success/record_failure releases it."""
        if not self.enabled:
            return True
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._cooldown_left() > 0.0:
                    return False
                self.state = "half_open"
                logger.warning(
                    "circuit breaker HALF-OPEN after %.1fs cooldown — "
                    "admitting one probe", self.cooldown_s)
            # half_open: exactly one probe in flight
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            reopened = self.state != "closed"
            if reopened:
                logger.warning("circuit breaker CLOSED (probe succeeded)")
            self.state = "closed"
            self.consecutive_failures = 0
            self._opened_at = None
            self._probe_inflight = False
        if reopened:
            # scrape-visible state for the autoscaler/router consumers
            # (docs/OBSERVABILITY.md): 1 while OPEN, 0 when closed
            from fast_autoaugment_tpu.core import telemetry

            telemetry.registry().gauge(
                "faa_breaker_open",
                "1 while the circuit breaker is OPEN, else 0",
                breaker=self.name).set(0.0)

    def record_failure(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.consecutive_failures += 1
            self._probe_inflight = False
            if self.state == "half_open" \
                    or (self.state == "closed"
                        and self.consecutive_failures >= self.threshold):
                self.state = "open"
                self.fires += 1
                self._opened_at = time.monotonic()
                fires = self.fires
                failures = self.consecutive_failures
                logger.error(
                    "circuit breaker OPEN (fire #%d, %d consecutive "
                    "failures) — failing fast for %.1fs",
                    fires, failures, self.cooldown_s)
            else:
                return
        # outside the lock: telemetry evidence for the OPEN transition
        # (core/telemetry.py — counter always, journal when armed)
        from fast_autoaugment_tpu.core import telemetry

        telemetry.registry().counter(
            "faa_breaker_fires_total",
            "circuit-breaker transitions into OPEN",
            breaker=self.name).inc()
        telemetry.registry().gauge(
            "faa_breaker_open",
            "1 while the circuit breaker is OPEN, else 0",
            breaker=self.name).set(1.0)
        telemetry.emit("breaker_fire", self.name, fires=fires,
                       consecutive_failures=failures,
                       cooldown_s=self.cooldown_s)

    def snapshot(self) -> dict:
        """Artifact-ready accounting (stamped into ``/stats`` and the
        serving bench JSON)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "state": self.state if self.enabled else "disabled",
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "fires": self.fires,
                "consecutive_failures": self.consecutive_failures,
                "retry_after_s": round(self._cooldown_left(), 3),
            }


# -- the preemption flag ----------------------------------------------
# A plain Event, set from the signal handler (handlers must not do I/O
# or grab locks); every reader polls it at safe boundaries.
_preempt_flag = threading.Event()
_handlers_installed = False


def _handler(signum, frame):  # pragma: no cover — exercised via os.kill
    # flag-only: the epoch/dispatch loop does the actual checkpoint +
    # exit at its next safe boundary
    _preempt_flag.set()


def install_signal_handlers(signals=(signal.SIGTERM, signal.SIGUSR1)) -> bool:
    """Install the flag-setting preemption handlers.  Idempotent;
    returns False (and changes nothing) off the main thread, where
    CPython forbids ``signal.signal``."""
    global _handlers_installed
    if _handlers_installed:
        return True
    try:
        for s in signals:
            signal.signal(s, _handler)
    except ValueError:  # not the main thread — caller keeps polling a
        logger.warning(  # flag that only request_preemption() can set
            "preemption handlers not installed (not on the main thread)")
        return False
    _handlers_installed = True
    return True


def preemption_requested() -> bool:
    """True once a shutdown signal arrived (or request_preemption ran)."""
    return _preempt_flag.is_set()


def request_preemption() -> None:
    """Set the preemption flag programmatically (tests, embedders)."""
    _preempt_flag.set()


def clear_preemption() -> None:
    """Reset the flag (a new run in the same process starts clean)."""
    _preempt_flag.clear()
