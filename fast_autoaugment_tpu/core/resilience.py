"""Fault-tolerance primitives: typed failures, the preemption contract.

Fast AutoAugment's three-phase pipeline is exactly the long-running
multi-host workload where TPU preemption, torn checkpoints and diverged
trials are routine (PAPERS.md: *Scalable Training of Language Models
using JAX pjit and TPUv4* treats preemption-tolerant checkpoint/restore
as a first-class subsystem; *Podracer architectures* requires workers to
survive individual failures without losing fleet progress).  This
module holds the pieces every layer shares:

- **typed failures** — :class:`CheckpointCorruptError` (digest/size
  mismatch or unreadable payload, raised by ``core/checkpoint.py``) and
  :class:`PreemptedError` (a graceful shutdown request was honored; the
  process should exit :data:`PREEMPTED_EXIT_CODE` so supervisors map it
  to "resume me", not "failed");
- **the preemption flag** — :func:`install_signal_handlers` registers
  SIGTERM/SIGUSR1 handlers that only set a flag; the training loops
  poll :func:`preemption_requested` at dispatch-chunk boundaries (the
  PR-4 boundaries already guarantee resumability there), checkpoint
  with ``preempted: true`` metadata and raise :class:`PreemptedError`;
- **exit-code contract** — exit 77 == preempted-and-checkpointed.  77
  is outside the shell (126+) and signal (128+N) ranges and collides
  with nothing the CLIs emit today; ``launch/fleet.py`` treats it as
  retry-eligible.

See docs/RESILIENCE.md for the full failure taxonomy and the
deterministic fault-injection harness (``utils/faultinject.py``) that
drives every recovery path in tests.
"""

from __future__ import annotations

import signal
import threading

from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = [
    "PREEMPTED_EXIT_CODE",
    "CheckpointCorruptError",
    "DispatchHungError",
    "PreemptedError",
    "install_signal_handlers",
    "preemption_requested",
    "request_preemption",
    "clear_preemption",
]

logger = get_logger("faa_tpu.resilience")

#: exit code meaning "preempted: state checkpointed, resume me"
PREEMPTED_EXIT_CODE = 77


class CheckpointCorruptError(RuntimeError):
    """A checkpoint payload failed its integrity check (digest or size
    mismatch against the ``.meta.json`` sidecar, or an unreadable /
    truncated payload).  ``load_checkpoint_chain`` treats this as "walk
    back one link"; bare ``load_checkpoint`` propagates it."""


class PreemptedError(RuntimeError):
    """A SIGTERM/SIGUSR1 shutdown request was honored at a safe
    boundary: state is checkpointed (``preempted: true`` metadata) and
    the process should exit :data:`PREEMPTED_EXIT_CODE`."""

    exit_code = PREEMPTED_EXIT_CODE


class DispatchHungError(RuntimeError):
    """A monitored device dispatch blew past its watchdog deadline
    (``core/watchdog.py``) — the scalar-collective rendezvous deadlock
    class measured in PR 4, or any other wedged XLA dispatch.  The
    in-flight device state is unrecoverable (its buffers are donated to
    the hung computation), so recovery is the PROCESS-restart arm of
    the exit-77 contract: the CLIs map this to
    :data:`PREEMPTED_EXIT_CODE` and the relaunch resumes from the
    newest intact checkpoint-chain link.  A wedged rendezvous costs one
    process restart, not the run."""

    exit_code = PREEMPTED_EXIT_CODE

    def __init__(self, label: str, deadline_sec: float, waited_sec: float):
        super().__init__(
            f"dispatch {label!r} exceeded its watchdog deadline "
            f"({waited_sec:.1f}s waited > {deadline_sec:.1f}s allowed) — "
            "treating the dispatch as hung")
        self.label = label
        self.deadline_sec = deadline_sec
        self.waited_sec = waited_sec


# -- the preemption flag ----------------------------------------------
# A plain Event, set from the signal handler (handlers must not do I/O
# or grab locks); every reader polls it at safe boundaries.
_preempt_flag = threading.Event()
_handlers_installed = False


def _handler(signum, frame):  # pragma: no cover — exercised via os.kill
    # flag-only: the epoch/dispatch loop does the actual checkpoint +
    # exit at its next safe boundary
    _preempt_flag.set()


def install_signal_handlers(signals=(signal.SIGTERM, signal.SIGUSR1)) -> bool:
    """Install the flag-setting preemption handlers.  Idempotent;
    returns False (and changes nothing) off the main thread, where
    CPython forbids ``signal.signal``."""
    global _handlers_installed
    if _handlers_installed:
        return True
    try:
        for s in signals:
            signal.signal(s, _handler)
    except ValueError:  # not the main thread — caller keeps polling a
        logger.warning(  # flag that only request_preemption() can set
            "preemption handlers not installed (not on the main thread)")
        return False
    _handlers_installed = True
    return True


def preemption_requested() -> bool:
    """True once a shutdown signal arrived (or request_preemption ran)."""
    return _preempt_flag.is_set()


def request_preemption() -> None:
    """Set the preemption flag programmatically (tests, embedders)."""
    _preempt_flag.set()


def clear_preemption() -> None:
    """Reset the flag (a new run in the same process starts clean)."""
    _preempt_flag.clear()
