"""Checkpointing with cheap, separately-readable step metadata.

The reference stores one pickled dict ``{epoch, log{...}, optimizer,
model, ema}`` via ``torch.save`` (``train.py:305-317``) — and then its
search driver POLLS those checkpoints every 10 s just to read
``ckpt['epoch']``, deserializing full model weights each time
(``search.py:186-190``).  Here the tensor payload is a msgpack of the
state pytree (flax serialization) and the metadata is a tiny JSON
sidecar, so progress polling never touches tensor bytes
(SURVEY.md section 5, checkpoint/resume).

Writes are atomic (tmp + rename) so a concurrently-polling reader never
sees a torn file — the reference guards this with bare ``except``
retries instead (``search.py:191-192``).
"""

from __future__ import annotations

import json
import os
from typing import Any

from flax import serialization

__all__ = ["save_checkpoint", "load_checkpoint", "read_metadata", "checkpoint_exists"]


def _meta_path(path: str) -> str:
    return path + ".meta.json"


def save_checkpoint(path: str, state: Any, metadata: dict | None = None):
    """Serialize `state` (any pytree) to `path` atomically; write the
    JSON `metadata` sidecar after the payload is in place."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    payload = serialization.to_bytes(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(payload)
    os.replace(tmp, path)
    meta = dict(metadata or {})
    tmp_meta = _meta_path(path) + ".tmp"
    with open(tmp_meta, "w") as fh:
        json.dump(meta, fh)
    os.replace(tmp_meta, _meta_path(path))


def load_checkpoint(path: str, target: Any, lenient: bool = False) -> Any:
    """Restore a pytree of the same structure as `target` from `path`.

    `lenient` merges only the fields present in the file onto the
    template (used for checkpoints imported from the reference's torch
    format, which carry params/batch_stats/ema but no optimizer state —
    the analog of the reference's raw-state-dict handling,
    ``train.py:191-204``).
    """
    with open(path, "rb") as fh:
        payload = fh.read()
    if not lenient:
        return serialization.from_bytes(target, payload)

    raw = serialization.msgpack_restore(payload)
    template = serialization.to_state_dict(target)

    def merge(tmpl, new):
        if tmpl is None:
            # template structure governs: a field the live state doesn't
            # carry (e.g. ema when conf ema=0) is dropped, not grafted
            return None
        if not isinstance(tmpl, dict) or not isinstance(new, dict):
            return new if new is not None else tmpl
        out = dict(tmpl)
        for k, v in new.items():
            if k in out:
                out[k] = merge(out[k], v)
        return out

    return serialization.from_state_dict(target, merge(template, raw))


def read_metadata(path: str) -> dict | None:
    """Read the metadata sidecar without touching tensor bytes.

    Returns None if the checkpoint (or sidecar) does not exist yet —
    callers poll this during search phase 1.
    """
    try:
        with open(_meta_path(path)) as fh:
            return json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def checkpoint_exists(path: str) -> bool:
    return os.path.exists(path) and os.path.exists(_meta_path(path))
