"""Checkpointing with cheap, separately-readable step metadata.

The reference stores one pickled dict ``{epoch, log{...}, optimizer,
model, ema}`` via ``torch.save`` (``train.py:305-317``) — and then its
search driver POLLS those checkpoints every 10 s just to read
``ckpt['epoch']``, deserializing full model weights each time
(``search.py:186-190``).  Here the tensor payload is a msgpack of the
state pytree (flax serialization) and the metadata is a tiny JSON
sidecar, so progress polling never touches tensor bytes
(SURVEY.md section 5, checkpoint/resume).

Writes are atomic (tmp + rename) so a concurrently-polling reader never
sees a torn file — the reference guards this with bare ``except``
retries instead (``search.py:191-192``).

Integrity + rollback (docs/RESILIENCE.md): every save stamps a sha256
content digest and the payload size into the sidecar and rotates a
bounded restore chain (``path``, ``path.prev``, ``path.prev2``, …,
depth ``keep``); :func:`load_checkpoint` verifies the digest and raises
:class:`~fast_autoaugment_tpu.core.resilience.CheckpointCorruptError`
on mismatch, and :func:`load_checkpoint_chain` walks back to the newest
intact snapshot — one torn/corrupt file costs an epoch, not the run.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Callable

from flax import serialization

from fast_autoaugment_tpu.core.resilience import CheckpointCorruptError
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "load_checkpoint_chain",
    "read_metadata",
    "checkpoint_exists",
    "chain_paths",
    "CheckpointCorruptError",
]

logger = get_logger("faa_tpu.checkpoint")

#: default rollback-chain depth (the live file plus one predecessor)
DEFAULT_KEEP = 2


def _meta_path(path: str) -> str:
    return path + ".meta.json"


def chain_paths(path: str, keep: int = DEFAULT_KEEP) -> list[str]:
    """The restore-chain filenames, newest first: ``path``,
    ``path.prev``, ``path.prev2``, …  (``keep`` total links)."""
    out = [path]
    for i in range(1, max(1, keep)):
        out.append(path + (".prev" if i == 1 else f".prev{i}"))
    return out


def _digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def _rotate_chain(path: str, keep: int) -> None:
    """Shift ``path`` -> ``path.prev`` -> … before a new save lands.

    Each payload/sidecar move is an atomic ``os.replace``; the pair is
    not atomic, but a crash between the two leaves a digest mismatch
    the chain walk detects and skips (docs/RESILIENCE.md, "torn
    rotation").
    """
    links = chain_paths(path, keep)
    # oldest link falls off the end; move back-to-front
    for newer, older in zip(reversed(links[:-1]), reversed(links[1:])):
        for suffix in ("", ".meta.json"):
            src, dst = newer + suffix, older + suffix
            if os.path.exists(src):
                os.replace(src, dst)
            elif os.path.exists(dst):
                # a fresh pair must never sit next to a stale leftover
                os.remove(dst)


def save_checkpoint(path: str, state: Any, metadata: dict | None = None,
                    keep: int = DEFAULT_KEEP):
    """Serialize `state` (any pytree) to `path` atomically; write the
    JSON `metadata` sidecar (stamped with the payload's sha256 digest
    and byte size) after the payload is in place.  ``keep >= 2`` first
    rotates the existing checkpoint into the rollback chain
    (:func:`chain_paths`); ``keep=1`` overwrites in place (the
    pre-chain behavior)."""
    from fast_autoaugment_tpu.utils import faultinject

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    payload = serialization.to_bytes(state)
    meta = dict(metadata or {})
    meta["digest"] = _digest(payload)
    meta["nbytes"] = len(payload)

    fi = faultinject.active_plan()
    if fi is not None:
        save_n = fi.next_save()
        if fi.torn_at(save_n):
            # simulate a torn non-atomic write: half the payload lands
            # under the FULL payload's digest, then the "process died" —
            # the chain is rotated first, exactly like a real crash
            # mid-save after rotation
            _rotate_chain(path, keep)
            with open(path, "wb") as fh:
                fh.write(payload[: max(1, len(payload) // 2)])
            with open(_meta_path(path), "w") as fh:
                json.dump(meta, fh)
            return
        if fi.corrupt_at(save_n):
            # silent bit-rot: flip bytes AFTER the digest was computed
            corrupted = bytearray(payload)
            corrupted[len(corrupted) // 2] ^= 0xFF
            payload = bytes(corrupted)

    if keep >= 2:
        _rotate_chain(path, keep)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(payload)
    os.replace(tmp, path)
    tmp_meta = _meta_path(path) + ".tmp"
    with open(tmp_meta, "w") as fh:
        json.dump(meta, fh)
    os.replace(tmp_meta, _meta_path(path))
    # journal evidence (no-op with telemetry off): when/where state hit
    # disk — the trace export renders these as checkpoint markers
    from fast_autoaugment_tpu.core import telemetry

    telemetry.registry().counter(
        "faa_checkpoints_saved_total", "checkpoint chain saves").inc()
    telemetry.emit("checkpoint", os.path.basename(path), action="save",
                   nbytes=len(payload), epoch=meta.get("epoch"))


def _read_payload(path: str) -> bytes:
    from fast_autoaugment_tpu.utils import faultinject

    fi = faultinject.active_plan()
    if fi is not None and fi.io_error_now():
        raise OSError(f"injected I/O error reading {path}")
    with open(path, "rb") as fh:
        return fh.read()


def _verify_payload(path: str, payload: bytes) -> None:
    """Check the payload against its sidecar's digest/size stamps.

    Pre-chain checkpoints (no ``digest`` key) pass unverified — their
    sidecars never carried one.  A missing sidecar also passes: callers
    that require it gate on :func:`checkpoint_exists` first.
    """
    meta = read_metadata(path)
    if meta is None:
        return
    nbytes = meta.get("nbytes")
    if nbytes is not None and int(nbytes) != len(payload):
        raise CheckpointCorruptError(
            f"{path}: payload is {len(payload)} bytes, sidecar says "
            f"{nbytes} (torn write?)")
    digest = meta.get("digest")
    if digest is not None and _digest(payload) != digest:
        raise CheckpointCorruptError(
            f"{path}: payload sha256 {_digest(payload)[:12]}… does not "
            f"match sidecar digest {str(digest)[:12]}…")


def load_checkpoint(path: str, target: Any, lenient: bool = False,
                    verify: bool = True) -> Any:
    """Restore a pytree of the same structure as `target` from `path`.

    `lenient` merges only the fields present in the file onto the
    template (used for checkpoints imported from the reference's torch
    format, which carry params/batch_stats/ema but no optimizer state —
    the analog of the reference's raw-state-dict handling,
    ``train.py:191-204``).

    `verify` (default) checks the payload against the sidecar's sha256
    digest and size and raises :class:`CheckpointCorruptError` on
    mismatch; pre-digest checkpoints pass through unchecked.
    """
    payload = _read_payload(path)
    if verify:
        try:
            _verify_payload(path, payload)
        except CheckpointCorruptError:
            from fast_autoaugment_tpu.core import telemetry

            telemetry.registry().counter(
                "faa_checkpoints_corrupt_total",
                "checkpoint loads failing digest/size verification").inc()
            telemetry.emit("checkpoint", os.path.basename(path),
                           action="corrupt")
            raise
    from fast_autoaugment_tpu.core import telemetry

    telemetry.registry().counter(
        "faa_checkpoints_loaded_total", "checkpoint restores").inc()
    telemetry.emit("checkpoint", os.path.basename(path), action="load",
                   nbytes=len(payload))
    if not lenient:
        return serialization.from_bytes(target, payload)

    raw = serialization.msgpack_restore(payload)
    template = serialization.to_state_dict(target)

    def merge(tmpl, new):
        if tmpl is None:
            # template structure governs: a field the live state doesn't
            # carry (e.g. ema when conf ema=0) is dropped, not grafted
            return None
        if not isinstance(tmpl, dict) or not isinstance(new, dict):
            return new if new is not None else tmpl
        out = dict(tmpl)
        for k, v in new.items():
            if k in out:
                out[k] = merge(out[k], v)
        return out

    return serialization.from_state_dict(target, merge(template, raw))


def load_checkpoint_chain(
    path: str,
    target: Any,
    *,
    lenient: bool = False,
    keep: int = DEFAULT_KEEP,
    accept: Callable[[dict], bool] | None = None,
) -> tuple[Any, dict, str] | None:
    """Restore from the NEWEST intact link of `path`'s rollback chain.

    Walks ``path``, ``path.prev``, … skipping links that are missing,
    corrupt (digest/size mismatch), unreadable, or rejected by the
    `accept` predicate on their metadata — each skip is logged loudly
    with the reason, so an operator can see exactly what a recovery
    cost.  Returns ``(state, metadata, used_path)`` or ``None`` when no
    link survives.
    """
    for link in chain_paths(path, keep):
        if not checkpoint_exists(link):
            continue
        meta = read_metadata(link) or {}
        if accept is not None and not accept(meta):
            logger.warning(
                "restore chain: skipping %s (metadata rejected: epoch=%s"
                "%s)", link, meta.get("epoch"),
                ", mid-epoch snapshot" if "in_epoch" in meta else "")
            continue
        try:
            state = load_checkpoint(link, target, lenient=lenient)
        except CheckpointCorruptError as e:
            logger.warning("restore chain: skipping CORRUPT link %s (%s)",
                           link, e)
            continue
        except OSError as e:
            logger.warning("restore chain: skipping unreadable link %s (%s)",
                           link, e)
            continue
        if link != path:
            logger.warning(
                "restore chain: recovered from OLDER link %s (epoch %s) — "
                "newer link(s) were corrupt or rejected",
                link, meta.get("epoch"))
        return state, meta, link
    return None


def read_metadata(path: str) -> dict | None:
    """Read the metadata sidecar without touching tensor bytes.

    Returns None if the checkpoint (or sidecar) does not exist yet, or
    if the sidecar is unreadable/torn — callers poll this during search
    phase 1 and must never crash on a file mid-write by another
    process.
    """
    from fast_autoaugment_tpu.utils import faultinject

    fi = faultinject.active_plan()
    if fi is not None and fi.io_error_now():
        return None
    try:
        with open(_meta_path(path)) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        # OSError covers FileNotFoundError plus the transient read
        # failures (EIO, stale NFS handles) the docstring promises to
        # absorb; a torn sidecar surfaces as JSONDecodeError
        return None


def checkpoint_exists(path: str) -> bool:
    """True when `path` holds a plausibly-restorable checkpoint: a
    NONZERO payload plus a parseable metadata sidecar.  A zero-byte
    payload left by a crashed pre-atomic-write process (or a payload
    whose sidecar never landed) does not count."""
    try:
        if os.path.getsize(path) == 0:
            return False
    except OSError:
        return False
    return read_metadata(path) is not None
