"""Losses and metric accumulation as pure functions / pytrees.

TPU-native re-design of the reference's ``metrics.py`` and
``aug_mixup.py``:

- label-smoothed cross entropy (reference ``metrics.py:26-46``) and its
  mixup variant (reference ``aug_mixup.py:26-32``) as pure jnp functions
  usable inside a jitted train step;
- batch mixup with lam ~ Beta(alpha, alpha), lam <- max(lam, 1-lam)
  (reference ``aug_mixup.py:13-23``) done on-device;
- top-k accuracy (reference ``metrics.py:10-23``);
- :class:`Accumulator` (reference ``metrics.py:49-85``): count-weighted
  sums normalized by total sample count.  Here it is a plain dict pytree
  so a sharded eval loop can ``jax.tree.map``-add jnp scalars without
  host sync, then ``normalize()`` once at the end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "cross_entropy",
    "smooth_cross_entropy",
    "mixup_batch",
    "mixup_cross_entropy",
    "top_k_correct",
    "accuracy",
    "Accumulator",
]


def cross_entropy(logits: jax.Array, labels: jax.Array, reduce_mean: bool = True) -> jax.Array:
    """Plain softmax cross entropy with integer labels."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return nll.mean() if reduce_mean else nll


def smooth_cross_entropy(logits: jax.Array, labels: jax.Array, epsilon: float = 0.0,
                         reduce_mean: bool = True) -> jax.Array:
    """Label-smoothed cross entropy.

    Matches ``CrossEntropyLabelSmooth`` (reference ``metrics.py:26-46``):
    targets = (1 - eps) * onehot + eps / num_classes.
    """
    if not epsilon:
        return cross_entropy(logits, labels, reduce_mean)
    num_classes = logits.shape[-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    targets = (1.0 - epsilon) * onehot + epsilon / num_classes
    nll = -(targets * logp).sum(axis=-1)
    return nll.mean() if reduce_mean else nll


def mixup_batch(key: jax.Array, images: jax.Array, labels: jax.Array, alpha: float):
    """Mix a batch with itself under a random permutation.

    Reference ``aug_mixup.py:13-23``: lam ~ Beta(alpha, alpha) (a single
    scalar per batch), then lam <- max(lam, 1 - lam) so the original
    image always dominates.  Returns (mixed_images, labels_a, labels_b, lam).
    """
    key_lam, key_perm = jax.random.split(key)
    lam = jax.random.beta(key_lam, alpha, alpha) if alpha > 0 else jnp.float32(1.0)
    lam = jnp.maximum(lam, 1.0 - lam)
    perm = jax.random.permutation(key_perm, images.shape[0])
    mixed = lam * images + (1.0 - lam) * images[perm]
    return mixed, labels, labels[perm], lam


def mixup_cross_entropy(logits, labels_a, labels_b, lam, epsilon: float = 0.0):
    """lam * CE(a) + (1 - lam) * CE(b) (reference ``aug_mixup.py:26-32``)."""
    loss_a = smooth_cross_entropy(logits, labels_a, epsilon)
    loss_b = smooth_cross_entropy(logits, labels_b, epsilon)
    return lam * loss_a + (1.0 - lam) * loss_b


def top_k_correct(logits: jax.Array, labels: jax.Array, k: int) -> jax.Array:
    """Number of samples whose true label is in the top-k logits."""
    _, topk = jax.lax.top_k(logits, k)
    return (topk == labels[:, None]).any(axis=-1).sum()


def accuracy(logits: jax.Array, labels: jax.Array, topk=(1,)):
    """Top-k accuracies as fractions (reference ``metrics.py:10-23``)."""
    n = logits.shape[0]
    return tuple(top_k_correct(logits, labels, k) / n for k in topk)


class Accumulator:
    """Count-weighted metric sums (reference ``metrics.py:49-85``).

    ``add_dict`` accumulates raw sums (caller pre-multiplies per-batch
    means by the batch size, as the reference does at ``train.py:73-78``);
    ``normalize()`` divides everything except the counter key by the
    total count.  Values may be python floats or jnp scalars — they are
    only forced to host floats at ``normalize``/``__getitem__`` time so
    the device is never stalled mid-epoch.
    """

    def __init__(self):
        self.metrics: dict = {}

    def add(self, key: str, value):
        self.metrics[key] = self.metrics.get(key, 0.0) + value

    def add_dict(self, d: dict):
        for k, v in d.items():
            self.add(k, v)

    def __getitem__(self, key: str) -> float:
        return float(self.metrics.get(key, 0.0))

    def __contains__(self, key: str) -> bool:
        return key in self.metrics

    def items(self):
        return self.metrics.items()

    def normalize(self, count_key: str = "num") -> dict:
        count = float(self.metrics.get(count_key, 0.0))
        out = {}
        for k, v in self.metrics.items():
            if k == count_key:
                out[k] = count
            else:
                out[k] = float(v) / count if count else 0.0
        return out

    def __repr__(self):
        return f"Accumulator({ {k: float(v) for k, v in self.metrics.items()} })"
