"""Monitored device dispatch: deadline-guarded execution, hang recovery.

The scalar-collective rendezvous deadlock measured in PR 4 (a few
hundred queued unsynced collective programs wedge the virtual-device
CPU backend's rendezvous — ``train/steps.py::make_replay_eval_step``)
is the concrete local instance of a general multi-host hazard: an XLA
dispatch that never completes.  Multi-host pjit deployments treat hang
detection as table stakes (PAPERS.md: *Scalable Training of Language
Models using JAX pjit and TPUv4*), because a wedged rendezvous blocks
EVERY participant forever — there is no exception to catch, the
process just stops making progress.

:class:`DispatchWatchdog` wraps a device dispatch (a jitted call plus
the ``block_until_ready`` on its outputs) in a worker thread and waits
with a deadline:

- the deadline derives from an **EMA of observed per-dispatch wall
  time** (``auto`` mode: ``max(min_deadline, hang_factor x EMA)``) or
  is a fixed operator-supplied number of seconds;
- the **first call per label gets a separate, generous compile
  allowance** — XLA compiles on first dispatch and a 30-55 s compile
  (BENCH_r02-r05) must never read as a hang;
- expiry raises the typed
  :class:`~fast_autoaugment_tpu.core.resilience.DispatchHungError`.
  The hung computation holds the donated state buffers, so there is
  nothing to checkpoint — the CLIs map the error to exit 77 and the
  relaunch resumes from the newest intact chain link (pair with
  ``--ckpt-every-dispatch M`` to bound the replayed work).

Blocking on each monitored dispatch serializes the dispatch pipeline,
which is why the default is **off** (bit-for-bit the historical async
stream — blocking changes wall time, never values).  ``--watchdog
auto`` (or an explicit deadline) buys hang detection for that cost.

Deterministic tests drive this through the ``FAA_FAULT`` verbs
``hang@step=K`` (the dispatch covering step K sleeps forever) and
``slow@step=K,factor=F`` (a straggler: the dispatch takes F x the
current EMA) — ``utils/faultinject.py``.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Any, Callable

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.resilience import DispatchHungError
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["DispatchWatchdog", "resolve_watchdog", "DispatchHungError",
           "arm_dispatch_serializer", "dispatch_enqueue_guard"]

# ---------------------------------------------------------------------
# Process-wide device-dispatch ENQUEUE serializer.
#
# The virtual-multi-device CPU backend deadlocks when two THREADS
# enqueue collective programs concurrently: each thread walks the
# per-device executors in its own interleaving, so device i can see
# program A before B while device j sees B before A — every
# participant then waits at a rendezvous the other program's
# participants never reach (observed live: CollectivePermute
# participants of two run_ids cross-blocked during an overlapped
# phase-1 train + 2-actor TTA run; the cross-thread sibling of the
# PR-4 scalar-collective deadlock, which was single-threaded queue
# depth).  The async search pipeline ARMS this lock so every compiled
# program launch in the process (trainer dispatch chunks, eval
# replays, TTA/audit rounds) enqueues under ONE lock — a consistent
# global program order on every device queue — while completion stays
# async: the lock covers the enqueue, never the wait, so the
# host/device overlap the pipeline exists for is untouched.  Device
# puts/gets are single-participant and stay unguarded.  Disarmed
# (the default, and every serial path) this is a no-op context.

_ENQUEUE_LOCK = threading.RLock()
_ENQUEUE_SERIALIZED = False


def arm_dispatch_serializer(on: bool = True) -> None:
    """Turn cross-thread enqueue serialization on/off (process-wide).
    ``search_policies`` arms it for async-pipeline runs and disarms it
    for serial runs, so one process can do both in sequence."""
    global _ENQUEUE_SERIALIZED
    _ENQUEUE_SERIALIZED = bool(on)


def dispatch_enqueue_guard():
    """Context manager for ONE compiled-program enqueue: the
    serializer lock when armed, a no-op otherwise."""
    if _ENQUEUE_SERIALIZED:
        return _ENQUEUE_LOCK
    return contextlib.nullcontext()

logger = get_logger("faa_tpu.watchdog")

#: first-call-per-label deadline: covers XLA compile (observed 23-55 s
#: per process on this repo's models, BENCH_r02-r05) with slack
DEFAULT_COMPILE_ALLOWANCE_SEC = 600.0
#: first-call deadline once the compile tax is KNOWN paid (persistent
#: compile cache hit / AOT-loaded executable): covers executable
#: deserialization plus a long first dispatch, nothing like a compile —
#: a warm process must not hide a 10-minute hang behind the blind
#: compile window above (core/compilecache.py)
DEFAULT_WARM_ALLOWANCE_SEC = 60.0
#: auto mode: deadline = max(min_deadline, hang_factor * EMA)
DEFAULT_HANG_FACTOR = 20.0
DEFAULT_MIN_DEADLINE_SEC = 10.0
#: EMA smoothing for observed dispatch wall times
DEFAULT_EMA_ALPHA = 0.2


class DispatchWatchdog:
    """Deadline-guarded dispatch execution with per-label EMA timing.

    ``mode`` is ``"off"`` (disabled — :meth:`run` calls through with
    zero overhead), ``"auto"`` (EMA-derived deadlines), or a positive
    float (fixed steady-state deadline in seconds; the first call per
    label still gets ``max(seconds, compile_allowance)``).

    One instance is shared across a whole run (trainer + search) so
    :attr:`fires` aggregates every monitored seam; labels keep their
    own EMA because a train dispatch chunk and a whole-split eval
    replay have very different steady-state walls.

    THREAD-SAFE: the async search pipeline (``search/pipeline.py``)
    runs one monitored dispatch per actor thread concurrently, plus
    the overlapped phase-1 trainer thread — every read/write of the
    shared label state (EMAs, call counts, warm labels, fire count)
    goes through one internal lock.  :meth:`run` itself holds the lock
    only around that bookkeeping, never across the monitored wait, so
    concurrent dispatches still overlap freely.
    """

    def __init__(self, mode: str | float = "off", *,
                 compile_allowance: float = DEFAULT_COMPILE_ALLOWANCE_SEC,
                 warm_allowance: float = DEFAULT_WARM_ALLOWANCE_SEC,
                 hang_factor: float = DEFAULT_HANG_FACTOR,
                 min_deadline: float = DEFAULT_MIN_DEADLINE_SEC,
                 ema_alpha: float = DEFAULT_EMA_ALPHA):
        if isinstance(mode, str):
            mode = mode.strip().lower()
            if mode not in ("off", "auto"):
                mode = float(mode)  # "SECONDS" string from the CLI
        if isinstance(mode, (int, float)):
            if float(mode) <= 0:
                raise ValueError(f"watchdog deadline must be > 0, got {mode}")
            mode = float(mode)
        self.mode = mode
        self.compile_allowance = float(compile_allowance)
        self.warm_allowance = float(warm_allowance)
        self.hang_factor = float(hang_factor)
        self.min_deadline = float(min_deadline)
        self.ema_alpha = float(ema_alpha)
        self.fires = 0
        self._ema: dict[str, float] = {}
        self._calls: dict[str, int] = {}
        # labels whose executable is KNOWN pre-compiled (AOT-loaded) —
        # their first call gets the warm allowance, never the blind
        # compile window
        self._warm_labels: set[str] = set()
        # guards every access to the shared label state above: the
        # async pipeline dispatches from several actor threads at once
        self._lock = threading.RLock()

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def ema(self, label: str) -> float | None:
        """Current EMA of observed wall seconds for `label` (None until
        the first completed call)."""
        with self._lock:
            return self._ema.get(label)

    def mark_compile_warm(self, label: str) -> None:
        """Declare `label`'s executable pre-compiled (AOT-loaded / known
        persistent-cache hit): its first call gets the bounded
        ``warm_allowance`` instead of the blind compile window."""
        with self._lock:
            self._warm_labels.add(label)

    def _first_call_warm(self, label: str) -> bool:
        """Whether `label`'s FIRST call should be treated as compile-free:
        explicitly marked warm, or the process has already proven the
        persistent compile cache warm (hits observed, zero misses —
        ``core/compilecache.py``)."""
        with self._lock:
            if label in self._warm_labels:
                return True
        try:
            from fast_autoaugment_tpu.core import compilecache
        except ImportError:  # pragma: no cover — core package is intact
            return False
        return compilecache.process_is_warm()

    def deadline(self, label: str) -> float:
        """The deadline the NEXT :meth:`run` for `label` will use.

        The first call per label normally gets the generous compile
        allowance (a 23-55 s first compile must never read as a hang);
        when the compile seam has reported cache hits and no misses —
        or the label's executable was AOT-loaded
        (:meth:`mark_compile_warm`) — that allowance shrinks to the
        normal deadline floor (``warm_allowance``), so a warm process
        cannot hide a genuine multi-minute hang behind a compile grace
        window it no longer needs."""
        with self._lock:
            first = self._calls.get(label, 0) == 0
        warm = first and self._first_call_warm(label)
        with self._lock:
            if isinstance(self.mode, float):
                if first and not warm:
                    return max(self.mode, self.compile_allowance)
                return self.mode
            # auto: generous compile allowance first, then EMA-derived
            if first or label not in self._ema:
                if warm:
                    return max(self.min_deadline, self.warm_allowance)
                return self.compile_allowance
            return max(self.min_deadline, self.hang_factor * self._ema[label])

    def observe(self, label: str, wall_sec: float) -> None:
        """Fold one observed dispatch wall time into the label's EMA.

        The first observation seeds the EMA directly — it is the
        compile call, but using it only ever makes deadlines MORE
        generous until steady-state observations pull the EMA down."""
        with self._lock:
            self._calls[label] = self._calls.get(label, 0) + 1
            prev = self._ema.get(label)
            if prev is None:
                self._ema[label] = float(wall_sec)
            else:
                self._ema[label] = (self.ema_alpha * float(wall_sec)
                                    + (1.0 - self.ema_alpha) * prev)
            ema = self._ema[label]
        # registry mirror (telemetry): the EMA any /metrics scrape or
        # bench stamp reads is the one the deadline math uses
        telemetry.registry().gauge(
            "faa_watchdog_ema_seconds",
            "per-label EMA of observed dispatch wall seconds",
            label=label).set(ema)

    def run(self, label: str, fn: Callable, *args: Any,
            inject_delay: float = 0.0) -> Any:
        """Run ``fn(*args)`` (plus ``block_until_ready`` on its result)
        under the label's deadline.

        Disabled mode calls through inline with zero overhead — except
        that an injected delay (the ``hang``/``slow`` fault verbs)
        still sleeps, reproducing the real unwatched wedge.  Raises
        :class:`DispatchHungError` on expiry; the worker thread is a
        daemon, so an actually-wedged dispatch cannot block process
        exit (the recovery IS a process exit)."""
        import jax

        if not self.enabled:
            _sleep(inject_delay)
            with dispatch_enqueue_guard():
                out = fn(*args)
            return jax.block_until_ready(out)

        deadline = self.deadline(label)
        out_q: queue.Queue = queue.Queue(maxsize=1)
        t0 = time.monotonic()

        def _worker():
            try:
                _sleep(inject_delay)
                with dispatch_enqueue_guard():
                    out = fn(*args)
                out = jax.block_until_ready(out)
                # put_nowait: maxsize-1 queue, single producer, one
                # put per worker — can never block (lint R9)
                out_q.put_nowait(("ok", out, time.monotonic() - t0))
            except BaseException as e:  # delivered to the caller below
                out_q.put_nowait(("err", e, time.monotonic() - t0))

        worker = threading.Thread(target=_worker, daemon=True,
                                  name=f"watchdog-{label}")
        worker.start()
        try:
            kind, value, wall = out_q.get(timeout=deadline)
        except queue.Empty:
            with self._lock:
                self.fires += 1
                ema = self._ema.get(label)
            waited = time.monotonic() - t0
            telemetry.registry().counter(
                "faa_watchdog_fires_total",
                "dispatch watchdog deadline expiries", label=label).inc()
            telemetry.emit("watchdog_fire", label,
                           deadline_sec=round(deadline, 3),
                           waited_sec=round(waited, 3),
                           ema_sec=None if ema is None else round(ema, 6))
            logger.error(
                "watchdog FIRED on %r: no completion after %.1fs "
                "(deadline %.1fs, ema %s) — dispatch presumed hung",
                label, waited, deadline,
                f"{ema:.3f}s" if ema is not None else "n/a")
            raise DispatchHungError(label, deadline, waited)
        if kind == "err":
            raise value
        self.observe(label, wall)
        return value

    def stats(self) -> dict:
        """Artifact-ready accounting: mode, fire count, per-label
        deadlines + EMAs (stamped into bench JSON and
        ``search_result.json`` so hangs and stragglers are
        distinguishable after the fact)."""
        with self._lock:
            labels = list(self._calls)
            ema = dict(self._ema)
            fires = self.fires
            warm = sorted(self._warm_labels)
        return {
            "mode": self.mode if isinstance(self.mode, str) else float(self.mode),
            "fires": fires,
            # deadline() re-locks per label: a concurrent observe
            # between snapshots only ever yields a FRESHER deadline
            "deadline_sec": {lb: self.deadline(lb) for lb in labels},
            "ema_sec": {lb: round(v, 6) for lb, v in ema.items()},
            "warm_labels": warm,
        }


def _sleep(delay: float) -> None:
    """Sleep `delay` seconds in bounded chunks (`inf` = sleep forever —
    the injected-hang case; chunking sidesteps time.sleep's OverflowError
    on infinite values)."""
    if not delay or delay <= 0:
        return
    remaining = float(delay)
    while remaining > 0:
        time.sleep(min(remaining, 60.0))  # robust: allow — deadline-bounded chunked sleep; inf = the deliberate injected wedge
        remaining -= 60.0


def resolve_watchdog(spec, **kwargs) -> DispatchWatchdog:
    """``--watchdog {off,auto,SECONDS}`` (or an existing instance) to a
    :class:`DispatchWatchdog`.  Passing an instance through unchanged
    lets one watchdog aggregate fire counts across the whole search."""
    if isinstance(spec, DispatchWatchdog):
        return spec
    if spec is None:
        spec = "off"
    return DispatchWatchdog(spec, **kwargs)
