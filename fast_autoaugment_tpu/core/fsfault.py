"""Deterministic shared-filesystem fault seam: ``FAA_FSFAULT``.

Every cross-host contract in this repo — the PR-6 lease queue, the
PR-13 fleet-search round transport and checkpoint publication, the
control plane's journal tailing — runs over a directory every host
mounts, and silently assumes that directory is POSIX-honest: writes
become visible everywhere immediately, re-reads never go backwards,
reads never fail transiently, and every host's wall clock agrees.
Real shared substrates (NFS attribute caches, object-store gateways,
preempted VMs with drifting clocks) break every one of those
assumptions routinely (PAPERS.md: the MPMD-pipeline and Podracer
papers both treat worker loss and substrate weirdness as the NORM).

This module is the single seam through which the shared-dir layers
(``launch/workqueue.py``, ``search/pipeline.py::FleetTransport``,
``control/drift.py::TrafficSampleReader``) read, list and write shared
files — and the place those assumptions are deliberately broken, under
a seeded, deterministic plan, so the hardening in those layers is
driven by tests instead of trusted on faith (the ``FAA_FAULT``
discipline of ``utils/faultinject.py``, extended to the filesystem).
faalint rule F1 keeps direct ``open``/``os.listdir``/``os.stat``/
``json.load`` out of those layers so the seam cannot rot.

Grammar — semicolon-separated specs, ``kind@key=value[,key=value]``::

    FAA_FSFAULT="lag@dir=work,secs=2;skew@host=1,offset=45;eio@p=0.05,seed=7"

``lag@dir=GLOB,secs=S``
    Delayed cross-host visibility: files under a directory whose NAME
    matches GLOB (component-wise fnmatch) are INVISIBLE to reads,
    listings and stats until S seconds after their mtime — except to
    the process that wrote them through this seam (close-to-open
    consistency: the writer always sees its own writes, remote hosts
    lag).  Models an NFS attribute/lookup cache or an async-replicated
    share.
``stale@dir=GLOB,window=S``
    Stale re-reads: a re-read of a matched file within S seconds of
    its last modification returns the PREVIOUS version this process
    observed (per-process content cache) instead of the fresh bytes —
    the classic stale-attribute-cache read.  After the window, reads
    see the new version.
``eio@p=P,seed=N``
    Transient read/list errors: every seam read/list consult draws
    from the seeded Bernoulli stream and raises ``OSError(EIO)`` with
    probability P.  The seam itself retries transient EIO/ESTALE a
    bounded number of times (that retry IS the hardening — remote
    filesystems return these for real), so callers see a failure only
    on an unlucky streak.
``skew@host=H,offset=±S``
    Per-host wall-clock offset, applied at the telemetry ``wall()``
    seam (``core/telemetry.py``) when ``FAA_HOST_ID`` matches H: every
    wall stamp this host writes (lease heartbeats, journal events,
    completion markers) is S seconds off.  Monotonic clocks are
    untouched — which is exactly why observer-local lease staleness
    (``launch/workqueue.py``) survives it.
``torn@path=GLOB``
    Truncated tails: the FIRST seam read of each file whose basename
    (or full path) matches GLOB returns the content with its tail cut
    off — the half-flushed file a reader can catch on a live share.
    Later reads see the full content (the write completed).

With ``FAA_FSFAULT`` unset every primitive is a thin passthrough
behind one cached ``None`` check — no new artifact keys, no behavior
change, and the ``wall()`` consult is a dict lookup.  Tests call
:func:`reset` after mutating the env var, exactly like
``faultinject.reset``.

Injections are counted per kind (``faa_fsfault_injections_total``
registry counter + a typed ``fsfault`` journal event per injection) so
``make status`` can show what the substrate did to a drill.
"""

from __future__ import annotations

import errno
import fnmatch
import json
import os
import random
import time

from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["FsFaultPlan", "active_plan", "reset", "parse_fsfault_spec",
           "wall_offset", "read_bytes", "read_json", "load_json",
           "read_from", "listdir", "glob_files", "getsize", "exists",
           "write_json_atomic", "ENV_VAR"]

logger = get_logger("faa_tpu.fsfault")

ENV_VAR = "FAA_FSFAULT"

_KINDS = {
    "lag": ("dir", "secs"),
    "stale": ("dir", "window"),
    "eio": ("p", "seed"),
    "skew": ("host", "offset"),
    "torn": ("path",),
}
_FLOAT_KEYS = {"secs", "window", "p", "offset"}
_STR_KEYS = {"dir", "path", "host"}
_OPTIONAL = {"seed"}

#: bounded in-seam retries for transient EIO/ESTALE (real remote
#: filesystems surface these; the retry is the hardening under test)
_TRANSIENT_ERRNOS = (errno.EIO, getattr(errno, "ESTALE", errno.EIO))
_READ_RETRIES = 3
_RETRY_SLEEP_S = 0.02


def parse_fsfault_spec(spec: str) -> list[dict]:
    """Parse the ``FAA_FSFAULT`` grammar.  Raises ValueError on unknown
    kinds/keys or malformed values — a typo must fail loudly, never
    silently inject nothing (the ``FAA_FAULT`` contract)."""
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise ValueError(
                f"bad fsfault spec {part!r}: expected "
                "kind@key=value[,key=value]")
        kind, _, argstr = part.partition("@")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fsfault kind {kind!r}: known {sorted(_KINDS)}")
        args: dict = {}
        for kv in argstr.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise ValueError(f"bad fsfault arg {kv!r} in {part!r}")
            key, _, val = kv.partition("=")
            key = key.strip()
            if key not in _KINDS[kind]:
                raise ValueError(
                    f"fsfault {kind!r} takes keys {_KINDS[kind]}, "
                    f"got {key!r}")
            if key in _FLOAT_KEYS:
                args[key] = float(val)
            elif key in _STR_KEYS:
                val = val.strip()
                if not val:
                    raise ValueError(
                        f"fsfault {kind!r} key {key!r} is empty")
                args[key] = val
            else:
                args[key] = int(val)
        missing = [k for k in _KINDS[kind]
                   if k not in args and k not in _OPTIONAL]
        if missing:
            raise ValueError(f"fsfault {kind!r} missing keys {missing}")
        if kind == "eio":
            args.setdefault("seed", 0)
            if not 0.0 <= args["p"] <= 1.0:
                raise ValueError(f"eio p={args['p']} outside [0, 1]")
        faults.append({"kind": kind, **args})
    return faults


def _dir_matches(path: str, pattern: str) -> bool:
    """True when any DIRECTORY component of `path` fnmatches `pattern`
    (``lag@dir=work`` hits every file under any ``work/``)."""
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    return any(fnmatch.fnmatch(p, pattern) for p in parts[:-1] if p)


class FsFaultPlan:
    """The parsed plan plus per-kind trigger state (one per process,
    cached by env value like ``faultinject.FaultPlan``)."""

    def __init__(self, faults: list[dict]):
        self.faults = faults
        self._lag = [f for f in faults if f["kind"] == "lag"]
        self._stale = [f for f in faults if f["kind"] == "stale"]
        self._torn = [f for f in faults if f["kind"] == "torn"]
        self._eio_rng = None
        self._eio_p = 0.0
        for f in faults:
            if f["kind"] == "eio":
                self._eio_rng = random.Random(int(f["seed"]))
                self._eio_p = float(f["p"])
        #: the wall offset for THIS host (resolved once per plan —
        #: tests that flip FAA_HOST_ID call reset())
        self.wall_offset = 0.0
        hid = str(os.environ.get("FAA_HOST_ID", "0"))
        for f in faults:
            if f["kind"] == "skew" and str(f["host"]) in (hid, f"host{hid}"):
                self.wall_offset += float(f["offset"])
        #: paths THIS process wrote through the seam (the writer always
        #: sees its own writes; only cross-host visibility lags)
        self.own_writes: set[str] = set()
        self._stale_cache: dict[str, bytes] = {}
        self._torn_fired: set[str] = set()
        #: injection counts per kind (mirrored to the metrics registry)
        self.injected: dict[str, int] = {}

    # ----------------------------------------------------------- record
    def _record(self, kind: str, path: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1
        try:  # lazy: telemetry imports this module for the wall() seam
            from fast_autoaugment_tpu.core import telemetry

            telemetry.registry().counter(
                "faa_fsfault_injections_total",
                "shared-filesystem faults injected by the FAA_FSFAULT "
                "seam", kind=kind).inc()
            telemetry.emit("fsfault", kind, path=path)
        except Exception as e:  # noqa: BLE001 — accounting never breaks a read
            logger.debug("fsfault: injection accounting failed (%s)", e)

    # ---------------------------------------------------------- verdicts
    def eio_now(self) -> bool:
        if self._eio_rng is None:
            return False
        return self._eio_rng.random() < self._eio_p

    def lag_hidden(self, path: str, mtime: float) -> bool:
        """True when the file is not yet visible to THIS observer."""
        if not self._lag or os.path.abspath(path) in self.own_writes:
            return False
        now = time.time()
        for f in self._lag:
            if _dir_matches(path, f["dir"]) and mtime > now - f["secs"]:
                return True
        return False

    def stale_view(self, path: str, data: bytes, mtime: float) -> bytes:
        """The bytes this observer sees: the PREVIOUS version while a
        matched file's change is inside the stale window."""
        apath = os.path.abspath(path)
        matched = [f for f in self._stale if _dir_matches(path, f["dir"])
                   and apath not in self.own_writes]
        if matched:
            cached = self._stale_cache.get(apath)
            now = time.time()
            if cached is not None and cached != data and any(
                    mtime > now - f["window"] for f in matched):
                self._record("stale", path)
                return cached
            self._stale_cache[apath] = data
        return data

    def torn_view(self, path: str, data: bytes) -> bytes:
        """First read of a matched path loses its tail (latched per
        path: the torn state is transient, later reads see it whole)."""
        if not self._torn or not data:
            return data
        apath = os.path.abspath(path)
        if apath in self._torn_fired:
            return data
        base = os.path.basename(path)
        for f in self._torn:
            if fnmatch.fnmatch(base, f["path"]) \
                    or fnmatch.fnmatch(apath, f["path"]):
                self._torn_fired.add(apath)
                self._record("torn", path)
                cut = max(1, min(64, len(data) // 2))
                return data[:-cut]
        return data


_plan: FsFaultPlan | None = None
_plan_env: str | None = None


def active_plan() -> FsFaultPlan | None:
    """The process-wide plan, or None when ``FAA_FSFAULT`` is unset —
    parsed once per env VALUE (tests flip it between cases)."""
    global _plan, _plan_env
    env = os.environ.get(ENV_VAR, "")
    if env != _plan_env:
        _plan_env = env
        _plan = FsFaultPlan(parse_fsfault_spec(env)) if env.strip() else None
        if _plan is not None:
            logger.warning("fsfault: ACTIVE with %d fault(s): %s "
                           "(wall offset %+gs on this host)",
                           len(_plan.faults), env, _plan.wall_offset)
    return _plan


def reset() -> None:
    """Forget the cached plan and all trigger state (test isolation)."""
    global _plan, _plan_env
    _plan = None
    _plan_env = None


def wall_offset() -> float:
    """This host's injected wall-clock offset (the ``skew`` verb),
    consulted by ``telemetry.wall()``.  0.0 when no plan is active."""
    plan = active_plan()
    return plan.wall_offset if plan is not None else 0.0


# --------------------------------------------------------------------------
# shared-dir primitives — the ONLY file operations the shared-dir
# layers (launch/, search/ transport, control/ tailing; faalint F1) use
# --------------------------------------------------------------------------


def _consult_eio(plan: FsFaultPlan | None, path: str) -> None:
    if plan is not None and plan.eio_now():
        plan._record("eio", path)
        raise OSError(errno.EIO,
                      "injected transient I/O error (FAA_FSFAULT eio)")


def _with_retries(fn, path: str):
    """Run one read primitive with bounded retries on transient
    EIO/ESTALE — the seam-side hardening every remote filesystem
    needs.  Non-transient OSErrors (ENOENT, ...) propagate at once."""
    for attempt in range(_READ_RETRIES):
        try:
            return fn()
        except OSError as e:
            if e.errno in _TRANSIENT_ERRNOS and attempt < _READ_RETRIES - 1:
                time.sleep(_RETRY_SLEEP_S * (attempt + 1))
                continue
            raise


def read_bytes(path: str) -> bytes:
    """Read a shared file's bytes through the fault seam.  Raises
    OSError exactly like ``open`` would (a lag-hidden file raises
    ENOENT — it does not exist yet for this observer)."""
    plan = active_plan()
    if plan is None:
        with open(path, "rb") as fh:
            return fh.read()

    def _read():
        _consult_eio(plan, path)
        st = os.stat(path)
        if plan.lag_hidden(path, st.st_mtime):
            plan._record("lag", path)
            raise OSError(errno.ENOENT,
                          "not yet visible to this host "
                          "(FAA_FSFAULT lag)", path)
        with open(path, "rb") as fh:
            data = fh.read()
        data = plan.stale_view(path, data, st.st_mtime)
        return plan.torn_view(path, data)

    return _with_retries(_read, path)


def read_json(path: str) -> dict | None:
    """Absorbing JSON read: missing, mid-replace, torn or unparseable
    -> None (every shared-dir writer is atomic, so this is transient —
    the historical ``workqueue._read_json`` contract)."""
    try:
        data = read_bytes(path)
        return json.loads(data.decode())
    except (OSError, ValueError):
        return None


def load_json(path: str):
    """Strict JSON read: OSError/ValueError propagate (resume paths
    that must fail loudly on a missing or corrupt artifact)."""
    return json.loads(read_bytes(path).decode())


def read_from(path: str, offset: int) -> str:
    """Incremental tail read from `offset` (journal tailing).  Applies
    eio + torn (a torn tail is re-served whole on the next poll);
    raises OSError like ``open``/``seek`` would."""
    plan = active_plan()
    if plan is None:
        with open(path) as fh:
            fh.seek(offset)
            return fh.read()
    _consult_eio(plan, path)
    with open(path, "rb") as fh:
        fh.seek(offset)
        data = fh.read()
    return plan.torn_view(path, data).decode(errors="replace")


def listdir(d: str) -> list[str]:
    """Sorted directory listing through the seam: lag-hidden entries
    are omitted (they do not exist yet for this observer).  Raises
    OSError like ``os.listdir``."""
    plan = active_plan()
    if plan is None:
        return sorted(os.listdir(d))

    def _list():
        _consult_eio(plan, d)
        names = sorted(os.listdir(d))
        if not plan._lag:
            return names
        out = []
        for name in names:
            path = os.path.join(d, name)
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                continue  # vanished mid-listing: not visible
            if plan.lag_hidden(path, mtime):
                plan._record("lag", path)
                continue
            out.append(name)
        return out

    return _with_retries(_list, d)


def glob_files(pattern: str, recursive: bool = True) -> list[str]:
    """Sorted glob through the seam (journal-segment discovery):
    lag-hidden files are omitted; transient errors absorb to the
    already-visible set (the next poll retries)."""
    import glob as _glob

    plan = active_plan()
    paths = sorted(_glob.glob(pattern, recursive=recursive))
    if plan is None:
        return paths
    out = []
    for path in paths:
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            continue
        if plan.lag_hidden(path, mtime):
            plan._record("lag", path)
            continue
        out.append(path)
    return out


def getsize(path: str) -> int:
    """File size through the seam (lag-hidden -> ENOENT)."""
    plan = active_plan()
    if plan is None:
        return os.path.getsize(path)

    def _size():
        _consult_eio(plan, path)
        st = os.stat(path)
        if plan.lag_hidden(path, st.st_mtime):
            plan._record("lag", path)
            raise OSError(errno.ENOENT,
                          "not yet visible to this host "
                          "(FAA_FSFAULT lag)", path)
        return st.st_size

    return _with_retries(_size, path)


def exists(path: str) -> bool:
    """Existence through the seam (lag-hidden -> False)."""
    plan = active_plan()
    if plan is None:
        return os.path.exists(path)
    try:
        return getsize(path) >= 0
    except OSError:
        return False


def write_json_atomic(path: str, obj) -> None:
    """The canonical fsync-then-rename atomic write (the
    ``search/driver.py`` idiom, host-only so control/ and launch/ can
    use it without importing jax), recording the path as an own-write
    so the ``lag`` verb never hides a host's writes from itself."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    plan = active_plan()
    if plan is not None:
        plan.own_writes.add(os.path.abspath(path))
