"""Epoch driver: the ``train_and_eval`` equivalent.

Mirrors the reference driver's contract (``train.py:110-322``): builds
data/model/optimizer/schedule, restores checkpoints, runs the epoch
loop with periodic evaluation (master-only), tracks the best metric,
reports progress to a callback (the search engine's hook,
``train.py:289-303``) and saves checkpoints with cheap metadata.

Differences by design, not omission:
- the per-batch work is ONE jitted step on the global mesh batch (no
  DDP wrapper, no host-side EMA loop, no H2D copy per tensor);
- the LR schedule is a pure function of the step baked into the
  optimizer, not a stateful scheduler stepped per batch;
- checkpoint progress metadata is readable without deserializing
  weights (``core/checkpoint.py``), which the search driver polls.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from fast_autoaugment_tpu.core.checkpoint import (
    load_checkpoint_chain,
    read_metadata,
    save_checkpoint,
)
from fast_autoaugment_tpu.core.compilecache import (
    compile_cache_stats,
    configure_compile_cache,
)
from fast_autoaugment_tpu.core.metrics import Accumulator
from fast_autoaugment_tpu.core.resilience import (
    PREEMPTED_EXIT_CODE,
    PreemptedError,
    install_signal_handlers,
    preemption_requested,
)
from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.telemetry import wall
from fast_autoaugment_tpu.core.watchdog import (
    dispatch_enqueue_guard,
    resolve_watchdog,
)
from fast_autoaugment_tpu.data.datasets import cv_split, load_dataset
from fast_autoaugment_tpu.data.pipeline import (
    BatchIterator,
    DeviceCache,
    prefetch,
    resolve_device_cache,
    split_dispatch_chunks,
    stacked_index_matrix,
    stacked_train_batches,
    train_index_matrix,
)
from fast_autoaugment_tpu.models import get_model, num_class
from fast_autoaugment_tpu.ops.optim import build_optimizer
from fast_autoaugment_tpu.ops.schedules import build_schedule
from fast_autoaugment_tpu.parallel.mesh import (
    make_fold_mesh,
    make_mesh,
    place_index_matrix,
    place_stacked_index_matrix,
    replicated,
    shard_transform,
    stacked_shard_transform,
)
from fast_autoaugment_tpu.policies.archive import load_policy, policy_to_tensor
from fast_autoaugment_tpu.train.steps import (
    create_train_state,
    make_eval_step,
    make_multistep_train_step,
    make_replay_eval_step,
    make_stacked_step_body,
    make_stacked_train_step,
    make_train_step,
    make_train_step_body,
    slice_state,
    stack_states,
)
from fast_autoaugment_tpu.utils import faultinject
from fast_autoaugment_tpu.utils.logging import get_logger, make_writers

__all__ = ["train_and_eval", "train_folds_stacked", "resolve_policy_tensor"]

logger = get_logger("faa_tpu.train")


# conf-name -> archive-name mapping (reference data.py:91-106)
AUG_ALIASES = {
    "fa_reduced_imagenet": "fa_resnet50_rimagenet",
    "arsaug": "arsaug_policy",
    "autoaug_cifar10": "autoaug_paper_cifar10",
    "autoaug_extend": "autoaug_policy",
}


def resolve_policy_tensor(aug: Any):
    """conf['aug'] -> policy tensor or None ('default').

    Accepts an archive name (or its conf alias), an explicit policy
    list (the search's decoded candidates), or 'default'/None.
    """
    if aug in (None, "default"):
        return None
    if isinstance(aug, str):
        return jnp.asarray(policy_to_tensor(load_policy(AUG_ALIASES.get(aug, aug))))
    # explicit list of sub-policies
    return jnp.asarray(policy_to_tensor([list(map(tuple, sub)) for sub in aug]))


def _run_eval(eval_step, params, batch_stats, batches, mesh) -> dict:
    """`batches` yields per-process (images, labels, mask) shards —
    padding/sharding lives in `eval_batches` (one place, multi-host
    aware), not here.  Host slicing/decoding and the H2D copy run in
    the prefetch worker so they overlap the previous batch's device
    eval.  The device-cache path evaluates differently: splits are
    placed once and replayed in one fused dispatch per shape group
    (:func:`_stacked_eval_splits` + :func:`_run_replay_eval` — the
    ``search/tta.py::eval_tta`` upload-once discipline applied to
    training eval)."""
    acc = Accumulator()
    sharded = prefetch(batches, transform=shard_transform(mesh, ("x", "y", "m")))
    for batch in sharded:
        acc.add_dict(eval_step(params, batch_stats, batch["x"], batch["y"], batch["m"]))
    return acc.normalize()


def _stacked_eval_splits(it: BatchIterator, global_batch: int, mesh,
                         eval_kw: dict) -> list:
    """Materialize one eval epoch as device-resident SHAPE-GROUPED batch
    stacks (``{"x": [S, B, ...], "y": [S, B], "m": [S, B]}``) for
    one-dispatch replay through ``make_replay_eval_step`` (usually one
    group; a padded final partial batch of a different size forms a
    second).  Placed once per split, reused every evaluation epoch."""
    from jax.sharding import NamedSharding, PartitionSpec

    groups: dict = {}
    for x, y, m in it.eval_epoch(global_batch, **eval_kw):
        groups.setdefault(x.shape, []).append((x, y, m))
    sharding = NamedSharding(mesh, PartitionSpec(None, "data"))
    out = []
    for items in groups.values():
        out.append({
            "x": jax.device_put(np.stack([x for x, _, _ in items]), sharding),
            "y": jax.device_put(np.stack([y for _, y, _ in items]), sharding),
            "m": jax.device_put(np.stack([m for _, _, m in items]), sharding),
        })
    return out


def _run_replay_eval(replay_step, params, batch_stats, groups,
                     wd=None) -> dict:
    """One fused dispatch per shape group over a replayed split (each
    deadline-guarded when a watchdog is enabled — the PR-4 rendezvous
    deadlock was first observed exactly here, in eval)."""
    acc = Accumulator()
    for g in groups:
        t0 = telemetry.mono()
        if wd is not None and wd.enabled:
            out = wd.run("replay_eval", replay_step, params, batch_stats,
                         g["x"], g["y"], g["m"])
        else:
            with dispatch_enqueue_guard():
                out = replay_step(params, batch_stats, g["x"], g["y"],
                                  g["m"])
        telemetry.record_dispatch("replay_eval", t0, telemetry.mono())
        acc.add_dict(out)
    return acc.normalize()


def _monitored_dispatch(wd, label: str, fi, step: int, fn, *args):
    """One device dispatch through the watchdog + telemetry span seam.

    With the watchdog off and no injected fault this is EXACTLY the
    historical direct call — async dispatch, no per-dispatch block (the
    span then times the ENQUEUE window, not device completion; the
    monitored path times the full blocking wall).
    With the watchdog on (or a ``hang``/``slow`` fault pinned at this
    step) the call runs deadline-guarded in a worker thread, blocking
    on completion; that serializes the dispatch pipeline (wall only —
    values are unchanged), which is why ``--watchdog`` defaults off.
    A fired deadline raises the typed ``DispatchHungError`` (exit-77
    recovery — core/watchdog.py).  Every path records the window
    through :func:`~fast_autoaugment_tpu.core.telemetry.record_dispatch`
    — the same span seam the TTA/audit and serve dispatches use."""
    inject = fi.dispatch_delay(step) if fi is not None else None
    if inject is None and not wd.enabled:
        # enqueue-order serialization (async pipeline only; no-op
        # otherwise) — completion stays async, the historical path
        t0 = telemetry.mono()
        with dispatch_enqueue_guard():
            out = fn(*args)
        telemetry.record_dispatch(label, t0, telemetry.mono(), step=step,
                                  blocking=False)
        return out
    delay = 0.0
    if inject is not None:
        kind, val = inject
        # slow = straggler at F x the label's observed EMA (F seconds
        # before any observation); hang = forever
        delay = val if kind == "hang" else val * (wd.ema(label) or 1.0)
    t0 = telemetry.mono()
    out = wd.run(label, fn, *args, inject_delay=delay)
    telemetry.record_dispatch(label, t0, telemetry.mono(), step=step,
                              blocking=True)
    return out


def _beat(heartbeat) -> None:
    """Lease/host heartbeat at a safe boundary.  LeaseLostError (the
    unit was reclaimed — launch/workqueue.py) propagates: this worker
    must abandon the unit, not finish and clobber the survivor."""
    if heartbeat is not None:
        heartbeat()


def _sum_metric_dicts(metric_dicts: list) -> dict:
    """Epoch-end host-side accumulation of per-dispatch metric sums.

    Sequential float32 adds over the synced values — the SAME chain the
    host path's on-device `Accumulator` adds compute, so the reported
    sums stay bit-identical.  Summing on host AFTER the epoch (the sums
    are read at epoch end regardless) instead of queueing one scalar-add
    program per metric per dispatch matters on the virtual CPU mesh:
    with a mesh-committed state those adds are all-participant
    collectives, and long unsynced chains of them deadlock the backend
    (``make_replay_eval_step`` docstring)."""
    sums: dict = {}
    for m in metric_dicts:
        for k, v in m.items():
            v32 = np.asarray(v, np.float32)
            sums[k] = v32 if k not in sums else np.float32(sums[k] + v32)
    return sums


def train_and_eval(
    conf,
    dataroot: str,
    *,
    test_ratio: float = 0.0,
    cv_fold: int = 0,
    reporter: Callable | None = None,
    metric: str = "last",
    save_path: str | None = None,
    only_eval: bool = False,
    evaluation_interval: int = 5,
    mesh=None,
    target_lb: int = -1,
    seed: int = 0,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
    device_cache: str = "auto",
    steps_per_dispatch: int = 1,
    divergence_retries: int = 0,
    ckpt_keep: int = 2,
    checkpoint_every_dispatch: int = 0,
    watchdog="off",
    heartbeat: Callable | None = None,
    compile_cache: str = "off",
) -> dict:
    """Train (or just evaluate) one model under `conf`.

    Returns the reference-shaped result dict with per-split loss/top1/
    top5 plus 'epoch'.  `metric` in {'last', 'train', 'valid', 'test'}
    selects what "best" means (reference ``train.py:286-303``).
    ``aug_dispatch``/``aug_groups`` pick the policy-application kernel
    ("exact" default, bit-for-bit historical; "grouped" scalar
    dispatch — see ``ops/augment.py``).

    ``device_cache`` ("auto"/"on"/"off") selects the device-resident
    data path: the whole eager dataset is uploaded ONCE (sharded over
    the mesh data axis), each epoch ships only the int32 index matrix of
    the IDENTICAL host-side shuffle, and the compiled program gathers
    its batches in place (``data.pipeline.DeviceCache``); eval splits
    are likewise placed once and replayed every evaluation epoch.
    "auto" enables it exactly for eager single-process datasets — lazy
    (ImageNet) datasets keep the prefetch/decode path.
    ``steps_per_dispatch`` (N, needs the cache) fuses N train steps into
    one ``lax.scan`` dispatch (``make_multistep_train_step``): N=1
    (default) is bit-for-bit the host-fed path; N>1 deviates by the
    documented ~1 f32 ULP/step scan-kernel bound (the fold-stacking
    deviation class — docs/BENCHMARKS.md "Step dispatch & device
    cache").

    Resilience (docs/RESILIENCE.md; defaults preserve the historical
    behavior bit-for-bit): SIGTERM/SIGUSR1 requests a graceful stop —
    the loop checkpoints at the next dispatch-chunk (cache path) or
    epoch boundary with ``preempted: true`` metadata and raises
    :class:`PreemptedError` (exit-code contract 77 = "resume me").
    ``divergence_retries`` (R, default 0 = raise as before) rolls a
    non-finite epoch loss back to the newest intact epoch-boundary
    checkpoint up to R times, folding the retry counter into the PRNG
    and shuffle seeds so the replay draws fresh randomness.
    ``ckpt_keep`` bounds the rollback chain (``path``, ``path.prev``,
    …).  ``checkpoint_every_dispatch`` (M, cache path only) adds a
    mid-epoch snapshot every M dispatches — resumable from the exact
    dispatch boundary, bit-identically.

    ``watchdog`` ("off" default / "auto" / seconds, or a shared
    :class:`~fast_autoaugment_tpu.core.watchdog.DispatchWatchdog`)
    deadline-guards every train dispatch and eval replay; a wedged
    dispatch raises the typed ``DispatchHungError`` (exit-77 restart
    recovery) instead of blocking forever.  ``heartbeat`` (callable,
    e.g. a work-queue lease renewal) is invoked at every dispatch-chunk
    boundary (cache path) and epoch boundary — a raised
    ``LeaseLostError`` propagates and aborts the unit.

    ``compile_cache`` ("off" default / a directory) points JAX's
    persistent compilation cache at a shared dir so a fresh process —
    an exit-77 resume, a fleet retry, a reclaimed work unit — reaches
    its first step in seconds instead of re-paying the 23-55 s compile
    tax (``core/compilecache.py``; "off" still honors an inherited
    ``FAA_COMPILE_CACHE``).  Caching never changes numerics — only
    where executables come from; the result carries the evidence under
    ``result['compile_cache']``.
    """
    cache_dir_active = configure_compile_cache(compile_cache)
    if mesh is None:
        mesh = make_mesh()
    is_master = jax.process_index() == 0

    dataset_name = conf["dataset"]
    num_classes = num_class(dataset_name)
    total_train, testset = load_dataset(dataset_name, dataroot)

    if test_ratio > 0.0:
        train_idx, valid_idx = cv_split(total_train.labels, test_ratio, cv_fold)
        if target_lb >= 0:
            # single-class restriction (reference data.py:199-201)
            train_idx = train_idx[total_train.labels[train_idx] == target_lb]
            valid_idx = valid_idx[total_train.labels[valid_idx] == target_lb]
    else:
        train_idx, valid_idx = np.arange(len(total_train)), np.array([], np.int64)

    is_imagenet = dataset_name.endswith("imagenet")
    from fast_autoaugment_tpu.models import input_image_size

    # conf['imgsize'] overrides the native resolution (the reference
    # evaluates ResNet-200 at 320px, README.md:44-46)
    image = int(conf.get("imgsize", 0) or 0) or input_image_size(
        dataset_name, conf["model"]["type"]
    )
    if is_imagenet:
        from fast_autoaugment_tpu.ops.preprocess_imagenet import (
            center_crop_box,
            imagenet_eval_batch,
            imagenet_train_batch,
            random_crop_box,
        )

        train_box = lambda rng, w, h: random_crop_box(rng, w, h, image)  # noqa: E731
        eval_box = lambda rng, w, h: center_crop_box(w, h, image)  # noqa: E731
    else:
        train_box = eval_box = None
    it_kw = dict(train_box_fn=train_box, eval_box_fn=eval_box, imgsize=image)
    train_it = BatchIterator(total_train, train_idx, **it_kw)
    valid_it = BatchIterator(total_train, valid_idx, **it_kw)
    test_it = BatchIterator(testset, **it_kw)

    use_cache = resolve_device_cache(device_cache, total_train,
                                     process_count=jax.process_count())
    steps_per_dispatch = int(steps_per_dispatch)
    if steps_per_dispatch > 1 and not use_cache:
        raise ValueError(
            f"steps_per_dispatch={steps_per_dispatch} needs the device "
            "cache (in-program batch gather); it is "
            f"{'off' if device_cache == 'off' else 'unavailable (lazy dataset or multi-host)'} "
            "here — use --device-cache auto/on with an eager dataset")

    batch_per_device = int(conf["batch"])
    global_batch = batch_per_device * mesh.size
    if not only_eval and len(train_idx) < global_batch:
        raise ValueError(
            f"training set has {len(train_idx)} examples < global batch "
            f"{global_batch} ({batch_per_device}/device x {mesh.size} devices); "
            "every epoch would be empty (train batches drop the last partial "
            "batch, reference data.py:215)"
        )
    steps_per_epoch = max(1, len(train_idx) // global_batch)
    epochs = int(conf["epoch"])

    model_conf = dict(conf["model"], dataset=dataset_name)
    model_conf.setdefault("precision", conf.get("precision", "f32"))
    model = get_model(model_conf, num_classes)
    lr_fn = build_schedule(conf, steps_per_epoch, world_lr_scale=float(mesh.size))
    optimizer_conf = conf["optimizer"]
    ema_mu = float(optimizer_conf.get("ema", 0.0) or 0.0)

    sample = jnp.zeros((2, image, image, 3), jnp.float32)
    rng = jax.random.PRNGKey(seed)

    optimizer = build_optimizer(optimizer_conf, lr_fn)
    state = create_train_state(model, optimizer, rng, sample, use_ema=ema_mu > 0.0)

    policy = resolve_policy_tensor(conf.get("aug", "default"))
    use_policy = policy is not None
    if is_imagenet:
        cutout_len = int(conf.get("cutout", 0) or 0)
        augment_fn = lambda images, pol, key: imagenet_train_batch(  # noqa: E731
            images, key, pol if use_policy else None, cutout_length=cutout_len,
            aug_dispatch=aug_dispatch, aug_groups=aug_groups,
        )
        eval_preprocess = imagenet_eval_batch
    else:
        augment_fn = None
        eval_preprocess = None
    step_kw = dict(
        num_classes=num_classes,
        mixup_alpha=float(conf.get("mixup", 0.0) or 0.0),
        lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
        ema_mu=ema_mu,
        cutout_length=int(conf.get("cutout", 0) or 0),
        use_policy=use_policy,
        augment_fn=augment_fn,
        aug_dispatch=aug_dispatch,
        aug_groups=aug_groups,
    )
    if use_cache:
        # device-resident path: the body is dispatched through the
        # multi-step gather program; at most two chunk shapes per epoch
        # (N and the clamped remainder), each compiled once and reused
        step_body = make_train_step_body(model, optimizer, **step_kw)
        multi_fns: dict[int, Callable] = {}

        def get_multi_step(n: int) -> Callable:
            if n not in multi_fns:
                multi_fns[n] = make_multistep_train_step(
                    step_body, steps_per_dispatch=n)
            return multi_fns[n]
    else:
        train_step = make_train_step(model, optimizer, **step_kw)
    eval_step = make_eval_step(model, num_classes=num_classes,
                               lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
                               preprocess_fn=eval_preprocess)
    replay_eval = make_replay_eval_step(
        model, num_classes=num_classes,
        lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
        preprocess_fn=eval_preprocess) if use_cache else None

    writers = make_writers(
        os.path.dirname(save_path) if save_path else None,
        os.path.basename(save_path or "run"),
        is_master,
    )

    ckpt_keep = max(1, int(ckpt_keep))
    divergence_retries = max(0, int(divergence_retries))
    checkpoint_every_dispatch = max(0, int(checkpoint_every_dispatch))
    wd = resolve_watchdog(watchdog)
    # flag-setting SIGTERM/SIGUSR1 handlers (idempotent, main thread
    # only): the epoch/dispatch loops below poll the flag at safe
    # boundaries — see core/resilience.py and docs/RESILIENCE.md
    install_signal_handlers()

    epoch_start = 1
    resume_pos = 0          # mid-epoch fast-forward (preempted snapshot)
    resume_sums: dict | None = None
    retries_done = 0        # divergence-retry counter (folds the PRNG)
    restored = None
    if save_path:
        # lenient when the file came from the torch importer (no opt_state)
        lenient = bool((read_metadata(save_path) or {}).get("imported_from"))
        # restore from the NEWEST intact chain link; mid-epoch
        # (preempted) snapshots are only restorable where the dispatch
        # position can be fast-forwarded — the device-cache index feed.
        # The host path walks back to an epoch-boundary link instead.
        restored = load_checkpoint_chain(
            save_path, state, lenient=lenient, keep=ckpt_keep,
            accept=None if use_cache else (lambda m: "in_epoch" not in m))
        if restored is not None and "in_epoch" in restored[1]:
            rec = restored[1]["in_epoch"] or {}
            if int(rec.get("epoch", -1)) != int(restored[1].get("epoch", 0)) + 1:
                logger.warning(
                    "inconsistent mid-epoch record in %s — falling back "
                    "to an epoch-boundary chain link", restored[2])
                restored = load_checkpoint_chain(
                    save_path, state, lenient=lenient, keep=ckpt_keep,
                    accept=lambda m: "in_epoch" not in m)
    if restored is not None:
        state, meta, used_path = restored
        lenient = bool(meta.get("imported_from"))
        epoch_start = int(meta.get("epoch", 0)) + 1
        in_epoch = meta.get("in_epoch")
        if in_epoch:
            resume_pos = int(in_epoch["pos"])
            resume_sums = {k: np.float32(v)
                           for k, v in (in_epoch.get("sums") or {}).items()}
            retries_done = int(in_epoch.get("retries", 0))
            logger.info(
                "resuming MID-EPOCH: epoch %d from dispatch position %d "
                "(preempted snapshot %s)", epoch_start, resume_pos,
                used_path)
        if lenient:
            fixes = {}
            # the schedule is a pure fn of step: place it at the resume
            # epoch, not back at warmup
            fixes["step"] = jnp.int32((epoch_start - 1) * steps_per_epoch)
            if state.ema is not None and not meta.get("has_ema"):
                # no EMA in the imported file: seed the shadow from the
                # imported weights, never from random init
                fixes["ema"] = jax.tree.map(
                    jnp.copy,
                    {"params": state.params, "batch_stats": state.batch_stats},
                )
            state = state.replace(**fixes)
        # resume-cost provenance: whether this resumed process will
        # deserialize its executables (warm cache) or re-pay the full
        # compile tax — the final compile_cache stamp carries the proof
        logger.info("resumed %s at epoch %d (compile cache: %s)",
                    used_path, epoch_start - 1,
                    cache_dir_active or "off — full recompile ahead")
        if epoch_start > epochs:
            only_eval = True
    elif only_eval and save_path:
        raise FileNotFoundError(f"--only-eval requires a checkpoint at {save_path}")

    result: dict = {"epoch": epoch_start - 1}
    best_metric = -1e9
    # device-cache eval replay: each split is placed once on first
    # evaluation and reused for every later one (and for the EMA pass,
    # which previously re-fed the split within the SAME evaluation)
    eval_replay: dict[str, list] = {}

    def evaluate(tag_prefix: str, epoch: int) -> dict:
        # empty splits are SKIPPED, not reported as zeros: with
        # test_ratio=0 (every phase-3 search retrain) a zero-row per
        # interval is pure noise, and `metric="valid"` would silently
        # track a best of 0.0 (the reference only ever evaluates real
        # splits, train.py:272-280)
        out = {}
        splits = [("valid", valid_it), ("test", test_it)]
        for split, it in splits:
            if len(it) == 0:
                continue
            eval_kw = dict(
                process_index=jax.process_index(),
                process_count=jax.process_count(),
                pad_multiple=mesh.size,
            )
            if use_cache:
                if split not in eval_replay:
                    eval_replay[split] = _stacked_eval_splits(
                        it, global_batch, mesh, eval_kw)
                norm = _run_replay_eval(
                    replay_eval, state.params, state.batch_stats,
                    eval_replay[split], wd=wd)
            else:
                norm = _run_eval(
                    eval_step, state.params, state.batch_stats,
                    it.eval_epoch(global_batch, **eval_kw), mesh,
                )
            out[split] = norm
            if state.ema is not None:
                if use_cache:
                    norm_ema = _run_replay_eval(
                        replay_eval, state.ema["params"],
                        state.ema["batch_stats"], eval_replay[split], wd=wd)
                else:
                    norm_ema = _run_eval(
                        eval_step, state.ema["params"],
                        state.ema["batch_stats"],
                        it.eval_epoch(global_batch, **eval_kw), mesh,
                    )
                # with EMA on, the REPORTED valid/test numbers are the
                # EMA model's (reference train.py:277-280 overwrites
                # rs['valid']/rs['test']); raw weights kept under _raw
                out[split + "_raw"] = norm
                out[split + "_ema"] = norm_ema
                out[split] = norm_ema
        return out

    if only_eval:
        evals = evaluate("only_eval", epoch_start)
        for split, m in evals.items():
            for k, v in m.items():
                result[f"{k}_{split}"] = v
        result["epoch"] = epoch_start - 1
        result["compile_cache"] = compile_cache_stats()
        return result

    # best-metric guards live AFTER the only_eval return (eval-only runs
    # never consult `metric`, including resumes that auto-flip only_eval)
    if metric not in ("last", "train", "valid", "test"):
        raise ValueError(f"unknown metric {metric!r}: use last/train/valid/test")
    if metric == "valid" and len(valid_it) == 0:
        raise ValueError(
            "metric='valid' with an empty validation split (test_ratio=0): "
            "the best-checkpoint tracker would silently follow a constant "
            "0.0 — pass metric='last'/'train'/'test' or a test_ratio > 0"
        )
    if metric == "test" and len(test_it) == 0:
        raise ValueError("metric='test' with an empty test split")

    train_cache = DeviceCache(total_train, mesh) if use_cache else None
    if train_cache is not None:
        logger.info(
            "device cache: %d examples (%.1f MiB uint8) resident, "
            "steps_per_dispatch=%d", train_cache.num_examples,
            train_cache.nbytes / 2**20, steps_per_dispatch)
        # commit the carried state + replicated inputs to the mesh
        # BEFORE the first dispatch: an uncommitted state compiled
        # against the mesh-committed cache knocks every later call off
        # the C++ fast dispatch path (make_multistep_train_step note)
        state = jax.device_put(state, replicated(mesh))
        rng = jax.device_put(rng, replicated(mesh))

    t_start = wall()
    pol = policy if policy is not None else jnp.zeros((1, 1, 3), jnp.float32)
    if train_cache is not None:
        pol = jax.device_put(pol, replicated(mesh))
    # while (not for): divergence recovery rolls `epoch` BACK to the
    # last good checkpoint's successor and replays with fresh randomness
    epoch = epoch_start
    while epoch <= epochs:
        fi = faultinject.active_plan()
        # divergence-retry randomness: after any rollback every epoch
        # draws retry-folded augmentation keys and shuffle seeds;
        # retries_done == 0 is bit-for-bit the historical stream
        if retries_done:
            rng_epoch = jax.random.fold_in(rng, 1_000_003 * retries_done)
            seed_epoch = seed + 1_000_003 * retries_done
            if train_cache is not None:
                rng_epoch = jax.device_put(rng_epoch, replicated(mesh))
        else:
            rng_epoch, seed_epoch = rng, seed
        acc = Accumulator()
        # live per-batch progress (the reference's tqdm postfix,
        # train.py:79-88): FAA_PROGRESS=N prints a loss-EMA line every N
        # batches (dispatches on the cache path).  Off by default —
        # reading metrics per batch forces a device sync and stalls the
        # dispatch pipeline, which is why the epoch loop otherwise never
        # touches metric values mid-epoch.
        try:
            progress_every = int(os.environ.get("FAA_PROGRESS", "0") or 0)
        except ValueError:  # cosmetic knob must never kill a run — but
            # the misconfiguration must be VISIBLE, not silently eaten
            logger.warning(
                "FAA_PROGRESS=%r is not an integer — live progress "
                "line disabled", os.environ.get("FAA_PROGRESS"))
            progress_every = 0
        loss_ema = None

        def progress(bi: int, metrics, epoch=epoch):
            nonlocal loss_ema
            if is_master and progress_every and (bi + 1) % progress_every == 0:
                cur = float(metrics["loss"]) / max(float(metrics["num"]), 1.0)
                loss_ema = cur if loss_ema is None else 0.9 * loss_ema + 0.1 * cur
                sys.stderr.write(
                    f"\r[epoch {epoch} batch {bi + 1}] loss_ema={loss_ema:.4f} ")
                sys.stderr.flush()

        if train_cache is not None:
            # device-resident feed: the per-epoch shuffle is the
            # IDENTICAL host permutation; only the index matrix is
            # shipped, and each dispatch advances a whole scan chunk
            mat = train_index_matrix(
                train_idx, global_batch, epoch, seed=seed_epoch,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
            pos = 0
            dispatch_metrics: list = []
            if resume_pos and epoch == epoch_start:
                # preempted mid-epoch: skip the dispatches already done
                # and seed the metric chain with the saved partial sums
                # — the host additions below continue the SAME
                # sequential f32 chain, so the epoch's reported metrics
                # are bit-identical to the uninterrupted run
                pos = resume_pos
                if resume_sums:
                    dispatch_metrics.append(dict(resume_sums))
            for di, n in enumerate(split_dispatch_chunks(
                    len(mat) - pos, steps_per_dispatch)):
                idx_dev = place_index_matrix(mesh, mat[pos:pos + n])
                state, metrics = _monitored_dispatch(
                    wd, "train_dispatch", fi,
                    (epoch - 1) * steps_per_epoch + pos + n,
                    get_multi_step(n),
                    state, train_cache.images, train_cache.labels,
                    idx_dev, pol, rng_epoch)
                # per-dispatch sums are kept as ASYNC device handles and
                # summed on host at epoch end (_sum_metric_dicts): with
                # the committed state a per-dispatch jnp add would queue
                # one tiny all-participant collective per metric, and
                # long unsynced chains of those wedge the CPU backend
                dispatch_metrics.append(metrics)
                progress(di, metrics)
                pos += n
                _beat(heartbeat)
                if fi is not None:
                    fi.maybe_signal((epoch - 1) * steps_per_epoch + pos)
                # resilience boundary: the PR-4 dispatch boundaries are
                # exact resume points — honor a preemption request (or
                # the periodic snapshot knob) here, mid-epoch
                periodic = (checkpoint_every_dispatch > 0
                            and (di + 1) % checkpoint_every_dispatch == 0)
                if pos < len(mat) and (preemption_requested() or periodic):
                    if save_path and is_master:
                        sums = _sum_metric_dicts(dispatch_metrics)
                        save_checkpoint(
                            save_path, state,
                            {"epoch": epoch - 1,
                             "step": (epoch - 1) * steps_per_epoch + pos,
                             "preempted": preemption_requested(),
                             "in_epoch": {
                                 "epoch": epoch, "pos": pos,
                                 "sums": {k: float(v)
                                          for k, v in sums.items()},
                                 "retries": retries_done}},
                            keep=ckpt_keep)
                        # saved sums replace the pending handles — the
                        # continued f32 chain is identical either way
                        dispatch_metrics = [
                            {k: np.float32(v) for k, v in sums.items()}]
                    if preemption_requested():
                        logger.warning(
                            "preempted at epoch %d dispatch boundary "
                            "(position %d/%d) — checkpointed, exit %d "
                            "means 'resume me'", epoch, pos, len(mat),
                            PREEMPTED_EXIT_CODE)
                        raise PreemptedError(
                            f"preempted mid-epoch {epoch} at dispatch "
                            f"position {pos}")
            acc.add_dict(_sum_metric_dicts(dispatch_metrics))
        else:
            batches = prefetch(
                train_it.train_epoch(
                    global_batch, epoch, seed=seed_epoch,
                    process_index=jax.process_index(),
                    process_count=jax.process_count(),
                ),
                transform=shard_transform(mesh),
            )
            for bi, batch in enumerate(batches):
                state, metrics = _monitored_dispatch(
                    wd, "train_step", fi,
                    (epoch - 1) * steps_per_epoch + bi + 1,
                    train_step, state, batch["x"], batch["y"],
                    pol, rng_epoch)
                acc.add_dict(metrics)
                progress(bi, metrics)
                if fi is not None:
                    fi.maybe_signal((epoch - 1) * steps_per_epoch + bi + 1)
        _beat(heartbeat)
        resume_pos, resume_sums = 0, None  # consumed by the first epoch
        if is_master and progress_every and loss_ema is not None:
            sys.stderr.write("\n")
        train_metrics = acc.normalize()
        if not train_metrics:
            raise RuntimeError(
                f"epoch {epoch} produced zero train batches "
                f"({len(train_idx)} examples, global batch {global_batch}) — "
                "feed pipeline bug or dataset/batch mismatch"
            )
        if fi is not None and fi.nan_loss_in((epoch - 1) * steps_per_epoch,
                                             epoch * steps_per_epoch):
            train_metrics["loss"] = float("nan")  # injected at the seam
        if not np.isfinite(train_metrics["loss"]):
            # divergence recovery (--divergence-retries R, default 0 =
            # the historical raise): roll back to the newest intact
            # EPOCH-BOUNDARY chain link and replay with retry-folded
            # randomness; re-raise only after R failed rollbacks
            if retries_done < divergence_retries and save_path:
                rolled = load_checkpoint_chain(
                    save_path, state, keep=ckpt_keep,
                    accept=lambda m: "in_epoch" not in m)
                if rolled is not None:
                    retries_done += 1
                    state, meta_rb, used_rb = rolled
                    if train_cache is not None:
                        state = jax.device_put(state, replicated(mesh))
                    rollback_epoch = int(meta_rb.get("epoch", 0)) + 1
                    logger.warning(
                        "divergence: non-finite loss at epoch %d — rolled "
                        "back to %s (replaying from epoch %d), retry %d/%d "
                        "with retry-folded PRNG/shuffle streams",
                        epoch, used_rb, rollback_epoch, retries_done,
                        divergence_retries)
                    epoch = rollback_epoch
                    continue
                logger.error(
                    "divergence: retries remain but NO intact rollback "
                    "checkpoint under %s — re-raising", save_path)
            raise RuntimeError("loss is NaN — training diverged (reference train.py:259)")

        # periodic EMA -> model weight restore (reference train.py:262-270)
        ema_interval = int(optimizer_conf.get("ema_interval", -1) or -1)
        if state.ema is not None and ema_interval > 0 and epoch % ema_interval == 0:
            logger.info("ema synced into model at epoch %d", epoch)
            # copy: params must not alias the EMA shadow (donated buffers)
            state = state.replace(
                params=jax.tree.map(jnp.copy, state.ema["params"]),
                batch_stats=jax.tree.map(jnp.copy, state.ema["batch_stats"]),
            )
        for k in ("loss", "top1", "top5"):
            writers[0].add_scalar(k, train_metrics[k], epoch)
        logger.info(
            "[%s %3d/%3d] loss=%.4f top1=%.4f lr=%.5f",
            "train", epoch, epochs, train_metrics["loss"], train_metrics["top1"],
            float(lr_fn(int(state.step) - 1)),
        )

        result.update({f"{k}_train": v for k, v in train_metrics.items() if k != "num"})
        result["epoch"] = epoch

        if epoch % evaluation_interval == 0 or epoch == epochs:
            evals = evaluate("eval", epoch)
            for split, m in evals.items():
                widx = 1 if split.startswith("valid") else 2
                if split.endswith("_ema"):
                    tag_suffix = "_ema"
                elif split.endswith("_raw"):
                    tag_suffix = "_raw"
                else:
                    tag_suffix = ""
                for k in ("loss", "top1", "top5"):
                    writers[widx].add_scalar(f"{k}{tag_suffix}", m.get(k, 0.0), epoch)
                for k, v in m.items():
                    result[f"{k}_{split}"] = v
                logger.info("[%s %3d/%3d] %s", split, epoch, epochs,
                            {k: round(float(v), 4) for k, v in m.items()})

            if metric == "last":
                cur = float(epoch)
            elif metric == "train":
                cur = train_metrics["top1"]
            else:
                cur = evals.get(metric, {}).get("top1", 0.0)
            if cur >= best_metric:
                best_metric = cur
                result["best_valid_top1"] = evals.get("valid", {}).get("top1", 0.0)
                result["best_test_top1"] = evals.get("test", {}).get("top1", 0.0)
                if save_path and is_master:
                    save_checkpoint(
                        save_path,
                        state,
                        {
                            "epoch": epoch,
                            "step": int(state.step),
                            "metrics": {k: float(v) for k, v in result.items()
                                        if isinstance(v, (int, float))},
                        },
                        keep=ckpt_keep,
                    )
            if reporter is not None:
                reporter(
                    loss_valid=evals.get("valid", {}).get("loss", 0.0),
                    top1_valid=evals.get("valid", {}).get("top1", 0.0),
                    loss_train=train_metrics["loss"],
                    top1_train=train_metrics["top1"],
                    epoch=epoch,
                )

        # graceful preemption at the epoch boundary (the host path's
        # only safe point; the cache path usually caught the flag at a
        # dispatch boundary already): checkpoint the COMPLETED epoch
        # with preempted metadata and exit via the 77 contract
        if preemption_requested():
            if save_path and is_master:
                save_checkpoint(
                    save_path, state,
                    {"epoch": epoch, "step": int(state.step),
                     "preempted": True,
                     "metrics": {k: float(v) for k, v in result.items()
                                 if isinstance(v, (int, float))}},
                    keep=ckpt_keep)
            logger.warning(
                "preempted at epoch %d boundary — checkpointed, exit %d "
                "means 'resume me'", epoch, PREEMPTED_EXIT_CODE)
            raise PreemptedError(f"preempted after epoch {epoch}")
        epoch += 1

    result["elapsed_sec"] = wall() - t_start
    # compile-tax evidence (hit/miss counts + per-label first-call
    # seconds through the seam): a resumed/warm process proves here
    # that it reached its first step in seconds, not minutes
    result["compile_cache"] = compile_cache_stats()
    for w in writers:
        w.close()
    return result


def train_folds_stacked(
    conf,
    dataroot: str,
    *,
    cv_ratio: float,
    folds: list[int],
    save_paths: list[str],
    seed: int = 0,
    seeds: list[int] | None = None,
    evaluation_interval: int = 5,
    mesh=None,
    resume: bool = True,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
    device_cache: str = "auto",
    steps_per_dispatch: int = 1,
    ckpt_keep: int = 2,
    watchdog="off",
    heartbeat: Callable | None = None,
    compile_cache: str = "off",
) -> dict[int, dict]:
    """Train K phase-1 fold models as ONE vmapped program per step.

    The fold-stacked counterpart of calling :func:`train_and_eval` once
    per fold with ``test_ratio=cv_ratio, cv_fold=fold, metric='last'``:
    all K fold states (params, batch_stats, opt_state, per-fold PRNG)
    advance together through :func:`make_stacked_train_step`, fed by
    :func:`stacked_train_batches` gathering the K per-fold shuffled
    index streams out of the ONE shared dataset.  The fold axis is a
    pure vmap of the sequential step body and each fold's data and key
    streams are reproduced exactly, so the stacked computation is the
    sequential one per fold — up to a measured ~1 f32 ULP/step kernel
    reduction-order difference (vmap lowers to batched conv/matmul
    kernels), which training dynamics amplify over a run exactly as the
    repo's documented single-vs-multi-device drift is amplified
    (tests/test_train.py::test_train_step_single_vs_eight_devices).
    The seeded equivalence test pins the bound at short horizons and
    checks eval-metric agreement at run end
    (tests/test_stacked_phase1.py); docs/BENCHMARKS.md records the
    deviation rationale.

    `mesh` defaults to :func:`make_fold_mesh` over all devices — folds
    shard across device groups when the counts divide (the per-fold
    global batch is then ``conf['batch'] x data_axis_size``; see
    `make_fold_mesh`).  `seeds` gives per-fold seeds (default: `seed`
    for every fold, matching the sequential phase-1 loop).  Per-fold
    checkpoints save/restore through :func:`slice_state` under the
    caller-supplied paths — the same layout the sequential path writes,
    so resume, the fold-oracle gate, and single-fold retrains consume
    them unchanged.  Returns ``{fold: result_dict}`` with the
    :func:`train_and_eval`-shaped per-fold metrics.

    In-memory datasets only: lazy (on-disk) datasets fall back to the
    sequential path in the search driver (per-fold host decode streams
    cannot be multiplexed bit-for-bit; ``stacked_train_batches``
    docstring).

    ``device_cache``/``steps_per_dispatch`` compose with the stack: the
    shared dataset is uploaded once, the multiplexed ``[steps, K, B]``
    index matrix replaces the image feed, and one ``lax.scan`` dispatch
    advances K folds x N steps (the scan sits outside the fold vmap —
    ``make_multistep_train_step``).  The dataset here is always eager
    (checked above), so "auto" enables the cache on single-process runs.

    Resilience (docs/RESILIENCE.md): a SIGTERM/SIGUSR1 preemption
    request is honored at the next dispatch-chunk boundary (cache path
    — every active fold checkpoints its slice with ``preempted: true``
    + the mid-epoch position, resumable bit-identically) or epoch
    boundary (host path), then :class:`PreemptedError` carries the
    exit-77 contract up.  ``ckpt_keep`` bounds each fold's rollback
    chain; restore walks to the newest intact link.  ``watchdog`` /
    ``heartbeat`` follow the :func:`train_and_eval` contract
    (deadline-guarded dispatches; lease renewal per dispatch/epoch
    boundary).
    """
    configure_compile_cache(compile_cache)
    if len(folds) != len(save_paths):
        raise ValueError(f"{len(folds)} folds but {len(save_paths)} paths")
    num_folds = len(folds)
    if seeds is None:
        seeds = [seed] * num_folds
    if mesh is None:
        mesh = make_fold_mesh(num_folds)
    data_size = mesh.shape["data"]
    is_master = jax.process_index() == 0
    t_start = wall()

    dataset_name = conf["dataset"]
    num_classes = num_class(dataset_name)
    total_train, testset = load_dataset(dataset_name, dataroot)
    if total_train.lazy:
        raise ValueError(
            "train_folds_stacked supports in-memory datasets only; "
            f"{dataset_name!r} is lazy — use the sequential per-fold path")

    fold_train_idx, fold_valid_idx = [], []
    for fold in folds:
        tr, va = cv_split(total_train.labels, cv_ratio, fold)
        fold_train_idx.append(tr)
        fold_valid_idx.append(va)

    from fast_autoaugment_tpu.models import input_image_size

    image = int(conf.get("imgsize", 0) or 0) or input_image_size(
        dataset_name, conf["model"]["type"]
    )
    batch_per_device = int(conf["batch"])
    global_batch = batch_per_device * data_size
    for fold, tr in zip(folds, fold_train_idx):
        if len(tr) < global_batch:
            raise ValueError(
                f"fold {fold} has {len(tr)} train examples < per-fold "
                f"global batch {global_batch} — every epoch would be empty")
    step_counts = {len(tr) // global_batch for tr in fold_train_idx}
    if len(step_counts) != 1:
        # the LR schedule is baked into the ONE shared optimizer as a
        # pure function of the step; folds with different step counts
        # need per-fold schedules the stack cannot represent
        raise ValueError(
            f"folds disagree on steps/epoch ({sorted(step_counts)}) — "
            "train them sequentially instead")
    steps_per_epoch = step_counts.pop()
    epochs = int(conf["epoch"])

    model_conf = dict(conf["model"], dataset=dataset_name)
    model_conf.setdefault("precision", conf.get("precision", "f32"))
    model = get_model(model_conf, num_classes)
    lr_fn = build_schedule(conf, steps_per_epoch, world_lr_scale=float(data_size))
    optimizer_conf = conf["optimizer"]
    ema_mu = float(optimizer_conf.get("ema", 0.0) or 0.0)
    optimizer = build_optimizer(optimizer_conf, lr_fn)

    sample = jnp.zeros((2, image, image, 3), jnp.float32)
    policy = resolve_policy_tensor(conf.get("aug", "default"))
    use_policy = policy is not None
    pol = policy if policy is not None else jnp.zeros((1, 1, 3), jnp.float32)

    use_cache = resolve_device_cache(device_cache, total_train,
                                     process_count=jax.process_count())
    steps_per_dispatch = int(steps_per_dispatch)
    if steps_per_dispatch > 1 and not use_cache:
        raise ValueError(
            f"steps_per_dispatch={steps_per_dispatch} needs the device "
            "cache (in-program batch gather) — use --device-cache auto/on")
    step_kw = dict(
        num_classes=num_classes,
        mixup_alpha=float(conf.get("mixup", 0.0) or 0.0),
        lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
        ema_mu=ema_mu,
        cutout_length=int(conf.get("cutout", 0) or 0),
        use_policy=use_policy,
        aug_dispatch=aug_dispatch,
        aug_groups=aug_groups,
    )
    if use_cache:
        stacked_body = make_stacked_step_body(model, optimizer, **step_kw)
        multi_fns: dict[int, Callable] = {}

        def get_multi_step(n: int) -> Callable:
            if n not in multi_fns:
                multi_fns[n] = make_multistep_train_step(
                    stacked_body, steps_per_dispatch=n, stacked=True)
            return multi_fns[n]
    else:
        stacked_step = make_stacked_train_step(model, optimizer, **step_kw)
    eval_step = make_eval_step(
        model, num_classes=num_classes,
        lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
    )
    replay_eval = make_replay_eval_step(
        model, num_classes=num_classes,
        lb_smooth=float(conf.get("lb_smooth", 0.0) or 0.0),
    ) if use_cache else None

    ckpt_keep = max(1, int(ckpt_keep))
    wd = resolve_watchdog(watchdog)
    install_signal_handlers()

    # per-fold init/restore (newest intact chain link), then one
    # stacked state
    states, epoch_starts, fold_metas = [], [], []
    for k, (fold, path) in enumerate(zip(folds, save_paths)):
        state = create_train_state(
            model, optimizer, jax.random.PRNGKey(seeds[k]), sample,
            use_ema=ema_mu > 0.0,
        )
        epoch_start, meta = 1, {}
        if resume and path:
            got = load_checkpoint_chain(path, state, keep=ckpt_keep)
            if got is not None:
                state, meta, used = got
                epoch_start = int(meta.get("epoch", 0)) + 1
                logger.info(
                    "stacked: resumed fold %d at epoch %d%s", fold,
                    epoch_start - 1,
                    " (mid-epoch snapshot)" if "in_epoch" in meta else "")
        states.append(state)
        epoch_starts.append(epoch_start)
        fold_metas.append(meta)

    # mid-epoch (preempted) snapshots fast-forward the stacked dispatch
    # loop only when EVERY restored record agrees on (epoch, pos) and
    # the device-cache index feed is active (positions can be skipped);
    # otherwise each mid-epoch fold falls back to its epoch-boundary
    # chain link — losing at most the interrupted epoch, never
    # silently double-training it
    in_epoch_recs = [m.get("in_epoch") for m in fold_metas]
    stk_resume_pos, stk_resume_epoch, stk_resume_sums = 0, -1, None
    if any(in_epoch_recs):
        ref = next(r for r in in_epoch_recs if r)
        agree = use_cache and all(
            (r is not None and r.get("epoch") == ref["epoch"]
             and r.get("pos") == ref["pos"])
            or (r is None and epoch_starts[k] > int(ref["epoch"]))
            for k, r in enumerate(in_epoch_recs))
        if agree:
            stk_resume_pos = int(ref["pos"])
            stk_resume_epoch = int(ref["epoch"])
            sum_keys = sorted({kk for r in in_epoch_recs if r
                               for kk in (r.get("sums") or {})})
            stk_resume_sums = {
                kk: np.asarray(
                    [(r.get("sums") or {}).get(kk, 0.0) if r else 0.0
                     for r in in_epoch_recs], np.float32)
                for kk in sum_keys}
            logger.info(
                "stacked: resuming MID-EPOCH at epoch %d, dispatch "
                "position %d", stk_resume_epoch, stk_resume_pos)
        else:
            for k, r in enumerate(in_epoch_recs):
                if r is None:
                    continue
                logger.warning(
                    "stacked: fold %d mid-epoch snapshot unusable here "
                    "(position disagreement or host feed) — falling back "
                    "to its epoch-boundary chain link", folds[k])
                got = load_checkpoint_chain(
                    save_paths[k], states[k], keep=ckpt_keep,
                    accept=lambda m: "in_epoch" not in m)
                if got is not None:
                    states[k], meta_k, _used = got
                    epoch_starts[k] = int(meta_k.get("epoch", 0)) + 1
                else:
                    states[k] = create_train_state(
                        model, optimizer, jax.random.PRNGKey(seeds[k]),
                        sample, use_ema=ema_mu > 0.0)
                    epoch_starts[k] = 1
    stacked = stack_states(states)
    del states
    # shard every state leaf's leading fold axis over the mesh fold
    # axis (a no-op layout on fold_shards=1 meshes): folds live on
    # their own device groups instead of replicating
    from jax.sharding import NamedSharding, PartitionSpec

    fold_placed = NamedSharding(mesh, PartitionSpec("fold"))
    stacked = jax.device_put(stacked, fold_placed)
    keys = jax.device_put(
        jnp.stack([jax.random.PRNGKey(s) for s in seeds]), fold_placed)

    valid_its = [BatchIterator(total_train, va) for va in fold_valid_idx]
    test_it = BatchIterator(testset)
    writers = [
        make_writers(os.path.dirname(p) if p else None,
                     os.path.basename(p or "run"), is_master)
        for p in save_paths
    ]
    results: dict[int, dict] = {
        fold: {"epoch": epoch_starts[k] - 1} for k, fold in enumerate(folds)
    }

    # device-cache eval replay: valid splits are per fold, the test
    # split is shared — each placed once, reused every eval epoch
    eval_replay: dict = {}

    def evaluate_fold(k: int, state_k) -> dict:
        out = {}
        eval_kw = dict(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            pad_multiple=data_size,
        )
        for split, it in (("valid", valid_its[k]), ("test", test_it)):
            if len(it) == 0:
                continue
            if use_cache:
                ck = ("test",) if split == "test" else ("valid", k)
                if ck not in eval_replay:
                    eval_replay[ck] = _stacked_eval_splits(
                        it, global_batch, mesh, eval_kw)
                out[split] = _run_replay_eval(
                    replay_eval, state_k.params, state_k.batch_stats,
                    eval_replay[ck], wd=wd)
            else:
                out[split] = _run_eval(
                    eval_step, state_k.params, state_k.batch_stats,
                    it.eval_epoch(global_batch, **eval_kw), mesh,
                )
        return out

    train_cache = DeviceCache(total_train, mesh) if use_cache else None
    if train_cache is not None:
        logger.info(
            "stacked device cache: %d examples (%.1f MiB uint8) resident, "
            "steps_per_dispatch=%d", train_cache.num_examples,
            train_cache.nbytes / 2**20, steps_per_dispatch)
        # the stacked state/keys are already mesh-committed (fold
        # placement above); the policy tensor must be too, or the first
        # compile pins a mixed-commitment signature that knocks later
        # dispatches off the C++ fast path (make_multistep_train_step)
        pol = jax.device_put(pol, replicated(mesh))
    first_epoch = min(epoch_starts)
    transform = stacked_shard_transform(mesh)
    for epoch in range(first_epoch, epochs + 1):
        fi = faultinject.active_plan()
        epoch_active = np.asarray(
            [1.0 if epoch >= epoch_starts[k] else 0.0
             for k in range(num_folds)], np.float32)
        ep_act_dev = jnp.asarray(epoch_active)

        def _save_fold_slices(meta_fn):
            """Checkpoint every active fold's slice (master only)."""
            if not is_master:
                return
            for k2 in range(num_folds):
                if not epoch_active[k2] or not save_paths[k2]:
                    continue
                save_checkpoint(save_paths[k2], slice_state(stacked, k2),
                                meta_fn(k2), keep=ckpt_keep)

        # per-fold sums stay DEVICE-side [K] vectors until epoch end —
        # reading them per batch would sync the dispatch pipeline (the
        # same discipline as the sequential epoch loop)
        epoch_sums: dict | None = None
        if train_cache is not None:
            chunks, act = stacked_index_matrix(
                fold_train_idx, global_batch, epoch, seeds=seeds,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
            act = act * epoch_active[None, :]
            pos = 0
            dispatch_metrics: list = []
            if stk_resume_pos and epoch == stk_resume_epoch:
                # preempted mid-epoch: skip the completed dispatches and
                # seed the per-fold f32 sum chain (bit-identical
                # continuation, as in the sequential trainer)
                pos = stk_resume_pos
                if stk_resume_sums:
                    dispatch_metrics.append(dict(stk_resume_sums))
            for n in split_dispatch_chunks(len(chunks) - pos,
                                           steps_per_dispatch):
                idx_dev, act_dev = place_stacked_index_matrix(
                    mesh, chunks[pos:pos + n], act[pos:pos + n])
                stacked, metrics = _monitored_dispatch(
                    wd, "stacked_dispatch", fi,
                    (epoch - 1) * steps_per_epoch + pos + n,
                    get_multi_step(n),
                    stacked, train_cache.images, train_cache.labels,
                    idx_dev, pol, keys, act_dev)
                # async device handles, host-summed at epoch end — a
                # per-dispatch device add of [K] committed vectors is an
                # all-participant collective; chains of those wedge the
                # CPU backend (_sum_metric_dicts / make_replay_eval_step)
                dispatch_metrics.append(metrics)
                pos += n
                _beat(heartbeat)
                if fi is not None:
                    fi.maybe_signal((epoch - 1) * steps_per_epoch + pos)
                if preemption_requested() and pos < len(chunks):
                    # dispatch-boundary preemption: every active fold
                    # checkpoints its slice with the shared mid-epoch
                    # position, then the 77 contract goes up
                    sums = _sum_metric_dicts(dispatch_metrics)
                    _save_fold_slices(lambda k2: {
                        "epoch": epoch - 1,
                        "step": (epoch - 1) * steps_per_epoch + pos,
                        "preempted": True,
                        "in_epoch": {
                            "epoch": epoch, "pos": pos,
                            "sums": {kk: float(np.asarray(v)[k2])
                                     for kk, v in sums.items()}}})
                    logger.warning(
                        "stacked: preempted at epoch %d dispatch boundary "
                        "(position %d/%d) — %d fold slice(s) checkpointed, "
                        "exit %d means 'resume me'", epoch, pos,
                        len(chunks), int(epoch_active.sum()),
                        PREEMPTED_EXIT_CODE)
                    raise PreemptedError(
                        f"stacked preempted mid-epoch {epoch} at dispatch "
                        f"position {pos}")
            if dispatch_metrics:
                epoch_sums = _sum_metric_dicts(dispatch_metrics)
        else:
            batches = prefetch(
                stacked_train_batches(
                    total_train, fold_train_idx, global_batch, epoch,
                    seeds=seeds,
                    process_index=jax.process_index(),
                    process_count=jax.process_count(),
                ),
                transform=transform,
            )
            for bi, batch in enumerate(batches):
                active = batch["a"] * ep_act_dev
                stacked, metrics = _monitored_dispatch(
                    wd, "stacked_step", fi,
                    (epoch - 1) * steps_per_epoch + bi + 1,
                    stacked_step,
                    stacked, batch["x"], batch["y"], pol, keys, active)
                epoch_sums = metrics if epoch_sums is None else {
                    kk: epoch_sums[kk] + metrics[kk] for kk in epoch_sums}
                if fi is not None:
                    fi.maybe_signal((epoch - 1) * steps_per_epoch + bi + 1)
            _beat(heartbeat)
        host_sums = {kk: np.asarray(v)
                     for kk, v in (epoch_sums or {}).items()}

        for k, fold in enumerate(folds):
            if not epoch_active[k]:
                continue
            num = float(host_sums["num"][k]) if host_sums else 0.0
            if num <= 0:
                raise RuntimeError(
                    f"stacked epoch {epoch} produced zero batches for fold "
                    f"{fold} — feed pipeline bug")
            train_metrics = {
                kk: float(host_sums[kk][k]) / num
                for kk in ("loss", "top1", "top5")}
            train_metrics["num"] = num
            if np.isnan(train_metrics["loss"]):
                raise RuntimeError(
                    f"fold {fold} loss is NaN — training diverged")
            for kk in ("loss", "top1", "top5"):
                writers[k][0].add_scalar(kk, train_metrics[kk], epoch)
            logger.info(
                "[stacked fold %d %3d/%3d] loss=%.4f top1=%.4f", fold,
                epoch, epochs, train_metrics["loss"], train_metrics["top1"],
            )
            results[fold].update(
                {f"{kk}_train": v for kk, v in train_metrics.items()
                 if kk != "num"})
            results[fold]["epoch"] = epoch

            if epoch % evaluation_interval == 0 or epoch == epochs:
                state_k = slice_state(stacked, k)
                evals = evaluate_fold(k, state_k)
                for split, m in evals.items():
                    widx = 1 if split.startswith("valid") else 2
                    for kk in ("loss", "top1", "top5"):
                        writers[k][widx].add_scalar(kk, m.get(kk, 0.0), epoch)
                    for kk, v in m.items():
                        results[fold][f"{kk}_{split}"] = v
                    logger.info(
                        "[stacked fold %d %s %3d/%3d] %s", fold, split,
                        epoch, epochs,
                        {kk: round(float(v), 4) for kk, v in m.items()})
                # metric='last' semantics (the phase-1 contract): every
                # eval epoch is the new best, checkpoint it
                results[fold]["best_valid_top1"] = evals.get(
                    "valid", {}).get("top1", 0.0)
                results[fold]["best_test_top1"] = evals.get(
                    "test", {}).get("top1", 0.0)
                if save_paths[k] and is_master:
                    save_checkpoint(
                        save_paths[k],
                        state_k,
                        {
                            "epoch": epoch,
                            "step": int(state_k.step),
                            "metrics": {kk: float(v)
                                        for kk, v in results[fold].items()
                                        if isinstance(v, (int, float))},
                        },
                        keep=ckpt_keep,
                    )

        # epoch-boundary preemption (the host path's only safe point):
        # checkpoint every active fold's COMPLETED epoch, exit via 77
        if preemption_requested():
            _save_fold_slices(lambda k2: {
                "epoch": epoch,
                "step": int(slice_state(stacked, k2).step),
                "preempted": True})
            logger.warning(
                "stacked: preempted at epoch %d boundary — checkpointed, "
                "exit %d means 'resume me'", epoch, PREEMPTED_EXIT_CODE)
            raise PreemptedError(f"stacked preempted after epoch {epoch}")

    elapsed = wall() - t_start
    cc = compile_cache_stats()
    logger.info("stacked: compile cache dir=%s hits=%d misses=%d "
                "first_step_secs=%.3f", cc["dir"], cc["hits"], cc["misses"],
                cc["first_step_secs"])
    for k, fold in enumerate(folds):
        results[fold]["elapsed_sec"] = elapsed
        results[fold]["compile_cache"] = cc
        for w in writers[k]:
            w.close()
    return results
