"""Jitted train/eval steps.

One pjit-compiled function per phase is the whole training runtime —
the analog of the reference's per-batch Python loop body
(``run_epoch``, ``train.py:35-107``), but with augmentation, forward,
loss (+wd), backward, clip, optimizer, EMA and metric reduction fused
into a single XLA program over the global batch:

- the global batch arrives sharded over the mesh's ``'data'`` axis;
  params are replicated; XLA inserts gradient allreduces over ICI
  (the DDP/NCCL equivalent, SURVEY.md section 2.2);
- BN statistics are global-batch statistics — cross-replica BN by
  construction (what ``tf_port/tpu_bn.py`` hand-built);
- augmentation policies enter as TENSORS, so changing policies never
  recompiles (the property the TTA search engine relies on);
- EMA is a pytree lerp on device (the reference's Python-loop EMA over
  ``state_dict`` items, ``common.py:46-51``, is a per-step host hot
  loop — SURVEY.md section 3.1 flags it);
- metrics leave the step as count-weighted sums, so the host only syncs
  when it reads them.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct

from fast_autoaugment_tpu.core.compilecache import seam_jit
from fast_autoaugment_tpu.core.metrics import (
    mixup_batch,
    mixup_cross_entropy,
    smooth_cross_entropy,
    top_k_correct,
)
from fast_autoaugment_tpu.ops.augment import (
    apply_policy_batch_grouped,
    check_aug_dispatch,
)
from fast_autoaugment_tpu.ops.optim import ema_update
from fast_autoaugment_tpu.ops.preprocess import cifar_eval_batch, cifar_train_batch

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_train_step_body",
    "make_stacked_train_step",
    "make_stacked_step_body",
    "make_multistep_train_step",
    "default_dispatch_unroll",
    "make_eval_step",
    "make_replay_eval_step",
    "stack_states",
    "slice_state",
]


# domain-separation tag for the stacked grouped-augmentation key
# derivation: the fold's step key is fold_in(keys[k], step[k]) — folding
# this tag on top keeps the grouped policy pass on a stream disjoint
# from the in-body augment/model keys derived from the same pair
_GROUPED_AUG_TAG = 7919


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    ema: Any  # {'params', 'batch_stats'} shadow, or None


def create_train_state(model, optimizer, rng, sample_input, use_ema: bool) -> TrainState:
    variables = model.init(
        {"params": rng, "shake": jax.random.fold_in(rng, 1)}, sample_input, train=False
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    # the EMA shadow must be a DISTINCT set of buffers: the train step
    # donates the whole state, and donating two references to one buffer
    # is an error
    ema = (
        jax.tree.map(jnp.copy, {"params": params, "batch_stats": batch_stats})
        if use_ema
        else None
    )
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        ema=ema,
    )


def _make_train_step_body(
    model,
    optimizer,
    *,
    num_classes: int,
    mixup_alpha: float = 0.0,
    lb_smooth: float = 0.0,
    ema_mu: float = 0.0,
    cutout_length: int = 16,
    use_policy: bool = True,
    augment_fn: Callable | None = None,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
) -> Callable:
    """The UNJITTED per-model train-step body shared by the sequential
    and fold-stacked variants: :func:`make_train_step` jits it directly;
    :func:`make_stacked_train_step` vmaps the identical computation over
    a leading fold axis — the candidate-axis construction of
    ``search/tta.py::make_tta_step``, applied to phase 1.  Unlike the
    eval-only TTA step, a TRAIN step under vmap lowers to batched
    conv/matmul kernels whose reduction order can differ from the
    unbatched ones by ~1 float32 ULP per step (measured; see
    ``train_folds_stacked``), so stacked equality with sequential
    training is ULP-exact per step but only tolerance-bounded over a
    full run — the same deviation class as the repo's documented
    single-vs-multi-device drift (tests/test_train.py).
    """
    check_aug_dispatch(aug_dispatch)
    if augment_fn is None:
        def augment_fn(images, policy, key):
            return cifar_train_batch(
                images, key, policy=policy if use_policy else None,
                cutout_length=cutout_length,
                aug_dispatch=aug_dispatch, aug_groups=aug_groups,
            )

    def loss_fn(params, batch_stats, images, labels, key):
        key_mix, key_shake, key_drop = jax.random.split(key, 3)
        apply = functools.partial(
            model.apply,
            {"params": params, "batch_stats": batch_stats},
            train=True,
            mutable=["batch_stats"],
            rngs={"shake": key_shake, "dropout": key_drop},
        )
        if mixup_alpha > 0.0:
            mixed, targets_a, targets_b, lam = mixup_batch(key_mix, images, labels, mixup_alpha)
            logits, mutated = apply(mixed)
            loss = mixup_cross_entropy(logits, targets_a, targets_b, lam, lb_smooth)
        else:
            logits, mutated = apply(images)
            loss = smooth_cross_entropy(logits, labels, lb_smooth)
        return loss, (logits, mutated["batch_stats"])

    def step_fn(state: TrainState, images, labels, policy, key):
        key_aug, key_model = jax.random.split(jax.random.fold_in(key, state.step))
        images = augment_fn(images, policy, key_aug)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (loss, (logits, new_batch_stats)), grads = grad_fn(
            state.params, state.batch_stats, images, labels, key_model
        )
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(lambda p, u: p + u, state.params, updates)

        new_ema = state.ema
        if state.ema is not None and ema_mu > 0.0:
            new_ema = ema_update(
                state.ema,
                {"params": new_params, "batch_stats": new_batch_stats},
                ema_mu,
                state.step + 1,  # 1-based, reference train.py:70
            )

        batch = labels.shape[0]
        metrics = {
            "loss": loss * batch,
            "top1": top_k_correct(logits, labels, 1).astype(jnp.float32),
            "top5": top_k_correct(logits, labels, min(5, num_classes)).astype(jnp.float32),
            "num": jnp.float32(batch),
        }
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
            ema=new_ema,
        )
        return new_state, metrics

    return step_fn


# public name: the device-cache multi-step dispatcher wraps this body in
# a lax.scan (make_multistep_train_step), and benches/tests build it too
make_train_step_body = _make_train_step_body


def make_train_step(
    model,
    optimizer,
    *,
    num_classes: int,
    mixup_alpha: float = 0.0,
    lb_smooth: float = 0.0,
    ema_mu: float = 0.0,
    cutout_length: int = 16,
    use_policy: bool = True,
    augment_fn: Callable | None = None,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
) -> Callable:
    """Build the jitted train step.

    Returns ``step_fn(state, images_u8, labels, policy, key) ->
    (state, metric_sums)``.  `augment_fn(images, policy, key)` defaults
    to the CIFAR/SVHN stack; pass an ImageNet stack for that family.
    ``aug_dispatch``/``aug_groups`` select the policy-application
    kernel of the DEFAULT augment_fn ("exact" = the historical
    per-image vmapped-switch path bit-for-bit; "grouped" = scalar
    dispatch with stratified per-chunk sub-policy draws); a custom
    `augment_fn` owns its own dispatch.
    """
    body = _make_train_step_body(
        model, optimizer, num_classes=num_classes, mixup_alpha=mixup_alpha,
        lb_smooth=lb_smooth, ema_mu=ema_mu, cutout_length=cutout_length,
        use_policy=use_policy, augment_fn=augment_fn,
        aug_dispatch=aug_dispatch, aug_groups=aug_groups,
    )
    # donate the state: params/opt-state/EMA buffers are overwritten in
    # place, halving peak HBM for the update.  Jitted through the
    # compile seam (core/compilecache.py): first-call compile is timed
    # and classified hit/miss against the persistent cache.
    return seam_jit(body, label="train_step", donate_argnums=(0,))


def make_stacked_step_body(
    model,
    optimizer,
    *,
    num_classes: int,
    mixup_alpha: float = 0.0,
    lb_smooth: float = 0.0,
    ema_mu: float = 0.0,
    cutout_length: int = 16,
    use_policy: bool = True,
    augment_fn: Callable | None = None,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
) -> Callable:
    """The UNJITTED fold-stacked step (fold vmap + grouped-dispatch
    hoist + active-lane masking): :func:`make_stacked_train_step` jits
    it directly; :func:`make_multistep_train_step` wraps it in a
    ``lax.scan`` over N steps (the scan sits OUTSIDE the fold vmap, so
    the grouped policy pass stays hoisted with a scalar switch index).
    See :func:`make_stacked_train_step` for the full contract."""
    check_aug_dispatch(aug_dispatch)
    pre_policy = (aug_dispatch == "grouped" and augment_fn is None
                  and use_policy)
    if pre_policy:
        def inner_augment(images, policy, key):
            # the grouped policy pass already ran outside the vmap
            return cifar_train_batch(images, key, policy=None,
                                     cutout_length=cutout_length)

        body = _make_train_step_body(
            model, optimizer, num_classes=num_classes,
            mixup_alpha=mixup_alpha, lb_smooth=lb_smooth, ema_mu=ema_mu,
            cutout_length=cutout_length, use_policy=use_policy,
            augment_fn=inner_augment,
        )
    else:
        body = _make_train_step_body(
            model, optimizer, num_classes=num_classes, mixup_alpha=mixup_alpha,
            lb_smooth=lb_smooth, ema_mu=ema_mu, cutout_length=cutout_length,
            use_policy=use_policy, augment_fn=augment_fn,
            aug_dispatch=aug_dispatch, aug_groups=aug_groups,
        )

    def stacked_fn(states, images, labels, policy, keys, active):
        if pre_policy:
            auged = []
            for k in range(images.shape[0]):  # static fold count
                key_pol = jax.random.fold_in(
                    jax.random.fold_in(keys[k], states.step[k]),
                    _GROUPED_AUG_TAG)
                auged.append(apply_policy_batch_grouped(
                    images[k].astype(jnp.float32), policy, key_pol,
                    groups=aug_groups))
            images = jnp.stack(auged)
        new_states, metrics = jax.vmap(
            body, in_axes=(0, 0, 0, None, 0)
        )(states, images, labels, policy, keys)

        def select(new, old):
            gate = active.reshape((-1,) + (1,) * (new.ndim - 1))
            return jnp.where(gate > 0, new, old)

        new_states = jax.tree.map(select, new_states, states)
        metrics = {k: v * active for k, v in metrics.items()}
        return new_states, metrics

    return stacked_fn


def make_stacked_train_step(
    model,
    optimizer,
    *,
    num_classes: int,
    mixup_alpha: float = 0.0,
    lb_smooth: float = 0.0,
    ema_mu: float = 0.0,
    cutout_length: int = 16,
    use_policy: bool = True,
    augment_fn: Callable | None = None,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
) -> Callable:
    """Build the fold-stacked train step: K fold models advance in ONE
    jitted program per step (the Podracer whole-learner-replica vmap,
    arXiv:2104.06272, applied to phase-1 fold pretraining).

    Returns ``fn(states, images_u8 [K,B,H,W,C], labels [K,B], policy,
    keys [K,2], active [K]) -> (states, metric_sums)`` where `states` is
    a :class:`TrainState` whose every leaf carries a leading fold axis
    (:func:`stack_states`) and `keys` stacks the per-fold base PRNG keys
    (fold k's per-step key is ``fold_in(keys[k], states.step[k])``
    inside the body — exactly the sequential step's derivation).

    The fold axis is a pure ``jax.vmap`` of the sequential step body:
    fold k's update is the sequential step on its slice, computed by
    batched kernels whose accumulation order may differ by ~1 f32 ULP
    (the documented stacked-vs-sequential bound; module body docstring).
    `active` (float 0/1 per fold) freezes finished lanes:
    inactive folds still ride through the program (one executable for
    any participation set — no recompiles when folds resume at different
    epochs or run out of batches), but their state is passed through
    unchanged and their metric sums are zeroed, so a masked lane is
    indistinguishable from not having stepped at all.

    ``aug_dispatch="grouped"`` needs special handling here: a grouped
    kernel INSIDE the fold-vmapped body would see its per-fold scalar
    sub-policy index re-batched by the fold axis, and ``lax.switch``
    would fall straight back to executing all branches (the exact-mode
    cost, with none of exact mode's distribution).  So the grouped
    policy application is HOISTED out of the vmap: each fold's raw
    batch goes through :func:`apply_policy_batch_grouped` in a static
    per-fold loop (scalar dispatch preserved), keyed by
    ``fold_in(fold_in(keys[k], states.step[k]), _GROUPED_AUG_TAG)`` so
    per-fold streams stay independent and step-fresh, and the
    fold-vmapped body then runs the policy-less per-image stack.
    Exact mode is untouched — augmentation stays inside the body,
    bit-for-bit the historical program.
    """
    body = make_stacked_step_body(
        model, optimizer, num_classes=num_classes, mixup_alpha=mixup_alpha,
        lb_smooth=lb_smooth, ema_mu=ema_mu, cutout_length=cutout_length,
        use_policy=use_policy, augment_fn=augment_fn,
        aug_dispatch=aug_dispatch, aug_groups=aug_groups,
    )
    return seam_jit(body, label="stacked_step", donate_argnums=(0,))


def default_dispatch_unroll(steps_per_dispatch: int) -> int:
    """Measured-default ``unroll`` for :func:`make_multistep_train_step`.

    On XLA:CPU, convolution BACKWARD passes inside a ``while`` loop hit
    a slow kernel path (~3-4x the out-of-loop cost per step, measured
    on wresnet10_1; dense-only bodies are unaffected) — any loop at all
    triggers it, so partial unroll buys nothing and the only fast CPU
    shape is the fully unrolled one (compile time then grows ~linearly
    with N; acceptable at the small N the CPU dev/test path uses).  On
    TPU the rolled scan is the standard pjit-trainer shape and keeps
    compile time independent of N, which is what production wants at
    N=32 on minutes-long WRN compiles.  See docs/BENCHMARKS.md "Step
    dispatch & device cache".
    """
    return steps_per_dispatch if jax.default_backend() == "cpu" else 1


def make_multistep_train_step(
    body: Callable,
    *,
    steps_per_dispatch: int,
    stacked: bool = False,
    unroll: int | None = None,
) -> Callable:
    """Fuse N train steps into ONE jitted dispatch over a device-resident
    dataset cache (`data.pipeline.DeviceCache`): a ``lax.scan`` over the
    step axis whose body gathers each batch from the cache BY INDEX
    inside the program — the sequence-of-steps-in-one-program structure
    of the Podracer architectures (arXiv:2104.06272) and the pjit-era
    LLM trainers.  The host loop's per-step work collapses from
    (fancy-gather + H2D image copy + dispatch) x N to shipping one int32
    index matrix and dispatching once.

    `body` is an UNJITTED step body:

    - sequential (``stacked=False``): :func:`make_train_step_body`'s
      ``(state, images, labels, policy, key) -> (state, metrics)``.
      Returns ``fn(state, cache_images, cache_labels, idx [N, B],
      policy, key) -> (state, metric_sums)``.
    - stacked (``stacked=True``): :func:`make_stacked_step_body`'s
      ``(states, images, labels, policy, keys, active)``.  Returns
      ``fn(states, cache_images, cache_labels, idx [N, K, B], policy,
      keys, active [N, K]) -> (states, metric_sums [K])``.  The scan
      sits OUTSIDE the fold vmap, so the PR-3 grouped-dispatch hoist
      inside the body keeps its scalar switch index.

    Per-step PRNG derivation is untouched: the body folds the carried
    ``state.step`` into the base key, so step t inside the scan draws
    exactly the keys the host loop's t-th dispatch would.  Metrics come
    back summed over the N steps (they are count-weighted sums already);
    with ``steps_per_dispatch=1`` the scan is skipped entirely and the
    program is the single-step body behind a gather — the configuration
    pinned bit-for-bit against the host path (tests/test_device_cache.py).

    The state is donated (same discipline as :func:`make_train_step`);
    the cache arrays are NOT — they persist across dispatches by design.
    Callers must COMMIT the carried state (and the small replicated
    inputs) to the mesh (``jax.device_put(state, replicated(mesh))``)
    before the first call: compiling with an uncommitted state against
    the mesh-committed cache arrays pushes every later call off the C++
    fast dispatch path onto a per-leaf reshard (measured ~17x per-call
    overhead on the 84-leaf WRN state) — the trainer does this, as the
    stacked trainer always has.  ``unroll`` feeds ``lax.scan``
    (default :func:`default_dispatch_unroll`: full unroll on the CPU
    backend, whose conv-backward-in-loop slow path otherwise eats the
    win; rolled on accelerators).
    """
    if steps_per_dispatch < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}")
    if unroll is None:
        unroll = default_dispatch_unroll(steps_per_dispatch)

    def gather(cache_images, cache_labels, idx_n):
        return (jnp.take(cache_images, idx_n, axis=0),
                jnp.take(cache_labels, idx_n, axis=0))

    if not stacked:
        def multi_fn(state, cache_images, cache_labels, idx, policy, key):
            def one(carry, idx_n):
                images, labels = gather(cache_images, cache_labels, idx_n)
                return body(carry, images, labels, policy, key)

            if steps_per_dispatch == 1:
                return one(state, idx[0])
            state, metrics = jax.lax.scan(one, state, idx, unroll=unroll)
            return state, jax.tree.map(lambda v: v.sum(axis=0), metrics)
    else:
        def multi_fn(states, cache_images, cache_labels, idx, policy, keys,
                     active):
            def one(carry, step_in):
                idx_n, active_n = step_in
                images, labels = gather(cache_images, cache_labels, idx_n)
                return body(carry, images, labels, policy, keys, active_n)

            if steps_per_dispatch == 1:
                return one(states, (idx[0], active[0]))
            states, metrics = jax.lax.scan(one, states, (idx, active),
                                           unroll=unroll)
            return states, jax.tree.map(lambda v: v.sum(axis=0), metrics)

    # seam labels match the watchdog's dispatch labels so the compile
    # evidence and the deadline evidence line up per entry point
    return seam_jit(multi_fn,
                    label="stacked_dispatch" if stacked else "train_dispatch",
                    donate_argnums=(0,))


def stack_states(states: list[TrainState]) -> TrainState:
    """Stack K per-fold states into one state with a leading fold axis
    on every leaf (``ema=None`` stays None)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def slice_state(states: TrainState, fold_axis_index: int) -> TrainState:
    """Extract fold k's unstacked state from a stacked state — the
    checkpoint-slicing primitive (each fold saves/restores under the
    same per-fold layout the sequential path uses)."""
    return jax.tree.map(lambda x: x[fold_axis_index], states)


def _make_eval_body(model, *, num_classes: int, lb_smooth: float = 0.0,
                    preprocess_fn: Callable | None = None) -> Callable:
    """The unjitted eval body shared by the per-batch and the fused
    replay eval steps."""
    if preprocess_fn is None:
        preprocess_fn = cifar_eval_batch

    def eval_fn(params, batch_stats, images, labels, mask):
        """`mask` [B] of 0/1 marks real examples — eval batches are padded
        up to a multiple of the mesh size and the padding masked out, so
        partial final batches (reference drop_last=False eval loaders)
        still shard evenly."""
        images = preprocess_fn(images)
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, images, train=False
        )
        nll = smooth_cross_entropy(logits, labels, lb_smooth, reduce_mean=False)
        top1 = jax.lax.top_k(logits, 1)[1] == labels[:, None]
        topk = jax.lax.top_k(logits, min(5, num_classes))[1] == labels[:, None]
        return {
            "loss": (nll * mask).sum(),
            "top1": (top1.any(axis=-1) * mask).sum().astype(jnp.float32),
            "top5": (topk.any(axis=-1) * mask).sum().astype(jnp.float32),
            "num": mask.sum().astype(jnp.float32),
        }

    return eval_fn


def make_eval_step(model, *, num_classes: int, lb_smooth: float = 0.0,
                   preprocess_fn: Callable | None = None) -> Callable:
    """Build the jitted eval step: ``fn(params, batch_stats, images_u8,
    labels, mask) -> metric_sums`` (loss/top1/top5/num as sums)."""
    return seam_jit(_make_eval_body(
        model, num_classes=num_classes, lb_smooth=lb_smooth,
        preprocess_fn=preprocess_fn), label="eval_step")


def make_replay_eval_step(model, *, num_classes: int, lb_smooth: float = 0.0,
                          preprocess_fn: Callable | None = None) -> Callable:
    """Whole-split evaluation in ONE dispatch: ``fn(params, batch_stats,
    images [S, B, H, W, C], labels [S, B], masks [S, B]) -> metric_sums``
    — a ``lax.scan`` of the eval body over a device-resident stack of
    batches with the metric sums reduced in-program.

    This is the eval twin of :func:`make_multistep_train_step` for the
    device-cache replay path, and it is a CORRECTNESS fix as well as a
    perf one: evaluating a replayed split per batch queues S eval
    programs plus 4S scalar-add programs, and with a mesh-committed
    state every one of those scalar adds lowers to an all-participant
    collective — on the 8-virtual-device CPU test mesh, hundreds of
    queued tiny collectives interleave their rendezvous and DEADLOCK
    the backend (observed: eval wedged in `Accumulator.add` with XLA
    "waiting for all participants" stalls).  One fused program per
    split sequences its internal collectives correctly and leaves the
    host with a single 4-scalar read.  Forward-only, so the XLA:CPU
    conv-backward-in-while pathology (`default_dispatch_unroll`) does
    not apply — the rolled scan is fast on every backend.
    """
    body = _make_eval_body(model, num_classes=num_classes,
                           lb_smooth=lb_smooth, preprocess_fn=preprocess_fn)

    def replay_fn(params, batch_stats, images, labels, masks):
        def one(carry, batch):
            x, y, m = batch
            return carry, body(params, batch_stats, x, y, m)

        _, sums = jax.lax.scan(one, jnp.zeros(()), (images, labels, masks))
        return jax.tree.map(lambda v: v.sum(axis=0), sums)

    return seam_jit(replay_fn, label="replay_eval")
