"""Lease-based work reclamation over a shared artifact directory.

PR 5 made a single run survive faults; this layer makes the FLEET
survive losing a machine.  The search's natural work units — phase-1
fold trainings, per-fold phase-2 trial searches, gate retrains — are
already resumable from the checkpoint chain + trial log by ANY host
that can see the shared ``save_dir``; what was missing is an ownership
protocol so a unit abandoned by a dead host is picked up by exactly
one survivor.  Podracer-style pods run on preemptible hardware exactly
this way (PAPERS.md: *Podracer architectures* — work units are
reclaimable by survivors, progress lives in shared storage).

Protocol (all state lives under ``<root>/``, assumed on a filesystem
every host mounts — the same assumption the shared ``save_dir``
scatter already makes):

``leases/<unit>.json``
    The lease: ``{unit, owner, attempt, heartbeat, claimed_at}``.
    **Claim** is an atomic ``os.link`` of an owner-unique temp file
    onto the lease path — exactly one linker wins, losers see
    ``FileExistsError``.  **Renewal** rewrites the file (via
    ``write_json_atomic``) with a fresh ``heartbeat`` wall-clock stamp;
    the trainer calls it at dispatch-chunk boundaries and the phase-2
    loop per trial round.  **Reclaim** of a stale lease (heartbeat
    older than ``lease_ttl``) first renames the lease to a
    fence path — ``os.rename`` succeeds for exactly one contender —
    then claims fresh with ``attempt + 1`` and the dead owner recorded.
``done/<unit>.json``
    Completion marker (atomic write): ``{unit, owner, attempt,
    reclaimed_from, info}``.  ``attempt > 1`` is the global
    "this unit was reclaimed" signal any host can read at the end.
    The ``info`` payload doubles as the REWARD-RETURN channel of the
    fleet-search round transport (``search/pipeline.py``): an actor
    host releases a claimed round unit with ``info={"rewards": [...]}``
    (or ``{"error": ...}``) and the learner host reads it back through
    :meth:`WorkQueue.done_info`.
``work/<unit>.json``
    OPTIONAL published payload (:meth:`WorkQueue.publish_unit`): the
    dynamic-unit form of the queue.  The original scatter's units
    (``p1-fold<k>``/``p2-fold<k>``) are known to every host up front;
    round units are MINTED by the learner at ask time, so the payload
    file is both the work description (trial ids + proposals) and the
    discovery surface (:meth:`WorkQueue.open_units` lists payloads
    without done markers — the actor's claim menu).
``hosts/<owner>.json``
    Host-level heartbeat (``beat_host``): consumed by the fleet
    supervisor's wedge detector and by the degraded-mode accounting
    (a host whose beat goes stale and never completes is ``lost``).

Clocks (the skew-proof contract, docs/RESILIENCE.md "Hostile shared
filesystem"): lease staleness is OBSERVER-LOCAL — a lease is stale
when its observed fingerprint (owner, heartbeat stamp, attempt, epoch)
has not CHANGED for ``lease_ttl`` seconds on the OBSERVER'S monotonic
clock.  Cross-host wall stamps are never compared, so arbitrary
per-host clock skew (``FAA_FSFAULT skew@host=...``) cannot produce a
spurious reclaim (a live host whose clock is behind) or an immortal
zombie (a dead host whose last stamp is in the future).  The cost is
that a claimant must WATCH a foreign lease for one TTL before stealing
it — the first claim() observes and declines, a later claim() past the
TTL reclaims.  Host-beat wall stamps are still written (``make
status`` renders them, flagging beats from the observer's future as
skew suspects) but they are accounting, not correctness.

Fencing: every lease carries a monotonically increasing **epoch** (the
fencing token — Lamport's lease-fencing idiom): fresh claim = 1,
every reclaim = previous + 1, renewals carry it forward, and
:meth:`WorkQueue.release` verifies at done-marker post time that this
host still owns the lease at the epoch it claimed.  A robbed zombie's
late release therefore raises :class:`LeaseLostError` instead of
clobbering the reclaimed unit's completion record, no matter how
skewed its clock is.  Old-format leases (no epoch field) reclaim
normally and simply enter the epoch sequence at 2.

A stolen owner discovers the loss at its next renewal
(``LeaseLostError``) and must stop working the unit — both sides write
checkpoints through the same atomic chain, so the worst case of a
slow-but-alive owner racing its reclaimer is duplicated compute, never
corrupted state (writes are idempotent: same seeds, same chain).

Fault injection: ``FAA_FAULT=stale_lease@unit=NAME`` drops renewals
for NAME from the first match onward, driving the reclaim path
deterministically in tests; ``FAA_FSFAULT`` (``core/fsfault.py``)
injects shared-filesystem faults under every read/list/write this
module performs (docs/RESILIENCE.md).
"""

from __future__ import annotations

import os
import time

from fast_autoaugment_tpu.core import fsfault, telemetry
from fast_autoaugment_tpu.utils import faultinject
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["WorkQueue", "LeaseLostError", "DEFAULT_LEASE_TTL_SEC"]

logger = get_logger("faa_tpu.workqueue")

DEFAULT_LEASE_TTL_SEC = 60.0


class LeaseLostError(RuntimeError):
    """This host's lease on a unit was reclaimed by another host (it
    missed enough heartbeats to be declared dead).  The worker must
    stop working the unit immediately — a survivor owns it now."""


def _read_json(path: str) -> dict | None:
    # missing, mid-replace, or torn by a dead writer: treated as
    # absent — every writer is atomic, so this is transient (the
    # fsfault seam additionally injects lag/stale/eio/torn here)
    return fsfault.read_json(path)


class _StalenessObserver:
    """Observer-local staleness: a record is stale when its observed
    fingerprint has not changed for `ttl` seconds on THIS process's
    monotonic clock.  Never compares cross-host wall stamps — the
    skew-proof half of the lease contract."""

    def __init__(self):
        self._seen: dict[str, tuple[tuple, float]] = {}

    def unchanged_for(self, key: str, fingerprint: tuple) -> float:
        """Seconds the fingerprint has been observed unchanged (0.0 on
        first sight or on any change)."""
        now = time.monotonic()
        prev = self._seen.get(key)
        if prev is None or prev[0] != fingerprint:
            self._seen[key] = (fingerprint, now)
            return 0.0
        return now - prev[1]

    def forget(self, key: str) -> None:
        self._seen.pop(key, None)


class WorkQueue:
    """One host's handle on the shared lease queue.

    `owner` must be unique per live process chain (the fleet passes
    ``host<id>``; a relaunched process REUSES its dead predecessor's
    owner string and may re-claim its own stale lease without waiting
    out the TTL — the predecessor is guaranteed dead by the supervisor
    before the relaunch)."""

    def __init__(self, root: str, owner: str, *,
                 lease_ttl: float = DEFAULT_LEASE_TTL_SEC):
        self.root = root
        self.owner = str(owner)
        self.lease_ttl = float(lease_ttl)
        self._leases = os.path.join(root, "leases")
        self._done = os.path.join(root, "done")
        self._hosts = os.path.join(root, "hosts")
        self._work = os.path.join(root, "work")
        for d in (self._leases, self._done, self._hosts, self._work):
            os.makedirs(d, exist_ok=True)
        #: units THIS host reclaimed from a dead owner (session-local;
        #: the global view comes from the done markers' attempt counts)
        self.reclaimed_units: list[str] = []
        #: observer-local staleness state (see module docstring)
        self._observer = _StalenessObserver()
        #: unit -> the lease epoch THIS host claimed at (the fencing
        #: token release() verifies at done-marker post time)
        self._held_epochs: dict[str, int] = {}

    def _lease_event(self, action: str, unit: str, **fields) -> None:
        """Registry counter + journal ``lease`` event for one lease
        transition (claim/reclaim/lost/release) — the fleet-status tool
        and the trace export read these (core/telemetry.py)."""
        telemetry.registry().counter(
            "faa_lease_events_total", "workqueue lease transitions",
            action=action).inc()
        telemetry.emit("lease", unit, action=action, owner=self.owner,
                       **fields)

    # -- paths ---------------------------------------------------------
    def _lease_path(self, unit: str) -> str:
        return os.path.join(self._leases, f"{_safe(unit)}.json")

    def _done_path(self, unit: str) -> str:
        return os.path.join(self._done, f"{_safe(unit)}.json")

    def _host_path(self, owner: str) -> str:
        return os.path.join(self._hosts, f"{_safe(owner)}.json")

    def _work_path(self, unit: str) -> str:
        return os.path.join(self._work, f"{_safe(unit)}.json")

    # -- dynamic (published) units --------------------------------------
    def publish_unit(self, unit: str, payload: dict) -> None:
        """Mint a claimable unit with an atomic payload write (the
        round-unit verb of the fleet-search transport).  Idempotent:
        re-publishing after a learner resume rewrites the identical
        payload (same ids, same proposals — the ledger replay is
        deterministic), so claimants can never read a torn or
        half-updated description."""
        fsfault.write_json_atomic(self._work_path(unit),
                                  dict(payload, unit=unit))

    def unit_payload(self, unit: str) -> dict | None:
        """The published payload for `unit`, or None (never torn — the
        writer is atomic)."""
        return _read_json(self._work_path(unit))

    def open_units(self, prefix: str = "") -> list[str]:
        """Published units with NO done marker yet, sorted — the claim
        menu for actor hosts.  A unit under a live foreign lease still
        lists (claim() on it just returns False); a done unit never
        does."""
        try:
            names = fsfault.listdir(self._work)
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json"):
                continue
            unit = name[:-5]
            if unit.startswith(prefix) and not self.is_done(unit):
                out.append(unit)
        return out

    # -- host heartbeat ------------------------------------------------
    def beat_host(self, extra: dict | None = None) -> None:
        """Write this host's liveness beat (fleet wedge detector +
        degraded accounting read it).  Stamped through the telemetry
        ``wall()`` seam so the FAA_FSFAULT skew verb drills the
        skewed-heartbeat case."""
        rec = {"owner": self.owner, "heartbeat": telemetry.wall(),
               "pid": os.getpid()}
        if extra:
            rec.update(extra)
        fsfault.write_json_atomic(self._host_path(self.owner), rec)

    def mark_host_done(self, info: dict | None = None) -> None:
        """Terminal host beat: a host that said ``done`` and then goes
        quiet is finished, not lost."""
        self.beat_host(dict(info or {}, done=True))

    # -- lease lifecycle -----------------------------------------------
    @staticmethod
    def _lease_fingerprint(lease: dict) -> tuple:
        """What "the lease changed" means to the observer: any owner /
        heartbeat-stamp / attempt / epoch movement resets staleness.
        The heartbeat VALUE is compared for identity only — never
        against the observer's clock (skew-proof)."""
        return (lease.get("owner"), lease.get("heartbeat"),
                lease.get("attempt"), lease.get("epoch"))

    def claim(self, unit: str) -> bool:
        """Try to take ownership of `unit`.  True = this host owns it
        (fresh claim, its own prior lease, or a stale-lease reclaim);
        False = done already, another host holds a live lease, or a
        foreign lease has not yet been OBSERVED unchanged for the TTL
        (a later claim() past the TTL reclaims it)."""
        if self.is_done(unit):
            return False
        path = self._lease_path(unit)
        lease = _read_json(path)
        if lease is None:
            claimed = self._claim_fresh(unit, attempt=1, epoch=1)
            if claimed:
                self._held_epochs[unit] = 1
                self._lease_event("claim", unit, lease_attempt=1,
                                  lease_epoch=1)
            return claimed
        if lease.get("owner") == self.owner:
            # our own lease (a relaunch of this owner resuming its
            # unit): refresh the heartbeat and carry on at the SAME
            # epoch (the predecessor is guaranteed dead — same owner
            # string means the supervisor relaunched us)
            epoch = int(lease.get("epoch", 1))
            self._write_lease(unit, attempt=int(lease.get("attempt", 1)),
                              epoch=epoch,
                              reclaimed_from=lease.get("reclaimed_from"))
            self._held_epochs[unit] = epoch
            return True
        # foreign lease: observer-local staleness — stale only once WE
        # have watched the fingerprint sit unchanged for a full TTL on
        # OUR monotonic clock (cross-host wall stamps are never
        # compared; arbitrary skew cannot fake liveness or death)
        unchanged = self._observer.unchanged_for(
            f"lease:{unit}", self._lease_fingerprint(lease))
        if unchanged <= self.lease_ttl:
            return False  # live elsewhere (or not yet proven dead)
        # stale: steal under a fence FILE (exactly one linker wins) so
        # the lease path itself never disappears — a remove-then-
        # recreate window would let a racing fresh claim land with
        # attempt=1 and silently drop the reclaim provenance
        if not self._win_steal_fence(unit):
            return False
        fence = self._lease_path(unit) + ".steal"
        try:
            current = _read_json(path)
            if current is None or \
                    current.get("owner") != lease.get("owner") or \
                    current.get("heartbeat") != lease.get("heartbeat"):
                # renewed/released/re-stolen while we raced: not stale
                return False
            dead_owner = lease.get("owner", "?")
            attempt = int(lease.get("attempt", 1)) + 1
            epoch = int(lease.get("epoch", 1)) + 1
            logger.warning(
                "workqueue: RECLAIMING unit %r from %r (lease observed "
                "unchanged %.1fs, ttl %.1fs) — attempt %d epoch %d",
                unit, dead_owner, unchanged, self.lease_ttl, attempt,
                epoch)
            # in-place replace: no absence window for fresh claims
            self._write_lease(unit, attempt=attempt, epoch=epoch,
                              reclaimed_from=dead_owner)
            self._held_epochs[unit] = epoch
            self._observer.forget(f"lease:{unit}")
            self.reclaimed_units.append(unit)
            self._lease_event("reclaim", unit, lease_attempt=attempt,
                              lease_epoch=epoch,
                              reclaimed_from=dead_owner,
                              observed_stale_sec=round(unchanged, 3))
            return True
        finally:
            try:
                os.remove(fence)
            except OSError as e:
                logger.warning("workqueue: fence cleanup failed (%s)", e)

    def _win_steal_fence(self, unit: str) -> bool:
        """Atomically take the per-unit steal fence (``<lease>.steal``).
        A fence left by a stealer that died mid-steal unblocks after
        being OBSERVED unchanged for the TTL (observer-local, like the
        lease itself — a skewed stealer's future stamp cannot wedge
        the unit)."""
        fence = self._lease_path(unit) + ".steal"
        stale = _read_json(fence)
        if stale is not None and self._observer.unchanged_for(
                f"fence:{unit}", (stale.get("owner"), stale.get("at"))
        ) > self.lease_ttl:
            try:
                os.remove(fence)  # dead stealer's leftover
                self._observer.forget(f"fence:{unit}")
            except OSError as e:
                logger.warning("workqueue: stale fence cleanup failed (%s)", e)
        tmp = fence + f".{_safe(self.owner)}.{os.getpid()}"
        fsfault.write_json_atomic(
            tmp, {"owner": self.owner, "at": telemetry.wall()})
        try:
            os.link(tmp, fence)
            return True
        except FileExistsError:
            return False
        except OSError as e:
            logger.warning("workqueue: steal fence failed for %r (%s)",
                           unit, e)
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError as e:
                logger.warning("workqueue: fence tmp cleanup failed (%s)", e)

    def _claim_fresh(self, unit: str, attempt: int, epoch: int,
                     reclaimed_from: str | None = None) -> bool:
        path = self._lease_path(unit)
        tmp = path + f".claim.{_safe(self.owner)}.{os.getpid()}"
        fsfault.write_json_atomic(
            tmp, self._lease_record(unit, attempt, epoch, reclaimed_from))
        try:
            os.link(tmp, path)  # atomic test-and-set
            return True
        except FileExistsError:
            return False
        except OSError as e:
            logger.warning("workqueue: claim link failed for %r (%s)",
                           unit, e)
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError as e:
                logger.warning("workqueue: claim tmp cleanup failed (%s)", e)

    def _lease_record(self, unit: str, attempt: int, epoch: int,
                      reclaimed_from: str | None) -> dict:
        rec = {"unit": unit, "owner": self.owner, "attempt": int(attempt),
               "epoch": int(epoch), "heartbeat": telemetry.wall(),
               "claimed_at": telemetry.wall()}
        if reclaimed_from:
            rec["reclaimed_from"] = reclaimed_from
        return rec

    def _write_lease(self, unit: str, attempt: int, epoch: int,
                     reclaimed_from: str | None = None) -> None:
        fsfault.write_json_atomic(
            self._lease_path(unit),
            self._lease_record(unit, attempt, epoch, reclaimed_from))

    def renew(self, unit: str) -> None:
        """Heartbeat the lease (called at dispatch/round boundaries).
        Raises :class:`LeaseLostError` when another host reclaimed the
        unit — the caller must abandon it."""
        fi = faultinject.active_plan()
        if fi is not None and fi.lease_stale(unit):
            return  # injected wedged-heartbeat: silently drop the beat
        lease = _read_json(self._lease_path(unit))
        if lease is None or lease.get("owner") != self.owner:
            self._lease_event("lost", unit,
                              new_owner=None if lease is None
                              else lease.get("owner"))
            raise LeaseLostError(
                f"lease on {unit!r} is {'gone' if lease is None else 'owned by ' + repr(lease.get('owner'))}"
                f" — this host was declared dead and the unit reclaimed")
        epoch = int(lease.get("epoch", self._held_epochs.get(unit, 1)))
        self._write_lease(unit, attempt=int(lease.get("attempt", 1)),
                          epoch=epoch,
                          reclaimed_from=lease.get("reclaimed_from"))
        self._held_epochs[unit] = epoch

    def release(self, unit: str, info: dict | None = None) -> None:
        """Mark `unit` complete (atomic done marker) and drop the
        lease.  Idempotent for the legitimate owner; the done marker
        records the final owner, attempt count AND lease epoch — the
        global reclaim evidence.

        FENCING (verified at done-marker post time): if another host
        reclaimed the unit — the lease's owner or epoch moved past what
        THIS host claimed — the release raises :class:`LeaseLostError`
        instead of writing, so a robbed zombie's late completion can
        never clobber the reclaimed unit's record, under any clock
        skew."""
        lease = _read_json(self._lease_path(unit))
        held = self._held_epochs.get(unit)
        if lease is not None and lease.get("owner") != self.owner:
            self._lease_event("fenced", unit,
                              new_owner=lease.get("owner"),
                              lease_epoch=lease.get("epoch"))
            raise LeaseLostError(
                f"done-marker post for {unit!r} FENCED: the lease is "
                f"owned by {lease.get('owner')!r} at epoch "
                f"{lease.get('epoch')} (this host claimed epoch {held}) "
                "— the unit was reclaimed; abandoning the late write")
        if lease is not None and held is not None \
                and int(lease.get("epoch", 1)) != held:
            self._lease_event("fenced", unit,
                              lease_epoch=lease.get("epoch"))
            raise LeaseLostError(
                f"done-marker post for {unit!r} FENCED: lease epoch "
                f"{lease.get('epoch')} != claimed epoch {held}")
        existing = _read_json(self._done_path(unit))
        if existing is not None:
            if existing.get("owner") == self.owner:
                return  # idempotent re-release
            if int(existing.get("epoch", 1)) >= (held or 1):
                self._lease_event("fenced", unit,
                                  done_owner=existing.get("owner"),
                                  done_epoch=existing.get("epoch"))
                raise LeaseLostError(
                    f"done-marker post for {unit!r} FENCED: "
                    f"{existing.get('owner')!r} already completed it at "
                    f"epoch {existing.get('epoch')} >= {held or 1}")
        lease = lease or {}
        epoch = int(lease.get("epoch", held or 1))
        rec = {"unit": unit, "owner": self.owner,
               "attempt": int(lease.get("attempt", 1)),
               "epoch": epoch, "completed_at": telemetry.wall()}
        if lease.get("reclaimed_from"):
            rec["reclaimed_from"] = lease["reclaimed_from"]
        if info:
            rec["info"] = info
        fsfault.write_json_atomic(self._done_path(unit), rec)
        self._lease_event("release", unit, lease_attempt=rec["attempt"],
                          lease_epoch=epoch)
        self._held_epochs.pop(unit, None)
        if lease.get("owner") == self.owner:
            try:
                os.remove(self._lease_path(unit))
            except OSError as e:
                logger.warning("workqueue: lease cleanup failed for %r (%s)",
                               unit, e)

    # -- read side -----------------------------------------------------
    def is_done(self, unit: str) -> bool:
        return _read_json(self._done_path(unit)) is not None

    def done_info(self, unit: str) -> dict | None:
        """The completion marker's ``info`` payload (gate exclusions,
        baselines, posted rewards — whatever the finishing host
        stamped), or None."""
        rec = _read_json(self._done_path(unit))
        return None if rec is None else rec.get("info") or {}

    def done_record(self, unit: str) -> dict | None:
        """The FULL completion marker (owner, attempt, completed_at,
        info) — the reward-return reader needs the provenance fields
        the plain ``done_info`` view drops."""
        return _read_json(self._done_path(unit))

    def read_lease(self, unit: str) -> dict | None:
        return _read_json(self._lease_path(unit))

    def known_hosts(self) -> dict[str, dict]:
        out = {}
        try:
            names = fsfault.listdir(self._hosts)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self._hosts, name))
            if rec and rec.get("owner"):
                out[rec["owner"]] = rec
        return out

    def lost_hosts(self) -> list[str]:
        """Hosts whose beat went stale WITHOUT a terminal done beat.
        The caller itself is excluded — a host computing the census is
        self-evidently alive, however long its last compile gap was.

        This census is wall-based ACCOUNTING (who to report as lost),
        not correctness — reclaim decisions use the observer-local
        lease protocol above.  A beat stamped in the observer's future
        (clock skew) counts as |age| so a skewed dead host is still
        reported once its beat stops moving."""
        now = time.time()
        return sorted(
            owner for owner, rec in self.known_hosts().items()
            if owner != self.owner and not rec.get("done")
            and abs(now - float(rec.get("heartbeat", 0.0))) > self.lease_ttl)

    def accounting(self) -> dict:
        """The degraded-mode stamp for ``search_result.json``: global
        reclaim evidence (done markers with attempt > 1) + host
        census.  Any surviving host computes the same answer."""
        reclaimed = []
        try:
            names = fsfault.listdir(self._done)
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(self._done, name))
            if rec and int(rec.get("attempt", 1)) > 1:
                reclaimed.append({
                    "unit": rec.get("unit", name[:-5]),
                    "attempt": rec["attempt"],
                    "epoch": int(rec.get("epoch", rec["attempt"])),
                    "finished_by": rec.get("owner"),
                    "reclaimed_from": rec.get("reclaimed_from")})
        lost = self.lost_hosts()
        return {
            "degraded": bool(reclaimed or lost),
            "lost_hosts": lost,
            "reclaimed_units": reclaimed,
            "num_reclaimed_units": len(reclaimed),
        }


def _safe(name: str) -> str:
    """Unit/owner id -> filename (no separators/parent escapes)."""
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in str(name))
