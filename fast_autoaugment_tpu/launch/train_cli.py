"""Training CLI — the ``python FastAutoAugment/train.py -c conf.yaml``
equivalent (reference ``train.py:325-356``).

    python -m fast_autoaugment_tpu.launch.train_cli -c confs/wresnet40x2_cifar.yaml \
        --dataroot /data --save ckpt/wrn.msgpack --tag wrn40x2

Multi-host: run the SAME command on every host (JAX multi-controller;
``--coordinator host0:1234 --num-hosts N --host-id k`` or TPU-pod
auto-detection) — there is no torch.distributed.launch equivalent to
wrangle, which is the point.
"""

from __future__ import annotations

import argparse
import json
import time

from fast_autoaugment_tpu.core.config import load_config
from fast_autoaugment_tpu.core.resilience import (
    PREEMPTED_EXIT_CODE,
    DispatchHungError,
    PreemptedError,
    install_signal_handlers,
)
from fast_autoaugment_tpu.train.trainer import train_and_eval
from fast_autoaugment_tpu.utils.logging import add_filehandler, get_logger

logger = get_logger("faa_tpu.train_cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="fast-autoaugment-tpu trainer")
    p.add_argument("-c", "--conf", required=True, help="YAML preset (confs/*.yaml)")
    p.add_argument("--dataroot", default="./data")
    p.add_argument("--save", default="", help="checkpoint path (.msgpack)")
    p.add_argument("--tag", default="")
    p.add_argument("--cv-ratio", type=float, default=0.0)
    p.add_argument("--cv", type=int, default=0, help="CV resample index")
    p.add_argument("--only-eval", action="store_true")
    p.add_argument("--evaluation-interval", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--aug-dispatch", default="exact",
                   choices=("exact", "grouped"),
                   help="policy-application kernel: 'exact' (default) is "
                        "the per-image vmapped-switch path bit-for-bit; "
                        "'grouped' keeps op dispatch scalar (one lax.switch "
                        "branch executes; stratified per-chunk sub-policy "
                        "draws — docs/BENCHMARKS.md 'Augmentation dispatch')")
    p.add_argument("--aug-groups", type=int, default=8,
                   help="chunks per batch for --aug-dispatch grouped")
    p.add_argument("--device-cache", default="auto",
                   choices=("auto", "on", "off"),
                   help="device-resident data path: upload the eager "
                        "dataset to HBM once (sharded over the mesh data "
                        "axis) and gather batches by index INSIDE the "
                        "compiled step — no per-step host image copy.  "
                        "'auto' (default) enables it for in-memory "
                        "datasets on a single host (bit-for-bit with the "
                        "host feed at --steps-per-dispatch 1); lazy "
                        "ImageNet datasets keep the prefetch path; 'on' "
                        "errors where auto would fall back "
                        "(docs/BENCHMARKS.md 'Step dispatch & device "
                        "cache')")
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="fuse N train steps into ONE dispatch (lax.scan "
                        "over the device cache; needs --device-cache "
                        "auto/on).  1 (default) = the historical "
                        "one-dispatch-per-step loop bit-for-bit; N>1 "
                        "deviates by the documented ~1 f32 ULP/step scan "
                        "bound and amortizes per-dispatch host overhead")
    p.add_argument("--divergence-retries", type=int, default=0,
                   help="on a NaN/inf epoch loss, roll back to the newest "
                        "intact checkpoint and replay with retry-folded "
                        "randomness up to R times before re-raising.  0 "
                        "(default) = the historical immediate raise "
                        "(docs/RESILIENCE.md)")
    p.add_argument("--ckpt-keep", type=int, default=2,
                   help="rollback-chain depth: the live checkpoint plus "
                        "N-1 predecessors (path, path.prev, ...).  Restore "
                        "walks to the newest INTACT link (sha256-verified), "
                        "so one torn/corrupt file costs an epoch, not the "
                        "run.  1 = the pre-chain overwrite-in-place")
    p.add_argument("--ckpt-every-dispatch", type=int, default=0,
                   help="checkpoint every M dispatch chunks MID-epoch "
                        "(device-cache path only; resumable bit-identically "
                        "from the exact dispatch boundary).  0 (default) = "
                        "checkpoint at evaluation epochs only")
    p.add_argument("--watchdog", default="off",
                   help="dispatch watchdog {off,auto,SECONDS}: run every "
                        "train dispatch / eval replay under a deadline "
                        "(auto = EMA of observed dispatch wall times with "
                        "a generous first-call compile allowance) and "
                        "treat expiry as a HUNG dispatch — exit 77 so the "
                        "supervisor relaunches and the rerun resumes from "
                        "the newest checkpoint-chain link (pair with "
                        "--ckpt-every-dispatch to bound replayed work).  "
                        "'off' (default) keeps the historical async "
                        "dispatch bit-for-bit (docs/RESILIENCE.md)")
    p.add_argument("--compile-cache", default="off", metavar="{off,DIR}",
                   help="persistent XLA compilation cache: point JAX's "
                        "on-disk executable cache at DIR so a fresh "
                        "process (exit-77 resume, fleet retry) "
                        "deserializes its executables instead of "
                        "re-paying the 23-55s first compile; hit/miss "
                        "counts are logged and stamped in the result.  "
                        "'off' (default) = the historical behavior "
                        "(still honors an inherited FAA_COMPILE_CACHE; "
                        "caching never changes numerics)")
    p.add_argument("--telemetry", default="off", metavar="{off,DIR}",
                   help="flight-recorder journal (core/telemetry.py): "
                        "typed dispatch/compile/checkpoint events under "
                        "DIR with rotation-bounded size, renderable as a "
                        "Chrome trace via tools/trace_export.py.  'off' "
                        "(default, bit-for-bit — no journal I/O) still "
                        "honors an inherited FAA_TELEMETRY")
    p.add_argument("--telemetry-port", type=int, default=0,
                   help="serve GET /metrics (Prometheus text exposition "
                        "of the in-memory telemetry registry, read-only) "
                        "while training runs.  0 = off")
    p.add_argument("--coordinator", default=None, help="host0 addr for multi-host")
    p.add_argument("--num-hosts", type=int, default=None)
    p.add_argument("--host-id", type=int, default=None)
    p.add_argument("override", nargs="*", help="dotted conf overrides key=value")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.coordinator:
        from fast_autoaugment_tpu.parallel.mesh import distributed_init

        distributed_init(args.coordinator, args.num_hosts, args.host_id)

    conf = load_config(args.conf, overrides=args.override)
    if args.tag:
        add_filehandler(logger, f"train_{args.tag}.log")
    if args.only_eval and not args.save:
        logger.warning("--only-eval requires --save (reference train.py:337)")
        raise SystemExit(1)

    # SIGTERM/SIGUSR1 -> graceful preemption: checkpoint at the next
    # safe boundary, exit 77 ("resume me" — docs/RESILIENCE.md)
    install_signal_handlers()
    from fast_autoaugment_tpu.core import telemetry

    telemetry.configure_telemetry(args.telemetry)
    metrics_httpd = None
    if args.telemetry_port:
        metrics_httpd, _port = telemetry.start_metrics_server(
            args.telemetry_port)
    t0 = time.time()
    try:
        result = train_and_eval(
            conf,
            args.dataroot,
            test_ratio=args.cv_ratio,
            cv_fold=args.cv,
            save_path=args.save or None,
            only_eval=args.only_eval,
            evaluation_interval=args.evaluation_interval,
            metric="last",
            seed=args.seed,
            aug_dispatch=args.aug_dispatch,
            aug_groups=args.aug_groups,
            device_cache=args.device_cache,
            steps_per_dispatch=args.steps_per_dispatch,
            divergence_retries=args.divergence_retries,
            ckpt_keep=args.ckpt_keep,
            checkpoint_every_dispatch=args.ckpt_every_dispatch,
            watchdog=args.watchdog,
            compile_cache=args.compile_cache,
        )
    except PreemptedError as e:
        logger.warning("preempted (%s) — exiting %d so the supervisor "
                       "resumes this run", e, PREEMPTED_EXIT_CODE)
        telemetry.emit("preempt", "train_cli", kind="preempted",
                       exit_code=PREEMPTED_EXIT_CODE)
        raise SystemExit(PREEMPTED_EXIT_CODE)
    except DispatchHungError as e:
        logger.error("dispatch HUNG (%s) — in-flight device state is "
                     "unrecoverable; exiting %d so the supervisor "
                     "relaunches and the rerun resumes from the newest "
                     "checkpoint-chain link", e, PREEMPTED_EXIT_CODE)
        telemetry.emit("preempt", "train_cli", kind="dispatch_hung",
                       label_detail=e.label, exit_code=PREEMPTED_EXIT_CODE)
        raise SystemExit(PREEMPTED_EXIT_CODE)
    finally:
        if metrics_httpd is not None:
            metrics_httpd.shutdown()
    elapsed = time.time() - t0
    cc = result.get("compile_cache") or {}
    if cc:
        # grep-stable line: the exit-77 resume e2e asserts the RESUMED
        # process reports hits here (docs/RESILIENCE.md resume cost)
        logger.info("compile cache: dir=%s hits=%d misses=%d "
                    "first_step_secs=%.3f", cc.get("dir"),
                    cc.get("hits", 0), cc.get("misses", 0),
                    cc.get("first_step_secs", 0.0))
    logger.info("done %s: %s", args.tag, json.dumps(
        {k: round(v, 5) if isinstance(v, float) else v for k, v in result.items()}))
    logger.info("elapsed: %.1f s (%.2f h)", elapsed, elapsed / 3600.0)
    return result


if __name__ == "__main__":
    main()
