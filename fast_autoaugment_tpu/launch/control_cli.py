"""Control-plane CLI — run the closed drift->promote loop against a
live serving fleet (docs/CONTROL.md).

    python -m fast_autoaugment_tpu.launch.control_cli \
        --telemetry /shared/run --port-dir /shared/run/replicas \
        --router-url http://127.0.0.1:8780 \
        --baseline-policy search_out/final_policy.json \
        --research-cmd "python -m fast_autoaugment_tpu.launch.search_cli \
            -c confs/wresnet40x2_cifar10.yaml --save-dir {out} \
            --num-search 200 --topup-trials 25 --async-pipeline on"

The loop tails the fleet's telemetry journal (replicas run with
``--traffic-stats --telemetry DIR``), raises a typed ``drift`` verdict
when the seeded CUSUM trips, runs the WARM-STARTED re-search command
(``{out}`` is replaced by a fresh candidate dir seeded from
``--base-search-dir``'s trial log + fold checkpoints; the command must
leave ``{out}/final_policy.json``), canaries the candidate onto the
rendezvous-selected replica subset via digest-verified ``POST
/reload``, splits traffic through the router's ``POST /canary`` admin,
and promotes fleet-wide or rolls back on the served-quality delta
gate — every stage a typed journal event, renderable end to end with
``make trace`` and summarized by ``make status``.

Fleet supervision: ``launch/fleet.py --no-rank-args --roles control``
runs this CLI exactly like a serving replica — ``--heartbeat-dir``
writes fleet-schema host beats so ``--heartbeat-timeout`` covers a
wedged controller, and SIGTERM exits 0 after stopping the loop.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import threading

from fast_autoaugment_tpu.utils.logging import get_logger

logger = get_logger("faa_tpu.control_cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="fast-autoaugment-tpu closed-loop control plane")
    p.add_argument("--telemetry", required=True, metavar="DIR",
                   help="the SHARED flight-recorder journal dir: the "
                        "drift monitor tails the replicas' serve "
                        "dispatch events here, and the loop's own "
                        "drift/research/canary/promote events land in "
                        "the same journal (one causal chain for make "
                        "trace)")
    p.add_argument("--port-dir", required=True, metavar="DIR",
                   help="replica-discovery dir (serve_cli --port-dir): "
                        "the census the canary rollout and fleet-wide "
                        "promotion actuate against")
    p.add_argument("--router-url", default=None,
                   help="router address (host:port or http://...) for "
                        "the POST /canary traffic-split admin; omit to "
                        "rely on replica-count splitting alone")
    p.add_argument("--baseline-policy", required=True,
                   help="the final_policy.json currently serving — the "
                        "rollback target (refreshed on every promotion)")
    # ---------------- re-search seam ---------------------------------
    p.add_argument("--research-cmd", default=None,
                   help="warm-started re-search command; '{out}' is "
                        "replaced by the candidate dir (seeded from "
                        "--base-search-dir), '{base}' by the base dir. "
                        "Must exit 0 leaving {out}/final_policy.json. "
                        "Typically search_cli with --topup-trials + "
                        "--async-pipeline on")
    p.add_argument("--base-search-dir", default=None, metavar="DIR",
                   help="the completed search dir whose trial log + "
                        "fold checkpoints seed each re-search (default: "
                        "the --baseline-policy file's directory)")
    p.add_argument("--candidate-dir", default=None, metavar="DIR",
                   help="where candidate dirs are created (default: "
                        "<base-search-dir>/research); episode i uses "
                        "<candidate-dir>/episode<i>")
    p.add_argument("--candidate-policy", default=None,
                   help="drill mode: a PRE-BUILT candidate policy JSON "
                        "served instead of running --research-cmd "
                        "(mutually exclusive with it)")
    # ---------------- drift monitor ----------------------------------
    p.add_argument("--drift-metrics", default="input_mean,reward_proxy",
                   help="comma list of served-traffic fields to watch "
                        "(the --traffic-stats journal fields)")
    p.add_argument("--baseline-samples", type=int, default=20,
                   help="dispatch samples frozen into the CUSUM "
                        "baseline window")
    p.add_argument("--cusum-k", type=float, default=1.5,
                   help="CUSUM slack in baseline sigmas (absorbs both "
                        "in-band noise AND the frozen window's "
                        "estimation error — see control/drift.py)")
    p.add_argument("--cusum-h", type=float, default=10.0,
                   help="CUSUM decision threshold in sigmas")
    # ---------------- canary + gate ----------------------------------
    p.add_argument("--canary-replicas", type=int, default=1,
                   help="replicas in the canary subset (>= 1 replica "
                        "always stays baseline)")
    p.add_argument("--split-every", type=int, default=2,
                   help="router split: every Nth digest-less request "
                        "routes to the canary arm")
    p.add_argument("--gate-polls", type=int, default=3,
                   help="judgeable comparison polls before the gate "
                        "decides")
    p.add_argument("--quality-margin", type=float, default=0.05,
                   help="non-inferiority bound on the canary-minus-"
                        "baseline median quality distance "
                        "(|reward_proxy - pre-drift baseline|)")
    p.add_argument("--gate-timeout-polls", type=int, default=50,
                   help="polls before a traffic-starved gate window "
                        "rolls back")
    p.add_argument("--min-arm-dispatches", type=float, default=1.0,
                   help="fresh dispatches BOTH arms need per poll for "
                        "it to count as judgeable")
    # ---------------- process ----------------------------------------
    p.add_argument("--resume", action="store_true",
                   help="crash recovery: reconstruct a dangling "
                        "episode from the journal WAL (a controller "
                        "SIGKILLed mid-canary leaves the router split "
                        "armed forever) and re-enter its stage "
                        "idempotently — the episode terminates in a "
                        "journaled promote or rollback.  Pre-crash "
                        "traffic is skipped, never replayed into the "
                        "fresh baseline")
    p.add_argument("--poll-interval", type=float, default=1.0)
    p.add_argument("--research-timeout", type=float, default=3600.0,
                   help="wall bound on one --research-cmd run (a wedged "
                        "re-search must not pin the loop forever)")
    p.add_argument("--reload-timeout", type=float, default=300.0,
                   help="per-replica POST /reload bound (covers the "
                        "off-to-the-side AOT warm)")
    p.add_argument("--control-seconds", type=float, default=0.0,
                   help="exit 0 after this many seconds (bounded "
                        "drills).  0 = run forever")
    p.add_argument("--heartbeat-dir", default=None, metavar="DIR",
                   help="write fleet-schema host beats to DIR/hosts/ so "
                        "fleet --heartbeat-timeout covers a wedged "
                        "controller")
    p.add_argument("--host-tag", default=None,
                   help="host beat tag (default host<FAA_HOST_ID or 0>)")
    p.add_argument("--stats-file", default=None, metavar="PATH",
                   help="write the loop's final stats JSON to PATH on "
                        "exit (drills read it)")
    return p


def _make_research_fn(args):
    """The stage-two seam: a pre-built candidate (drill mode) or the
    --research-cmd subprocess over a freshly seeded candidate dir."""
    from fast_autoaugment_tpu.control.research import (
        seed_research_dir,
        load_provenance,
        policy_file_digest,
        write_provenance,
    )

    base_dir = args.base_search_dir or os.path.dirname(
        os.path.abspath(args.baseline_policy))
    cand_root = args.candidate_dir or os.path.join(base_dir, "research")
    episode = {"n": 0}

    def _stamp(policy_path: str, verdict: dict, extra: dict) -> dict:
        if load_provenance(policy_path) is None:
            write_provenance(policy_path, {
                "kind": extra.get("kind", "control_candidate"),
                "drift": verdict, **extra})
        prov = load_provenance(policy_path)
        if prov is None:  # sidecar write raced/failed: digest directly
            prov = {"policy_digest": policy_file_digest(policy_path)}
        return prov

    def research(verdict: dict) -> dict:
        episode["n"] += 1
        if args.candidate_policy:
            prov = _stamp(args.candidate_policy, verdict,
                          {"kind": "prebuilt_candidate"})
            return {"policy": args.candidate_policy, "provenance": prov}
        out_dir = os.path.join(cand_root, f"episode{episode['n']}")
        seeded = seed_research_dir(base_dir, out_dir)
        cmd = args.research_cmd.replace("{out}", out_dir) \
                               .replace("{base}", base_dir)
        logger.info("re-search episode %d: %s", episode["n"], cmd)
        proc = subprocess.run(shlex.split(cmd), cwd=os.getcwd(),
                              timeout=args.research_timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"research command exited {proc.returncode}")
        policy_path = os.path.join(out_dir, "final_policy.json")
        if not os.path.exists(policy_path):
            raise RuntimeError(
                f"research command left no {policy_path}")
        prov = _stamp(policy_path, verdict,
                      {"kind": "warm_started_research",
                       "base_dir": os.path.abspath(base_dir),
                       "seeded_files": seeded,
                       "episode": episode["n"]})
        return {"policy": policy_path, "provenance": prov}

    return research


def _beat_loop(stop: threading.Event, beat_dir: str, tag: str,
               interval_s: float) -> None:
    from fast_autoaugment_tpu.serve.serve_cli import _write_beat

    host_dir = os.path.join(beat_dir, "hosts")
    os.makedirs(host_dir, exist_ok=True)
    path = os.path.join(host_dir, f"{tag}.json")
    while not stop.wait(interval_s):
        try:
            _write_beat(path, tag)
        except OSError as e:
            logger.warning("host beat write failed: %s", e)
    try:
        _write_beat(path, tag, done=True)
    except OSError as e:
        logger.warning("final host beat write failed: %s", e)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if bool(args.research_cmd) == bool(args.candidate_policy):
        build_parser().error(
            "give exactly one of --research-cmd / --candidate-policy")
    from fast_autoaugment_tpu.core.telemetry import configure_telemetry
    from fast_autoaugment_tpu.control import (
        CanaryController,
        ControlLoop,
        DriftMonitor,
        PromotionGate,
        ReplicaQualityScraper,
        TrafficSampleReader,
    )
    from fast_autoaugment_tpu.control.research import policy_file_digest
    from fast_autoaugment_tpu.serve.router import discover_replicas

    configure_telemetry(args.telemetry)
    metrics = tuple(m.strip() for m in args.drift_metrics.split(",")
                    if m.strip())
    reader = TrafficSampleReader(args.telemetry, fields=metrics)
    monitor = DriftMonitor(reader.poll, metrics=metrics,
                           baseline_n=args.baseline_samples,
                           cusum_k=args.cusum_k, cusum_h=args.cusum_h)
    canary_ctl = CanaryController(
        lambda: discover_replicas(args.port_dir) or [],
        router_url=args.router_url, timeout_s=args.reload_timeout)
    gate = PromotionGate(gate_polls=args.gate_polls,
                         quality_margin=args.quality_margin,
                         min_arm_dispatches=args.min_arm_dispatches,
                         timeout_polls=args.gate_timeout_polls)
    loop = ControlLoop(
        monitor, _make_research_fn(args), canary_ctl, gate,
        ReplicaQualityScraper(),
        baseline_policy=args.baseline_policy,
        baseline_digest=policy_file_digest(args.baseline_policy),
        n_canary=args.canary_replicas, split_every=args.split_every,
        poll_interval_s=args.poll_interval)
    if args.resume:
        from fast_autoaugment_tpu.control.resume import (
            read_control_events,
            reconstruct_inflight_episode,
        )

        # never replay the pre-crash episode's drifted traffic into a
        # fresh baseline — the WAL (not the sample stream) carries the
        # in-flight state across the crash
        skipped = reader.skip_to_end()
        episode = reconstruct_inflight_episode(
            read_control_events(args.telemetry))
        if episode is not None:
            logger.warning(
                "--resume: dangling %s-stage episode reconstructed "
                "from the journal (%d segment(s) fast-forwarded) — "
                "re-entering", episode["stage"], skipped)
            loop.resume(episode)
        else:
            logger.info("--resume: journal WAL is clean (%d segment(s) "
                        "fast-forwarded) — watching", skipped)
    loop.start()
    logger.info("control loop watching %s (replicas via %s, baseline "
                "%s)", args.telemetry, args.port_dir,
                loop.baseline_digest)

    done = threading.Event()

    def shutdown(signum, frame):
        logger.info("signal %d: stopping control loop", signum)
        done.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    if args.heartbeat_dir:
        tag = args.host_tag or f"host{os.environ.get('FAA_HOST_ID', '0')}"
        threading.Thread(target=_beat_loop,
                         args=(done, args.heartbeat_dir, tag, 1.0),
                         daemon=True, name="host-beat").start()
    if args.control_seconds > 0:
        timer = threading.Timer(args.control_seconds, done.set)
        timer.daemon = True
        timer.start()
    while not done.wait(0.25):
        pass
    loop.stop()
    stats = loop.stats()
    if args.stats_file:
        from fast_autoaugment_tpu.control.research import (
            _write_json_atomic,
        )

        _write_json_atomic(args.stats_file, stats)
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
