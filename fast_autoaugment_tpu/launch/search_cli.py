"""Policy-search CLI — the ``python search.py -c conf.yaml --redis ...``
equivalent (reference ``search.py:137-154``) without Ray/Redis.

    python -m fast_autoaugment_tpu.launch.search_cli -c confs/wresnet40x2_cifar.yaml \
        --dataroot /data --save-dir search_out --smoke-test

Runs phases 1+2 (K-fold no-aug pretrain, TPE TTA search) and then
phase 3 (``--num-result-per-cv`` full retrains with default vs found
policies, averaged — reference ``search.py:264-312``).
"""

from __future__ import annotations

import argparse
import math
import time

import numpy as np

from fast_autoaugment_tpu.core.config import load_config
from fast_autoaugment_tpu.core.resilience import (
    PREEMPTED_EXIT_CODE,
    DispatchHungError,
    PreemptedError,
    install_signal_handlers,
)
from fast_autoaugment_tpu.search.driver import search_policies, write_json_atomic
from fast_autoaugment_tpu.train.trainer import train_and_eval
from fast_autoaugment_tpu.utils.logging import get_logger

logger = get_logger("faa_tpu.search_cli")


def _quality_floor_arg(value: str) -> str:
    """Validate ``--fold-quality-floor`` at parse time (ADVICE r4): the
    accepted forms are 'auto', 'off'/'none', or a float literal; a typo
    fails as a CLI usage error instead of a float() traceback deep in
    the search."""
    if value.lower() in ("auto", "off", "none"):
        return value.lower()
    try:
        f = float(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto', 'off' or a float, got {value!r}")
    if not math.isfinite(f):
        # float('nan') parses but nan > 0 is False, which would
        # silently disable the gate downstream
        raise argparse.ArgumentTypeError(
            f"expected a finite float, got {value!r}")
    return value


def _watchdog_arg(value: str) -> str:
    """Validate ``--watchdog`` at parse time: 'off', 'auto', or a
    positive float deadline in seconds."""
    v = value.lower()
    if v in ("off", "auto"):
        return v
    try:
        f = float(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'off', 'auto' or SECONDS, got {value!r}")
    if not math.isfinite(f) or f <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive finite deadline, got {value!r}")
    return value


def _fold_stack_arg(value: str) -> "str | int":
    """Validate ``--fold-stack`` at parse time: '0' (sequential,
    bit-for-bit the pre-stacking path), 'auto' (stack every fold that
    needs training), or an int K >= 2 (stack width cap)."""
    if value.lower() == "auto":
        return "auto"
    try:
        k = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or an integer, got {value!r}")
    if k < 0:
        raise argparse.ArgumentTypeError(
            f"expected a non-negative stack width, got {value!r}")
    return k


def random_arm_skip_reason(result: dict) -> str | None:
    """Why a requested --phase3-random control arm cannot run, or None.

    The random set can legitimately come back empty — the 0.95 audit
    floor dropping every uniform draw is plausible for destructive
    random policies — but silently persisting a two-arm artifact
    defeats the three-way comparison the flag asked for (ADVICE r5,
    medium).  The caller logs the reason prominently and records it in
    the artifact as ``random_arm_skip_reason``."""
    if result.get("random_policy_set"):
        return None
    drawn = int(result.get("num_sub_policies_random_drawn") or 0)
    dropped = int(result.get("num_sub_policies_random_dropped") or 0)
    if drawn and dropped >= drawn:
        return (f"all {drawn} drawn random sub-policies were dropped by "
                "the audit")
    if drawn:
        return (f"random set empty after audit ({drawn} drawn, "
                f"{dropped} recorded dropped)")
    return ("no random policy set was drawn (search ended before the "
            "random-control step)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="fast-autoaugment-tpu policy search")
    p.add_argument("-c", "--conf", required=True)
    p.add_argument("--dataroot", default="./data")
    p.add_argument("--save-dir", default="search_out")
    p.add_argument("--num-fold", type=int, default=5, help="K (reference cv_num=5)")
    p.add_argument("--cv-ratio", type=float, default=0.4)
    p.add_argument("--num-policy", type=int, default=5)
    p.add_argument("--num-op", type=int, default=2)
    p.add_argument("--num-search", type=int, default=200)
    p.add_argument("--topup-trials", type=int, default=0,
                   help="warm-started incremental RE-SEARCH (the control "
                        "plane's entry point, docs/CONTROL.md): extend a "
                        "completed --save-dir's per-fold trial budget by "
                        "this many trials.  Resume replays the persisted "
                        "trial log — --async-pipeline on routes it "
                        "through the PR-9 replay_trial_log ledger, so "
                        "the TPE continues exactly where the original "
                        "run left off — and only the top-up trials "
                        "dispatch; search_result.json stamps "
                        "'warm_start'.  0 (default) = the historical "
                        "budget, artifact stream untouched")
    p.add_argument("--num-top", type=int, default=10)
    p.add_argument("--async-pipeline", default="off", choices=("off", "on"),
                   help="streaming actor/learner phase-2 scheduler "
                        "(search/pipeline.py): device actor threads pull "
                        "ready-built candidate rounds from a bounded "
                        "queue while the TPE learner digests completed "
                        "results and refills proposals concurrently "
                        "(tells apply in trial-id order, so the schedule "
                        "is deterministic), and phase-2 trials on fold k "
                        "start the moment fold k's phase-1 gate clears "
                        "while later folds still train.  'off' (default) "
                        "= the historical serial driver bit-for-bit; "
                        "'on' with --pipeline-actors 1 --pipeline-queue-"
                        "depth 0 reproduces the serial trial log exactly "
                        "(docs/BENCHMARKS.md 'Search pipelining')")
    p.add_argument("--pipeline-actors", type=int, default=1,
                   help="device actor threads per fold in --async-"
                        "pipeline on (each runs one monitored TTA "
                        "dispatch at a time against the shared compiled "
                        "step)")
    p.add_argument("--pipeline-queue-depth", type=int, default=1,
                   help="candidate rounds proposed AHEAD of the actors "
                        "in --async-pipeline on (the in-flight window is "
                        "actors + depth rounds; pending rounds contribute "
                        "constant-liar placeholders to the posterior).  "
                        "0 = lockstep ask-after-tell")
    p.add_argument("--trial-batch", type=int, default=1,
                   help="K concurrent TPE trials per fold, evaluated by ONE "
                        "vmapped TTA program per batch (constant-liar "
                        "proposals; the single-host answer to the "
                        "reference's 80 concurrent Ray trials, "
                        "search.py:230).  1 (default) = the sequential "
                        "scheduler, bit-for-bit")
    p.add_argument("--aug-dispatch", default="exact",
                   choices=("exact", "grouped"),
                   help="policy-application kernel for phase-2 TTA, the "
                        "sub-policy audit and phase-3 policy-on retrains. "
                        "'exact' (default) = the historical per-image "
                        "vmapped-switch path bit-for-bit (XLA executes all "
                        "19 op branches per image); 'grouped' = scalar "
                        "dispatch (one branch executes; stratified "
                        "per-chunk sub-policy draws with identical "
                        "per-image marginals — docs/BENCHMARKS.md "
                        "'Augmentation dispatch')")
    p.add_argument("--aug-groups", type=int, default=8,
                   help="chunks per batch for --aug-dispatch grouped "
                        "(each chunk shares one sub-policy draw)")
    p.add_argument("--fold-stack", default=0, type=_fold_stack_arg,
                   help="phase-1 fold stacking: train K fold models as "
                        "ONE vmapped program per step, folds sharded "
                        "onto the mesh data axis when the counts divide "
                        "(the phase-1 counterpart of --trial-batch).  "
                        "0 (default) = the sequential per-fold loop "
                        "bit-for-bit; 'auto' stacks every fold needing "
                        "training; K caps the stack width")
    p.add_argument("--device-cache", default="auto",
                   choices=("auto", "on", "off"),
                   help="device-resident data path for phase-1 fold "
                        "pretraining, gate retrains and phase-3 retrains: "
                        "upload the eager dataset once, gather batches by "
                        "index inside the compiled step.  'auto' "
                        "(default) = on for in-memory single-host "
                        "datasets, bit-for-bit at --steps-per-dispatch 1; "
                        "lazy ImageNet datasets keep the prefetch path "
                        "(docs/BENCHMARKS.md 'Step dispatch & device "
                        "cache')")
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="fuse N train steps into ONE dispatch (lax.scan "
                        "over the device cache; composes with "
                        "--fold-stack: one dispatch then advances "
                        "K folds x N steps).  1 (default) = historical "
                        "per-step dispatch bit-for-bit; N>1 deviates by "
                        "the documented ~1 f32 ULP/step scan bound")
    p.add_argument("--num-result-per-cv", type=int, default=5,
                   help="phase-3 retrains per mode (reference search.py:270)")
    p.add_argument("--until", type=int, default=3,
                   help="run phases up to this number (1, 2 or 3)")
    p.add_argument("--folds", default=None,
                   help="comma-separated fold subset for multi-host scatter")
    p.add_argument("--smoke-test", action="store_true")
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fold-quality-floor", default="auto",
                   type=_quality_floor_arg,
                   help="fold-oracle gate: retrain (fresh seed) folds whose "
                        "no-policy baseline accuracy is below this, exclude "
                        "them from ranking if still weak.  'auto' (default) "
                        "= chance + 0.35*(1-chance); a float sets it "
                        "explicitly; 'off' disables "
                        "(docs/search_postmortem_r2.md)")
    p.add_argument("--fold-retrain-tries", type=int, default=2)
    p.add_argument("--phase1-epochs", type=int, default=None,
                   help="override conf['epoch'] for phase-1 fold pretraining")
    p.add_argument("--phase3-random", action="store_true",
                   help="add a random-policy control arm to phase 3: an "
                        "equal-size uniform draw from the search space, "
                        "audited identically, retrained on the same seeds "
                        "(the density-matching claim is searched > random, "
                        "not just searched > no-aug)")
    p.add_argument("--divergence-retries", type=int, default=0,
                   help="phase-1/3 training runs: on a NaN/inf epoch "
                        "loss, roll back to the newest intact checkpoint "
                        "and replay with retry-folded randomness up to R "
                        "times before re-raising.  0 (default) = the "
                        "historical immediate raise (docs/RESILIENCE.md)")
    p.add_argument("--ckpt-keep", type=int, default=2,
                   help="rollback-chain depth for every checkpoint this "
                        "search writes (path, path.prev, ...); restore "
                        "walks to the newest sha256-intact link")
    p.add_argument("--ckpt-every-dispatch", type=int, default=0,
                   help="mid-epoch snapshot every M dispatch chunks in "
                        "phase-3 retrains (device-cache path; bit-"
                        "identical dispatch-boundary resume).  0 = off")
    p.add_argument("--watchdog", default="off", type=_watchdog_arg,
                   help="dispatch watchdog: deadline-guard every device "
                        "dispatch (train chunks, TTA/eval replays) and "
                        "treat one that blows its deadline as HUNG — the "
                        "typed DispatchHungError maps to exit 77 and the "
                        "relaunch resumes from the newest checkpoint-chain "
                        "link.  'off' (default) = the historical async "
                        "dispatch bit-for-bit; 'auto' = deadlines from an "
                        "EMA of observed dispatch wall times (generous "
                        "first-call compile allowance); SECONDS = a fixed "
                        "deadline (docs/RESILIENCE.md)")
    p.add_argument("--fleet-transport", default=None, metavar="DIR",
                   help="multi-host MPMD fleet search: promote the "
                        "--async-pipeline candidate queue to a cross-"
                        "host round transport under DIR (a directory "
                        "every host mounts).  The LEARNER host "
                        "(--search-role learner) trains phase-1 folds, "
                        "publishes gate-cleared checkpoints, and "
                        "publishes TPE ask rounds as leased work "
                        "units; ACTOR hosts (--search-role actor) "
                        "claim rounds, run the TTA dispatches, and "
                        "post rewards back.  The fleet reproduces the "
                        "single-host --async-pipeline artifacts bit "
                        "for bit when every host shares the same "
                        "flags; dead actors are reclaimed by the lease "
                        "TTL.  Default: inherited FAA_FLEET_TRANSPORT "
                        "(the fleet launcher's --fleet-transport "
                        "exports it); 'off'/unset = single host "
                        "(docs/RESILIENCE.md 'Fleet search')")
    p.add_argument("--search-role", default="auto",
                   choices=("auto", "learner", "actor"),
                   help="this host's role in a --fleet-transport "
                        "search.  'auto' (default) reads "
                        "FAA_SEARCH_ROLE (the fleet launcher's --roles "
                        "exports it per host) and falls back to "
                        "'learner'.  'actor' runs no training and no "
                        "TPE: it serves published rounds until the "
                        "learner marks the search done, then exits 0 "
                        "(preemption/hang map to exit 77 like every "
                        "other worker)")
    p.add_argument("--ckpt-publish-timeout", type=float, default=900.0,
                   help="actor hosts: seconds to wait for a claimed "
                        "round's fold checkpoint to be published (and "
                        "digest-match locally) before exiting loudly")
    p.add_argument("--workqueue", default=None, metavar="DIR",
                   help="elastic multi-host scatter: claim phase-1 fold "
                        "trainings and per-fold phase-2 searches off a "
                        "lease queue under DIR (a directory every host "
                        "mounts), renewing leases at dispatch/round "
                        "boundaries and RECLAIMING units whose lease went "
                        "stale — a dead host's fold is finished by a "
                        "survivor and the search completes with any >= 1 "
                        "live host, stamping degraded/lost_hosts/"
                        "reclaimed_units into search_result.json.  "
                        "Replaces the static --folds assignment "
                        "(docs/RESILIENCE.md 'Self-healing fleet')")
    p.add_argument("--lease-ttl", type=float, default=60.0,
                   help="seconds without a heartbeat before a --workqueue "
                        "lease counts as stale and survivors may reclaim "
                        "its unit (must dominate NTP skew + the longest "
                        "dispatch gap between renewals)")
    p.add_argument("--host-tag", default=None,
                   help="this host's stable owner id in the --workqueue "
                        "(default: host<--host-id> under the fleet "
                        "launcher, else host<pid>).  A relaunch must "
                        "REUSE its predecessor's tag to resume its own "
                        "leases without waiting out the TTL")
    # accepted so the fleet launcher can drive this CLI like train_cli;
    # --host-id doubles as the default --host-tag
    p.add_argument("--coordinator", default=None,
                   help="host0 addr for multi-host JAX (fleet launcher "
                        "passes it; only used when --workqueue is unset)")
    p.add_argument("--num-hosts", type=int, default=None)
    p.add_argument("--host-id", type=int, default=None)
    p.add_argument("--compile-cache", default="off", metavar="{off,DIR}",
                   help="persistent XLA compilation cache shared by "
                        "every compile this search pays (phase-1 "
                        "training, TTA, audit, phase-3 retrains): a "
                        "fresh process — exit-77 resume, fleet retry, "
                        "reclaimed work unit — deserializes its "
                        "executables from DIR instead of re-paying the "
                        "23-55s compile tax; hit/miss counts land in "
                        "search_result.json['compile_cache'].  'off' "
                        "(default) = historical behavior (still honors "
                        "an inherited FAA_COMPILE_CACHE; caching never "
                        "changes numerics).  The fleet launcher's "
                        "--compile-cache exports the dir to every host")
    p.add_argument("--telemetry", default="off", metavar="{off,DIR}",
                   help="flight-recorder journal (core/telemetry.py): "
                        "typed dispatch/compile/checkpoint/lease/trial "
                        "events under DIR with rotation-bounded size, "
                        "renderable as a Chrome trace via tools/"
                        "trace_export.py and aggregated fleet-wide via "
                        "tools/faa_status.py.  'off' (default, bit-for-"
                        "bit — no journal I/O) still honors an inherited "
                        "FAA_TELEMETRY")
    p.add_argument("--telemetry-port", type=int, default=0,
                   help="serve GET /metrics (Prometheus text exposition "
                        "of the in-memory telemetry registry, read-only) "
                        "on this port while the search runs.  0 = off")
    p.add_argument("--audit-floor", type=float, default=0.95,
                   help="drop selected sub-policies whose standalone "
                        "mean-over-draws fold accuracy < floor x baseline "
                        "(<=0 disables).  Default 0.95: the validated "
                        "round-3 recipe — the old 0.7 default measurably "
                        "ships destructive policies "
                        "(search_e2e_r3/search_result_floor0.70.json)")
    p.add_argument("override", nargs="*")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    conf = load_config(args.conf, overrides=args.override)
    if args.coordinator and not args.workqueue:
        # one JAX job spanning hosts (the train_cli contract); in
        # --workqueue mode every host is its own single-process JAX job
        # sharing only the artifact directory
        from fast_autoaugment_tpu.parallel.mesh import distributed_init

        distributed_init(args.coordinator, args.num_hosts, args.host_id)
    # SIGTERM/SIGUSR1 -> graceful preemption: the in-flight training run
    # checkpoints at its next safe boundary (per-trial logs are already
    # persisted per round) and the process exits 77 = "resume me"
    install_signal_handlers()
    from fast_autoaugment_tpu.core import telemetry

    # journal + read-only /metrics exposition (core/telemetry.py);
    # both default off = the historical stream
    telemetry.configure_telemetry(args.telemetry)
    metrics_httpd = None
    if args.telemetry_port:
        metrics_httpd, _port = telemetry.start_metrics_server(
            args.telemetry_port)
    t_start = time.time()

    try:
        return _run(args, conf, t_start)
    except PreemptedError as e:
        logger.warning(
            "preempted (%s) — exiting %d; rerunning the same command "
            "resumes from the per-fold checkpoints and trial log",
            e, PREEMPTED_EXIT_CODE)
        telemetry.emit("preempt", "search_cli", kind="preempted",
                       exit_code=PREEMPTED_EXIT_CODE)
        raise SystemExit(PREEMPTED_EXIT_CODE)
    except DispatchHungError as e:
        logger.error(
            "dispatch HUNG (%s) — the in-flight device state is "
            "unrecoverable; exiting %d so the supervisor relaunches and "
            "the rerun resumes from the newest checkpoint-chain link",
            e, PREEMPTED_EXIT_CODE)
        telemetry.emit("preempt", "search_cli", kind="dispatch_hung",
                       label_detail=e.label, exit_code=PREEMPTED_EXIT_CODE)
        raise SystemExit(PREEMPTED_EXIT_CODE)
    finally:
        if metrics_httpd is not None:
            metrics_httpd.shutdown()


def _owner_tag(args) -> str:
    """Stable owner id for lease-holding layers: --host-tag, then
    host<--host-id> (the fleet launcher's per-host identity — a
    relaunch reclaims its own leases immediately), then host<pid>."""
    import os

    return args.host_tag or (
        f"host{args.host_id}" if args.host_id is not None
        else f"host{os.getpid()}")


def _build_workqueue(args):
    """The shared lease queue (or None)."""
    if not args.workqueue:
        return None
    from fast_autoaugment_tpu.launch.workqueue import WorkQueue

    tag = _owner_tag(args)
    wq = WorkQueue(args.workqueue, tag, lease_ttl=args.lease_ttl)
    logger.info("workqueue: owner=%s root=%s lease_ttl=%.1fs",
                tag, args.workqueue, args.lease_ttl)
    return wq


def _resolve_fleet_transport(args):
    """``(transport, role)``: the cross-host round transport (or None)
    plus this host's resolved role.  The dir falls back to the
    FAA_FLEET_TRANSPORT env handoff (the fleet launcher exports it to
    every host launch and retry, like FAA_COMPILE_CACHE)."""
    import os

    from fast_autoaugment_tpu.search.pipeline import (
        FLEET_TRANSPORT_ENV_VAR,
        FleetTransport,
        resolve_search_role,
    )

    role = resolve_search_role(args.search_role)
    spec = (args.fleet_transport or "").strip()
    if spec.lower() in ("", "off"):
        spec = os.environ.get(FLEET_TRANSPORT_ENV_VAR, "").strip()
    if spec.lower() in ("", "off"):
        if role == "actor":
            raise SystemExit(
                "search_cli: --search-role actor needs a --fleet-"
                "transport DIR (or the FAA_FLEET_TRANSPORT handoff) — "
                "an actor host without a transport has nothing to serve")
        return None, role
    if args.workqueue:
        raise SystemExit(
            "search_cli: --fleet-transport and --workqueue are mutually "
            "exclusive (rounds-over-hosts vs folds-over-hosts)")
    transport = FleetTransport(spec, _owner_tag(args),
                               lease_ttl=args.lease_ttl, role=role)
    logger.info("fleet transport: role=%s owner=%s root=%s "
                "lease_ttl=%.1fs", role, transport.owner, spec,
                args.lease_ttl)
    return transport, role


def _run_actor(args, conf, transport):
    """The --search-role actor main path: serve published rounds until
    the learner marks the search done; write no search artifacts."""
    from fast_autoaugment_tpu.search.driver import search_actor

    stats = search_actor(
        conf,
        dataroot=args.dataroot,
        save_dir=args.save_dir,
        fleet_transport=transport,
        cv_num=args.num_fold,
        cv_ratio=args.cv_ratio,
        num_policy=args.num_policy,
        num_op=args.num_op,
        trial_batch=args.trial_batch,
        seed=args.seed,
        aug_dispatch=args.aug_dispatch,
        aug_groups=args.aug_groups,
        watchdog=args.watchdog,
        compile_cache=args.compile_cache,
        telemetry_spec=args.telemetry,
        ckpt_timeout=args.ckpt_publish_timeout,
    )
    transport.mark_host_done({"rounds_ok": stats["rounds_ok"],
                              "rounds_err": stats["rounds_err"]})
    return stats


def _run(args, conf, t_start):
    transport, role = _resolve_fleet_transport(args)
    if role == "actor":
        return _run_actor(args, conf, transport)
    work_queue = _build_workqueue(args)
    result = search_policies(
        conf,
        dataroot=args.dataroot,
        save_dir=args.save_dir,
        cv_num=args.num_fold,
        cv_ratio=args.cv_ratio,
        num_policy=args.num_policy,
        num_op=args.num_op,
        num_search=args.num_search,
        num_top=args.num_top,
        smoke_test=args.smoke_test,
        resume=not args.no_resume,
        until=args.until,
        folds=[int(f) for f in args.folds.split(",")] if args.folds else None,
        seed=args.seed,
        fold_quality_floor=args.fold_quality_floor,
        fold_retrain_tries=args.fold_retrain_tries,
        phase1_epochs=args.phase1_epochs,
        audit_floor=args.audit_floor if args.audit_floor > 0 else None,
        random_control=args.phase3_random,
        trial_batch=args.trial_batch,
        fold_stack=args.fold_stack,
        aug_dispatch=args.aug_dispatch,
        aug_groups=args.aug_groups,
        device_cache=args.device_cache,
        steps_per_dispatch=args.steps_per_dispatch,
        divergence_retries=args.divergence_retries,
        ckpt_keep=args.ckpt_keep,
        watchdog=args.watchdog,
        work_queue=work_queue,
        compile_cache=args.compile_cache,
        async_pipeline=args.async_pipeline,
        pipeline_actors=args.pipeline_actors,
        pipeline_queue_depth=args.pipeline_queue_depth,
        telemetry_spec=args.telemetry,
        fleet_transport=transport,
        topup_trials=args.topup_trials,
    )
    final_policy_set = result["final_policy_set"]
    random_policy_set = result.get("random_policy_set") or []
    logger.info("final policy set: %d sub-policies", len(final_policy_set))

    if args.phase3_random:
        skip_reason = random_arm_skip_reason(result)
        if skip_reason is not None:
            logger.warning(
                "=" * 66 + "\n"
                "--phase3-random was requested but the RANDOM CONTROL ARM "
                "WILL NOT RUN: %s.\nPhase 3 degrades to a two-arm "
                "(default vs augment) comparison — the searched-beats-"
                "random claim is NOT being tested by this run.\n" + "=" * 66,
                skip_reason,
            )
            result["random_arm_skipped"] = True
            result["random_arm_skip_reason"] = skip_reason

    _UNSERIALIZED = ("final_policy_set", "random_policy_set")

    def persist():
        """(Re)write search_result.json — called after EVERY phase-3
        run so a killed process still leaves the partial record
        (per-seed values to date) on disk."""
        import jax

        hours = (time.time() - t_start) * jax.device_count() / 3600.0
        # honest name + legacy alias; `backend` (from search_policies)
        # says what actually measured these hours
        result["device_hours_total"] = hours
        result["tpu_hours_total"] = hours
        # refresh: phase-3 retrains pay compiles after search_policies
        # stamped its snapshot
        from fast_autoaugment_tpu.core.compilecache import compile_cache_stats

        result["compile_cache"] = compile_cache_stats()
        write_json_atomic(
            f"{args.save_dir}/search_result.json",
            {k: v for k, v in result.items() if k not in _UNSERIALIZED})
        return result

    if args.until < 3 or not final_policy_set:
        if work_queue is not None:
            work_queue.mark_host_done()
        if transport is not None:
            transport.mark_host_done()
        return persist()

    phase3_hb = None
    if transport is not None:
        # the learner retrains alone (actors drained on search_done),
        # but its host beat must stay fresh or the fleet's wedge
        # detector would SIGKILL a healthy learner mid-retrain
        phase3_hb = transport.beat
    if work_queue is not None:
        # phase 3 is one unit: exactly one host runs the retrains (a
        # stale lease lets a survivor reclaim them; per-run checkpoints
        # make the rerun resume)
        if not work_queue.claim("phase3"):
            logger.info(
                "workqueue: phase 3 is owned elsewhere (or done) — this "
                "host is finished; the owner persists the final result")
            work_queue.mark_host_done()
            return persist()

        def phase3_hb():
            work_queue.renew("phase3")
            work_queue.beat_host()

    # phase 3: full retrains, default vs augmented (search.py:264-312)
    # plus an optional random-policy control arm.  Unlike the
    # reference's bare means, record per-seed values, the spread and
    # paired t-tests (runs pair by seed: identical data and init, only
    # the augmentation differs) — VERDICT r3 next-4 / r4 next-4.
    num_runs = 1 if args.smoke_test else args.num_result_per_cv
    seeds = [args.seed + run for run in range(num_runs)]
    modes = [("default", "default"), ("augment", final_policy_set)]
    if args.phase3_random and random_policy_set:
        modes.append(("random", random_policy_set))
    outcomes: dict[str, list[float]] = {name: [] for name, _ in modes}
    phase3: dict = {"num_runs": num_runs, "seeds": seeds}
    result["phase3"] = phase3

    def update_stats():
        from fast_autoaugment_tpu.utils.stats import paired_t_test

        for name, _aug in modes:
            vals = outcomes[name]
            if not vals:
                continue
            phase3[name] = {
                "per_seed": vals,
                "mean": float(np.mean(vals)),
                "std": float(np.std(vals, ddof=1)) if len(vals) > 1 else 0.0,
            }
        for a, b in (("augment", "default"), ("augment", "random"),
                     ("random", "default")):
            n = min(len(outcomes.get(a, [])), len(outcomes.get(b, [])))
            if n > 1:
                phase3[f"paired_{a}_minus_{b}"] = paired_t_test(
                    outcomes[a][:n], outcomes[b][:n])
        if outcomes["default"]:
            result["top1_test_default_mean"] = float(
                np.mean(outcomes["default"]))
        if outcomes["augment"]:
            result["top1_test_augment_mean"] = float(
                np.mean(outcomes["augment"]))

    # seed-major order: every completed seed adds one PAIRED
    # observation to all arms, so an interrupted run still yields a
    # balanced three-way comparison at whatever n it reached
    for run in range(num_runs):
        for mode, aug in modes:
            mode_conf = conf.replace(aug=aug)
            path = f"{args.save_dir}/final_{mode}_{run}.msgpack"
            res = train_and_eval(
                mode_conf, args.dataroot, test_ratio=0.0,
                save_path=path, metric="last", seed=seeds[run],
                aug_dispatch=args.aug_dispatch, aug_groups=args.aug_groups,
                device_cache=args.device_cache,
                steps_per_dispatch=args.steps_per_dispatch,
                divergence_retries=args.divergence_retries,
                ckpt_keep=args.ckpt_keep,
                checkpoint_every_dispatch=args.ckpt_every_dispatch,
                watchdog=args.watchdog, heartbeat=phase3_hb,
                compile_cache=args.compile_cache,
            )
            outcomes[mode].append(float(res.get("top1_test", 0.0)))
            logger.info("phase3 %s run %d: top1_test=%.4f", mode, run,
                        outcomes[mode][-1])
            update_stats()
            persist()

    summary = " vs ".join(
        "%s %.4f±%.4f" % (name, phase3[name]["mean"], phase3[name]["std"])
        for name, _ in modes if name in phase3)
    pvals = ", ".join(
        "%s p=%.3f" % (k[len("paired_"):], phase3[k]["p_value"])
        for k in sorted(phase3) if k.startswith("paired_"))
    logger.info("phase3 (n=%d): %s%s", num_runs, summary,
                " [%s]" % pvals if pvals else "")

    if work_queue is not None:
        work_queue.release("phase3", info={"num_runs": num_runs})
        work_queue.mark_host_done()
    if transport is not None:
        transport.mark_host_done()
    persist()
    logger.info("search complete: %.3f device-hours on %s",
                result["tpu_hours_total"], result.get("backend", "?"))
    return result


if __name__ == "__main__":
    main()
