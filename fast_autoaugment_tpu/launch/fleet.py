"""Multi-host fleet launcher + process supervision.

Replaces the reference's ``train_dist.py`` (SSH loop wrapping
``torch.distributed.launch``, ``train_dist.py:105-143``) and its
Horovod-derived ``safe_shell_exec.py`` process supervisor.  Because JAX
is multi-controller, every host simply runs the SAME command with its
``--host-id``; there is no per-GPU process fan-out to babysit.

What remains worth keeping from the reference's design is the process
hygiene, provided here natively:

- every remote command stays attached to the ``ssh -tt`` pty as its
  controlling terminal, so when the local ssh dies the kernel delivers
  SIGHUP to the remote foreground process group and the tree dies with
  it (the goal of the reference's fork-middleman + explicit
  signal-forwarding machinery, ``safe_shell_exec.py:29-60``);
- local SIGINT/SIGTERM (and normal exit) fan out kills to every host;
- remote stdout/stderr is streamed line-by-line with a ``[host]``
  prefix (``safe_shell_exec.py:63-87``);
- a host that fails FOR GOOD tears the fleet down and propagates the
  exit code (``train_dist.py:15-27``).

Resilience additions (docs/RESILIENCE.md):

- ``--host-retries N`` (default 0 = the historical tear-down-on-first-
  failure) relaunches a failed host up to N times with exponential
  backoff before giving up; a preempted host (exit
  :data:`~fast_autoaugment_tpu.core.resilience.PREEMPTED_EXIT_CODE`,
  77) is explicitly retry-eligible — its training checkpointed before
  exiting, so the relaunch RESUMES rather than restarts;
- the fleet's exit code is the FIRST GENUINE failure: hosts that die
  from the teardown kill (or only ever exited 0/77-retried) no longer
  mask the root cause — the old ``worst = worst or code`` could report
  a teardown-induced SIGTERM instead of the real failing host when
  wait order and failure order disagreed;
- the final log line reports per-host attempt counts.

    python -m fast_autoaugment_tpu.launch.fleet --hosts host1,host2,host3,host4 \
        --coordinator host1:8476 -- python -m fast_autoaugment_tpu.launch.train_cli \
        -c confs/resnet50.yaml --dataroot /data

``--hosts N`` expands to task1..taskN like the reference
(``train_dist.py:118-121``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import threading
import time

from fast_autoaugment_tpu.core.resilience import PREEMPTED_EXIT_CODE
from fast_autoaugment_tpu.utils.logging import get_logger

logger = get_logger("faa_tpu.fleet")

__all__ = ["expand_hosts", "launch_fleet", "main"]


def expand_hosts(spec: str) -> list[str]:
    """'4' -> [task1..task4]; 'a,b,c' -> [a, b, c] (train_dist.py:118-121)."""
    if spec.isdigit():
        return [f"task{i + 1}" for i in range(int(spec))]
    return [h.strip() for h in spec.split(",") if h.strip()]


def _remote_argv(host: str, wire: str) -> list[str]:
    """The local argv that runs `wire` on `host` (separate function so
    tests can substitute a local shell for ssh)."""
    return ["ssh", "-tt", "-o", "BatchMode=yes", host, wire]


class _Fleet:
    def __init__(self):
        self.procs: set[subprocess.Popen] = set()
        self._lock = threading.Lock()
        # once set, new launches stop and in-flight failures are
        # recorded as teardown-induced rather than root causes
        self.teardown = threading.Event()
        # (monotonic time, host, code) of genuine failures, in order
        self.failures: list[tuple[float, str, int]] = []

    def track(self, p: subprocess.Popen):
        with self._lock:
            self.procs.add(p)

    def untrack(self, p: subprocess.Popen):
        with self._lock:
            self.procs.discard(p)

    def record_failure(self, host: str, code: int):
        with self._lock:
            self.failures.append((time.monotonic(), host, code))

    def kill_all(self, sig=signal.SIGTERM):
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            if p.poll() is None:
                try:
                    # the local ssh runs in its own session; killing it
                    # closes the remote pty, and the kernel HUPs the
                    # remote foreground process group (the command tree
                    # is deliberately NOT setsid-detached from the pty)
                    os.killpg(os.getpgid(p.pid), sig)
                except (ProcessLookupError, PermissionError):
                    pass


def _stream(host: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{host}] ".encode() + line)
        out.flush()
    pipe.close()


def _supervise(fleet: _Fleet, host_id: int, host: str, command: list[str],
               coordinator: str, num_hosts: int,
               env_passthrough: tuple[str, ...], host_retries: int,
               retry_backoff: float, attempts_out: dict):
    """Launch + babysit one host: relaunch on failure (exit 77 included)
    up to `host_retries` times with exponential backoff; on final
    failure record the code and trigger fleet teardown."""
    remote_cmd = command + [
        "--coordinator", coordinator,
        "--num-hosts", str(num_hosts),
        "--host-id", str(host_id),
    ]
    envs = " ".join(
        f"{k}={shlex.quote(os.environ[k])}"
        for k in env_passthrough if k in os.environ
    )
    # NO setsid: the remote command must keep the ssh pty as its
    # controlling terminal so pty teardown HUPs the whole foreground
    # group — a setsid-detached tree would never see the hangup and
    # Ctrl-C here would orphan remote training processes
    # (safe_shell_exec.py:98-131 solves the same problem with an
    # explicit signal-forwarding middleman)
    wire = f"cd {shlex.quote(os.getcwd())} && {envs} exec " + " ".join(
        shlex.quote(c) for c in remote_cmd
    )
    attempt = 0
    while not fleet.teardown.is_set():
        attempt += 1
        attempts_out[host] = attempt
        full = _remote_argv(host, wire)
        logger.info("[%s] (attempt %d) %s", host, attempt, " ".join(full))
        try:
            p = subprocess.Popen(
                full, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except FileNotFoundError:
            logger.error("ssh binary not found — the fleet launcher needs "
                         "an ssh client on the controlling host")
            fleet.record_failure(host, 127)
            fleet.teardown.set()
            fleet.kill_all()
            return
        fleet.track(p)
        t = threading.Thread(target=_stream,
                             args=(host, p.stdout, sys.stdout.buffer),
                             daemon=True)
        t.start()
        code = p.wait()
        t.join(timeout=2)
        fleet.untrack(p)
        if code == 0:
            return
        if fleet.teardown.is_set():
            # killed by (or failed during) teardown: NOT a root cause
            logger.info("[%s] exited %d during teardown", host, code)
            return
        preempted = code == PREEMPTED_EXIT_CODE
        if attempt <= host_retries:
            delay = retry_backoff * (2 ** (attempt - 1))
            logger.warning(
                "[%s] exited %d (%s) — relaunching in %.1fs "
                "(attempt %d/%d)", host, code,
                "preempted: resume me" if preempted else "failed",
                delay, attempt, host_retries + 1)
            # interruptible sleep: a teardown elsewhere aborts the retry
            if fleet.teardown.wait(delay):
                return
            continue
        logger.warning("[%s] exited %d (%s) — out of retries, tearing "
                       "down fleet", host, code,
                       "preempted" if preempted else "failed")
        fleet.record_failure(host, code)
        fleet.teardown.set()
        fleet.kill_all()
        return


def launch_fleet(hosts: list[str], command: list[str],
                 coordinator: str | None,
                 env_passthrough: tuple[str, ...] = ("JAX_PLATFORMS",),
                 host_retries: int = 0,
                 retry_backoff: float = 1.0) -> int:
    """Run `command` on every host over SSH; returns the first genuine
    failure's exit code (0 when every host eventually succeeds).

    `host_retries` relaunches a failed host (exponential backoff
    starting at `retry_backoff` seconds) before the failure counts;
    exit 77 (preempted — state checkpointed, docs/RESILIENCE.md) is
    retry-eligible like any failure, and the relaunch resumes from the
    checkpoint."""
    fleet = _Fleet()
    coordinator = coordinator or f"{hosts[0]}:8476"
    host_retries = max(0, int(host_retries))

    def handler(signum, frame):
        logger.info("signal %d: killing fleet", signum)
        fleet.teardown.set()
        fleet.kill_all(signal.SIGTERM)
        sys.exit(128 + signum)

    prev_int = signal.signal(signal.SIGINT, handler)
    prev_term = signal.signal(signal.SIGTERM, handler)

    attempts: dict[str, int] = {}
    supervisors = []
    for host_id, host in enumerate(hosts):
        t = threading.Thread(
            target=_supervise,
            args=(fleet, host_id, host, command, coordinator, len(hosts),
                  env_passthrough, host_retries, retry_backoff, attempts),
            daemon=True,
        )
        t.start()
        supervisors.append(t)
    try:
        for t in supervisors:
            t.join()
    finally:
        fleet.teardown.set()
        fleet.kill_all()
        # restore whatever handlers the embedding process had (e.g. the
        # resilience preemption handlers when launched in-process)
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)
    # first GENUINE failure wins: teardown-induced exits were never
    # recorded, so a late sibling killed with SIGTERM cannot mask (or
    # be masked by) the root cause
    worst = 0
    if fleet.failures:
        fleet.failures.sort(key=lambda f: f[0])
        _, first_host, worst = fleet.failures[0]
        logger.warning("fleet: first genuine failure on [%s] with exit %d",
                       first_host, worst)
    logger.info(
        "fleet done: exit %d; attempts per host: %s", worst,
        " ".join(f"{h}={attempts.get(h, 0)}" for h in hosts))
    return worst


def main(argv=None):
    p = argparse.ArgumentParser(description="multi-host launcher")
    p.add_argument("--hosts", required=True, help="N or comma-separated hostnames")
    p.add_argument("--coordinator", default=None, help="addr:port of host 0")
    p.add_argument("--host-retries", type=int, default=0,
                   help="relaunch a failed host up to N times (exponential "
                        "backoff) before tearing down the fleet; exit 77 "
                        "(preempted, checkpointed) is retry-eligible and "
                        "the relaunch RESUMES (docs/RESILIENCE.md)")
    p.add_argument("--retry-backoff", type=float, default=1.0,
                   help="base seconds for the exponential retry backoff")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host (prefix with --)")
    args = p.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no command given")
    hosts = expand_hosts(args.hosts)
    code = launch_fleet(hosts, command, args.coordinator,
                        host_retries=args.host_retries,
                        retry_backoff=args.retry_backoff)
    sys.exit(code)


if __name__ == "__main__":
    main()
