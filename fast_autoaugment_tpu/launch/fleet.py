"""Multi-host fleet launcher + process supervision.

Replaces the reference's ``train_dist.py`` (SSH loop wrapping
``torch.distributed.launch``, ``train_dist.py:105-143``) and its
Horovod-derived ``safe_shell_exec.py`` process supervisor.  Because JAX
is multi-controller, every host simply runs the SAME command with its
``--host-id``; there is no per-GPU process fan-out to babysit.

What remains worth keeping from the reference's design is the process
hygiene, provided here natively:

- every remote command stays attached to the ``ssh -tt`` pty as its
  controlling terminal, so when the local ssh dies the kernel delivers
  SIGHUP to the remote foreground process group and the tree dies with
  it (the goal of the reference's fork-middleman + explicit
  signal-forwarding machinery, ``safe_shell_exec.py:29-60``);
- local SIGINT/SIGTERM (and normal exit) fan out kills to every host;
- remote stdout/stderr is streamed line-by-line with a ``[host]``
  prefix (``safe_shell_exec.py:63-87``);
- non-zero exit on any host tears the fleet down and propagates the
  exit code (``train_dist.py:15-27``).

    python -m fast_autoaugment_tpu.launch.fleet --hosts host1,host2,host3,host4 \
        --coordinator host1:8476 -- python -m fast_autoaugment_tpu.launch.train_cli \
        -c confs/resnet50.yaml --dataroot /data

``--hosts N`` expands to task1..taskN like the reference
(``train_dist.py:118-121``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import threading

from fast_autoaugment_tpu.utils.logging import get_logger

logger = get_logger("faa_tpu.fleet")

__all__ = ["expand_hosts", "launch_fleet", "main"]


def expand_hosts(spec: str) -> list[str]:
    """'4' -> [task1..task4]; 'a,b,c' -> [a, b, c] (train_dist.py:118-121)."""
    if spec.isdigit():
        return [f"task{i + 1}" for i in range(int(spec))]
    return [h.strip() for h in spec.split(",") if h.strip()]


class _Fleet:
    def __init__(self):
        self.procs: list[subprocess.Popen] = []
        self.failed: dict[str, int] = {}
        self._lock = threading.Lock()

    def kill_all(self, sig=signal.SIGTERM):
        with self._lock:
            for p in self.procs:
                if p.poll() is None:
                    try:
                        # the local ssh runs in its own session; killing it
                        # closes the remote pty, and the kernel HUPs the
                        # remote foreground process group (the command tree
                        # is deliberately NOT setsid-detached from the pty)
                        os.killpg(os.getpgid(p.pid), sig)
                    except (ProcessLookupError, PermissionError):
                        pass


def _stream(host: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{host}] ".encode() + line)
        out.flush()
    pipe.close()


def launch_fleet(hosts: list[str], command: list[str], coordinator: str | None,
                 env_passthrough: tuple[str, ...] = ("JAX_PLATFORMS",)) -> int:
    """Run `command` on every host over SSH; returns the worst exit code."""
    fleet = _Fleet()
    coordinator = coordinator or f"{hosts[0]}:8476"

    def handler(signum, frame):
        logger.info("signal %d: killing fleet", signum)
        fleet.kill_all(signal.SIGTERM)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    threads = []
    for host_id, host in enumerate(hosts):
        remote_cmd = command + [
            "--coordinator", coordinator,
            "--num-hosts", str(len(hosts)),
            "--host-id", str(host_id),
        ]
        envs = " ".join(
            f"{k}={shlex.quote(os.environ[k])}" for k in env_passthrough if k in os.environ
        )
        # NO setsid: the remote command must keep the ssh pty as its
        # controlling terminal so pty teardown HUPs the whole foreground
        # group — a setsid-detached tree would never see the hangup and
        # Ctrl-C here would orphan remote training processes
        # (safe_shell_exec.py:98-131 solves the same problem with an
        # explicit signal-forwarding middleman)
        wire = f"cd {shlex.quote(os.getcwd())} && {envs} exec " + " ".join(
            shlex.quote(c) for c in remote_cmd
        )
        full = ["ssh", "-tt", "-o", "BatchMode=yes", host, wire]
        logger.info("[%s] %s", host, " ".join(full))
        try:
            p = subprocess.Popen(
                full, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except FileNotFoundError:
            logger.error("ssh binary not found — the fleet launcher needs an "
                         "ssh client on the controlling host")
            fleet.kill_all()
            return 127
        fleet.procs.append(p)
        t = threading.Thread(target=_stream, args=(host, p.stdout, sys.stdout.buffer),
                             daemon=True)
        t.start()
        threads.append(t)

    worst = 0
    try:
        for host, p in zip(hosts, fleet.procs):
            code = p.wait()
            if code != 0:
                logger.warning("[%s] exited %d — tearing down fleet", host, code)
                worst = worst or code
                fleet.kill_all()
    finally:
        fleet.kill_all()
        for t in threads:
            t.join(timeout=2)
    return worst


def main(argv=None):
    p = argparse.ArgumentParser(description="multi-host launcher")
    p.add_argument("--hosts", required=True, help="N or comma-separated hostnames")
    p.add_argument("--coordinator", default=None, help="addr:port of host 0")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host (prefix with --)")
    args = p.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no command given")
    hosts = expand_hosts(args.hosts)
    code = launch_fleet(hosts, command, args.coordinator)
    sys.exit(code)


if __name__ == "__main__":
    main()
