"""Multi-host fleet launcher + process supervision.

Replaces the reference's ``train_dist.py`` (SSH loop wrapping
``torch.distributed.launch``, ``train_dist.py:105-143``) and its
Horovod-derived ``safe_shell_exec.py`` process supervisor.  Because JAX
is multi-controller, every host simply runs the SAME command with its
``--host-id``; there is no per-GPU process fan-out to babysit.

What remains worth keeping from the reference's design is the process
hygiene, provided here natively:

- every remote command stays attached to the ``ssh -tt`` pty as its
  controlling terminal, so when the local ssh dies the kernel delivers
  SIGHUP to the remote foreground process group and the tree dies with
  it (the goal of the reference's fork-middleman + explicit
  signal-forwarding machinery, ``safe_shell_exec.py:29-60``);
- local SIGINT/SIGTERM (and normal exit) fan out kills to every host;
- remote stdout/stderr is streamed line-by-line with a ``[host]``
  prefix (``safe_shell_exec.py:63-87``);
- a host that fails FOR GOOD tears the fleet down and propagates the
  exit code (``train_dist.py:15-27``).

Resilience additions (docs/RESILIENCE.md):

- ``--host-retries N`` (default 0 = the historical tear-down-on-first-
  failure) relaunches a failed host up to N times with exponential
  backoff before giving up; a preempted host (exit
  :data:`~fast_autoaugment_tpu.core.resilience.PREEMPTED_EXIT_CODE`,
  77) is explicitly retry-eligible — its training checkpointed before
  exiting, so the relaunch RESUMES rather than restarts;
- the fleet's exit code is the FIRST GENUINE failure: hosts that die
  from the teardown kill (or only ever exited 0/77-retried) no longer
  mask the root cause — the old ``worst = worst or code`` could report
  a teardown-induced SIGTERM instead of the real failing host when
  wait order and failure order disagreed;
- the final log line reports per-host attempt counts.

Self-healing fleet additions (this is the supervisor half of the
``launch/workqueue.py`` lease layer — docs/RESILIENCE.md
"Self-healing fleet"):

- ``--elastic``: a host that fails FOR GOOD no longer tears the fleet
  down — it is declared LOST, the survivors keep running, and (when
  the workers share a ``--workqueue``) they reclaim the dead host's
  stale leases and finish its work units.  The fleet completes with
  any >= 1 live host; exit 0 when at least one host succeeded.
- ``--workqueue DIR --heartbeat-timeout S``: the supervisor consumes
  the workers' host heartbeats (``DIR/hosts/<tag>.json``, written at
  dispatch/round boundaries).  A process that is ALIVE but whose beat
  is older than S is WEDGED beyond what its in-process watchdog could
  catch (e.g. the interpreter itself is stuck in a rendezvous) — the
  supervisor SIGKILLs it and the normal retry path relaunches it,
  resuming from the checkpoint chain.
- every supervisor log line carries ``host=<id> attempt=<n>`` so
  interleaved multi-host logs stay attributable; each launch exports
  ``FAA_ATTEMPT=<n>`` so fault-injection specs can be gated to a
  specific attempt in the process chain (``utils/faultinject.py``).

    python -m fast_autoaugment_tpu.launch.fleet --hosts host1,host2,host3,host4 \
        --coordinator host1:8476 -- python -m fast_autoaugment_tpu.launch.train_cli \
        -c confs/resnet50.yaml --dataroot /data

``--hosts N`` expands to task1..taskN like the reference
(``train_dist.py:118-121``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import threading
import time

from fast_autoaugment_tpu.core.resilience import PREEMPTED_EXIT_CODE
from fast_autoaugment_tpu.utils.logging import get_logger

logger = get_logger("faa_tpu.fleet")

__all__ = ["expand_hosts", "launch_fleet", "main", "resolve_roles",
           "DEFAULT_ENV_PASSTHROUGH"]


def expand_hosts(spec: str) -> list[str]:
    """'4' -> [task1..task4]; 'a,b,c' -> [a, b, c] (train_dist.py:118-121)."""
    if spec.isdigit():
        return [f"task{i + 1}" for i in range(int(spec))]
    return [h.strip() for h in spec.split(",") if h.strip()]


def _remote_argv(host: str, wire: str) -> list[str]:
    """The local argv that runs `wire` on `host` (separate function so
    tests can substitute a local shell for ssh)."""
    return ["ssh", "-tt", "-o", "BatchMode=yes", host, wire]


class _Fleet:
    def __init__(self):
        self.procs: set[subprocess.Popen] = set()
        self._lock = threading.Lock()
        # once set, new launches stop and in-flight failures are
        # recorded as teardown-induced rather than root causes
        self.teardown = threading.Event()
        # (monotonic time, host, code) of genuine failures, in order
        self.failures: list[tuple[float, str, int]] = []
        # hosts that eventually exited 0 / were declared lost (elastic)
        self.successes: list[str] = []
        self.lost: list[str] = []
        # wedged processes the heartbeat monitor had to kill
        self.hang_kills = 0

    def track(self, p: subprocess.Popen):
        with self._lock:
            self.procs.add(p)

    def untrack(self, p: subprocess.Popen):
        with self._lock:
            self.procs.discard(p)

    def record_failure(self, host: str, code: int):
        with self._lock:
            self.failures.append((time.monotonic(), host, code))

    def record_success(self, host: str):
        with self._lock:
            self.successes.append(host)

    def record_lost(self, host: str):
        with self._lock:
            self.lost.append(host)

    def kill_all(self, sig=signal.SIGTERM):
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            if p.poll() is None:
                try:
                    # the local ssh runs in its own session; killing it
                    # closes the remote pty, and the kernel HUPs the
                    # remote foreground process group (the command tree
                    # is deliberately NOT setsid-detached from the pty)
                    os.killpg(os.getpgid(p.pid), sig)
                except (ProcessLookupError, PermissionError):
                    pass


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(prefix.encode() + line)
        out.flush()
    pipe.close()


def _heartbeat_age(workqueue_dir: str, host_tag: str) -> float | None:
    """Seconds since the worker's last host beat, None when unknown
    (no beat yet — e.g. still compiling — or unreadable mid-write) or
    when the worker marked itself done (finished, not wedged).  The
    supervisor and its workers share one machine (and one clock), so
    this wall comparison is not a cross-host skew hazard."""
    from fast_autoaugment_tpu.core import fsfault

    path = os.path.join(workqueue_dir, "hosts", f"{host_tag}.json")
    rec = fsfault.read_json(path)
    if rec is None or rec.get("done"):
        return None
    try:
        return max(0.0, time.time() - float(rec["heartbeat"]))
    except (KeyError, TypeError, ValueError):
        return None


def _wait_with_heartbeat(fleet: _Fleet, p: subprocess.Popen, host: str,
                         attempt: int, host_tag: str,
                         workqueue_dir: str | None,
                         heartbeat_timeout: float) -> int:
    """Wait for the process; with a workqueue + timeout configured,
    SIGKILL it when its host beat goes stale — the beyond-the-watchdog
    wedge (the interpreter itself stuck in a rendezvous) that no
    in-process deadline can catch."""
    if not workqueue_dir or heartbeat_timeout <= 0:
        return p.wait()
    while True:
        try:
            return p.wait(timeout=max(0.2, heartbeat_timeout / 4.0))
        except subprocess.TimeoutExpired:
            if fleet.teardown.is_set():
                return p.wait()
            age = _heartbeat_age(workqueue_dir, host_tag)
            if age is not None and age > heartbeat_timeout:
                logger.warning(
                    "host=%s attempt=%d heartbeat %.1fs stale "
                    "(timeout %.1fs) — killing WEDGED process",
                    host, attempt, age, heartbeat_timeout)
                fleet.hang_kills += 1
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                return p.wait()


def _supervise(fleet: _Fleet, host_id: int, host: str, command: list[str],
               coordinator: str, num_hosts: int,
               env_passthrough: tuple[str, ...], host_retries: int,
               retry_backoff: float, attempts_out: dict,
               elastic: bool = False, workqueue_dir: str | None = None,
               heartbeat_timeout: float = 0.0, rank_args: bool = True,
               role: str | None = None):
    """Launch + babysit one host: relaunch on failure (exit 77 included)
    up to `host_retries` times with exponential backoff, SIGKILLing a
    heartbeat-stale (wedged) process first when configured; on final
    failure either tear the fleet down (default) or — ``elastic`` —
    declare the host LOST and let the survivors finish its work.

    ``rank_args=False`` (fleet ``--no-rank-args``) launches the command
    VERBATIM — replica supervision for commands with no multi-controller
    rank surface (e.g. ``serve/serve_cli.py`` policy-serving replicas,
    which would choke on ``--coordinator``); the replica still gets
    ``FAA_HOST_ID``/``FAA_ATTEMPT`` in its environment so host beats
    and attempt-gated fault specs stay addressable."""
    if rank_args:
        remote_cmd = command + [
            "--coordinator", coordinator,
            "--num-hosts", str(num_hosts),
            "--host-id", str(host_id),
        ]
    else:
        remote_cmd = list(command)
    host_tag = f"host{host_id}"
    base_envs = " ".join(
        f"{k}={shlex.quote(os.environ[k])}"
        for k in env_passthrough if k in os.environ
    )
    attempt = 0
    while not fleet.teardown.is_set():
        attempt += 1
        attempts_out[host] = attempt
        # FAA_ATTEMPT gates fault-injection specs to one attempt in the
        # process chain (a relaunch re-reads the same FAA_FAULT);
        # FAA_HOST_ID addresses rank-free replicas (serve host beats);
        # FAA_SEARCH_ROLE is the per-host fleet-search role (--roles),
        # re-exported on every RETRY so a relaunched actor stays an
        # actor
        envs = (f"{base_envs} FAA_ATTEMPT={attempt} "
                f"FAA_HOST_ID={host_id}"
                + (f" FAA_SEARCH_ROLE={shlex.quote(role)}" if role
                   else "")).strip()
        # NO setsid: the remote command must keep the ssh pty as its
        # controlling terminal so pty teardown HUPs the whole foreground
        # group — a setsid-detached tree would never see the hangup and
        # Ctrl-C here would orphan remote training processes
        # (safe_shell_exec.py:98-131 solves the same problem with an
        # explicit signal-forwarding middleman)
        wire = f"cd {shlex.quote(os.getcwd())} && {envs} exec " + " ".join(
            shlex.quote(c) for c in remote_cmd
        )
        full = _remote_argv(host, wire)
        logger.info("host=%s attempt=%d launching: %s", host, attempt,
                    " ".join(full))
        try:
            p = subprocess.Popen(
                full, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except FileNotFoundError:
            logger.error("host=%s attempt=%d ssh binary not found — the "
                         "fleet launcher needs an ssh client on the "
                         "controlling host", host, attempt)
            fleet.record_failure(host, 127)
            fleet.teardown.set()
            fleet.kill_all()
            return
        fleet.track(p)
        if fleet.teardown.is_set():
            # we raced the teardown: a sibling failed between our
            # launch check and track(), so its kill_all() missed this
            # process — kill it ourselves or it outlives the fleet
            fleet.kill_all()
        t = threading.Thread(
            target=_stream,
            args=(f"[host={host} attempt={attempt}] ", p.stdout,
                  sys.stdout.buffer),
            daemon=True)
        t.start()
        code = _wait_with_heartbeat(fleet, p, host, attempt, host_tag,
                                    workqueue_dir, heartbeat_timeout)
        t.join(timeout=2)
        fleet.untrack(p)
        if code == 0:
            fleet.record_success(host)
            return
        if fleet.teardown.is_set():
            # killed by (or failed during) teardown: NOT a root cause
            logger.info("host=%s attempt=%d exited %d during teardown",
                        host, attempt, code)
            return
        preempted = code == PREEMPTED_EXIT_CODE
        if attempt <= host_retries:
            delay = retry_backoff * (2 ** (attempt - 1))
            logger.warning(
                "host=%s attempt=%d exited %d (%s) — relaunching in %.1fs "
                "(attempt %d/%d)", host, attempt, code,
                "preempted: resume me" if preempted else "failed",
                delay, attempt, host_retries + 1)
            # interruptible sleep: a teardown elsewhere aborts the retry
            if fleet.teardown.wait(delay):
                return
            continue
        fleet.record_failure(host, code)
        if elastic:
            # degraded-mode completion: survivors keep running and (via
            # the shared workqueue) reclaim this host's stale leases
            fleet.record_lost(host)
            logger.warning(
                "host=%s attempt=%d exited %d (%s) — out of retries; "
                "host LOST, elastic fleet continues degraded (survivors "
                "reclaim its work units)", host, attempt, code,
                "preempted" if preempted else "failed")
            return
        logger.warning("host=%s attempt=%d exited %d (%s) — out of "
                       "retries, tearing down fleet", host, attempt, code,
                       "preempted" if preempted else "failed")
        fleet.teardown.set()
        fleet.kill_all()
        return


#: env vars forwarded to every host launch AND retry by default — the
#: whole fleet-sharing contract for the compile cache, the telemetry
#: journal, the serial-baseline dispatch trace, and the fleet-search
#: role/transport handoff (pinned by tests/test_fleet_search.py)
DEFAULT_ENV_PASSTHROUGH = ("JAX_PLATFORMS", "FAA_COMPILE_CACHE",
                           "FAA_TELEMETRY", "FAA_PIPELINE_TRACE",
                           "FAA_SEARCH_ROLE", "FAA_FLEET_TRANSPORT")


def resolve_roles(spec: str | None, num_hosts: int) -> list[str | None]:
    """``--roles`` to a per-host role list.  A single role broadcasts;
    otherwise the comma list must match the host count (a silently
    truncated or recycled role plan is exactly the launch bug this
    raises on).  None/'' = no role exports (non-search fleets)."""
    if not spec:
        return [None] * num_hosts
    roles = [r.strip() for r in str(spec).split(",") if r.strip()]
    if len(roles) == 1:
        return roles * num_hosts
    if len(roles) != num_hosts:
        raise ValueError(
            f"--roles names {len(roles)} role(s) for {num_hosts} host(s) "
            "— give one role per host (or a single role to broadcast)")
    return roles


def launch_fleet(hosts: list[str], command: list[str],
                 coordinator: str | None,
                 env_passthrough: tuple[str, ...] = DEFAULT_ENV_PASSTHROUGH,
                 host_retries: int = 0,
                 retry_backoff: float = 1.0,
                 elastic: bool = False,
                 workqueue_dir: str | None = None,
                 heartbeat_timeout: float = 0.0,
                 rank_args: bool = True,
                 roles: list[str | None] | None = None) -> int:
    """Run `command` on every host over SSH; returns the first genuine
    failure's exit code (0 when every host eventually succeeds).

    `host_retries` relaunches a failed host (exponential backoff
    starting at `retry_backoff` seconds) before the failure counts;
    exit 77 (preempted — state checkpointed, docs/RESILIENCE.md) is
    retry-eligible like any failure, and the relaunch resumes from the
    checkpoint.

    `elastic` completes the fleet with any >= 1 live host: a host out
    of retries is declared LOST instead of tearing the fleet down, and
    the exit code is 0 when at least one host succeeded (the workers'
    shared ``--workqueue`` makes the survivors finish the dead host's
    units).  `workqueue_dir` + `heartbeat_timeout` arm the wedge
    detector: an alive process whose host beat under
    ``<dir>/hosts/host<id>.json`` is older than the timeout is
    SIGKILLed and relaunched through the normal retry path.

    `rank_args=False` runs the command verbatim (no
    ``--coordinator/--num-hosts/--host-id`` suffix) — REPLICA
    supervision for rank-free services; each replica still gets
    ``FAA_HOST_ID``/``FAA_ATTEMPT`` exported.  The serving use:
    ``--no-rank-args -- python -m fast_autoaugment_tpu.serve.serve_cli
    --policy … --breaker-exit --heartbeat-dir Q`` gives every serving
    replica breaker-open restart (exit 77 is retry-eligible) and
    wedge-detection for free (docs/RESILIENCE.md "Serving under
    overload")."""
    fleet = _Fleet()
    coordinator = coordinator or f"{hosts[0]}:8476"
    host_retries = max(0, int(host_retries))
    if roles is None:
        roles = [None] * len(hosts)
    if len(roles) != len(hosts):
        raise ValueError(f"{len(roles)} role(s) for {len(hosts)} host(s)")

    def handler(signum, frame):
        logger.info("signal %d: killing fleet", signum)
        fleet.teardown.set()
        fleet.kill_all(signal.SIGTERM)
        sys.exit(128 + signum)

    prev_int = signal.signal(signal.SIGINT, handler)
    prev_term = signal.signal(signal.SIGTERM, handler)

    attempts: dict[str, int] = {}
    supervisors = []
    for host_id, host in enumerate(hosts):
        t = threading.Thread(
            target=_supervise,
            args=(fleet, host_id, host, command, coordinator, len(hosts),
                  env_passthrough, host_retries, retry_backoff, attempts,
                  elastic, workqueue_dir, heartbeat_timeout, rank_args,
                  roles[host_id]),
            daemon=True,
        )
        t.start()
        supervisors.append(t)
    try:
        for t in supervisors:
            # bounded joins (lint R4): the supervisor threads exit on
            # their own, but an untimed join here would silently hang
            # the whole launcher if one ever wedged
            while t.is_alive():
                t.join(timeout=5.0)
    finally:
        fleet.teardown.set()
        fleet.kill_all()
        # restore whatever handlers the embedding process had (e.g. the
        # resilience preemption handlers when launched in-process)
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)
    # first GENUINE failure wins: teardown-induced exits were never
    # recorded, so a late sibling killed with SIGTERM cannot mask (or
    # be masked by) the root cause
    worst = 0
    if fleet.failures:
        fleet.failures.sort(key=lambda f: f[0])
        _, first_host, worst = fleet.failures[0]
        logger.warning("fleet: first genuine failure on host=%s with exit %d",
                       first_host, worst)
    if elastic and fleet.successes and worst != 0:
        # degraded completion: >= 1 host finished the (shared-queue)
        # work, so the FLEET succeeded even though hosts were lost —
        # the worker stamped degraded/lost_hosts into the result
        logger.warning(
            "fleet: DEGRADED completion — %d host(s) lost (%s), %d "
            "succeeded; exit 0", len(fleet.lost),
            ",".join(fleet.lost) or "-", len(fleet.successes))
        worst = 0
    logger.info(
        "fleet done: exit %d; attempts per host: %s%s%s", worst,
        " ".join(f"{h}={attempts.get(h, 0)}" for h in hosts),
        f"; lost: {','.join(fleet.lost)}" if fleet.lost else "",
        f"; wedged-killed: {fleet.hang_kills}" if fleet.hang_kills else "")
    return worst


def main(argv=None):
    p = argparse.ArgumentParser(description="multi-host launcher")
    p.add_argument("--hosts", required=True, help="N or comma-separated hostnames")
    p.add_argument("--coordinator", default=None, help="addr:port of host 0")
    p.add_argument("--host-retries", type=int, default=0,
                   help="relaunch a failed host up to N times (exponential "
                        "backoff) before tearing down the fleet; exit 77 "
                        "(preempted, checkpointed) is retry-eligible and "
                        "the relaunch RESUMES (docs/RESILIENCE.md)")
    p.add_argument("--retry-backoff", type=float, default=1.0,
                   help="base seconds for the exponential retry backoff")
    p.add_argument("--elastic", action="store_true",
                   help="degraded-mode completion: a host out of retries "
                        "is declared LOST instead of tearing the fleet "
                        "down; survivors keep running (and, with a shared "
                        "--workqueue, reclaim its work units).  Fleet "
                        "exit 0 when >= 1 host succeeds "
                        "(docs/RESILIENCE.md 'Self-healing fleet')")
    p.add_argument("--no-rank-args", action="store_true",
                   help="launch the command VERBATIM (no --coordinator/"
                        "--num-hosts/--host-id suffix): replica "
                        "supervision for rank-free services like the "
                        "serving CLI — retries, --elastic and "
                        "--heartbeat-timeout all apply; each replica "
                        "gets FAA_HOST_ID/FAA_ATTEMPT exported")
    p.add_argument("--workqueue", default=None, metavar="DIR",
                   help="the workers' shared lease-queue dir (pass the "
                        "same DIR to the worker CLI); arms the "
                        "supervisor-side heartbeat wedge detector")
    p.add_argument("--heartbeat-timeout", type=float, default=0.0,
                   help="SIGKILL + relaunch an ALIVE worker whose "
                        "DIR/hosts/host<id>.json beat is older than this "
                        "many seconds — the interpreter-level wedge the "
                        "in-process --watchdog cannot catch.  0 = off")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="shared persistent XLA compilation cache: "
                        "exported to every host (and every RETRY — the "
                        "relaunch deserializes the executables its "
                        "predecessor compiled) as FAA_COMPILE_CACHE.  "
                        "Point it at a directory all hosts mount; the "
                        "worker CLIs pick it up without extra flags "
                        "(core/compilecache.py)")
    p.add_argument("--roles", default=None, metavar="R1,R2,...",
                   help="per-host fleet role (learner/actor for a "
                        "--fleet-transport search; control for a "
                        "control_cli host riding a --no-rank-args "
                        "serving fleet), exported as FAA_SEARCH_ROLE "
                        "to every launch "
                        "AND retry so search_cli --search-role auto "
                        "resolves it.  One role broadcasts to all "
                        "hosts; otherwise the list must match the host "
                        "count.  Example: --roles learner,actor,actor")
    p.add_argument("--fleet-transport", default=None, metavar="DIR",
                   help="shared fleet-search round-transport dir: "
                        "exported to every host (and every retry) as "
                        "FAA_FLEET_TRANSPORT, so the worker CLIs pick "
                        "up the transport without extra flags — the "
                        "same contract as --compile-cache/--telemetry "
                        "(docs/RESILIENCE.md 'Fleet search')")
    p.add_argument("--telemetry", default=None, metavar="DIR",
                   help="shared flight-recorder journal dir: exported to "
                        "every host (and every retry) as FAA_TELEMETRY so "
                        "each worker journals under DIR with its own "
                        "host/attempt identity; tools/faa_status.py "
                        "aggregates the result into one fleet table "
                        "(core/telemetry.py)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host (prefix with --)")
    args = p.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no command given")
    if args.compile_cache and args.compile_cache.lower() != "off":
        # the env-passthrough list already forwards FAA_COMPILE_CACHE to
        # every host launch (retries included) — setting it here is the
        # whole fleet-sharing contract
        os.environ["FAA_COMPILE_CACHE"] = args.compile_cache
    if args.telemetry and args.telemetry.lower() != "off":
        # same contract as the compile cache: the env-passthrough list
        # forwards FAA_TELEMETRY to every host launch and retry
        os.environ["FAA_TELEMETRY"] = args.telemetry
    if args.fleet_transport and args.fleet_transport.lower() != "off":
        # and again for the fleet-search round transport
        os.environ["FAA_FLEET_TRANSPORT"] = args.fleet_transport
    hosts = expand_hosts(args.hosts)
    try:
        roles = resolve_roles(args.roles, len(hosts))
    except ValueError as e:
        p.error(str(e))
    code = launch_fleet(hosts, command, args.coordinator,
                        host_retries=args.host_retries,
                        retry_backoff=args.retry_backoff,
                        elastic=args.elastic,
                        workqueue_dir=args.workqueue,
                        heartbeat_timeout=args.heartbeat_timeout,
                        rank_args=not args.no_rank_args,
                        roles=roles)
    sys.exit(code)


if __name__ == "__main__":
    main()
