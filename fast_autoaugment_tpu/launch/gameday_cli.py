"""``python -m fast_autoaugment_tpu.launch.gameday_cli`` — trace-driven
game days (docs/GAMEDAYS.md).

Thin front end over ``gameday/runner.py``: pick scenarios, run them,
print the verdict table, exit 0 only when the SUITE is green (every
verdict matched its spec's ``expect`` — a broken-config scenario that
failed on cue is green; one that passed is not).

The suite JSON (``--out``) carries the bench provenance stamps
(``bench.py``: contention + ``single_core_caveat``) because a verdict
captured on a contended host is evidence about the HOST, not the
plane.  All filesystem work lives in the runner — this module stays
FS-free (faalint F1 polices ``launch/``).

Examples::

    python -m fast_autoaugment_tpu.launch.gameday_cli --list
    python -m fast_autoaugment_tpu.launch.gameday_cli --suite \\
        --out docs/gameday.json                       # make gameday
    python -m fast_autoaugment_tpu.launch.gameday_cli --suite --smoke
    python -m fast_autoaugment_tpu.launch.gameday_cli \\
        --scenario flash-crowd-10x --seed 21
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gameday",
        description="deterministic game-day drills with journaled "
                    "verdicts over the live serving plane")
    p.add_argument("--scenario", action="append", default=None,
                   metavar="NAME",
                   help="run one named scenario (repeatable); default "
                        "is the full suite")
    p.add_argument("--suite", action="store_true",
                   help="run the full registered suite, broken-config "
                        "demonstrations included (the default when no "
                        "--scenario is given)")
    p.add_argument("--smoke", action="store_true",
                   help="time/load-shrunk pass over the same topologies "
                        "and predicates (scenario.scaled)")
    p.add_argument("--smoke-factor", type=float, default=0.4,
                   help="load shrink factor for --smoke (default 0.4; "
                        "dispatch floors scale inversely so overload "
                        "scenarios still overload)")
    p.add_argument("--seed", type=int, default=None,
                   help="override every scenario's seed (same "
                        "(scenario, seed) => byte-identical schedule)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the suite JSON (records + verdict table "
                        "+ provenance stamps) here")
    p.add_argument("--keep", action="store_true",
                   help="keep the per-scenario workdirs (journals, "
                        "policies) for post-mortem instead of deleting")
    p.add_argument("--list", action="store_true",
                   help="list registered scenarios and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from fast_autoaugment_tpu.gameday.scenario import SCENARIOS, suite_names

    if args.list:
        for name in suite_names():
            s = SCENARIOS[name]
            print(f"{name} (expect {s.expect}): {s.summary}")
        return 0

    names = suite_names() if (args.suite or not args.scenario) \
        else list(args.scenario)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)}; "
              f"--list shows the registry", file=sys.stderr)
        return 2

    # provenance stamps ride the suite JSON: a verdict captured on a
    # contended host is evidence about the host, not the plane
    extra = {"single_core_caveat": True}
    try:
        if _REPO not in sys.path:
            sys.path.insert(0, _REPO)
        from bench import (host_contention_stamp,
                           refuse_or_flag_contention, telemetry_stamp)
        contention = refuse_or_flag_contention(host_contention_stamp())
        extra.update(telemetry_stamp(contention=contention))
    except ImportError:
        pass  # running from an installed package without the bench kit

    from fast_autoaugment_tpu.gameday.runner import run_suite
    result = run_suite(names, smoke=args.smoke,
                       smoke_factor=args.smoke_factor, seed=args.seed,
                       out=args.out, keep=args.keep, extra=extra)
    print(result["table"])
    return 0 if result["suite_green"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
