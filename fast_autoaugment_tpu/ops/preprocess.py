"""On-device train/eval preprocessing stacks.

The reference runs its baseline transforms (RandomCrop+pad, HFlip,
Normalize, Cutout) per-image on CPU DataLoader workers
(``data.py:38-47,111-112``).  Here the full train-time stack — baseline
transforms, the augmentation *policy*, normalization and post-normalize
cutout — is one jit-compiled batched function executed on device, fused
with the train step.  The host only supplies raw uint8 batches.

Order reproduces the reference exactly (``data.py:88-112``): the policy
is applied FIRST (inserted at transforms[0], on raw pixels), then random
crop + flip, then normalize, then CutoutDefault (which zeroes a box on
the *normalized* tensor — so the fill is the per-channel mean, unlike
the policy's gray Cutout op on raw pixels).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fast_autoaugment_tpu.ops.augment import (
    apply_policy,
    apply_policy_batch_grouped,
    apply_policy_scalar_single,
    check_aug_dispatch,
)

__all__ = [
    "CIFAR_MEAN",
    "CIFAR_STD",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "normalize",
    "random_crop_with_pad",
    "random_hflip",
    "cutout_default",
    "cifar_train_batch",
    "cifar_eval_batch",
]

CIFAR_MEAN = (0.4914, 0.4822, 0.4465)  # reference data.py:34
CIFAR_STD = (0.2023, 0.1994, 0.2010)
IMAGENET_MEAN = (0.485, 0.456, 0.406)  # reference data.py:71
IMAGENET_STD = (0.229, 0.224, 0.225)


def normalize(img: jax.Array, mean: Sequence[float], std: Sequence[float]) -> jax.Array:
    """uint8-valued [0..255] float -> normalized float (ToTensor + Normalize)."""
    mean = jnp.asarray(mean, img.dtype)
    std = jnp.asarray(std, img.dtype)
    return (img / 255.0 - mean) / std


def random_crop_with_pad(img: jax.Array, key: jax.Array, pad: int = 4) -> jax.Array:
    """torchvision RandomCrop(size, padding=pad) with zero fill: pad all
    sides then take a random crop at the original size."""
    h, w, c = img.shape
    padded = jnp.pad(img, ((pad, pad), (pad, pad), (0, 0)))
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (), 0, 2 * pad + 1)
    ox = jax.random.randint(kx, (), 0, 2 * pad + 1)
    return jax.lax.dynamic_slice(padded, (oy, ox, 0), (h, w, c))


def random_hflip(img: jax.Array, key: jax.Array) -> jax.Array:
    return jnp.where(jax.random.uniform(key) < 0.5, img[:, ::-1], img)


def cutout_default(img: jax.Array, key: jax.Array, length: int) -> jax.Array:
    """DARTS-style cutout on the normalized tensor (reference
    ``CutoutDefault``, ``data.py:228-250``): zero a length x length box
    centered at a uniform integer pixel, clipped at the borders."""
    h, w = img.shape[0], img.shape[1]
    ky, kx = jax.random.split(key)
    y = jax.random.randint(ky, (), 0, h)
    x = jax.random.randint(kx, (), 0, w)
    ys, xs = jnp.mgrid[0:h, 0:w]
    inside = (
        (ys >= y - length // 2)
        & (ys < y + length // 2)
        & (xs >= x - length // 2)
        & (xs < x + length // 2)
    )
    return jnp.where(inside[..., None], 0.0, img)


def _cifar_train_one(img, policy, key, cutout_length, mean, std,
                     single_sub_scalar=False):
    k_policy, k_crop, k_flip, k_cutout = jax.random.split(key, 4)
    if policy is not None:
        if single_sub_scalar:
            # bitwise-identical to apply_policy on a [1, num_op, 3]
            # tensor, but the op index stays scalar under the batch vmap
            img = apply_policy_scalar_single(img, policy, k_policy)
        else:
            img = apply_policy(img, policy, k_policy)
    img = random_crop_with_pad(img, k_crop, 4)
    img = random_hflip(img, k_flip)
    img = normalize(img, mean, std)
    if cutout_length > 0:
        img = cutout_default(img, k_cutout, cutout_length)
    return img


def cifar_train_batch(
    images: jax.Array,
    key: jax.Array,
    policy: jax.Array | None = None,
    cutout_length: int = 16,
    mean: Sequence[float] = CIFAR_MEAN,
    std: Sequence[float] = CIFAR_STD,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
) -> jax.Array:
    """Full CIFAR/SVHN train-time stack on a [B, H, W, C] uint8-valued batch.

    `policy` is a [num_sub, num_op, 3] tensor (or None for 'default' aug).
    ``aug_dispatch="exact"`` (default) is bit-for-bit the historical
    per-image path; ``"grouped"`` applies the policy through
    :func:`apply_policy_batch_grouped` (scalar op dispatch, stratified
    per-chunk sub-policy draws, `aug_groups` chunks) before the
    per-image crop/flip/normalize/cutout stack.  A single-sub-policy
    tensor under "grouped" takes the bitwise-exact scalar path instead
    (no selection to stratify)."""
    check_aug_dispatch(aug_dispatch)
    images = images.astype(jnp.float32)
    single_sub = policy is not None and int(policy.shape[0]) == 1
    if aug_dispatch == "grouped" and policy is not None and not single_sub:
        key, key_pol = jax.random.split(key)
        images = apply_policy_batch_grouped(images, policy, key_pol,
                                            groups=aug_groups)
        policy = None
    scalar = aug_dispatch == "grouped" and single_sub
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(
        lambda im, k: _cifar_train_one(im, policy, k, cutout_length, mean, std,
                                       single_sub_scalar=scalar)
    )(images, keys)


def cifar_eval_batch(
    images: jax.Array,
    mean: Sequence[float] = CIFAR_MEAN,
    std: Sequence[float] = CIFAR_STD,
) -> jax.Array:
    """Eval stack: normalize only (reference ``data.py:45-47``)."""
    return normalize(images.astype(jnp.float32), mean, std)
