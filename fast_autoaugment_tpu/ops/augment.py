"""On-device batched augmentation kernels.

The reference applies its 19 registered augmentation ops per-image with
PIL on CPU DataLoader workers (reference ``augmentations.py:13-194``,
``data.py:253-264``).  Here every op is a pure ``jnp`` function on a
``[H, W, C]`` float32 image holding integral uint8 values in [0, 255],
with explicit PRNG keys, vmapped over the batch and jit-compiled — the
augmentation runs on the TPU, fused into the input side of the train
step, and the *policy is a tensor input* rather than Python structure.
That last property is what makes TTA policy search fast: one compiled
evaluation step serves every candidate policy (SURVEY.md section 7).

Semantics were pinned against PIL empirically and are exact (see
``tests/test_augment_golden.py``):

- affine/rotate: nearest-neighbor, ``src = floor(A @ (x, y) + t + 0.5)``,
  fill 0, rotate about ``(W/2, H/2)``  (PIL ``Image.transform``
  with ``AFFINE`` / ``Image.rotate``, reference ``augmentations.py:17-62``)
- L (grayscale): ``(r*19595 + g*38470 + b*7471 + 0x8000) >> 16``
- enhance ops: ``clip(trunc(deg + (img - deg) * factor), 0, 255)`` in
  float32 (PIL ``ImageEnhance`` via ``Image.blend``)
- equalize / autocontrast: PIL's exact integer LUT constructions
- SMOOTH filter (sharpness degenerate): 3x3 kernel [[1,1,1],[1,5,1],
  [1,1,1]]/13, ``trunc(acc + 0.5)``, 1-pixel border copied unfiltered

Op registry (19 ops) mirrors the reference's ``augment_list(True)``
(``augmentations.py:156-182``): indices 0-14 are the searchable ops
(``augment_list(False)``), 15-18 the AutoAugment-compat extras.  ``Flip``
exists in the reference source but is never registered (SURVEY.md
errata 1) — provided here as a standalone function only.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "OP_NAMES",
    "SEARCH_OP_NAMES",
    "AUG_DISPATCH_MODES",
    "op_index",
    "augment_list",
    "apply_augment",
    "apply_op",
    "apply_subpolicy",
    "apply_subpolicy_batch",
    "apply_policy",
    "apply_policy_scalar_single",
    "apply_policy_batch",
    "apply_policy_batch_grouped",
    "check_aug_dispatch",
    "CUTOUT_COLOR",
]

# (name, low, high, mirrored): value = level * (high - low) + low, then the
# sign is flipped with prob 0.5 when `mirrored` (reference `random_mirror`,
# augmentations.py:10-16; TranslateX/YAbs always mirror, :44-56).
_OP_TABLE = (
    ("ShearX", -0.3, 0.3, True),
    ("ShearY", -0.3, 0.3, True),
    ("TranslateX", -0.45, 0.45, True),
    ("TranslateY", -0.45, 0.45, True),
    ("Rotate", -30.0, 30.0, True),
    ("AutoContrast", 0.0, 1.0, False),
    ("Invert", 0.0, 1.0, False),
    ("Equalize", 0.0, 1.0, False),
    ("Solarize", 0.0, 256.0, False),
    ("Posterize", 4.0, 8.0, False),
    ("Contrast", 0.1, 1.9, False),
    ("Color", 0.1, 1.9, False),
    ("Brightness", 0.1, 1.9, False),
    ("Sharpness", 0.1, 1.9, False),
    ("Cutout", 0.0, 0.2, False),
    ("CutoutAbs", 0.0, 20.0, False),  # no sign flip (augmentations.py:127-131)
    ("Posterize2", 0.0, 4.0, False),
    ("TranslateXAbs", 0.0, 10.0, True),
    ("TranslateYAbs", 0.0, 10.0, True),
)

OP_NAMES: tuple[str, ...] = tuple(t[0] for t in _OP_TABLE)
NUM_OPS = len(OP_NAMES)
SEARCH_OP_NAMES: tuple[str, ...] = OP_NAMES[:15]  # augment_list(False)
_OP_LOW = np.array([t[1] for t in _OP_TABLE], np.float32)
_OP_HIGH = np.array([t[2] for t in _OP_TABLE], np.float32)
_OP_MIRROR = np.array([t[3] for t in _OP_TABLE], np.bool_)

CUTOUT_COLOR = (125.0, 123.0, 114.0)  # reference augmentations.py:140

# dispatch modes for batched policy application: "exact" is the i.i.d.
# per-image sub-policy draw (vmapped lax.switch — XLA lowers the batched
# op index to executing ALL 19 branches per image and selecting one);
# "grouped" keeps the switch index SCALAR inside the compiled program
# (stratified per-chunk sub-policy draws; one branch executes).
AUG_DISPATCH_MODES = ("exact", "grouped")


def check_aug_dispatch(mode: str) -> str:
    if mode not in AUG_DISPATCH_MODES:
        raise ValueError(
            f"aug_dispatch must be one of {AUG_DISPATCH_MODES}, got {mode!r}")
    return mode


def op_index(name: str) -> int:
    return OP_NAMES.index(name)


def augment_list(for_autoaug: bool = True) -> list[tuple[str, float, float]]:
    """Name/range table, same contract as reference ``augment_list``."""
    rows = _OP_TABLE if for_autoaug else _OP_TABLE[:15]
    return [(name, low, high) for name, low, high, _ in rows]


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def _to_int(img: jax.Array) -> jax.Array:
    return jnp.clip(img, 0.0, 255.0).astype(jnp.int32)


def _grayscale_u8(img: jax.Array) -> jax.Array:
    """PIL 'L' conversion on integral-valued float input -> int32 [H, W]."""
    ii = _to_int(img)
    r, g, b = ii[..., 0], ii[..., 1], ii[..., 2]
    return (r * 19595 + g * 38470 + b * 7471 + 0x8000) >> 16


def _blend(degenerate: jax.Array, img: jax.Array, factor: jax.Array) -> jax.Array:
    """PIL Image.blend + uint8 store: float32 lerp, trunc, clip."""
    out = degenerate + (img - degenerate) * factor
    return jnp.clip(jnp.trunc(out), 0.0, 255.0)


def _apply_lut(img: jax.Array, lut: jax.Array) -> jax.Array:
    """Per-channel 256-entry LUT gather; lut [C, 256] or [256]."""
    ii = _to_int(img)
    if lut.ndim == 1:
        return lut[ii].astype(jnp.float32)
    out = jnp.stack([lut[c][ii[..., c]] for c in range(img.shape[-1])], axis=-1)
    return out.astype(jnp.float32)


def _warp_affine_nearest(img: jax.Array, mat: jax.Array) -> jax.Array:
    """PIL-exact nearest affine warp with zero fill.

    `mat` is the 2x3 PIL-convention inverse map [[a, b, c], [d, e, f]]
    from output to source coords.  PIL samples at pixel centers with a
    plain floor: ``src = floor(A @ (x+0.5, y+0.5) + t)`` (pinned
    empirically; the center offset matters for tie-breaking at .5).
    """
    h, w = img.shape[0], img.shape[1]
    ys, xs = jnp.mgrid[0:h, 0:w]
    xsf, ysf = xs.astype(jnp.float32) + 0.5, ys.astype(jnp.float32) + 0.5
    sx = jnp.floor(mat[0, 0] * xsf + mat[0, 1] * ysf + mat[0, 2]).astype(jnp.int32)
    sy = jnp.floor(mat[1, 0] * xsf + mat[1, 1] * ysf + mat[1, 2]).astype(jnp.int32)
    valid = (sx >= 0) & (sx < w) & (sy >= 0) & (sy < h)
    gathered = img[jnp.clip(sy, 0, h - 1), jnp.clip(sx, 0, w - 1)]
    return jnp.where(valid[..., None], gathered, 0.0)


def _histogram256(channel_int: jax.Array) -> jax.Array:
    """256-bin histogram via sort + searchsorted.

    Scatter-adds serialize on TPU and a [N, 256] one-hot materializes
    ~100x more intermediate data; sorting the N pixels and differencing
    bin-edge ranks is ~9x faster (measured in tools/bench_aug.py — the
    histogram made Equalize the single hottest augmentation op) and
    vmaps cleanly.
    """
    flat = channel_int.reshape(-1)
    s = jnp.sort(flat)
    edges = jnp.arange(257, dtype=jnp.int32)
    ranks = jnp.searchsorted(s, edges, side="left").astype(jnp.int32)
    return jnp.diff(ranks)


# ---------------------------------------------------------------------------
# the 19 ops — each is (img [H,W,C] f32 integral, value f32 scalar, key) -> img
# ---------------------------------------------------------------------------


def shear_x(img, v, key):
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[0, 1].set(v))


def shear_y(img, v, key):
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[1, 0].set(v))


def translate_x(img, v, key):
    # fractional of width (reference augmentations.py:28-33)
    shift = v * img.shape[1]
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[0, 2].set(shift))


def translate_y(img, v, key):
    shift = v * img.shape[0]
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[1, 2].set(shift))


def translate_x_abs(img, v, key):
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[0, 2].set(v))


def translate_y_abs(img, v, key):
    return _warp_affine_nearest(img, jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]).at[1, 2].set(v))


def rotate(img, v, key):
    """PIL Image.rotate(v): CCW degrees about (W/2, H/2), nearest."""
    h, w = img.shape[0], img.shape[1]
    cx, cy = w / 2.0, h / 2.0
    rad = v * (np.pi / 180.0)
    ca, sa = jnp.cos(rad), jnp.sin(rad)
    mat = jnp.array(
        [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]
    )
    mat = mat.at[0, 0].set(ca).at[0, 1].set(-sa).at[0, 2].set(cx - ca * cx + sa * cy)
    mat = mat.at[1, 0].set(sa).at[1, 1].set(ca).at[1, 2].set(cy - sa * cx - ca * cy)
    return _warp_affine_nearest(img, mat)


def auto_contrast(img, v, key):
    """PIL ImageOps.autocontrast(cutoff=0): per-channel min/max stretch LUT.

    Computed as the exact rational ``(i - lo) * 255 // (hi - lo)``.  PIL
    evaluates the same map in double precision with truncation, which
    lands 1 below the exact value on ~20% of images — so outputs may
    differ from PIL by at most 1 (deliberate deviation; the exact form
    is stable in float-free integer math on device).
    """
    ii = _to_int(img)
    lo = ii.min(axis=(0, 1))  # [C]
    hi = ii.max(axis=(0, 1))
    ix = jnp.arange(256, dtype=jnp.int32)
    span = jnp.maximum(hi - lo, 1)
    lut = jnp.clip((ix[None, :] - lo[:, None]) * 255 // span[:, None], 0, 255)
    identity = hi <= lo
    lut = jnp.where(identity[:, None], ix[None, :], lut)
    return _apply_lut(img, lut)


def invert(img, v, key):
    return 255.0 - jnp.clip(img, 0.0, 255.0)


def equalize(img, v, key):
    """PIL ImageOps.equalize: per-channel integer histogram remap."""
    ii = _to_int(img)

    def one_channel(ch):
        h = _histogram256(ch)
        total = jnp.sum(h)
        nonzero = h > 0
        num_nonzero = jnp.sum(nonzero)
        # value of the last nonzero bin
        last_idx = 255 - jnp.argmax(nonzero[::-1])
        h_last = h[last_idx]
        step = (total - h_last) // 255
        csum = jnp.cumsum(h) - h  # exclusive cumsum
        n = step // 2 + csum
        lut = jnp.clip(n // jnp.maximum(step, 1), 0, 255)
        ix = jnp.arange(256, dtype=jnp.int32)
        use_identity = (num_nonzero <= 1) | (step == 0)
        return jnp.where(use_identity, ix, lut)

    lut = jnp.stack([one_channel(ii[..., c]) for c in range(img.shape[-1])])
    return _apply_lut(img, lut)


def solarize(img, v, key):
    ii = jnp.clip(img, 0.0, 255.0)
    return jnp.where(ii < v, ii, 255.0 - ii)


def _posterize_bits(img, bits):
    mask = jnp.left_shift(jnp.int32(0xFF), 8 - bits) & 0xFF
    return (_to_int(img) & mask).astype(jnp.float32)


def posterize(img, v, key):
    # int(v), v in [4, 8] (reference augmentations.py:85-88)
    return _posterize_bits(img, jnp.trunc(v).astype(jnp.int32))


def posterize2(img, v, key):
    # v in [0, 4] (reference augmentations.py:91-94)
    return _posterize_bits(img, jnp.trunc(v).astype(jnp.int32))


def contrast(img, v, key):
    gray = _grayscale_u8(img)
    mean = jnp.trunc(gray.astype(jnp.float32).mean() + 0.5)
    return _blend(jnp.full_like(img, mean), jnp.clip(img, 0.0, 255.0), v)


def color(img, v, key):
    deg = jnp.repeat(_grayscale_u8(img)[..., None].astype(jnp.float32), img.shape[-1], axis=-1)
    return _blend(deg, jnp.clip(img, 0.0, 255.0), v)


def brightness(img, v, key):
    return _blend(jnp.zeros_like(img), jnp.clip(img, 0.0, 255.0), v)


def _smooth_degenerate(img: jax.Array) -> jax.Array:
    """PIL ImageFilter.SMOOTH: 3x3 [[1,1,1],[1,5,1],[1,1,1]]/13, border copied."""
    h, w = img.shape[0], img.shape[1]
    kernel = np.array([[1, 1, 1], [1, 5, 1], [1, 1, 1]], np.float32) / 13.0
    padded = jnp.pad(img, ((1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros_like(img)
    for dy in range(3):
        for dx in range(3):
            acc = acc + kernel[dy, dx] * jax.lax.dynamic_slice(
                padded, (dy, dx, 0), (h, w, img.shape[2])
            )
    sm = jnp.clip(jnp.trunc(acc + 0.5), 0.0, 255.0)
    border = jnp.zeros((h, w, 1), bool).at[0, :].set(True).at[-1, :].set(True).at[:, 0].set(True).at[:, -1].set(True)
    return jnp.where(border, jnp.clip(img, 0.0, 255.0), sm)


def sharpness(img, v, key):
    return _blend(_smooth_degenerate(img), jnp.clip(img, 0.0, 255.0), v)


def _cutout_abs(img, v, key):
    """Gray rectangle at uniform center (reference CutoutAbs, augmentations.py:127-146).

    PIL's ImageDraw.rectangle fills the box *inclusive* of (x1, y1).
    """
    h, w = img.shape[0], img.shape[1]
    kx, ky = jax.random.split(key)
    x0f = jax.random.uniform(kx, (), minval=0.0, maxval=float(w))
    y0f = jax.random.uniform(ky, (), minval=0.0, maxval=float(h))
    x0 = jnp.trunc(jnp.maximum(0.0, x0f - v / 2.0))
    y0 = jnp.trunc(jnp.maximum(0.0, y0f - v / 2.0))
    x1 = jnp.minimum(float(w), x0 + v)
    y1 = jnp.minimum(float(h), y0 + v)
    ys, xs = jnp.mgrid[0:h, 0:w]
    inside = (
        (xs.astype(jnp.float32) >= x0)
        & (xs.astype(jnp.float32) <= x1)
        & (ys.astype(jnp.float32) >= y0)
        & (ys.astype(jnp.float32) <= y1)
    )
    fill = jnp.asarray(CUTOUT_COLOR, img.dtype)
    out = jnp.where(inside[..., None], fill, img)
    return jnp.where(v < 0.0, img, out)


def cutout(img, v, key):
    # fractional of width; <= 0 is identity (reference augmentations.py:118-124)
    out = _cutout_abs(img, v * img.shape[1], key)
    return jnp.where(v <= 0.0, img, out)


def cutout_abs(img, v, key):
    return _cutout_abs(img, v, key)


def flip(img, v, key):
    """PIL ImageOps.mirror — defined in the reference but never registered."""
    return img[:, ::-1]


_OP_FNS = (
    shear_x, shear_y, translate_x, translate_y, rotate,
    auto_contrast, invert, equalize, solarize, posterize,
    contrast, color, brightness, sharpness, cutout,
    cutout_abs, posterize2, translate_x_abs, translate_y_abs,
)
assert len(_OP_FNS) == NUM_OPS


# ---------------------------------------------------------------------------
# dispatch + policy application
# ---------------------------------------------------------------------------


def apply_augment(img: jax.Array, name: str, level, key: jax.Array) -> jax.Array:
    """Single named op at `level` in [0, 1] (reference ``apply_augment``,
    ``augmentations.py:192-194``) — includes the random mirror."""
    return apply_op(img, jnp.int32(op_index(name)), jnp.float32(level), key)


@functools.lru_cache(maxsize=None)
def _op_range_constants():
    """Device-resident (low, high, mirror) op-range tables, built ONCE.

    ``apply_op`` used to call ``jnp.asarray(_OP_LOW)`` (and friends) per
    invocation, rebuilding the three constants on every trace.  Lazy
    (not module-level) so importing this module never eagerly
    initializes a JAX backend — bench tools probe backend liveness
    before touching the device.  ``ensure_compile_time_eval`` keeps the
    cached values CONCRETE even when the first call lands inside a
    trace (a cached tracer would escape its trace scope)."""
    with jax.ensure_compile_time_eval():
        return (jnp.asarray(_OP_LOW), jnp.asarray(_OP_HIGH),
                jnp.asarray(_OP_MIRROR))


def apply_op(img: jax.Array, op_idx: jax.Array, level: jax.Array, key: jax.Array) -> jax.Array:
    """Apply op `op_idx` (traced scalar) at `level` in [0, 1].

    Maps level -> value = level*(high-low)+low and flips the sign with
    prob 0.5 for mirrored (geometric) ops, then dispatches via
    ``lax.switch`` so the op id can be a runtime tensor (policy-as-data).
    """
    key_mirror, key_op = jax.random.split(key)
    op_low, op_high, op_mirror = _op_range_constants()
    low = op_low[op_idx]
    high = op_high[op_idx]
    value = level * (high - low) + low
    mirrored = op_mirror[op_idx]
    sign = jnp.where(
        mirrored & (jax.random.uniform(key_mirror) > 0.5), -1.0, 1.0
    )
    value = value * sign
    branches = [functools.partial(_call_op, fn) for fn in _OP_FNS]
    return jax.lax.switch(op_idx, branches, img, value, key_op)


def _call_op(fn, img, value, key):
    return fn(img, value, key)


def apply_subpolicy(img: jax.Array, subpolicy: jax.Array, key: jax.Array) -> jax.Array:
    """Apply one sub-policy: rows of (op_idx, prob, level).

    Each op fires independently with its probability (reference
    ``Augmentation.__call__``, ``data.py:257-263``).
    """
    num_op = subpolicy.shape[0]

    def body(i, carry):
        img, key = carry
        key, key_gate, key_op = jax.random.split(key, 3)
        op_idx = subpolicy[i, 0].astype(jnp.int32)
        prob = subpolicy[i, 1]
        level = subpolicy[i, 2]
        out = apply_op(img, op_idx, level, key_op)
        img = jnp.where(jax.random.uniform(key_gate) < prob, out, img)
        return img, key

    # num_op is tiny (2); unrolled python loop keeps XLA free to fuse
    carry = (img, key)
    for i in range(num_op):
        carry = body(i, carry)
    return carry[0]


def apply_policy(img: jax.Array, policy: jax.Array, key: jax.Array) -> jax.Array:
    """Pick one random sub-policy from `policy` [num_sub, num_op, 3] and
    apply it (reference ``Augmentation``, ``data.py:253-264``)."""
    key_choice, key_sub = jax.random.split(key)
    idx = jax.random.randint(key_choice, (), 0, policy.shape[0])
    return apply_subpolicy(img, policy[idx], key_sub)


def apply_policy_batch(images: jax.Array, policy: jax.Array, key: jax.Array) -> jax.Array:
    """vmapped :func:`apply_policy` over a [B, H, W, C] batch.

    This is the EXACT dispatch path: every image draws its sub-policy
    i.i.d., which makes the ``lax.switch`` op index a batched tensor —
    XLA lowers that to executing all ``NUM_OPS`` branches for every
    image per op slot and selecting one (~19x redundant op compute).
    :func:`apply_policy_batch_grouped` is the scalar-dispatch
    alternative."""
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(apply_policy, in_axes=(0, None, 0))(images, policy, keys)


# ---------------------------------------------------------------------------
# grouped scalar dispatch
# ---------------------------------------------------------------------------


def apply_policy_scalar_single(img: jax.Array, policy: jax.Array, key: jax.Array) -> jax.Array:
    """:func:`apply_policy` specialized to a SINGLE-sub-policy tensor.

    Consumes the key stream identically (the sub-policy-choice key is
    split off and discarded — with one sub-policy the draw is
    vacuous), but indexes ``policy[0]`` statically instead of through a
    traced ``randint``: under an outer per-image vmap the op indices
    stay UNBATCHED, so ``lax.switch`` keeps its scalar index and
    executes exactly one branch.  Output is bitwise identical to
    :func:`apply_policy` on the same ``[1, num_op, 3]`` policy."""
    _key_choice, key_sub = jax.random.split(key)
    return apply_subpolicy(img, policy[0], key_sub)


def apply_subpolicy_batch(images: jax.Array, subpolicy: jax.Array, key: jax.Array) -> jax.Array:
    """Apply ONE sub-policy to a whole [B, H, W, C] batch with scalar
    op dispatch: `subpolicy` is unbatched under the image vmap, so each
    ``lax.switch`` executes exactly one branch for the whole batch.
    Per-image randomness (the `prob` gates, mirror signs, Cutout
    centers) stays per-image through the vmapped keys."""
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(apply_subpolicy, in_axes=(0, None, 0))(images, subpolicy, keys)


def grouped_permutation(key: jax.Array, batch: int):
    """Shared helper: a PRNG-derived batch permutation and its inverse.

    ``out[inv]`` undoes ``x[perm]`` — the grouped kernels shuffle with
    `perm`, process contiguous chunks, and restore original order with
    `inv`."""
    perm = jax.random.permutation(key, batch)
    inv = jnp.argsort(perm)
    return perm, inv


def apply_policy_batch_grouped(images: jax.Array, policy: jax.Array,
                               key: jax.Array, *, groups: int) -> jax.Array:
    """Grouped scalar-dispatch :func:`apply_policy_batch`.

    Permutes the batch with a PRNG-derived permutation, splits it into
    `groups` contiguous chunks, draws ONE sub-policy per chunk and
    applies each chunk through :func:`apply_subpolicy` with a SCALAR op
    index (the chunk loop is a ``lax.scan``, so the compiled program
    contains one switch per op slot and each invocation executes
    exactly one branch), then inverse-permutes.  Per-image `prob`
    gating, mirror signs and op randomness remain exactly per-image.

    Distributional deviation vs the exact path (documented in
    docs/BENCHMARKS.md "Augmentation dispatch"): sub-policy selection
    is STRATIFIED — each batch sees fixed per-chunk counts instead of
    i.i.d. per-image draws.  The per-image marginal is unchanged (the
    uniform permutation makes every image's chunk — hence its
    sub-policy — uniform); only within-batch selection counts and the
    joint (images of one chunk share a sub-policy) differ.

    A single-sub-policy tensor short-circuits to the bitwise-exact
    scalar path (:func:`apply_policy_scalar_single`): with one
    sub-policy there is no selection to stratify, so grouped == exact
    bit-for-bit — the case the TTA sub-policy audit runs per lane.
    """
    b = images.shape[0]
    g = int(groups)
    if g < 1:
        raise ValueError(f"groups must be >= 1, got {groups}")
    if int(policy.shape[0]) == 1:
        keys = jax.random.split(key, b)
        return jax.vmap(apply_policy_scalar_single, in_axes=(0, None, 0))(
            images, policy, keys)
    g = min(g, b)
    key_perm, key_groups = jax.random.split(key)
    perm, inv = grouped_permutation(key_perm, b)
    shuffled = jnp.take(images, perm, axis=0)
    chunk = -(-b // g)  # ceil: uneven batches pad up to g full chunks
    pad = g * chunk - b
    if pad:
        shuffled = jnp.concatenate([shuffled, shuffled[:pad]], axis=0)
    grouped = shuffled.reshape((g, chunk) + images.shape[1:])
    group_keys = jax.random.split(key_groups, g)

    def one_group(_, xs):
        imgs, k = xs
        key_choice, key_apply = jax.random.split(k)
        idx = jax.random.randint(key_choice, (), 0, policy.shape[0])
        return None, apply_subpolicy_batch(imgs, policy[idx], key_apply)

    _, out = jax.lax.scan(one_group, None, (grouped, group_keys))
    out = out.reshape((g * chunk,) + images.shape[1:])[:b]
    return jnp.take(out, inv, axis=0)
