"""Stochastic shake regularizers with independent forward/backward noise.

ShakeShake (reference ``networks/shakeshake/shakeshake.py:9-26``) mixes
two branches with per-sample alpha ~ U(0,1) in the forward pass but
back-propagates through a FRESH per-sample beta ~ U(0,1) — the backward
randomness is intentionally different from the forward.  ShakeDrop
(reference ``networks/shakedrop.py:9-45``) gates a residual branch with
a per-call Bernoulli; on "drop" it scales forward by per-sample
alpha ~ U(-1,1) and backward by fresh beta ~ U(0,1).

Autodiff can't express "different randomness on the way back", so these
are ``jax.custom_vjp`` primitives taking BOTH noises as explicit array
arguments (sampled by the caller from split PRNG keys).  This keeps
them pure, jit/vmap/pjit-compatible, and trivially testable — the VJP
tests verify the backward really uses beta, not alpha.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["shake_shake", "shake_shake_eval", "shake_drop", "shake_drop_eval",
           "sample_shake_shake_noise", "sample_shake_drop_noise"]


# ---------------------------------------------------------------------------
# ShakeShake
# ---------------------------------------------------------------------------


@jax.custom_vjp
def shake_shake(x1: jax.Array, x2: jax.Array, alpha: jax.Array, beta: jax.Array) -> jax.Array:
    """Forward: alpha * x1 + (1 - alpha) * x2; backward mixes grads by beta.

    alpha/beta broadcast against x (shape [B, 1, 1, 1] for per-sample).
    """
    return alpha * x1 + (1.0 - alpha) * x2


def _shake_shake_fwd(x1, x2, alpha, beta):
    return shake_shake(x1, x2, alpha, beta), beta


def _shake_shake_bwd(beta, g):
    return (beta * g, (1.0 - beta) * g, jnp.zeros_like(beta), jnp.zeros_like(beta))


shake_shake.defvjp(_shake_shake_fwd, _shake_shake_bwd)


def shake_shake_eval(x1: jax.Array, x2: jax.Array) -> jax.Array:
    """Eval path: deterministic 0.5 mix (reference ``shakeshake.py:17``)."""
    return 0.5 * (x1 + x2)


def sample_shake_shake_noise(key: jax.Array, batch: int, dtype=jnp.float32):
    """Per-sample (alpha, beta) ~ U(0,1), shaped [B, 1, 1, 1]."""
    ka, kb = jax.random.split(key)
    shape = (batch, 1, 1, 1)
    return (jax.random.uniform(ka, shape, dtype),
            jax.random.uniform(kb, shape, dtype))


# ---------------------------------------------------------------------------
# ShakeDrop
# ---------------------------------------------------------------------------


@jax.custom_vjp
def shake_drop(x: jax.Array, gate: jax.Array, alpha: jax.Array, beta: jax.Array) -> jax.Array:
    """Forward: x if gate else alpha * x; backward: g if gate else beta * g.

    gate is a scalar (per call, as in the reference ``shakedrop.py:14``);
    alpha/beta are per-sample [B, 1, 1, 1].
    """
    return jnp.where(gate > 0.5, x, alpha * x)


def _shake_drop_fwd(x, gate, alpha, beta):
    return shake_drop(x, gate, alpha, beta), (gate, beta)


def _shake_drop_bwd(res, g):
    gate, beta = res
    return (
        jnp.where(gate > 0.5, g, beta * g),
        jnp.zeros_like(gate),
        jnp.zeros_like(beta),
        jnp.zeros_like(beta),
    )


shake_drop.defvjp(_shake_drop_fwd, _shake_drop_bwd)


def shake_drop_eval(x: jax.Array, p_drop: float) -> jax.Array:
    """Eval path: expectation scaling by (1 - p_drop) (reference ``shakedrop.py:22``)."""
    return (1.0 - p_drop) * x


def sample_shake_drop_noise(key: jax.Array, batch: int, p_drop: float, dtype=jnp.float32):
    """(gate, alpha, beta): scalar gate ~ Bernoulli(1 - p_drop) (1 = keep),
    alpha ~ U(-1,1), beta ~ U(0,1), per-sample [B, 1, 1, 1]."""
    kg, ka, kb = jax.random.split(key, 3)
    shape = (batch, 1, 1, 1)
    gate = jax.random.bernoulli(kg, 1.0 - p_drop).astype(dtype)
    alpha = jax.random.uniform(ka, shape, dtype, minval=-1.0, maxval=1.0)
    beta = jax.random.uniform(kb, shape, dtype)
    return gate, alpha, beta
