"""Learning-rate schedules as pure functions of the global step.

The reference steps its torch schedulers once per BATCH with a
fractional epoch ``epoch - 1 + steps/total`` (``train.py:90-91``), so
every schedule here is a pure function of fractional epoch
``t = step / steps_per_epoch``, trivially usable inside a jitted train
step.  Implemented schedules (reference ``train.py:158-174``,
``lr_scheduler.py:6-27``):

- cosine: ``base * (1 + cos(pi t / T)) / 2`` (CosineAnnealingLR, eta_min 0)
- resnet step: x0.1 at {30, 60, 80} for 90 epochs / {90, 180, 240} for 270
- efficientnet: ``0.97 ** int(t / 2.4)``
- gradual warmup wrapper (the external ``warmup_scheduler`` package the
  reference depends on): linear base -> base*multiplier over
  ``warmup_epoch``, after which the inner schedule runs with its epoch
  shifted by -warmup_epoch and its base lr scaled by the multiplier.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = ["cosine", "multistep", "exponential_efficientnet", "warmup_wrap", "build_schedule"]

Schedule = Callable  # t (fractional epoch, jnp scalar) -> lr


def cosine(base_lr: float, total_epochs: float) -> Schedule:
    def fn(t):
        return base_lr * (1.0 + jnp.cos(jnp.pi * t / total_epochs)) / 2.0

    return fn


def multistep(base_lr: float, milestones: Sequence[float], gamma: float = 0.1) -> Schedule:
    ms = np.asarray(milestones, np.float32)

    def fn(t):
        count = jnp.sum(jnp.asarray(t, jnp.float32) >= ms)
        return base_lr * gamma ** count.astype(jnp.float32)

    return fn


def exponential_efficientnet(base_lr: float, warmup_epoch: float) -> Schedule:
    """LambdaLR ``0.97 ** int((x + warmup_epoch) / 2.4)`` (``train.py:163-164``)
    where x is the post-warmup shifted epoch."""

    def fn(t_shifted):
        k = jnp.floor((t_shifted + warmup_epoch) / 2.4)
        return base_lr * 0.97**k

    return fn


def warmup_wrap(inner: Schedule, base_lr: float, multiplier: float, warmup_epoch: float,
                inner_base_scale: bool = True) -> Schedule:
    """GradualWarmupScheduler semantics.

    For t <= warmup_epoch: ``base * ((multiplier - 1) * t / warmup + 1)``.
    After: ``multiplier * inner(t - warmup_epoch)`` (the package rescales
    the inner scheduler's base lrs and shifts its epoch).
    """

    def fn(t):
        t = jnp.asarray(t, jnp.float32)
        warm = base_lr * ((multiplier - 1.0) * t / warmup_epoch + 1.0)
        after = multiplier * inner(t - warmup_epoch) if inner_base_scale else inner(t - warmup_epoch)
        return jnp.where(t <= warmup_epoch, warm, after)

    return fn


def build_schedule(conf: Any, steps_per_epoch: int, world_lr_scale: float = 1.0) -> Callable:
    """Build lr(step) from the conf schema
    ``{lr, epoch, lr_schedule{type, warmup{multiplier, epoch}}}``.

    `world_lr_scale` reproduces the linear LR scaling by data-parallel
    world size (``train.py:117``).  Returns a function of the global
    (0-based) optimizer step.
    """
    base_lr = float(conf["lr"]) * world_lr_scale
    total_epochs = float(conf["epoch"])
    sched_conf = conf.get("lr_schedule", {}) or {}
    kind = sched_conf.get("type", "cosine") if hasattr(sched_conf, "get") else "cosine"
    warmup = sched_conf.get("warmup", None) if hasattr(sched_conf, "get") else None
    warmup_epoch = float(warmup["epoch"]) if warmup else 0.0

    if kind == "cosine":
        inner = cosine(base_lr, total_epochs)
    elif kind == "resnet":
        if int(total_epochs) == 90:
            inner = multistep(base_lr, (30, 60, 80))
        elif int(total_epochs) == 270:
            inner = multistep(base_lr, (90, 180, 240))
        else:
            raise ValueError(f"invalid epoch={total_epochs} for resnet schedule")
    elif kind == "efficientnet":
        inner = exponential_efficientnet(base_lr, warmup_epoch)
    else:
        raise ValueError(f"invalid lr_schedule {kind!r}")

    if warmup and warmup_epoch > 0:
        epoch_fn = warmup_wrap(inner, base_lr, float(warmup["multiplier"]), warmup_epoch)
    else:
        epoch_fn = inner

    def lr_at_step(step):
        t = jnp.asarray(step, jnp.float32) / float(steps_per_epoch)
        return epoch_fn(t)

    return lr_at_step
