"""ImageNet preprocessing: host crop/resize + on-device batched augment.

The reference's ImageNet train stack (``data.py:60-74``) is
EfficientNetRandomCrop -> bicubic resize -> HFlip -> ColorJitter(0.4,
0.4, 0.4) -> ToTensor -> PCA Lighting(0.1) -> Normalize, all per-image
on CPU workers.  TPU-native split:

- **Host** (variable-size source images): decode, pick the TF
  ``sample_distorted_bounding_box``-style crop (the exact rejection-
  sampling loop of ``EfficientNetRandomCrop``, ``data.py:267-320``, with
  the same center-crop fallback, ``data.py:323-345``), crop + bicubic
  resize to the static target size.  Scalar math + PIL's native resize;
  this is the only part that genuinely needs variable shapes.
- **Device** (static [B, S, S, 3]): augmentation policy, horizontal
  flip, ColorJitter with torchvision semantics (factors ~ U(1-s, 1+s),
  the three adjustments applied in random order — each adjustment is
  the PIL-exact enhance kernel from ``ops/augment``), AlexNet-style PCA
  lighting noise (``augmentations.py:197-215``), normalize.

Deliberate deviation: the reference inserts the policy at transforms[0]
(full-resolution source image); here it applies after crop/resize at
the network resolution — required for static shapes, and harmless to
density matching since all geometric op magnitudes are
resolution-relative or resolution-independent.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from fast_autoaugment_tpu.ops.augment import (
    apply_policy,
    apply_policy_batch_grouped,
    apply_policy_scalar_single,
    check_aug_dispatch,
)
from fast_autoaugment_tpu.ops.augment import brightness as _brightness
from fast_autoaugment_tpu.ops.augment import color as _saturation
from fast_autoaugment_tpu.ops.augment import contrast as _contrast
from fast_autoaugment_tpu.ops.preprocess import IMAGENET_MEAN, IMAGENET_STD, normalize

__all__ = [
    "random_crop_box",
    "center_crop_box",
    "host_train_image",
    "host_eval_image",
    "imagenet_train_batch",
    "imagenet_eval_batch",
]

# reference data.py:21-33
_PCA_EIGVAL = np.array([0.2175, 0.0188, 0.0045], np.float32)
_PCA_EIGVEC = np.array(
    [[-0.5675, 0.7192, 0.4009],
     [-0.5808, -0.0045, -0.8140],
     [-0.5836, -0.6948, 0.4203]],
    np.float32,
)


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------


def center_crop_box(width: int, height: int, imgsize: int):
    """EfficientNetCenterCrop box (``data.py:326-345``)."""
    short = min(width, height)
    crop_size = float(imgsize) / (imgsize + 32) * short
    top = int(round((height - crop_size) / 2.0))
    left = int(round((width - crop_size) / 2.0))
    return left, top, left + crop_size, top + crop_size


def random_crop_box(rng: np.random.Generator, width: int, height: int, imgsize: int,
                    min_covered=0.1, aspect_ratio_range=(3.0 / 4, 4.0 / 3),
                    area_range=(0.08, 1.0), max_attempts=10):
    """The TF sample-distorted-bounding-box rejection loop
    (``data.py:281-320``); falls back to the center crop."""
    min_area = area_range[0] * width * height
    max_area = area_range[1] * width * height
    for _ in range(max_attempts):
        aspect_ratio = rng.uniform(*aspect_ratio_range)
        h = int(round(math.sqrt(min_area / aspect_ratio)))
        max_h = int(round(math.sqrt(max_area / aspect_ratio)))
        if max_h * aspect_ratio > width:
            max_h = int((width + 0.5 - 1e-7) / aspect_ratio)
            if max_h * aspect_ratio > width:
                max_h -= 1
        max_h = min(max_h, height)
        if h >= max_h:
            h = max_h
        h = int(round(rng.uniform(h, max_h)))
        w = int(round(h * aspect_ratio))
        area = w * h
        if area < min_area or area > max_area:
            continue
        if w > width or h > height:
            continue
        if area < min_covered * width * height:
            continue
        if w == width and h == height:
            return center_crop_box(width, height, imgsize)
        x = int(rng.integers(0, width - w + 1))
        y = int(rng.integers(0, height - h + 1))
        return x, y, x + w, y + h
    return center_crop_box(width, height, imgsize)


def host_train_image(img, rng: np.random.Generator, imgsize: int) -> np.ndarray:
    """PIL image -> cropped + bicubic-resized uint8 [S, S, 3]."""
    import PIL.Image

    box = random_crop_box(rng, img.width, img.height, imgsize)
    out = img.crop(box).resize((imgsize, imgsize), PIL.Image.BICUBIC)
    return np.asarray(out, np.uint8)


def host_eval_image(img, imgsize: int) -> np.ndarray:
    import PIL.Image

    box = center_crop_box(img.width, img.height, imgsize)
    out = img.crop(box).resize((imgsize, imgsize), PIL.Image.BICUBIC)
    return np.asarray(out, np.uint8)


# ---------------------------------------------------------------------------
# device side
# ---------------------------------------------------------------------------


def _color_jitter(img, key, strength: float = 0.4):
    """torchvision ColorJitter(brightness/contrast/saturation = s):
    each factor ~ U(1-s, 1+s), the three adjustments in random order."""
    k_perm, k_b, k_c, k_s = jax.random.split(key, 4)
    fb = jax.random.uniform(k_b, (), minval=1 - strength, maxval=1 + strength)
    fc = jax.random.uniform(k_c, (), minval=1 - strength, maxval=1 + strength)
    fs = jax.random.uniform(k_s, (), minval=1 - strength, maxval=1 + strength)

    def b(im):
        return _brightness(im, fb, None)

    def c(im):
        return _contrast(im, fc, None)

    def s(im):
        return _saturation(im, fs, None)

    orders = [(b, c, s), (b, s, c), (c, b, s), (c, s, b), (s, b, c), (s, c, b)]
    branches = [
        (lambda fns: (lambda im: fns[2](fns[1](fns[0](im)))))(fns) for fns in orders
    ]
    idx = jax.random.randint(k_perm, (), 0, len(branches))
    return jax.lax.switch(idx, branches, img)


def _lighting(img01, key, alphastd: float = 0.1):
    """AlexNet PCA noise on the [0,1]-scaled image (``augmentations.py:197-215``)."""
    alpha = jax.random.normal(key, (3,)) * alphastd
    rgb = (jnp.asarray(_PCA_EIGVEC) * alpha[None, :] * jnp.asarray(_PCA_EIGVAL)[None, :]).sum(1)
    return img01 + rgb[None, None, :]


def _train_one(img, policy, key, cutout_length, single_sub_scalar=False):
    from fast_autoaugment_tpu.ops.preprocess import cutout_default

    k_pol, k_flip, k_jit, k_light, k_cut = jax.random.split(key, 5)
    if policy is not None:
        if single_sub_scalar:
            img = apply_policy_scalar_single(img, policy, k_pol)
        else:
            img = apply_policy(img, policy, k_pol)
    img = jnp.where(jax.random.uniform(k_flip) < 0.5, img[:, ::-1], img)
    img = _color_jitter(img, k_jit)
    img01 = img / 255.0
    img01 = _lighting(img01, k_light)
    mean = jnp.asarray(IMAGENET_MEAN, img01.dtype)
    std = jnp.asarray(IMAGENET_STD, img01.dtype)
    out = (img01 - mean) / std
    if cutout_length > 0:
        # CutoutDefault applies post-normalize on every dataset family
        # when conf cutout > 0 (reference data.py:111-112)
        out = cutout_default(out, k_cut, cutout_length)
    return out


def imagenet_train_batch(images: jax.Array, key: jax.Array,
                         policy: jax.Array | None = None,
                         cutout_length: int = 0,
                         aug_dispatch: str = "exact",
                         aug_groups: int = 8) -> jax.Array:
    """Device-side ImageNet train stack on host-cropped uint8 batches.

    ``aug_dispatch``/``aug_groups`` mirror
    :func:`fast_autoaugment_tpu.ops.preprocess.cifar_train_batch`:
    "exact" (default) is the historical per-image path bit-for-bit,
    "grouped" applies the policy with scalar op dispatch (stratified
    per-chunk sub-policy draws) before the per-image jitter stack."""
    check_aug_dispatch(aug_dispatch)
    images = images.astype(jnp.float32)
    single_sub = policy is not None and int(policy.shape[0]) == 1
    if aug_dispatch == "grouped" and policy is not None and not single_sub:
        key, key_pol = jax.random.split(key)
        images = apply_policy_batch_grouped(images, policy, key_pol,
                                            groups=aug_groups)
        policy = None
    scalar = aug_dispatch == "grouped" and single_sub
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(lambda im, k: _train_one(im, policy, k, cutout_length,
                                             single_sub_scalar=scalar))(images, keys)


def imagenet_eval_batch(images: jax.Array) -> jax.Array:
    return normalize(images.astype(jnp.float32), IMAGENET_MEAN, IMAGENET_STD)
