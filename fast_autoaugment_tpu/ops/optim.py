"""Optimizers, weight decay, clipping and EMA as optax transforms.

Reproduces the reference training update exactly (``train.py:47-93``):

1. loss adds a manual decoupled L2 term ``wd/2 * sum(p**2)`` over all
   params NOT in BatchNorm modules (``train.py:40,61``) — implemented
   as a masked ``add_decayed_weights`` (identical gradient);
2. global-norm clip at ``optimizer.clip`` (default 5.0) AFTER the wd
   term is folded in (``train.py:63-65``);
3. the core update: torch-semantics SGD with Nesterov momentum
   (``train.py:139-145``), or :func:`rmsprop_tf` — the reference's
   TF-port RMSprop (``tf_port/rmsprop.py:5-101``) whose quirks matter
   for EfficientNet: ms initialized to ONES (not zeros), epsilon INSIDE
   the sqrt, and the learning rate folded into the momentum buffer.

Known deliberate deviation: the reference's non-BN filter is
name-based (``'_bn' in name or '.bn' in name``) and therefore silently
*decays* BN params inside the shake-net branches (which are indexed, not
named ``bn*``).  Here BN params are never decayed, in every model.

EMA (reference ``common.py:28-51``, applied ``train.py:69-70``): shadow
of params+batch_stats with TF-style warmup ``mu_t = min(mu,
(1+step)/(10+step))``, as a pure pytree lerp inside the jitted step —
not a Python loop over tensors like the reference.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "non_bn_mask",
    "build_optimizer",
    "rmsprop_tf",
    "ema_update",
    "init_ema",
]


def non_bn_mask(params) -> Any:
    """Pytree mask: True for params that should receive weight decay
    (everything except BatchNorm scale/bias, identified by module name).

    Passed to optax as a CALLABLE so the optimizer can be built before
    parameters exist — optax evaluates it lazily at ``init``.
    """

    def is_bn_path(path) -> bool:
        return any("bn" in str(getattr(k, "key", k)).lower() for k in path)

    return jax.tree_util.tree_map_with_path(lambda p, _: not is_bn_path(p), params)


class RmspropTFState(NamedTuple):
    step: jax.Array
    ms: Any
    mom: Any


def rmsprop_tf(
    learning_rate: Callable[[jax.Array], jax.Array] | float,
    alpha: float = 0.9,
    momentum: float = 0.9,
    eps: float = 1e-3,
) -> optax.GradientTransformation:
    """TF-semantics RMSprop (reference ``tf_port/rmsprop.py:75-100``).

    ms <- ms + (g^2 - ms) * (1 - alpha)        [ms init = ones]
    mom <- momentum * mom + lr * g / sqrt(ms + eps)
    update = -mom
    """

    def init_fn(params):
        return RmspropTFState(
            step=jnp.zeros((), jnp.int32),
            ms=jax.tree.map(jnp.ones_like, params),
            mom=jax.tree.map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        del params
        lr = learning_rate(state.step) if callable(learning_rate) else learning_rate
        ms = jax.tree.map(lambda m, g: m + (g * g - m) * (1.0 - alpha), state.ms, updates)
        mom = jax.tree.map(
            lambda v, g, m: momentum * v + lr * g / jnp.sqrt(m + eps),
            state.mom,
            updates,
            ms,
        )
        new_updates = jax.tree.map(lambda v: -v, mom)
        return new_updates, RmspropTFState(step=state.step + 1, ms=ms, mom=mom)

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(
    optimizer_conf: Any,
    learning_rate: Callable[[jax.Array], jax.Array],
) -> optax.GradientTransformation:
    """Weight-decay -> clip -> core optimizer chain, from the conf schema
    ``optimizer{type, decay, (momentum), (nesterov), (clip)}``.

    The non-BN mask is a callable, so no parameters are needed up front.
    """
    kind = optimizer_conf["type"]
    decay = float(optimizer_conf.get("decay", 0.0))
    clip = float(optimizer_conf.get("clip", 5.0))

    chain = []
    if decay > 0:
        chain.append(optax.add_decayed_weights(decay, mask=non_bn_mask))
    if clip > 0:
        chain.append(optax.clip_by_global_norm(clip))

    if kind == "sgd":
        momentum = float(optimizer_conf.get("momentum", 0.9))
        nesterov = bool(optimizer_conf.get("nesterov", True))
        chain.append(optax.trace(decay=momentum, nesterov=nesterov))
        chain.append(optax.scale_by_learning_rate(learning_rate))
    elif kind == "rmsprop":
        chain.append(rmsprop_tf(learning_rate, alpha=0.9, momentum=0.9, eps=1e-3))
    else:
        raise ValueError(f"invalid optimizer type {kind!r}")
    return optax.chain(*chain)


def init_ema(tree):
    """Initialize the EMA shadow as a copy of (params, batch_stats)."""
    return jax.tree.map(jnp.asarray, tree)


def ema_update(shadow, new_tree, mu: float, step: jax.Array):
    """shadow <- (1 - mu_t) * new + mu_t * shadow, with TF warmup
    ``mu_t = min(mu, (1 + step) / (10 + step))`` (reference ``common.py:39-51``).

    `step` is the 1-based global step, matching ``train.py:70``.
    """
    step = jnp.asarray(step, jnp.float32)
    mu_t = jnp.minimum(mu, (1.0 + step) / (10.0 + step))
    return jax.tree.map(lambda s, x: (1.0 - mu_t) * x + mu_t * s, shadow, new_tree)
