"""Device mesh, sharding helpers and the distributed backend.

The reference's distributed layer is NCCL through torch.distributed:
one process per GPU, DDP gradient allreduce inside ``loss.backward()``,
explicit broadcasts for init/EMA sync (``train.py:113-119,220-224``),
plus an SSH launcher (``train_dist.py``).  The TPU-native design
replaces ALL of that with the XLA SPMD model:

- one process per HOST (``jax.distributed.initialize`` for multi-host),
- a ``jax.sharding.Mesh`` over all devices with a ``'data'`` axis,
- the train step jitted with the global batch sharded over ``'data'``
  and parameters replicated: XLA inserts the gradient reductions as ICI
  collectives automatically — there is no DDP wrapper to write, and
  "broadcast params from rank 0" is simply device placement of the
  replicated sharding,
- BN statistics are computed over the global batch under jit, which is
  exactly the cross-replica sync-BN the reference approximates with
  ``nn.SyncBatchNorm`` / ``TpuBatchNormalization`` allreduces.

NCCL-op -> XLA mapping (SURVEY.md section 5): allreduce(grads) ->
implicit psum under jit / ``lax.psum`` under shard_map; broadcast ->
replicated NamedSharding placement; allreduce(BN stats) -> global-batch
statistics (or ``lax.pmean`` with an axis_name under shard_map).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "make_fold_mesh",
    "data_sharding",
    "replicated",
    "shard_batch",
    "shard_stacked_batch",
    "shard_transform",
    "stacked_shard_transform",
    "place_dataset",
    "place_index_matrix",
    "place_stacked_index_matrix",
    "distributed_init",
    "local_batch_to_global",
]


def make_mesh(devices=None, axis_name: str = "data") -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices.

    Model families here are all sub-100M-param CNNs, so data parallelism
    is the whole story (SURVEY.md section 2.3); the mesh keeps an
    explicit axis so wider layouts can be added without API change.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(-1), (axis_name,))


def make_fold_mesh(num_folds: int, devices=None, *,
                   fold_shards: int | None = None,
                   fold_axis: str = "fold", data_axis: str = "data") -> Mesh:
    """2-D ``(fold, data)`` mesh for the fold-stacked phase-1 trainer.

    The fold-to-mesh mapping rule: the fold axis takes
    ``gcd(num_folds, n_devices)`` shards by default, the data axis the
    rest.  With devices >= K (and K | n_devices) every fold owns a
    disjoint device group — folds are SHARDED across the machine
    instead of replicated onto every device; with one device (or
    coprime counts) the fold axis stays unsharded and stacking is pure
    program fusion.  Each fold's per-fold global batch is
    ``batch_per_device x (n_devices / fold_shards)`` — exactly the
    global batch a sequential run restricted to that fold's device
    group would use, which is what keeps the seeded stacked-vs-
    sequential equivalence well-defined at any layout (pass
    ``fold_shards=1`` to reproduce the all-devices-per-fold sequential
    semantics bit-for-bit).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if fold_shards is None:
        fold_shards = math.gcd(int(num_folds), n)
    if fold_shards < 1 or n % fold_shards:
        raise ValueError(
            f"fold_shards={fold_shards} does not divide {n} devices")
    return Mesh(devices.reshape(fold_shards, n // fold_shards),
                (fold_axis, data_axis))


def data_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis_name: str = "data"):
    """Place a host batch onto the mesh, sharded along the batch dim.

    Single-process: a plain device_put of the global batch.  Multi-host:
    each process passes its LOCAL shard (the pipeline yields per-process
    shards) and the global array is assembled across processes — the
    jax analog of DistributedSampler feeding per-rank loaders
    (reference ``data.py:205-212``).
    """
    sharding = data_sharding(mesh, axis_name)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

    def put(x):
        global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree.map(put, batch)


def shard_stacked_batch(mesh: Mesh, batch, fold_axis: str = "fold",
                        data_axis: str = "data"):
    """Place a stacked ``{x: [K,B,...], y: [K,B], a: [K]}`` batch on a
    :func:`make_fold_mesh` mesh: the leading fold axis maps onto the
    mesh's fold axis, the per-fold batch dim onto the data axis, and
    rank-1 fold-aligned tensors (the active mask) ride the fold axis
    alone.  Multi-host: each process passes its per-fold LOCAL batch
    shard (dim 1), mirroring :func:`shard_batch`."""
    def spec(x):
        return P(fold_axis, data_axis) if x.ndim >= 2 else P(fold_axis)

    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec(x))), batch)

    def put(x):
        global_shape = x.shape
        if x.ndim >= 2:
            global_shape = (x.shape[0], x.shape[1] * jax.process_count(),
                            ) + x.shape[2:]
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec(x)), x, global_shape)

    return jax.tree.map(put, batch)


def stacked_shard_transform(mesh: Mesh, keys=("x", "y", "a"),
                            fold_axis: str = "fold",
                            data_axis: str = "data"):
    """`transform=` hook for ``prefetch`` over
    :func:`data.pipeline.stacked_train_batches` tuples — the stacked
    analog of :func:`shard_transform`."""
    def transform(item):
        return shard_stacked_batch(
            mesh, dict(zip(keys, item, strict=True)), fold_axis, data_axis)

    return transform


def shard_transform(mesh: Mesh, keys=("x", "y"), axis_name: str = "data"):
    """`transform=` hook for `data.pipeline.prefetch`: maps a pipeline
    tuple to a `shard_batch`-placed dict in the prefetch worker thread,
    so the H2D copy overlaps the in-flight step's device work."""
    def transform(item):
        return shard_batch(mesh, dict(zip(keys, item, strict=True)), axis_name)

    return transform


def place_dataset(mesh: Mesh, images: np.ndarray, labels: np.ndarray,
                  axis_name: str = "data"):
    """Upload a whole eager dataset ONCE, example axis sharded over the
    mesh's data axis — the storage placement behind
    ``data.pipeline.DeviceCache``.

    The example count is padded up to a multiple of the data-axis shard
    count with zero rows so every device holds an equal slab; pad rows
    are never referenced (the index matrices only name real examples).
    Train steps then gather their batches from this resident copy by
    index INSIDE the compiled program — no per-step H2D image copy.
    Returns ``(images_dev, labels_dev)``.
    """
    shards = mesh.shape[axis_name]
    n = images.shape[0]
    if labels.shape[0] != n:
        raise ValueError(f"{n} images but {labels.shape[0]} labels")
    pad = (-n) % shards
    if pad:
        images = np.concatenate(
            [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
        labels = np.concatenate(
            [labels, np.zeros((pad,) + labels.shape[1:], labels.dtype)])
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.device_put(images, sharding), jax.device_put(labels, sharding)


def place_index_matrix(mesh: Mesh, idx: np.ndarray, axis_name: str = "data"):
    """Place a ``[N, B]`` per-dispatch batch-index matrix: the scan
    (step) axis replicated, the batch axis sharded over the data axis —
    the only per-step H2D traffic the device-cache path ships (int32,
    ~KBs instead of the uint8 image batch)."""
    spec = P(*([None] * (idx.ndim - 1) + [axis_name]))
    return jax.device_put(np.ascontiguousarray(idx, np.int32),
                          NamedSharding(mesh, spec))


def place_stacked_index_matrix(mesh: Mesh, idx: np.ndarray,
                               active: np.ndarray,
                               fold_axis: str = "fold",
                               data_axis: str = "data"):
    """Stacked counterpart of :func:`place_index_matrix` for a
    :func:`make_fold_mesh` mesh: ``idx [N, K, B]`` rides (scan, fold,
    data), ``active [N, K]`` rides (scan, fold)."""
    idx_dev = jax.device_put(
        np.ascontiguousarray(idx, np.int32),
        NamedSharding(mesh, P(None, fold_axis, data_axis)))
    act_dev = jax.device_put(
        np.ascontiguousarray(active, np.float32),
        NamedSharding(mesh, P(None, fold_axis)))
    return idx_dev, act_dev


def local_batch_to_global(batch_per_device: int, mesh: Mesh) -> int:
    return batch_per_device * mesh.size


def distributed_init(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None):
    """Multi-host rendezvous (replaces torch.distributed.launch env-var
    plumbing, reference ``train_dist.py:126-131``).  On TPU pods the
    arguments are auto-detected from the environment."""
    if jax.process_count() > 1:
        return  # already initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        # single-process (tests, single-chip); nothing to do
        pass
