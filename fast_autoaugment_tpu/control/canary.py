"""Canary rollout + served-quality comparison + the promotion gate.

Stages three and four of the control loop (docs/CONTROL.md):

- **Rollout** (:class:`CanaryController`): the candidate policy is
  pushed to a ROUTER-SELECTED replica subset — the replicas ranked
  first by rendezvous hashing the candidate's digest
  (``serve/router.py::rendezvous_order``), so when the candidate is
  later promoted, its affinity traffic lands on replicas already
  AOT-warm — via each replica's ``POST /reload``.  The reload response
  now echoes the resident digest (the PR-14 serve fix); a mismatch is
  a hard rollout failure, never a silent wrong-policy canary.  The
  router's ``POST /canary`` admin splits traffic deterministically
  between the arms while the comparison runs.

- **Comparison** (:class:`ReplicaQualityScraper` + :func:`compare_arms`):
  each replica's Prometheus ``/metrics`` carries its served-traffic
  gauges (``faa_serve_reward_proxy`` — the ``--traffic-stats``
  surface) and volume counters; the comparator samples both arms and
  scores each by its QUALITY DISTANCE — ``|reward_proxy - target|``
  where the target is the drift monitor's pre-drift baseline mean —
  plus its per-dispatch error evidence.

- **Gate** (:class:`PromotionGate`): a pure hysteresis state machine
  in the ``AutoscalerPolicy`` mold: after ``gate_polls`` comparison
  polls in which both arms saw fresh traffic, the canary PROMOTES when
  its median quality distance is no worse than baseline's by more than
  ``quality_margin`` AND it produced no new dispatch errors; otherwise
  it ROLLS BACK.  Non-inferiority is deliberate: at canary scale the
  candidate must first prove it does no harm — absolute quality
  recovery is judged by the re-baselined drift monitor after
  promotion (docs/CONTROL.md "Gate semantics").
"""

from __future__ import annotations

import json

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.telemetry import mono
from fast_autoaugment_tpu.serve.autoscaler import parse_prometheus_text
from fast_autoaugment_tpu.serve.router import rendezvous_order
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["select_canary_replicas", "ReplicaQualityScraper",
           "compare_arms", "PromotionGate", "CanaryController"]

logger = get_logger("faa_tpu.control.canary")


def select_canary_replicas(candidate_digest: str, tags: list[str],
                           n_canary: int) -> list[str]:
    """The router-selected canary subset: the first `n_canary` replicas
    in rendezvous order for the CANDIDATE's digest — deterministic
    across every control-plane instance, and exactly the replicas the
    promoted policy's affinity traffic will land on (already warm).
    At least one replica always stays baseline."""
    tags = sorted(set(str(t) for t in tags))
    if len(tags) < 2:
        raise ValueError(
            f"canary rollout needs >= 2 replicas (one must stay "
            f"baseline), got {tags}")
    n = max(1, min(int(n_canary), len(tags) - 1))
    return rendezvous_order(str(candidate_digest), tags)[:n]


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if n == 0:
        return None
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


class ReplicaQualityScraper:
    """Per-replica quality sample from the Prometheus surface.

    One ``sample(replicas)`` returns, per tag: the served-traffic
    gauges (reward proxy / input moments), cumulative dispatch and
    breaker-fire counts, and the DELTAS since this scraper's previous
    sample — fresh-traffic evidence the gate requires before judging
    an arm (a canary nobody hit proves nothing)."""

    TRAFFIC_GAUGES = ("faa_serve_reward_proxy", "faa_serve_input_mean",
                      "faa_serve_input_std")
    DISPATCHES = "faa_serve_dispatches_total"
    BREAKER_FIRES = "faa_breaker_fires_total"

    def __init__(self, timeout_s: float = 2.0):
        self.timeout_s = float(timeout_s)
        self._prev: dict[str, dict] = {}

    def _scrape_one(self, host: str, port: int) -> str | None:
        import http.client

        try:
            conn = http.client.HTTPConnection(host, port,
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                body = resp.read()
                return body.decode() if resp.status == 200 else None
            finally:
                conn.close()
        except OSError:
            return None

    def sample(self, replicas: list[dict]) -> dict[str, dict]:
        """`replicas`: ``[{tag, host, port}, ...]`` (the port-dir
        census).  Returns ``{tag: row}`` with unreachable replicas
        marked — the gate treats missing arms as not-yet-judgeable."""
        out: dict[str, dict] = {}
        for rec in replicas:
            tag = str(rec["tag"])
            text = self._scrape_one(rec["host"], rec["port"])
            if text is None:
                out[tag] = {"reachable": False}
                continue
            fams = parse_prometheus_text(text)

            def _first(name: str):
                vals = fams.get(name, [])
                return vals[0][1] if vals else None

            row: dict = {"reachable": True}
            for g in self.TRAFFIC_GAUGES:
                short = g[len("faa_serve_"):]
                row[short] = _first(g)
            row["dispatches"] = sum(v for _l, v
                                    in fams.get(self.DISPATCHES, []))
            row["breaker_fires"] = sum(v for _l, v
                                       in fams.get(self.BREAKER_FIRES, []))
            prev = self._prev.get(tag, {})
            row["new_dispatches"] = max(
                0.0, row["dispatches"] - prev.get("dispatches", 0.0))
            row["new_breaker_fires"] = max(
                0.0, row["breaker_fires"] - prev.get("breaker_fires", 0.0))
            self._prev[tag] = row
            out[tag] = row
        return out


def compare_arms(samples: dict[str, dict], canary_tags: list[str],
                 target: float) -> dict:
    """One comparison poll's evidence: per-arm median quality distance
    ``|reward_proxy - target|``, fresh-traffic counts, and new error
    counts.  Pure — no I/O, no clocks."""
    canary_set = set(canary_tags)

    def arm_rows(in_canary: bool):
        return [r for t, r in samples.items()
                if r.get("reachable")
                and (t in canary_set) == in_canary
                and r.get("reward_proxy") is not None]

    def arm_summary(rows):
        return {
            "replicas": len(rows),
            "quality_distance": _median(
                [abs(float(r["reward_proxy"]) - target) for r in rows]),
            "reward_proxy": _median(
                [float(r["reward_proxy"]) for r in rows]),
            "new_dispatches": sum(r.get("new_dispatches", 0.0)
                                  for r in rows),
            "new_errors": sum(r.get("new_breaker_fires", 0.0)
                              for r in rows),
        }

    canary = arm_summary(arm_rows(True))
    baseline = arm_summary(arm_rows(False))
    delta = (None if canary["quality_distance"] is None
             or baseline["quality_distance"] is None
             else canary["quality_distance"] - baseline["quality_distance"])
    return {"canary": canary, "baseline": baseline, "target": target,
            "quality_delta": delta}


class PromotionGate:
    """The pure promote/rollback decision (hysteresis + evidence
    bounds, the ``AutoscalerPolicy`` discipline).

    Feed one :func:`compare_arms` evidence dict per poll; after
    `gate_polls` JUDGEABLE polls (both arms reachable with >=
    `min_arm_dispatches` fresh dispatches) the gate answers
    ``("promote"| "rollback", reason, evidence)``.  Any poll with new
    canary errors rolls back IMMEDIATELY — a broken candidate must not
    keep serving canary traffic for the rest of the window."""

    def __init__(self, *, gate_polls: int = 3,
                 quality_margin: float = 0.05,
                 min_arm_dispatches: float = 1.0,
                 timeout_polls: int = 50):
        self.gate_polls = max(1, int(gate_polls))
        self.quality_margin = float(quality_margin)
        self.min_arm_dispatches = float(min_arm_dispatches)
        self.timeout_polls = max(self.gate_polls, int(timeout_polls))
        self._window: list[dict] = []
        self._polls = 0

    def reset(self) -> None:
        self._window = []
        self._polls = 0

    def decide(self, evidence: dict) -> tuple[str | None, str, dict]:
        """One poll's verdict: ``(action, reason, summary)`` with
        action None while the window is still filling."""
        self._polls += 1
        canary, base = evidence["canary"], evidence["baseline"]
        if canary.get("new_errors", 0) > 0:
            return "rollback", (
                f"canary produced {canary['new_errors']:g} new dispatch "
                f"error(s) — immediate rollback"), self._summary(evidence)
        judgeable = (
            evidence.get("quality_delta") is not None
            and canary.get("new_dispatches", 0) >= self.min_arm_dispatches
            and base.get("new_dispatches", 0) >= self.min_arm_dispatches)
        if judgeable:
            self._window.append(evidence)
        if len(self._window) >= self.gate_polls:
            deltas = [e["quality_delta"] for e in self._window]
            med = _median(deltas)
            summary = self._summary(evidence, med)
            if med <= self.quality_margin:
                return "promote", (
                    f"median quality delta {med:+.6f} within margin "
                    f"{self.quality_margin} over {len(self._window)} "
                    f"judgeable poll(s)"), summary
            return "rollback", (
                f"median quality delta {med:+.6f} exceeds margin "
                f"{self.quality_margin} over {len(self._window)} "
                f"judgeable poll(s)"), summary
        if self._polls >= self.timeout_polls:
            return "rollback", (
                f"gate window never filled ({len(self._window)}/"
                f"{self.gate_polls} judgeable polls in "
                f"{self._polls}) — canary starved of traffic"), \
                self._summary(evidence)
        return None, (f"observing ({len(self._window)}/"
                      f"{self.gate_polls} judgeable polls)"), {}

    def _summary(self, last: dict, med=None) -> dict:
        return {
            "judgeable_polls": len(self._window),
            "total_polls": self._polls,
            "median_quality_delta": med,
            "quality_margin": self.quality_margin,
            "last": last,
        }

    def snapshot(self) -> dict:
        return {
            "gate_polls": self.gate_polls,
            "quality_margin": self.quality_margin,
            "min_arm_dispatches": self.min_arm_dispatches,
            "judgeable_polls": len(self._window),
            "total_polls": self._polls,
        }


class CanaryController:
    """HTTP actuation of rollout / promote / rollback against the
    replica fleet (the port-dir census) and, optionally, the router's
    canary-split admin.

    `reload_fn(host, port, policy_path)` defaults to a real ``POST
    /reload``; tests inject a stub.  Every reload's echoed digest is
    verified against the expected one — the canary comparator must
    never compare against a replica that silently kept the old
    policy."""

    def __init__(self, replicas_fn, *, router_url: str | None = None,
                 reload_fn=None, timeout_s: float = 120.0,
                 name: str = "control"):
        self.replicas_fn = replicas_fn
        self.router_url = router_url
        self.reload_fn = reload_fn or self._http_reload
        self.timeout_s = float(timeout_s)
        self.name = str(name)

    # ------------------------------------------------------------ HTTP

    def _http_reload(self, host: str, port: int, policy_path: str) -> dict:
        import http.client

        body = json.dumps({"policy": policy_path}).encode()
        conn = http.client.HTTPConnection(host, port,
                                          timeout=self.timeout_s)
        try:
            conn.request("POST", "/reload", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"reload on {host}:{port} answered {resp.status}: "
                    f"{data[:200]!r}")
            return json.loads(data)
        finally:
            conn.close()

    def _router_canary(self, payload: dict) -> dict | None:
        """POST the split admin; returns the router's parsed echo
        (``{"canary": {...}}``) or None when no router is configured."""
        if not self.router_url:
            return None
        import http.client
        from urllib.parse import urlsplit

        parts = urlsplit(self.router_url if "//" in self.router_url
                         else f"http://{self.router_url}")
        body = json.dumps(payload).encode()
        conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                          timeout=self.timeout_s)
        try:
            conn.request("POST", "/canary", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"router canary admin answered {resp.status}: "
                    f"{data[:200]!r}")
            try:
                return json.loads(data)
            except ValueError:
                return None
        finally:
            conn.close()

    # ------------------------------------------------------- actuation

    def _reload_verified(self, rec: dict, policy_path: str,
                         expect_digest: str) -> dict:
        info = self.reload_fn(rec["host"], rec["port"], policy_path)
        echoed = info.get("digest")
        if echoed != expect_digest:
            raise RuntimeError(
                f"replica {rec['tag']} reloaded but echoed digest "
                f"{echoed!r} != expected {expect_digest!r} — refusing "
                "to canary an unverified policy")
        return info

    def rollout(self, policy_path: str, expect_digest: str, *,
                n_canary: int = 1, split_every: int = 2) -> dict:
        """Push the candidate to the router-selected subset and arm the
        traffic split.  Returns ``{"canary": tags, "baseline": tags,
        "replicas": census}``; raises on any verification failure
        (nothing is half-rolled-out: a failed replica aborts before
        the split arms)."""
        census = {str(r["tag"]): r for r in self.replicas_fn()}
        canary_tags = select_canary_replicas(
            expect_digest, list(census), n_canary)
        baseline_tags = sorted(t for t in census if t not in canary_tags)
        t0 = mono()
        for tag in canary_tags:
            info = self._reload_verified(census[tag], policy_path,
                                         expect_digest)
            telemetry.emit("canary", self.name, action="rollout",
                           replica=tag, digest=expect_digest,
                           policy=policy_path,
                           warm_sec=info.get("warm_sec"))
        self._router_canary({"digest": expect_digest,
                             "replicas": canary_tags,
                             "every": split_every})
        logger.info("canary rollout: %s on %s (baseline %s) in %.2fs",
                    expect_digest, canary_tags, baseline_tags,
                    mono() - t0)
        return {"canary": canary_tags, "baseline": baseline_tags,
                "replicas": census}

    def assert_split(self, expect_digest: str, canary_tags: list[str],
                     split_every: int = 2) -> None:
        """Idempotently RE-ASSERT the canary split (called on every
        gate poll): ``POST /canary`` replaces any current split, so a
        router that restarted mid-canary — which would otherwise route
        100%% baseline while the gate kept scoring a phantom canary arm
        — is re-armed within one poll.  The router's echo is verified;
        a digest mismatch (another controller armed a DIFFERENT split)
        raises rather than letting two control planes fight."""
        echo = self._router_canary({"digest": expect_digest,
                                    "replicas": list(canary_tags),
                                    "every": int(split_every)})
        if echo is None:
            return  # no router configured: replica-count split only
        armed = (echo.get("canary") or {})
        if armed.get("digest") != expect_digest:
            raise RuntimeError(
                f"router canary echo mismatch: armed digest "
                f"{armed.get('digest')!r} != expected {expect_digest!r} "
                "— refusing to score a split this controller does not "
                "own")

    def promote(self, policy_path: str, expect_digest: str,
                census: dict, canary_tags: list[str]) -> None:
        """Fleet-wide reload of the candidate (canaries already hold
        it — their reload is an idempotent digest re-verify) and clear
        the split."""
        for tag in sorted(census):
            if tag in canary_tags:
                continue
            self._reload_verified(census[tag], policy_path, expect_digest)
        self._router_canary({"clear": True})

    def rollback(self, baseline_policy: str, baseline_digest: str,
                 census: dict, canary_tags: list[str]) -> None:
        """Reload the BASELINE policy back onto the canary subset and
        clear the split."""
        for tag in canary_tags:
            self._reload_verified(census[tag], baseline_policy,
                                  baseline_digest)
        self._router_canary({"clear": True})
