"""Drift detection over served-traffic statistics.

The first stage of the closed control loop (docs/CONTROL.md): a
serving fleet with ``--traffic-stats`` stamps per-dispatch input
moments and a reward proxy onto its journal's ``serve_dispatch``
events (``serve/policy_server.py``); this module tails those journals,
maintains a frozen baseline window per metric, and raises a typed
DRIFT VERDICT when a seeded statistical test trips.

The test is a two-sided CUSUM mean-shift detector (Page 1954 — the
standard sequential change-point test): each sample is standardized
against the frozen baseline (``z = (x - mu) / sigma``) and two
one-sided cumulative sums accumulate evidence of an up/down shift::

    S+ <- max(0, S+ + z - k)        S- <- max(0, S- - z - k)

``k`` (the slack, in sigmas) absorbs in-band noise so stationary
traffic never accumulates; the detector trips when either sum crosses
the decision threshold ``h`` (sigmas).  Both are configuration — the
classical ARL trade-off — and both land in the verdict's evidence so
``make trace`` / ``make status`` show WHY the loop acted.  The
defaults (k=1.5, h=10) are deliberately coarser than the textbook
k=0.5: the baseline mean/sigma come from a SMALL frozen window, and
the slack must also absorb that estimation error or stationary
traffic random-walks over the threshold (measured: k=0.5/h=8 false-
trips ~40%% of seeds within 2000 samples at baseline_n=20; k=1.5/h=10
tripped 0/50 while still detecting a 4-sigma shift in ~4 samples —
serving drifts of interest here are tens of sigmas).  Everything
here is a pure function of the sample stream (no clocks of its own),
so the FAA_FAULT ``drift@dispatch=N,shift=S`` drill reproduces the
same verdict at the same sample index every run.

Detection LATCHES: one verdict per drift episode.  After the loop
promotes (or rolls back) it calls :meth:`DriftMonitor.rebaseline` —
the post-action traffic becomes the new baseline, which is what
"converging back to a stable regime" means operationally.
"""

from __future__ import annotations

import json
import math
import os

from fast_autoaugment_tpu.core import fsfault, telemetry
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["CusumMeanShift", "TrafficSampleReader", "DriftMonitor",
           "DEFAULT_DRIFT_METRICS"]

logger = get_logger("faa_tpu.control.drift")

#: the served-traffic statistics watched by default (the fields
#: --traffic-stats stamps onto serve dispatch events)
DEFAULT_DRIFT_METRICS = ("input_mean", "reward_proxy")


class CusumMeanShift:
    """Two-sided CUSUM over one metric with a frozen baseline window.

    The first `baseline_n` samples form the baseline (mean/sigma
    frozen once full); later samples accumulate the one-sided sums.
    Pure and deterministic: no I/O, no clocks — fully drivable in
    tests and byte-reproducible in the drill."""

    def __init__(self, metric: str, *, baseline_n: int = 20,
                 k: float = 1.5, h: float = 10.0,
                 min_sigma: float = 1e-4):
        if baseline_n < 2:
            raise ValueError(f"baseline_n must be >= 2, got {baseline_n}")
        if k < 0 or h <= 0:
            raise ValueError(f"need k >= 0 and h > 0, got k={k} h={h}")
        self.metric = str(metric)
        self.baseline_n = int(baseline_n)
        self.k = float(k)
        self.h = float(h)
        self.min_sigma = float(min_sigma)
        self._baseline: list[float] = []
        self._mu: float | None = None
        self._sigma: float | None = None
        self._s_pos = 0.0
        self._s_neg = 0.0
        self.samples = 0

    @property
    def baselined(self) -> bool:
        return self._mu is not None

    def _freeze(self) -> None:
        n = len(self._baseline)
        mu = sum(self._baseline) / n
        var = sum((x - mu) ** 2 for x in self._baseline) / n
        self._mu = mu
        self._sigma = max(math.sqrt(var), self.min_sigma)
        logger.info("drift[%s]: baseline frozen over %d samples "
                    "(mu=%.6g sigma=%.6g)", self.metric, n, mu,
                    self._sigma)

    def update(self, value: float) -> dict | None:
        """Feed one sample; returns the verdict evidence dict when the
        test trips, else None.  The caller latches — this detector
        keeps accumulating regardless."""
        value = float(value)
        self.samples += 1
        if self._mu is None:
            self._baseline.append(value)
            if len(self._baseline) >= self.baseline_n:
                self._freeze()
            return None
        z = (value - self._mu) / self._sigma
        self._s_pos = max(0.0, self._s_pos + z - self.k)
        self._s_neg = max(0.0, self._s_neg - z - self.k)
        if self._s_pos <= self.h and self._s_neg <= self.h:
            return None
        direction = "up" if self._s_pos > self.h else "down"
        return {
            "metric": self.metric,
            "direction": direction,
            "stat": round(max(self._s_pos, self._s_neg), 4),
            "threshold": self.h,
            "slack": self.k,
            "baseline_mean": round(self._mu, 6),
            "baseline_sigma": round(self._sigma, 6),
            "baseline_n": self.baseline_n,
            "value": round(value, 6),
            "sample": self.samples,
        }

    def reset(self) -> None:
        """Forget everything (baseline included) — the re-baseline
        after a promote/rollback."""
        self._baseline = []
        self._mu = None
        self._sigma = None
        self._s_pos = 0.0
        self._s_neg = 0.0
        self.samples = 0

    def snapshot(self) -> dict:
        return {
            "metric": self.metric,
            "baselined": self.baselined,
            "baseline_mean": (None if self._mu is None
                              else round(self._mu, 6)),
            "baseline_sigma": (None if self._sigma is None
                               else round(self._sigma, 6)),
            "s_pos": round(self._s_pos, 4),
            "s_neg": round(self._s_neg, 4),
            "samples": self.samples,
        }


class TrafficSampleReader:
    """Incremental tail over a telemetry journal dir: new
    ``serve_dispatch`` records carrying traffic-stat fields, in
    (host, pid, seq) order.

    Per-file byte offsets make each :meth:`poll` cheap over a growing
    journal; segment rotation shows up as new files (old offsets for
    deleted segments are simply dropped).  Torn trailing lines (a
    writer mid-flush) are retried on the next poll by not advancing
    past them.  All file access goes through the ``core/fsfault.py``
    seam, and exactly-once delivery is enforced by a per-(host, pid)
    sequence-number WATERMARK rather than by trusting offsets alone —
    a stale re-read or a shrink-then-grow file (the hostile-share
    cases) can therefore never double-feed the CUSUM.  Read-only over
    shared files — the same contract as ``tools/faa_status.py``."""

    def __init__(self, journal_dir: str, *, label: str = "serve_dispatch",
                 fields: tuple = DEFAULT_DRIFT_METRICS):
        self.journal_dir = journal_dir
        self.label = str(label)
        self.fields = tuple(fields)
        self._offsets: dict[str, int] = {}
        #: (host, pid) -> highest seq already delivered; re-reads of
        #: already-seen records are dropped here (idempotent tailing)
        self._watermarks: dict[tuple, int] = {}

    def _poll_file(self, path: str) -> list[dict]:
        out: list[dict] = []
        start = self._offsets.get(path, 0)
        try:
            size = fsfault.getsize(path)
            if size < start:
                start = 0  # truncated/replaced (or stale re-read):
                # start over — the seq watermark deduplicates
            if size == start:
                return out
            data = fsfault.read_from(path, start)
        except OSError:
            return out  # transient (injected eio / half-visible file)
        # only consume COMPLETE lines; a torn tail stays unconsumed
        consumed = data.rfind("\n") + 1
        self._offsets[path] = start + len(data[:consumed].encode())
        for line in data[:consumed].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn mid-file line from a killed writer
            if not isinstance(rec, dict):
                continue
            if rec.get("type") != "dispatch" or rec.get("label") != self.label:
                continue
            if not any(f in rec for f in self.fields):
                continue
            out.append(rec)
        return out

    def poll(self) -> list[dict]:
        pattern = os.path.join(self.journal_dir, "**", "journal-*.jsonl")
        records: list[dict] = []
        for path in fsfault.glob_files(pattern):
            records.extend(self._poll_file(path))
        records.sort(key=lambda r: (str(r.get("host")), r.get("pid", 0),
                                    r.get("seq", 0)))
        fresh: list[dict] = []
        for rec in records:
            key = (str(rec.get("host")), rec.get("pid", 0))
            seq = rec.get("seq")
            if isinstance(seq, int):
                if seq <= self._watermarks.get(key, -1):
                    continue  # re-read of an already-delivered record
                self._watermarks[key] = seq
            fresh.append(rec)
        return fresh

    def skip_to_end(self) -> int:
        """Fast-forward every CURRENT journal segment to its end
        without delivering the content: a resumed controller
        (``control_cli --resume``) must judge post-resume traffic, not
        replay the pre-crash episode's drifted history into a fresh
        baseline.  Returns the number of files skipped."""
        pattern = os.path.join(self.journal_dir, "**", "journal-*.jsonl")
        n = 0
        for path in fsfault.glob_files(pattern):
            try:
                self._offsets[path] = fsfault.getsize(path)
                n += 1
            except OSError:
                continue
        return n


class DriftMonitor:
    """Journal-fed drift detection with a latched, journaled verdict.

    `sample_fn` yields the next batch of traffic records (a
    :class:`TrafficSampleReader`'s ``poll``, or any callable in tests);
    each record feeds every configured metric's CUSUM.  The FIRST trip
    latches the monitor and emits one typed ``drift`` journal event
    with the full evidence inline; further samples are still consumed
    (offsets advance) but judged only after :meth:`rebaseline`."""

    def __init__(self, sample_fn, *, metrics=DEFAULT_DRIFT_METRICS,
                 baseline_n: int = 20, cusum_k: float = 1.5,
                 cusum_h: float = 10.0, name: str = "drift"):
        self.sample_fn = sample_fn
        self.name = str(name)
        self._detectors = {
            m: CusumMeanShift(m, baseline_n=baseline_n, k=cusum_k,
                              h=cusum_h)
            for m in metrics}
        self._verdict: dict | None = None
        self._verdict_seq = 0
        self._ctr = telemetry.registry().counter(
            "faa_control_drift_verdicts_total",
            "drift verdicts raised by the control plane's monitor",
            monitor=self.name)

    @property
    def latched(self) -> bool:
        return self._verdict is not None

    @property
    def verdict(self) -> dict | None:
        return None if self._verdict is None else dict(self._verdict)

    def poll(self) -> dict | None:
        """Consume new samples; returns the verdict when the monitor
        trips ON THIS POLL, else None (already-latched polls keep
        consuming samples but answer None — one verdict per episode)."""
        was_latched = self.latched
        records = self.sample_fn()
        for rec in records:
            for metric, det in self._detectors.items():
                if metric not in rec:
                    continue
                evidence = det.update(rec[metric])
                if evidence is None or self._verdict is not None:
                    continue
                self._verdict_seq += 1
                verdict = {
                    "id": f"{self.name}-{self._verdict_seq}",
                    **evidence,
                    "source_host": rec.get("host"),
                    "source_seq": rec.get("seq"),
                }
                self._verdict = verdict
                self._ctr.inc()
                telemetry.emit("drift", self.name, **verdict)
                logger.warning(
                    "DRIFT detected: %s shifted %s (CUSUM %.2f > h=%.2f "
                    "at sample %d; baseline mu=%.6g sigma=%.6g, value "
                    "%.6g)", verdict["metric"], verdict["direction"],
                    verdict["stat"], verdict["threshold"],
                    verdict["sample"], verdict["baseline_mean"],
                    verdict["baseline_sigma"], verdict["value"])
        return self.verdict if self.latched and not was_latched else None

    def rebaseline(self) -> None:
        """Clear the latch and every detector: the NEXT window of
        served traffic becomes the new baseline (called after a
        promote/rollback settles the fleet on a policy)."""
        for det in self._detectors.values():
            det.reset()
        self._verdict = None
        logger.info("drift monitor %s re-baselined (detectors reset)",
                    self.name)

    def stats(self) -> dict:
        return {
            "monitor": self.name,
            "latched": self.latched,
            "verdict": self.verdict,
            "detectors": {m: d.snapshot()
                          for m, d in self._detectors.items()},
        }
