"""Warm-started incremental re-search + candidate provenance.

Stage two of the control loop (docs/CONTROL.md): when the drift
monitor trips, the loop does NOT search from scratch — it warm-starts
the TPE from the persisted trial log through the PR-9
``replay_trial_log`` ledger seam (``search_policies(topup_trials=N,
resume=True, async_pipeline="on")``) and runs a bounded TOP-UP search,
so the device cost of reacting to drift is ``topup_trials`` TTA
rounds, not a full search.  ``topup_trials=0`` degenerates to a plain
resume: the candidate ``final_policy.json`` is byte-identical to the
one-shot artifact (pinned by tests — the defaults-safety contract).

Every candidate carries a PROVENANCE SIDECAR
(``final_policy.provenance.json`` next to the policy): the policy's
tensor digest, the base artifacts it warm-started from, the trial
budget split, and the drift verdict that triggered it.  serve_cli
attaches the sidecar to ``/stats`` and the ``/reload`` response, which
is how the canary comparator verifies WHICH policy generation actually
answered (``control/canary.py``).
"""

from __future__ import annotations

import os
import shutil

from fast_autoaugment_tpu.core import fsfault, telemetry
from fast_autoaugment_tpu.core.telemetry import wall
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["provenance_path", "write_provenance", "load_provenance",
           "policy_file_digest", "seed_research_dir", "warm_started_research",
           "PROVENANCE_SCHEMA_VERSION"]

logger = get_logger("faa_tpu.control.research")

PROVENANCE_SCHEMA_VERSION = 1


def provenance_path(policy_path: str) -> str:
    """``.../final_policy.json`` -> ``.../final_policy.provenance.json``
    (non-.json paths get the suffix appended — never shadow the policy
    file itself)."""
    p = str(policy_path)
    if p.endswith(".json"):
        return p[:-len(".json")] + ".provenance.json"
    return p + ".provenance.json"


def policy_file_digest(policy_path: str) -> str:
    """The canonical serving-plane digest of a policy FILE — the same
    12-hex ``policy_digest`` the tenancy LRU, the router's rendezvous
    hash and the reload echo all use (``serve/policy_server.py``)."""
    from fast_autoaugment_tpu.policies.archive import policy_to_tensor
    from fast_autoaugment_tpu.serve.policy_server import policy_digest

    raw = fsfault.load_json(policy_path)
    if not raw:
        raise ValueError(f"{policy_path} holds an empty policy set")
    subs = [[(str(op), float(p), float(lv)) for op, p, lv in sub]
            for sub in raw]
    return policy_digest(policy_to_tensor(subs))


def write_provenance(policy_path: str, stamp: dict) -> str:
    """Write the provenance sidecar for `policy_path` (digest computed
    here so the stamp can never disagree with the bytes it describes).
    Returns the sidecar path."""
    out = {
        "schema_version": PROVENANCE_SCHEMA_VERSION,
        "policy_digest": policy_file_digest(policy_path),
        "created_at": wall(),
        "host": f"host{os.environ.get('FAA_HOST_ID', '0')}",
        **stamp,
    }
    path = provenance_path(policy_path)
    _write_json_atomic(path, out)
    return path


def _write_json_atomic(path: str, obj) -> None:
    """The fsync-then-rename idiom through the fsfault seam (host-only
    — importing search.driver here would pull jax into a
    pure-bookkeeping path)."""
    fsfault.write_json_atomic(path, obj)


def load_provenance(policy_path: str) -> dict | None:
    """The sidecar for `policy_path`, or None (missing/unreadable —
    provenance bookkeeping must never break a caller)."""
    path = provenance_path(policy_path)
    if not os.path.exists(path):
        return None
    prov = fsfault.read_json(path)
    if prov is None:
        logger.warning("unreadable provenance sidecar %s", path)
        return None
    return prov if isinstance(prov, dict) else None


def seed_research_dir(base_dir: str, out_dir: str) -> list[str]:
    """Copy the warm-start substrate from a completed search dir into
    `out_dir`: per-fold trial logs, per-fold checkpoints (+ sidecars /
    chain links), and the cached audit records resume reads.  The base
    dir is never written — re-search must not disturb the serving
    fleet's provenance trail."""
    os.makedirs(out_dir, exist_ok=True)
    copied: list[str] = []
    try:
        names = fsfault.listdir(base_dir)
    except OSError as e:
        raise ValueError(f"unreadable base search dir {base_dir}: {e}")
    # everything resume reads comes along (trial logs, fold
    # checkpoints + chain links/sidecars, audit caches); the DERIVED
    # outputs stay behind so a half-finished re-search can never serve
    # a stale candidate, and journal segments stay with their run
    skip_prefixes = ("final_policy", "random_final_policy",
                     "search_result", "journal-")
    for name in names:
        src = os.path.join(base_dir, name)
        if not os.path.isfile(src):
            continue
        if name.startswith(skip_prefixes) or ".tmp" in name:
            continue
        shutil.copy2(src, os.path.join(out_dir, name))
        copied.append(name)
    if not any(n.startswith("search_trials") for n in copied):
        raise ValueError(
            f"base search dir {base_dir} holds no trial log "
            "(search_trials*.json) — nothing to warm-start from")
    return copied


def warm_started_research(conf, dataroot: str, base_dir: str,
                          out_dir: str, *, topup_trials: int,
                          drift: dict | None = None,
                          **search_kwargs) -> dict:
    """Run the incremental re-search: seed `out_dir` from `base_dir`'s
    persisted artifacts, top up the trial budget, and stamp the
    candidate's provenance sidecar.

    `search_kwargs` must name the SAME search geometry the base run
    used (num_search, cv_num, trial_batch, seed, ...) — the replay is
    only exact against the log it wrote.  ``async_pipeline`` defaults
    on so the warm start routes through the ``replay_trial_log``
    ledger (the RNG stream continues exactly where the base run left
    it).  Returns ``{"policy": path, "provenance": dict,
    "result": SearchResult}``."""
    from fast_autoaugment_tpu.search.driver import search_policies

    copied = seed_research_dir(base_dir, out_dir)
    search_kwargs.setdefault("async_pipeline", "on")
    search_kwargs.setdefault("resume", True)
    t0 = telemetry.mono()
    result = search_policies(
        conf, dataroot, out_dir,
        topup_trials=max(0, int(topup_trials)),
        **search_kwargs)
    policy_path = os.path.join(out_dir, "final_policy.json")
    stamp = {
        "kind": "warm_started_research",
        "base_dir": os.path.abspath(base_dir),
        "seeded_files": copied,
        "topup_trials": max(0, int(topup_trials)),
        "warm_start": result.get("warm_start"),
        "num_sub_policies": result.get("num_sub_policies"),
        "drift": drift,
        "research_wall_sec": round(telemetry.mono() - t0, 3),
    }
    sidecar = write_provenance(policy_path, stamp)
    prov = load_provenance(policy_path)
    telemetry.emit("research", "warm_start",
                   candidate=policy_path,
                   digest=prov.get("policy_digest") if prov else None,
                   topup_trials=stamp["topup_trials"],
                   base_dir=stamp["base_dir"],
                   wall_sec=stamp["research_wall_sec"],
                   drift_id=(drift or {}).get("id"))
    logger.info("re-search complete: candidate %s (digest %s, sidecar "
                "%s)", policy_path,
                prov.get("policy_digest") if prov else "?", sidecar)
    return {"policy": policy_path, "provenance": prov, "result": result}
