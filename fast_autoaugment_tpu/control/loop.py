"""The closed control loop: drift -> re-search -> canary -> promote.

One journaled state machine (docs/CONTROL.md) binding the four stages
this package provides:

1. **watching** — :class:`~fast_autoaugment_tpu.control.drift.
   DriftMonitor` tails the serving fleet's journal; a tripped CUSUM
   emits the typed ``drift`` event and moves the loop on.
2. **research** — `research_fn(verdict)` produces a candidate
   ``final_policy.json`` (+ provenance sidecar).  The production
   implementation is the warm-started top-up search
   (``control/research.py``); drills inject a stub.  A research
   failure journals the error and returns to watching (the fleet keeps
   serving the baseline — reacting to drift must never break serving).
3. **canary** — :class:`~fast_autoaugment_tpu.control.canary.
   CanaryController` pushes the candidate to the rendezvous-selected
   replica subset (digest-verified reloads) and arms the router's
   deterministic traffic split.
4. **observing/gate** — each poll samples both arms' Prometheus
   metrics, feeds :class:`~fast_autoaugment_tpu.control.canary.
   PromotionGate`, and on a verdict PROMOTES fleet-wide or ROLLS the
   canaries back — the decision journaled as a typed ``promote`` /
   ``rollback`` event with the comparison evidence INLINE, exactly
   like the PR-12 autoscaler's scale events.  Either way the drift
   monitor re-baselines: the post-decision traffic is the new normal.

The loop lives only in the process that runs it (``control_cli``) —
trainers, searchers and replicas are untouched, so "control loop off"
is the historical stream by construction.
"""

from __future__ import annotations

import threading

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.telemetry import mono
from fast_autoaugment_tpu.utils.logging import get_logger

from fast_autoaugment_tpu.control.canary import (
    CanaryController,
    PromotionGate,
    ReplicaQualityScraper,
    compare_arms,
)
from fast_autoaugment_tpu.control.drift import DriftMonitor

__all__ = ["ControlLoop"]

logger = get_logger("faa_tpu.control.loop")


class ControlLoop:
    """The journaled drift->promote loop (one ``step()`` per poll).

    `research_fn(verdict) -> {"policy": path, "provenance": dict}`
    owns stage two; everything else is wired here.  `baseline_policy`
    / `baseline_digest` are the rollback target — refreshed on every
    promotion (the promoted candidate becomes the next baseline)."""

    def __init__(self, monitor: DriftMonitor, research_fn,
                 canary_ctl: CanaryController, gate: PromotionGate,
                 scraper: ReplicaQualityScraper, *,
                 baseline_policy: str, baseline_digest: str,
                 n_canary: int = 1, split_every: int = 2,
                 poll_interval_s: float = 1.0, name: str = "control"):
        self.monitor = monitor
        self.research_fn = research_fn
        self.canary_ctl = canary_ctl
        self.gate = gate
        self.scraper = scraper
        self.baseline_policy = str(baseline_policy)
        self.baseline_digest = str(baseline_digest)
        self.n_canary = max(1, int(n_canary))
        self.split_every = max(1, int(split_every))
        self.poll_interval_s = float(poll_interval_s)
        self.name = str(name)
        self.state = "watching"
        self._episode: dict | None = None
        #: a reconstructed episode handed in by resume(); adopted by
        #: the NEXT step() so loop state stays single-writer (the
        #: poll thread) — see control/resume.py
        self._pending_resume: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        reg = telemetry.registry()
        self._decision_ctr = {a: reg.counter(
            "faa_control_decisions_total",
            "control-loop gate decisions by action",
            action=a, loop=self.name) for a in ("promote", "rollback")}
        self._episode_ctr = reg.counter(
            "faa_control_episodes_total",
            "drift episodes the loop has entered", loop=self.name)

    # ------------------------------------------------------ the stages

    def _quality_target(self) -> float:
        """The comparator's quality target: the drift monitor's frozen
        PRE-drift reward-proxy baseline (what 'back to baseline
        quality' means), falling back to the episode's first baseline-
        arm observation when the proxy was not a watched metric."""
        det = self.monitor.stats()["detectors"].get("reward_proxy")
        if det and det.get("baseline_mean") is not None:
            return float(det["baseline_mean"])
        return float((self._episode or {}).get("fallback_target") or 0.0)

    def _enter_research(self, verdict: dict) -> None:
        self._episode_ctr.inc()
        self._episode = {"verdict": verdict, "t_detect": mono()}
        self.state = "research"

    def _run_research(self) -> None:
        ep = self._episode
        t0 = mono()
        try:
            candidate = self.research_fn(ep["verdict"])
        except Exception as e:  # noqa: BLE001 — journaled, loop survives
            logger.error("re-search FAILED (%s: %s) — returning to "
                         "watching; the fleet keeps serving the "
                         "baseline", type(e).__name__, e)
            telemetry.emit("mark", self.name, event="research_failed",
                           error=f"{type(e).__name__}: {e}",
                           drift_id=ep["verdict"].get("id"))
            self._finish_episode(rebaseline=False)
            return
        prov = candidate.get("provenance") or {}
        digest = prov.get("policy_digest")
        if not digest:
            from fast_autoaugment_tpu.control.research import (
                policy_file_digest,
            )

            digest = policy_file_digest(candidate["policy"])
        ep.update(candidate=candidate["policy"], digest=digest,
                  provenance=prov, t_candidate=mono())
        # the loop journals the stage transition regardless of HOW the
        # candidate was produced (in-process warm start, a search_cli
        # subprocess, a drill's pre-built policy)
        telemetry.emit("research", self.name,
                       candidate=candidate["policy"], digest=digest,
                       topup_trials=prov.get("topup_trials"),
                       base_dir=prov.get("base_dir"),
                       wall_sec=round(mono() - t0, 3),
                       drift_id=ep["verdict"].get("id"))
        if digest == self.baseline_digest:
            # the re-search reproduced the serving policy (no-drift
            # degenerate case, or the drift was not policy-addressable)
            logger.info("re-search candidate == baseline policy (%s) — "
                        "nothing to canary; re-baselining the monitor",
                        digest)
            telemetry.emit("mark", self.name,
                           event="candidate_is_baseline", digest=digest,
                           drift_id=ep["verdict"].get("id"))
            self._finish_episode(rebaseline=True)
            return
        self.state = "canary"

    def _run_canary_rollout(self) -> None:
        ep = self._episode
        try:
            arms = self.canary_ctl.rollout(
                ep["candidate"], ep["digest"],
                n_canary=self.n_canary, split_every=self.split_every)
        except Exception as e:  # noqa: BLE001 — journaled, loop survives
            logger.error("canary rollout FAILED (%s: %s) — rolling the "
                         "subset back to the baseline",
                         type(e).__name__, e)
            telemetry.emit("mark", self.name, event="rollout_failed",
                           error=f"{type(e).__name__}: {e}",
                           digest=ep.get("digest"))
            self._rollback(reason=f"rollout failed: {e}", evidence={})
            return
        ep.update(arms=arms, t_canary=mono())
        self.gate.reset()
        self.state = "observing"

    def _run_observe(self) -> None:
        ep = self._episode
        # RE-ASSERT the canary split every poll (idempotent POST
        # /canary with echo verification): a router that restarted
        # mid-canary silently routes 100% baseline while the gate
        # keeps scoring a canary arm that no longer exists — one poll
        # later the split is re-armed.  An echo mismatch (a split this
        # controller does not own) rolls back instead of fighting.
        try:
            self.canary_ctl.assert_split(ep["digest"],
                                         ep["arms"]["canary"],
                                         self.split_every)
        except Exception as e:  # noqa: BLE001 — journaled, loop survives
            logger.error("canary split re-assert FAILED (%s: %s) — "
                         "rolling the subset back", type(e).__name__, e)
            telemetry.emit("mark", self.name, event="split_reassert_failed",
                           error=f"{type(e).__name__}: {e}",
                           digest=ep.get("digest"))
            self._rollback(reason=f"canary split re-assert failed: {e}",
                           evidence={})
            return
        census = {str(r["tag"]): r for r in self.canary_ctl.replicas_fn()}
        samples = self.scraper.sample(list(census.values()))
        if "fallback_target" not in ep:
            base_rows = [r for t, r in samples.items()
                         if t not in set(ep["arms"]["canary"])
                         and r.get("reward_proxy") is not None]
            if base_rows:
                ep["fallback_target"] = float(
                    base_rows[0]["reward_proxy"])
        evidence = compare_arms(samples, ep["arms"]["canary"],
                                self._quality_target())
        action, reason, summary = self.gate.decide(evidence)
        if action is None:
            return
        ep["census"] = census
        if action == "promote":
            self._promote(reason, summary)
        else:
            self._rollback(reason=reason, evidence=summary)

    def _promote(self, reason: str, evidence: dict) -> None:
        ep = self._episode
        self.canary_ctl.promote(ep["candidate"], ep["digest"],
                                ep.get("census", {}),
                                ep["arms"]["canary"])
        self._decision_ctr["promote"].inc()
        telemetry.emit(
            "promote", self.name, digest=ep["digest"],
            policy=ep["candidate"], reason=reason,
            drift_id=ep["verdict"].get("id"),
            canary=ep["arms"]["canary"],
            detect_to_promote_sec=round(mono() - ep["t_detect"], 3),
            evidence=evidence)
        logger.warning("PROMOTED %s fleet-wide (%s)", ep["digest"],
                       reason)
        # the promoted candidate is the new baseline for the next
        # episode's rollback target
        self.baseline_policy = ep["candidate"]
        self.baseline_digest = ep["digest"]
        self._finish_episode(rebaseline=True)

    def _rollback(self, *, reason: str, evidence: dict) -> None:
        ep = self._episode
        try:
            self.canary_ctl.rollback(
                self.baseline_policy, self.baseline_digest,
                ep.get("census") or {
                    str(r["tag"]): r for r in self.canary_ctl.replicas_fn()},
                (ep.get("arms") or {}).get("canary", []))
        except Exception as e:  # noqa: BLE001 — journaled, loop survives
            logger.error("rollback actuation failed (%s: %s) — replicas "
                         "may need operator attention",
                         type(e).__name__, e)
        self._decision_ctr["rollback"].inc()
        telemetry.emit(
            "rollback", self.name, digest=ep.get("digest"),
            baseline_digest=self.baseline_digest, reason=reason,
            drift_id=ep["verdict"].get("id"),
            canary=(ep.get("arms") or {}).get("canary", []),
            evidence=evidence)
        logger.warning("ROLLED BACK canary %s (%s)", ep.get("digest"),
                       reason)
        self._finish_episode(rebaseline=True)

    def _finish_episode(self, *, rebaseline: bool) -> None:
        if rebaseline:
            # the post-decision traffic is the new normal: the monitor
            # re-learns its baseline instead of re-tripping forever on
            # a shift the loop already handled
            self.monitor.rebaseline()
        self._episode = None
        self.state = "watching"

    # ---------------------------------------------------------- resume

    def resume(self, episode: dict) -> str:
        """Schedule a reconstructed in-flight episode for adoption
        (``control_cli --resume`` after a controller crash —
        ``control/resume.py`` rebuilt it from the journal WAL).  The
        NEXT step() adopts it, so the poll thread stays the only
        writer of loop state.

        Stages re-enter IDEMPOTENTLY: a ``research``-stage episode
        re-runs the re-search from the journaled verdict; a ``canary``/
        ``observing``-stage episode re-enters at the ROLLOUT — every
        reload is a digest-echoing re-verify and ``POST /canary``
        replaces any dangling split, so replicas already holding the
        candidate re-verify instantly, a router restarted baseline-only
        re-arms, and the gate restarts its window on fresh traffic.  A
        rollout that can no longer succeed rolls the subset back — a
        SIGKILLed controller's dangling canary always terminates in a
        journaled promote or rollback, never a forever-split."""
        verdict = dict(episode.get("verdict") or {})
        stage = ("canary" if episode.get("digest")
                 and str(episode.get("stage")) in ("canary", "observing")
                 else "research")
        with self._lock:
            self._pending_resume = dict(episode, verdict=verdict,
                                        stage=stage)
        telemetry.emit("mark", self.name, event="resume", stage=stage,
                       drift_id=verdict.get("id"),
                       digest=episode.get("digest"))
        logger.warning("control loop RESUMING a dangling %s-stage "
                       "episode (drift %s, candidate digest %s)",
                       stage, verdict.get("id"), episode.get("digest"))
        return stage

    def _adopt_resume(self, pending: dict) -> None:
        """Turn the scheduled episode into live loop state (poll
        thread only)."""
        self._episode_ctr.inc()
        ep = {"verdict": pending["verdict"], "t_detect": mono()}
        if pending["stage"] == "canary":
            ep.update(candidate=pending["candidate"],
                      digest=pending["digest"],
                      provenance=pending.get("provenance") or {},
                      t_candidate=mono())
            self.state = "canary"
        else:
            self.state = "research"
        self._episode = ep

    # ---------------------------------------------------------- driver

    def step(self) -> str:
        """One poll of whatever stage the loop is in; returns the
        state AFTER the step (the drill's observable)."""
        with self._lock:
            if self._pending_resume is not None:
                pending, self._pending_resume = self._pending_resume, None
                self._adopt_resume(pending)
                return self.state
            if self.state == "watching":
                verdict = self.monitor.poll()
                if verdict is not None:
                    self._enter_research(verdict)
            elif self.state == "research":
                self._run_research()
            elif self.state == "canary":
                self._run_canary_rollout()
            elif self.state == "observing":
                self.monitor.poll()  # keep journal offsets advancing
                self._run_observe()
            return self.state

    def loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except OSError as e:
                logger.warning("control poll failed: %s", e)

    def start(self) -> "ControlLoop":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self.loop, daemon=True,
                                            name="control-loop")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            # bounded join (lint R6/R9): the loop is a daemon either way
            self._thread.join(timeout=timeout)

    def stats(self) -> dict:
        with self._lock:
            ep = self._episode
            return {
                "loop": self.name,
                "state": self.state,
                "baseline_policy": self.baseline_policy,
                "baseline_digest": self.baseline_digest,
                "poll_interval_s": self.poll_interval_s,
                "episode": None if ep is None else {
                    "drift_id": ep["verdict"].get("id"),
                    "candidate": ep.get("candidate"),
                    "digest": ep.get("digest"),
                    "canary": (ep.get("arms") or {}).get("canary"),
                },
                "monitor": self.monitor.stats(),
                "gate": self.gate.snapshot(),
                "promotes": int(self._decision_ctr["promote"].value),
                "rollbacks": int(self._decision_ctr["rollback"].value),
            }
