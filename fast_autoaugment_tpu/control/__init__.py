"""Closed-loop control plane: drift detection, warm-started re-search,
canary rollout, gated promotion (docs/CONTROL.md).

Host-only orchestration over the seams the earlier subsystems built —
the telemetry journal (PR 10), the ``replay_trial_log`` TPE ledger
(PR 9), ``POST /reload`` (PR 8) and the digest-affinity router
(PR 12).  Nothing here touches a device; the loop decides WHEN to
search and WHAT to serve."""

from fast_autoaugment_tpu.control.canary import (
    CanaryController,
    PromotionGate,
    ReplicaQualityScraper,
    compare_arms,
    select_canary_replicas,
)
from fast_autoaugment_tpu.control.drift import (
    CusumMeanShift,
    DriftMonitor,
    TrafficSampleReader,
)
from fast_autoaugment_tpu.control.loop import ControlLoop
from fast_autoaugment_tpu.control.resume import (
    read_control_events,
    reconstruct_inflight_episode,
)
from fast_autoaugment_tpu.control.research import (
    load_provenance,
    policy_file_digest,
    provenance_path,
    warm_started_research,
    write_provenance,
)

__all__ = [
    "CanaryController",
    "ControlLoop",
    "CusumMeanShift",
    "DriftMonitor",
    "PromotionGate",
    "ReplicaQualityScraper",
    "TrafficSampleReader",
    "compare_arms",
    "load_provenance",
    "policy_file_digest",
    "provenance_path",
    "read_control_events",
    "reconstruct_inflight_episode",
    "select_canary_replicas",
    "warm_started_research",
    "write_provenance",
]
