"""Crash-resumable control loop: journal-as-WAL reconstruction.

The control loop's journal events were designed as an audit trail
(PR 14); this module treats them as a WRITE-AHEAD LOG.  Every stage
transition the loop makes is journaled BEFORE its effects matter
(``drift`` before research starts, ``research`` before the rollout,
``canary``/rollout before the gate window, ``promote``/``rollback``/
terminal ``mark``s when an episode closes), and every stage action is
idempotent (reloads echo digests, ``POST /canary`` replaces the
split), so a controller that dies at ANY point can be restarted with
``control_cli --resume``: the journal names the dangling episode and
the stage it reached, the live router/replica state is re-asserted by
re-entering that stage, and the episode terminates in a journaled
promote or rollback instead of splitting traffic forever.

Reconstruction is read-only over the shared journal (through the
``core/fsfault.py`` seam — a resuming controller is exactly the kind
of reader a hostile share bites) and pure given the record stream, so
it is drivable in tests without any live fleet.
"""

from __future__ import annotations

import json
import os

from fast_autoaugment_tpu.core import fsfault
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["read_control_events", "reconstruct_inflight_episode",
           "CONTROL_EVENT_TYPES", "TERMINAL_MARKS"]

logger = get_logger("faa_tpu.control.resume")

#: the journal event types that carry control-loop WAL state
CONTROL_EVENT_TYPES = ("drift", "research", "canary", "promote",
                       "rollback", "mark")

#: ``mark`` events that CLOSE an episode without a promote/rollback
TERMINAL_MARKS = ("research_failed", "candidate_is_baseline")

#: journal-envelope keys stripped when a drift event is turned back
#: into the verdict dict the loop carries
_ENVELOPE_KEYS = ("type", "label", "host", "pid", "tid", "thread",
                  "seq", "t_wall", "t_mono", "attempt")


def read_control_events(journal_dir: str) -> list[dict]:
    """Every control-relevant journal record under `journal_dir`, in
    (host, pid, seq) order — one controller writes them, so this is
    the WAL's append order."""
    pattern = os.path.join(journal_dir, "**", "journal-*.jsonl")
    records: list[dict] = []
    for path in fsfault.glob_files(pattern):
        try:
            data = fsfault.read_from(path, 0)
        except OSError:
            continue  # transient (injected eio / half-visible file)
        for line in data.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn line from the killed writer
            if isinstance(rec, dict) \
                    and rec.get("type") in CONTROL_EVENT_TYPES:
                records.append(rec)
    records.sort(key=lambda r: (str(r.get("host")), r.get("pid", 0),
                                r.get("seq", 0)))
    return records


def _verdict_from_event(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in _ENVELOPE_KEYS}


def reconstruct_inflight_episode(events: list[dict]) -> dict | None:
    """The dangling episode a dead controller left behind, or None
    when the WAL is clean (every drift episode reached a terminal
    promote / rollback / terminal mark).

    Returns ``{"verdict", "stage", "candidate", "digest",
    "provenance"}`` with stage ``research`` (drift seen, no candidate
    yet) or ``canary`` (candidate known — rollout may or may not have
    completed; re-entering the rollout is idempotent either way)."""
    episode: dict | None = None
    for rec in events:
        etype = rec.get("type")
        if etype == "drift":
            episode = {"verdict": _verdict_from_event(rec),
                       "stage": "research", "candidate": None,
                       "digest": None, "provenance": {}}
        elif episode is None:
            continue
        elif etype == "research":
            if rec.get("candidate") and rec.get("digest"):
                episode.update(candidate=rec["candidate"],
                               digest=rec["digest"], stage="canary")
        elif etype == "canary" and rec.get("action") in ("rollout",
                                                         "split_set"):
            episode["stage"] = "canary"
        elif etype in ("promote", "rollback"):
            episode = None
        elif etype == "mark" and rec.get("event") in TERMINAL_MARKS:
            episode = None
    if episode is None:
        return None
    # the provenance sidecar (if the candidate file survived) rides
    # along so the resumed rollout re-verifies the same digest chain
    if episode.get("candidate"):
        try:
            from fast_autoaugment_tpu.control.research import (
                load_provenance,
            )

            episode["provenance"] = load_provenance(
                episode["candidate"]) or {}
        except Exception as e:  # noqa: BLE001 — provenance is best-effort
            logger.warning("resume: provenance sidecar unreadable for "
                           "%s (%s)", episode["candidate"], e)
            episode["provenance"] = {}
    logger.warning(
        "journal WAL shows a DANGLING control episode: drift %s at "
        "stage %s (candidate %s, digest %s)",
        (episode.get("verdict") or {}).get("id"), episode.get("stage"),
        episode.get("candidate"), episode.get("digest"))
    return episode
