"""PyramidNet with ShakeDrop in Flax, NHWC.

Capability match for the reference ``networks/pyramidnet.py:15-248``:
additive pyramidal channel growth (``addrate = alpha / (3n)``), zero-init
BN-led bottleneck blocks, per-block linearly increasing ShakeDrop death
rates up to 0.5 (``pyramidnet.py:135``), average-pool downsampling and
zero-padded channel-mismatch shortcut adds.  The flagship config is
pyramid272 (depth=272, alpha=200, bottleneck) used for the best CIFAR
numbers (``confs/pyramid272_cifar.yaml``).

Channel bookkeeping reproduces the reference exactly: widths accumulate
as floats and round per block, with the block input tracked as
``round(width) * expansion``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from fast_autoaugment_tpu.models.layers import BatchNorm, global_avg_pool, he_normal_fanout
from fast_autoaugment_tpu.ops.shake import (
    sample_shake_drop_noise,
    shake_drop,
    shake_drop_eval,
)

__all__ = ["PyramidNet", "pyramidnet_plan"]


def _conv(features, kernel, stride=1, dtype=None, name=None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=[(kernel // 2, kernel // 2)] * 2,
        use_bias=False,
        kernel_init=he_normal_fanout,
        dtype=dtype,
        name=name,
    )


def pyramidnet_plan(depth: int, alpha: float, bottleneck: bool):
    """Per-block (width, stride, p_shakedrop) plan, replicating the
    reference's float accumulation (``pyramidnet.py:128-214``)."""
    if bottleneck:
        n = (depth - 2) // 9
        expansion = 4
    else:
        n = (depth - 2) // 6
        expansion = 1
    total = 3 * n
    addrate = alpha / (3.0 * n)
    ps = [(0.5 / total) * (i + 1) for i in range(total)]
    plan = []
    featuremap_dim = 16.0
    for stage in range(3):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            featuremap_dim += addrate
            plan.append((int(round(featuremap_dim)), stride, ps.pop(0)))
    assert not ps
    return plan, expansion


class _ShakeDropGate(nn.Module):
    """Apply shake-drop noise from the 'shake' RNG stream."""

    p_drop: float

    @nn.compact
    def __call__(self, x, train: bool):
        if train:
            gate, alpha, beta = sample_shake_drop_noise(
                self.make_rng("shake"), x.shape[0], self.p_drop, x.dtype
            )
            return shake_drop(x, gate, alpha, beta)
        return shake_drop_eval(x, self.p_drop)


class PyramidBasicBlock(nn.Module):
    """BN-conv3-BN-relu-conv3-BN (+ShakeDrop) (reference ``pyramidnet.py:15-60``)."""

    features: int
    stride: int
    p_shakedrop: float
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        out = BatchNorm(name="bn1")(x, train)
        out = _conv(self.features, 3, self.stride, dtype=self.dtype, name="conv1")(out)
        out = BatchNorm(name="bn2")(out, train)
        out = nn.relu(out)
        out = _conv(self.features, 3, 1, dtype=self.dtype, name="conv2")(out)
        out = BatchNorm(name="bn3")(out, train)
        out = _ShakeDropGate(self.p_shakedrop, name="shake_drop")(out, train)
        return _shortcut_add(x, out, self.stride)


class PyramidBottleneck(nn.Module):
    """BN-1x1-BN-relu-3x3-BN-relu-1x1-BN (+ShakeDrop)
    (reference ``pyramidnet.py:63-118``)."""

    features: int
    stride: int
    p_shakedrop: float
    expansion: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        out = BatchNorm(name="bn1")(x, train)
        out = _conv(self.features, 1, dtype=self.dtype, name="conv1")(out)
        out = BatchNorm(name="bn2")(out, train)
        out = nn.relu(out)
        out = _conv(self.features, 3, self.stride, dtype=self.dtype, name="conv2")(out)
        out = BatchNorm(name="bn3")(out, train)
        out = nn.relu(out)
        out = _conv(self.features * self.expansion, 1, dtype=self.dtype, name="conv3")(out)
        out = BatchNorm(name="bn4")(out, train)
        out = _ShakeDropGate(self.p_shakedrop, name="shake_drop")(out, train)
        return _shortcut_add(x, out, self.stride)


def _shortcut_add(x, out, stride):
    """Average-pool downsample + zero-channel-pad shortcut
    (reference ``pyramidnet.py:41-60,98-117,200-202``)."""
    shortcut = x
    if stride != 1:
        # AvgPool2d((2,2), stride=2, ceil_mode=True)
        h, w = x.shape[1], x.shape[2]
        pad_h, pad_w = h % 2, w % 2
        padded = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        counts = jnp.ones((1, h, w, 1), x.dtype)
        counts = jnp.pad(counts, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        summed = nn.avg_pool(padded, (2, 2), strides=(2, 2)) * 4.0
        denom = nn.avg_pool(counts, (2, 2), strides=(2, 2)) * 4.0
        shortcut = summed / denom
    pad_c = out.shape[-1] - shortcut.shape[-1]
    if pad_c > 0:
        shortcut = jnp.pad(shortcut, ((0, 0), (0, 0), (0, 0), (0, pad_c)))
    return out + shortcut


class PyramidNet(nn.Module):
    """dataset in {'cifar10', 'cifar100', 'svhn'}; ImageNet variant uses the
    4-stage stem (reference ``pyramidnet.py:157-190``)."""

    dataset: str
    depth: int
    alpha: float
    num_classes: int
    bottleneck: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        plan, expansion = pyramidnet_plan(self.depth, self.alpha, self.bottleneck)
        block = PyramidBottleneck if self.bottleneck else PyramidBasicBlock
        out = _conv(16, 3, 1, dtype=self.dtype, name="conv1")(x)
        out = BatchNorm(name="bn1")(out, train)
        for idx, (width, stride, p_sd) in enumerate(plan):
            out = block(width, stride, p_sd, dtype=self.dtype,
                        name=f"block{idx}")(out, train)
        out = BatchNorm(name="bn_final")(out, train)
        out = nn.relu(out)
        out = global_avg_pool(out).astype(jnp.float32)
        return nn.Dense(self.num_classes, name="fc")(out)
