"""ResNet (CIFAR and ImageNet variants) in Flax, NHWC.

Capability match for the reference ``networks/resnet.py:13-180``
(torchvision-style pre-2016 ResNet): BasicBlock/Bottleneck, CIFAR stem
(3x3, 16 planes, 3 stages) for depth 6n+2 / 9n+2, ImageNet stem
(7x7/2 + maxpool 3x3/2, 4 stages) for depths {18, 34, 50, 101, 152,
200}.  He-normal fan-out conv init, BN gamma=1/beta=0
(``resnet.py:126-132``); downsample shortcut is 1x1-conv + BN.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from fast_autoaugment_tpu.models.layers import BatchNorm, global_avg_pool, he_normal_fanout

__all__ = ["ResNet", "IMAGENET_LAYERS"]

IMAGENET_LAYERS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
    200: ("bottleneck", (3, 24, 36, 3)),
}


def _conv(features, kernel, stride, dtype=None, name=None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=[(kernel // 2, kernel // 2)] * 2,
        use_bias=False,
        kernel_init=he_normal_fanout,
        dtype=dtype,
        name=name,
    )


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        out = _conv(self.features, 3, self.stride, dtype=self.dtype, name="conv1")(x)
        out = BatchNorm(name="bn1")(out, train)
        out = nn.relu(out)
        out = _conv(self.features, 3, 1, dtype=self.dtype, name="conv2")(out)
        out = BatchNorm(name="bn2")(out, train)
        if self.stride != 1 or x.shape[-1] != self.features:
            residual = _conv(self.features, 1, self.stride, dtype=self.dtype,
                             name="downsample_conv")(x)
            residual = BatchNorm(name="downsample_bn")(residual, train)
        return nn.relu(out + residual)


class Bottleneck(nn.Module):
    features: int  # bottleneck width; output is 4x
    stride: int = 1
    expansion: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        out_features = self.features * self.expansion
        residual = x
        out = _conv(self.features, 1, 1, dtype=self.dtype, name="conv1")(x)
        out = nn.relu(BatchNorm(name="bn1")(out, train))
        out = _conv(self.features, 3, self.stride, dtype=self.dtype, name="conv2")(out)
        out = nn.relu(BatchNorm(name="bn2")(out, train))
        out = _conv(out_features, 1, 1, dtype=self.dtype, name="conv3")(out)
        out = BatchNorm(name="bn3")(out, train)
        if self.stride != 1 or x.shape[-1] != out_features:
            residual = _conv(out_features, 1, self.stride, dtype=self.dtype,
                             name="downsample_conv")(x)
            residual = BatchNorm(name="downsample_bn")(residual, train)
        return nn.relu(out + residual)


class ResNet(nn.Module):
    """dataset='cifar' (depth 6n+2 basic / 9n+2 bottleneck) or 'imagenet'."""

    dataset: str
    depth: int
    num_classes: int
    bottleneck: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.dataset.startswith("cifar") or self.dataset in ("svhn",):
            if self.bottleneck:
                n = (self.depth - 2) // 9
                block, widths = Bottleneck, (16, 32, 64)
            else:
                n = (self.depth - 2) // 6
                block, widths = BasicBlock, (16, 32, 64)
            out = _conv(16, 3, 1, dtype=self.dtype, name="conv1")(x)
            out = nn.relu(BatchNorm(name="bn1")(out, train))
            for stage, width in enumerate(widths):
                for i in range(n):
                    stride = 2 if (stage > 0 and i == 0) else 1
                    out = block(width, stride, dtype=self.dtype,
                                name=f"layer{stage + 1}_{i}")(out, train)
        elif self.dataset == "imagenet":
            kind, counts = IMAGENET_LAYERS[self.depth]
            block = BasicBlock if kind == "basic" else Bottleneck
            out = nn.Conv(
                64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                use_bias=False, kernel_init=he_normal_fanout, dtype=self.dtype,
                name="conv1",
            )(x)
            out = nn.relu(BatchNorm(name="bn1")(out, train))
            out = nn.max_pool(out, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
            for stage, (width, count) in enumerate(zip((64, 128, 256, 512), counts)):
                for i in range(count):
                    stride = 2 if (stage > 0 and i == 0) else 1
                    out = block(width, stride, dtype=self.dtype,
                                name=f"layer{stage + 1}_{i}")(out, train)
        else:
            raise ValueError(f"unknown dataset {self.dataset!r}")

        out = global_avg_pool(out).astype(jnp.float32)
        return nn.Dense(self.num_classes, name="fc")(out)
