"""WideResNet (WRN-d-k) in Flax, NHWC.

Capability match for the reference ``networks/wideresnet.py:21-85``:
pre-activation wide basic blocks with conv bias=True, dropout between
the two convs, BN with torch-momentum 0.9 (i.e. running stats track the
latest batch heavily), 1x1-conv shortcut on shape change, global
average pool head.  Parameter init follows PyTorch defaults (the
reference's custom init is commented out, ``wideresnet.py:66``).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from fast_autoaugment_tpu.models.layers import (
    BatchNorm,
    global_avg_pool,
    torch_default_bias_for,
    torch_default_kernel,
)

__all__ = ["WideResNet"]

_BN_MOMENTUM = 0.9  # torch convention, reference wideresnet.py:24


def _conv(features: int, kernel: int, stride: int, in_features: int,
          dtype=None, name: str | None = None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=[(kernel // 2, kernel // 2)] * 2,
        use_bias=True,
        kernel_init=torch_default_kernel(),
        bias_init=torch_default_bias_for(in_features * kernel * kernel),
        dtype=dtype,
        name=name,
    )


class WideBasic(nn.Module):
    """Pre-activation wide basic block (reference ``wideresnet.py:21-41``)."""

    features: int
    stride: int
    dropout_rate: float
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool, dropout_rng=None):
        in_features = x.shape[-1]
        out = nn.relu(BatchNorm(momentum=_BN_MOMENTUM, name="bn1")(x, train))
        out = _conv(self.features, 3, 1, in_features, dtype=self.dtype, name="conv1")(out)
        if self.dropout_rate > 0.0:
            out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        out = nn.relu(BatchNorm(momentum=_BN_MOMENTUM, name="bn2")(out, train))
        out = _conv(self.features, 3, self.stride, self.features, dtype=self.dtype,
                    name="conv2")(out)
        if self.stride != 1 or in_features != self.features:
            shortcut = _conv(self.features, 1, self.stride, in_features,
                             dtype=self.dtype, name="shortcut")(x)
        else:
            shortcut = x
        return out + shortcut


class WideResNet(nn.Module):
    """WRN-depth-widen_factor; depth = 6n + 4 (reference ``wideresnet.py:44-85``)."""

    depth: int
    widen_factor: int
    num_classes: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        assert (self.depth - 4) % 6 == 0, "WideResNet depth must be 6n+4"
        n = (self.depth - 4) // 6
        k = self.widen_factor
        stages = (16, 16 * k, 32 * k, 64 * k)

        out = _conv(stages[0], 3, 1, x.shape[-1], dtype=self.dtype, name="conv1")(x)
        for stage, (features, stride) in enumerate(
            zip(stages[1:], (1, 2, 2)), start=1
        ):
            for i in range(n):
                out = WideBasic(
                    features,
                    stride if i == 0 else 1,
                    self.dropout_rate,
                    dtype=self.dtype,
                    name=f"layer{stage}_{i}",
                )(out, train)
        out = nn.relu(BatchNorm(momentum=_BN_MOMENTUM, name="bn1")(out, train))
        out = global_avg_pool(out)
        out = nn.Dense(
            self.num_classes,
            kernel_init=torch_default_kernel(),
            bias_init=torch_default_bias_for(stages[3]),
            name="linear",
        )(out.astype(jnp.float32))
        return out
