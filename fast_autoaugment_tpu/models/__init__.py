"""Model registry.

Capability match for the reference ``networks/__init__.py:19-103``:
string model types map to Flax modules.  Unlike the reference (which
also wraps models in DDP/.cuda() here), device placement and sharding
are the train step's concern — a module is pure structure.

Supported types (reference parity): resnet50, resnet200, wresnet40_2,
wresnet28_10, shakeshake26_2x32d / 2x64d / 2x96d / 2x112d,
shakeshake26_2x96d_next, pyramid, efficientnet-b0..b7 (+condconv).
"""

from __future__ import annotations

from typing import Any

from flax import linen as nn

from fast_autoaugment_tpu.models.pyramidnet import PyramidNet
from fast_autoaugment_tpu.models.resnet import ResNet
from fast_autoaugment_tpu.models.shake_resnet import ShakeResNet, ShakeResNeXt
from fast_autoaugment_tpu.models.wideresnet import WideResNet

__all__ = ["get_model", "num_class", "input_image_size"]


def num_class(dataset: str) -> int:
    """Class count per dataset (reference ``networks/__init__.py:93-103``)."""
    if dataset.startswith("synthetic_shapes"):
        return 10  # glyph task is always 10-class (any _nN train size)
    if dataset.startswith("synthetic"):
        return 100 if dataset.endswith("100") else 10
    return {
        "cifar10": 10,
        "reduced_cifar10": 10,
        "cifar10.1": 10,
        "cifar100": 100,
        "svhn": 10,
        "reduced_svhn": 10,
        "imagenet": 1000,
        "reduced_imagenet": 120,
    }[dataset]


def input_image_size(dataset: str, model_type: str) -> int:
    """Native input resolution for dataset/model."""
    if dataset.endswith("imagenet"):
        if model_type.startswith("efficientnet"):
            from fast_autoaugment_tpu.models.efficientnet import efficientnet_params

            return efficientnet_params(model_type.replace("-condconv", ""))[2]
        return 224
    return 32


def get_model(conf: Any, num_classes: int) -> nn.Module:
    """Build a Flax module from a model config mapping.

    `conf` needs `.type` plus model-specific fields (reference conf
    schema: `model{type, (depth, alpha, bottleneck) | (condconv_num_expert)}`).
    """
    name = conf["type"]
    dataset = conf.get("dataset", "cifar")
    # mixed precision: 'bf16' runs activations in bfloat16 (params, BN
    # statistics and logits stay float32) — threaded through every family
    precision = str(conf.get("precision", "f32") or "f32").lower()
    import jax.numpy as jnp

    if precision in ("bf16", "bfloat16"):
        dtype = jnp.bfloat16
    elif precision in ("f32", "fp32", "float32"):
        dtype = jnp.float32
    else:
        raise ValueError(
            f"unknown precision {precision!r}; use 'f32' or 'bf16'"
        )

    if name in ("resnet50", "resnet200"):
        return ResNet(dataset="imagenet", depth=int(name[len("resnet"):]),
                      num_classes=num_classes, bottleneck=True, dtype=dtype)
    if name.startswith("wresnet"):
        # wresnet{depth}_{widen}
        depth, widen = name[len("wresnet"):].split("_")
        return WideResNet(
            depth=int(depth),
            widen_factor=int(widen),
            num_classes=num_classes,
            dropout_rate=0.0,
            dtype=dtype,
        )
    if name.startswith("shakeshake26_2x"):
        rest = name[len("shakeshake26_2x"):]
        if rest.endswith("d_next"):
            return ShakeResNeXt(
                depth=26, w_base=int(rest[:-len("d_next")]), cardinality=4,
                num_classes=num_classes, dtype=dtype,
            )
        assert rest.endswith("d")
        return ShakeResNet(depth=26, w_base=int(rest[:-1]), num_classes=num_classes,
                           dtype=dtype)
    if name == "pyramid":
        return PyramidNet(
            dataset=dataset if dataset.startswith("cifar") else "cifar10",
            depth=int(conf["depth"]),
            alpha=float(conf["alpha"]),
            num_classes=num_classes,
            bottleneck=bool(conf.get("bottleneck", True)),
            dtype=dtype,
        )
    if name.startswith("efficientnet"):
        from fast_autoaugment_tpu.models.efficientnet import EfficientNet

        condconv = "condconv" in name
        base = name.replace("-condconv", "")
        return EfficientNet.from_name(
            base,
            num_classes=num_classes,
            condconv_num_expert=int(conf.get("condconv_num_expert", 0)) if condconv else 0,
            dtype=dtype,
        )
    raise ValueError(f"unknown model type {name!r}")
