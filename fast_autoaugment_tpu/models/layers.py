"""Shared layers and initializers for the model zoo.

The reference models inherit PyTorch's *default* parameter init in most
places (WideResNet's custom ``conv_init`` is commented out,
``wideresnet.py:66``), while ImageNet ResNet uses He-normal fan-out
(``resnet.py:126-132``).  Those distributions affect reproducibility,
so both are provided here explicitly:

- :data:`torch_default_kernel` / :func:`torch_default_bias_for` —
  PyTorch's kaiming-uniform(a=sqrt 5) conv/linear default:
  U(+-1/sqrt(fan_in)).
- :data:`he_normal_fanout` — N(0, sqrt(2 / (k*k*c_out))).

All modules run NHWC, the TPU-native layout.  BatchNorm momentum
conventions differ between frameworks: torch ``momentum`` is the weight
of the NEW batch statistic, flax's is the weight of the OLD running
average; the ``bn_momentum`` arguments here follow the torch convention
used in the reference and are converted internally.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

__all__ = [
    "torch_default_kernel",
    "torch_default_bias_for",
    "he_normal_fanout",
    "BatchNorm",
    "global_avg_pool",
]


def torch_default_kernel(dtype=jnp.float32):
    """PyTorch default conv/linear weight init: kaiming_uniform(a=sqrt(5)),
    i.e. U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    return jax.nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform", dtype=dtype)


def torch_default_bias_for(fan_in: int, dtype=jnp.float32) -> Callable:
    """PyTorch default bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in)).

    flax bias initializers don't see fan_in, so the caller supplies it
    at module build time (``bias_init=torch_default_bias_for(fan_in)``).
    """
    bound = 1.0 / np.sqrt(fan_in) if fan_in > 0 else 0.0

    def init(key, shape, dtype=dtype):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


he_normal_fanout = jax.nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class BatchNorm(nn.Module):
    """BatchNorm with torch-convention momentum.

    Under a single jitted train step over the global (mesh-sharded)
    batch, XLA computes batch statistics over ALL replicas — this is
    exactly the cross-replica BN the reference's ``TpuBatchNormalization``
    (``tf_port/tpu_bn.py:8-58``) emulated with explicit allreduces, and
    it is simply the default here.  When the train step instead runs
    per-replica inside ``shard_map``, pass ``axis_name='data'`` to
    reduce statistics with ``lax.pmean`` (the NCCL-allreduce analog).
    """

    momentum: float = 0.1  # torch convention: weight of the NEW stat
    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        # statistics always in float32 (mixed-precision safety: bf16
        # variance accumulation is too coarse); output follows the
        # activation dtype
        in_dtype = x.dtype
        norm = nn.BatchNorm(
            use_running_average=not train,
            momentum=1.0 - self.momentum,
            epsilon=self.epsilon,
            use_scale=self.use_scale,
            use_bias=self.use_bias,
            axis_name=self.axis_name,
            dtype=jnp.float32,
        )
        return norm(x.astype(jnp.float32)).astype(in_dtype)


def global_avg_pool(x: jax.Array) -> jax.Array:
    """NHWC global average pool -> [N, C]."""
    return x.mean(axis=(1, 2))
