"""Shake-Shake ResNet / ResNeXt (26-layer, 3-stage) in Flax, NHWC.

Capability match for the reference
``networks/shakeshake/shake_resnet.py:12-81`` and
``shake_resnext.py:12-84``: each block computes two parallel branches
mixed by the stochastic :func:`~fast_autoaugment_tpu.ops.shake.shake_shake`
op (per-sample forward alpha, fresh backward beta), with the two-path
1x1-conv downsampling ``Shortcut`` (second path shifted one pixel via
crop-and-pad before subsampling, reference ``shakeshake.py:29-48``).
He-normal fan-out init, zero linear bias (reference
``shake_resnet.py:55-63``).

Noise keys come from the ``'shake'`` RNG collection when ``train=True``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from fast_autoaugment_tpu.models.layers import BatchNorm, global_avg_pool, he_normal_fanout
from fast_autoaugment_tpu.ops.shake import (
    sample_shake_shake_noise,
    shake_shake,
    shake_shake_eval,
)

__all__ = ["ShakeResNet", "ShakeResNeXt"]


def _conv(features, kernel, stride=1, groups=1, bias=False, dtype=None, name=None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=[(kernel // 2, kernel // 2)] * 2,
        feature_group_count=groups,
        use_bias=bias,
        kernel_init=he_normal_fanout,
        dtype=dtype,
        name=name,
    )


class Shortcut(nn.Module):
    """Two-path strided 1x1 shortcut (reference ``shakeshake.py:29-48``).

    Path 1 subsamples at even offsets; path 2 shifts by one pixel
    (crop top-left, zero-pad bottom-right) before subsampling, so the
    two paths see complementary pixels; halves concatenated then BN.
    """

    out_ch: int
    stride: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.relu(x)
        s = self.stride
        h1 = h[:, ::s, ::s, :]
        h1 = _conv(self.out_ch // 2, 1, dtype=self.dtype, name="conv1")(h1)
        # F.pad(h, (-1, 1, -1, 1)): crop first row/col, pad one at the end
        h2 = jnp.pad(h[:, 1:, 1:, :], ((0, 0), (0, 1), (0, 1), (0, 0)))[:, ::s, ::s, :]
        h2 = _conv(self.out_ch // 2, 1, dtype=self.dtype, name="conv2")(h2)
        return BatchNorm(name="bn")(jnp.concatenate([h1, h2], axis=-1), train)


class _ShakeBranchBasic(nn.Module):
    """relu-conv3-BN-relu-conv3-BN branch (reference ``shake_resnet.py:29-36``)."""

    out_ch: int
    stride: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.relu(x)
        h = _conv(self.out_ch, 3, self.stride, dtype=self.dtype, name="conv1")(h)
        h = BatchNorm(name="bn1")(h, train)
        h = nn.relu(h)
        h = _conv(self.out_ch, 3, 1, dtype=self.dtype, name="conv2")(h)
        return BatchNorm(name="bn2")(h, train)


class _ShakeBranchBottleneck(nn.Module):
    """1x1 - grouped 3x3 - 1x1 branch (reference ``shake_resnext.py:29-38``)."""

    mid_ch: int
    out_ch: int
    cardinality: int
    stride: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        h = _conv(self.mid_ch, 1, dtype=self.dtype, name="conv1")(x)
        h = nn.relu(BatchNorm(name="bn1")(h, train))
        h = _conv(self.mid_ch, 3, self.stride, groups=self.cardinality,
                  dtype=self.dtype, name="conv2")(h)
        h = nn.relu(BatchNorm(name="bn2")(h, train))
        h = _conv(self.out_ch, 1, dtype=self.dtype, name="conv3")(h)
        return BatchNorm(name="bn3")(h, train)


class _ShakeMix(nn.Module):
    """Mix two branches with shake-shake noise from the 'shake' RNG stream."""

    @nn.compact
    def __call__(self, h1, h2, train: bool):
        if train:
            key = self.make_rng("shake")
            alpha, beta = sample_shake_shake_noise(key, h1.shape[0], h1.dtype)
            return shake_shake(h1, h2, alpha, beta)
        return shake_shake_eval(h1, h2)


class ShakeResNet(nn.Module):
    """Shake-Shake-26 2x{w_base}d (reference ``shake_resnet.py:39-81``)."""

    depth: int
    w_base: int
    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        n_units = (self.depth - 2) // 6
        chs = (16, self.w_base, self.w_base * 2, self.w_base * 4)
        h = _conv(chs[0], 3, bias=True, dtype=self.dtype, name="c_in")(x)
        for stage in range(3):
            out_ch = chs[stage + 1]
            for i in range(n_units):
                stride = 2 if (stage > 0 and i == 0) else 1
                in_ch = h.shape[-1]
                h1 = _ShakeBranchBasic(out_ch, stride, dtype=self.dtype,
                                       name=f"s{stage}_{i}_branch1")(h, train)
                h2 = _ShakeBranchBasic(out_ch, stride, dtype=self.dtype,
                                       name=f"s{stage}_{i}_branch2")(h, train)
                mixed = _ShakeMix(name=f"s{stage}_{i}_mix")(h1, h2, train)
                if in_ch == out_ch:
                    h0 = h
                else:
                    h0 = Shortcut(out_ch, stride, dtype=self.dtype,
                                  name=f"s{stage}_{i}_shortcut")(h, train)
                h = mixed + h0
        h = nn.relu(h)
        h = global_avg_pool(h).astype(jnp.float32)
        return nn.Dense(self.num_classes, bias_init=nn.initializers.zeros, name="fc_out")(h)


class ShakeResNeXt(nn.Module):
    """Shake-Shake-26 2x{w_base}d ResNeXt, cardinality 4
    (reference ``shake_resnext.py:42-84``)."""

    depth: int
    w_base: int
    cardinality: int
    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        n_units = (self.depth - 2) // 9
        n_chs = (64, 128, 256, 1024)
        h = _conv(n_chs[0], 3, bias=True, dtype=self.dtype, name="c_in")(x)
        for stage in range(3):
            mid_ch = n_chs[stage] * (self.w_base // 64) * self.cardinality
            out_ch = n_chs[stage] * 4
            for i in range(n_units):
                stride = 2 if (stage > 0 and i == 0) else 1
                in_ch = h.shape[-1]
                h1 = _ShakeBranchBottleneck(
                    mid_ch, out_ch, self.cardinality, stride, dtype=self.dtype,
                    name=f"s{stage}_{i}_branch1"
                )(h, train)
                h2 = _ShakeBranchBottleneck(
                    mid_ch, out_ch, self.cardinality, stride, dtype=self.dtype,
                    name=f"s{stage}_{i}_branch2"
                )(h, train)
                mixed = _ShakeMix(name=f"s{stage}_{i}_mix")(h1, h2, train)
                if in_ch == out_ch:
                    h0 = h
                else:
                    h0 = Shortcut(out_ch, stride, dtype=self.dtype,
                                  name=f"s{stage}_{i}_shortcut")(h, train)
                h = mixed + h0
        h = nn.relu(h)
        h = global_avg_pool(h).astype(jnp.float32)
        return nn.Dense(self.num_classes, bias_init=nn.initializers.zeros, name="fc_out")(h)
