"""EfficientNet (b0..b7) with optional CondConv experts, in Flax NHWC.

Capability match for the reference
``networks/efficientnet_pytorch/model.py`` + ``utils.py`` +
``condconv.py``, redesigned for TPU:

- **TF-SAME padding**: the reference carries an entire static/dynamic
  padding subsystem (``utils.py:101-154``) because torch lacks TF
  semantics; XLA convolutions have them natively — every conv here just
  uses ``padding='SAME'``.
- **Swish**: the reference's ``MemoryEfficientSwish`` custom Function
  (``utils.py:38-54``) re-derives silu's VJP to save memory;
  ``jax.nn.silu`` + XLA fusion/remat makes that moot.
- **CondConv** (``condconv.py:86-173``): per-sample expert-mixed
  kernels.  The reference manually folds the batch into channels to run
  one grouped conv; here the per-sample conv is a ``jax.vmap`` over the
  kernel operand, which XLA lowers to a single batched-group
  convolution on the MXU — the same trick, derived by the compiler.
- **Cross-replica BN**: the reference plumbs ``TpuBatchNormalization``
  (``tf_port/tpu_bn.py``) but ships with it disabled; under a jitted
  global-batch step it is the default here.

Architecture parity: block-string codec (``utils.py:186-260``),
width/depth/resolution scaling (``utils.py:160-183``), SE on the
pre-expansion filter count, drop-connect scaled by block index
(``model.py:206-210``, including the reference's non-standard
no-rescale-at-train semantics, ``utils.py:92-99``), BN eps 1e-3 /
torch-momentum 0.01, TF-style init (normal std sqrt(2/fan_out) conv,
uniform +-1/sqrt(fan_out) linear, xavier routing —
``networks/__init__.py:50-77``), CondConv on the last 3 block groups
(``utils.py:275-279``).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, replace
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fast_autoaugment_tpu.models.layers import BatchNorm

__all__ = ["EfficientNet", "efficientnet_params", "BlockArgs", "decode_block_string"]

_BN_MOMENTUM_TORCH = 0.01  # 1 - 0.99 (reference utils.py:282, model.py:37)
_BN_EPS = 1e-3

conv_tf_init = jax.nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_tf_init = jax.nn.initializers.variance_scaling(1.0 / 3.0, "fan_out", "uniform")
routing_init = jax.nn.initializers.xavier_uniform()


def efficientnet_params(model_name: str):
    """(width, depth, resolution, dropout) per variant (``utils.py:160-172``)."""
    params = {
        "efficientnet-b0": (1.0, 1.0, 224, 0.2),
        "efficientnet-b1": (1.0, 1.1, 240, 0.2),
        "efficientnet-b2": (1.1, 1.2, 260, 0.3),
        "efficientnet-b3": (1.2, 1.4, 300, 0.3),
        "efficientnet-b4": (1.4, 1.8, 380, 0.4),
        "efficientnet-b5": (1.6, 2.2, 456, 0.4),
        "efficientnet-b6": (1.8, 2.6, 528, 0.5),
        "efficientnet-b7": (2.0, 3.1, 600, 0.5),
    }
    return params[model_name]


@dataclass(frozen=True)
class BlockArgs:
    kernel_size: int
    num_repeat: int
    input_filters: int
    output_filters: int
    expand_ratio: int
    se_ratio: Optional[float]
    stride: int
    id_skip: bool = True
    condconv_num_expert: int = 0


# the seven block groups of the EfficientNet backbone (utils.py:266-271)
_BLOCK_STRINGS = [
    "r1_k3_s11_e1_i32_o16_se0.25",
    "r2_k3_s22_e6_i16_o24_se0.25",
    "r2_k5_s22_e6_i24_o40_se0.25",
    "r3_k3_s22_e6_i40_o80_se0.25",
    "r3_k5_s11_e6_i80_o112_se0.25",
    "r4_k5_s22_e6_i112_o192_se0.25",
    "r1_k3_s11_e6_i192_o320_se0.25",
]


def decode_block_string(block_string: str) -> BlockArgs:
    """Block-string codec (``utils.py:186-216``), e.g. 'r2_k5_s22_e6_i24_o40_se0.25'."""
    options = {}
    for op in block_string.split("_"):
        splits = re.split(r"(\d.*)", op)
        if len(splits) >= 2:
            options[splits[0]] = splits[1]
    assert len(options["s"]) in (1, 2)
    return BlockArgs(
        kernel_size=int(options["k"]),
        num_repeat=int(options["r"]),
        input_filters=int(options["i"]),
        output_filters=int(options["o"]),
        expand_ratio=int(options["e"]),
        se_ratio=float(options["se"]) if "se" in options else None,
        stride=int(options["s"][0]),
        id_skip="noskip" not in block_string,
    )


def round_filters(filters: int, width_coefficient: float, divisor: int = 8) -> int:
    """Width scaling with 8-divisibility (``utils.py:55-67``)."""
    if not width_coefficient:
        return filters
    filters *= width_coefficient
    new_filters = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new_filters < 0.9 * filters:
        new_filters += divisor
    return int(new_filters)


def round_repeats(repeats: int, depth_coefficient: float) -> int:
    if not depth_coefficient:
        return repeats
    return int(math.ceil(depth_coefficient * repeats))


def expand_blocks(blocks_args, width_coefficient: float,
                  depth_coefficient: float) -> list[BlockArgs]:
    """Apply width/depth scaling and unroll repeats into a flat
    per-block list (``model.py:166-180``); shared by the module and the
    checkpoint importer."""
    expanded: list[BlockArgs] = []
    for args in blocks_args:
        args = replace(
            args,
            input_filters=round_filters(args.input_filters, width_coefficient),
            output_filters=round_filters(args.output_filters, width_coefficient),
            num_repeat=round_repeats(args.num_repeat, depth_coefficient),
        )
        expanded.append(args)
        for _ in range(args.num_repeat - 1):
            expanded.append(replace(args, input_filters=args.output_filters, stride=1))
    return expanded


def drop_connect(x, key, drop_p: float, train: bool):
    """Reference semantics (``utils.py:92-99``): train -> per-sample
    Bernoulli(1-p) WITHOUT rescaling; eval -> scale by (1-p).  (The
    rescaled variant exists only as commented-out code there.)"""
    if not train:
        return x * (1.0 - drop_p)
    keep = jax.random.bernoulli(key, 1.0 - drop_p, (x.shape[0], 1, 1, 1))
    return x * keep.astype(x.dtype)


def _conv_same(features, kernel, stride=1, groups=1, bias=False, dtype=None,
               name=None):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding="SAME",
        feature_group_count=groups,
        use_bias=bias,
        kernel_init=conv_tf_init,
        bias_init=nn.initializers.zeros,
        dtype=dtype,
        name=name,
    )


class CondConv(nn.Module):
    """Conditionally-parameterized convolution (``condconv.py:86-173``).

    Holds `num_experts` kernels; each sample's kernel is the routing-
    weighted mixture.  The per-sample convolution is vmapped over the
    kernel operand — XLA lowers this to one grouped convolution, which
    is the hand-written batch-folding trick of the reference
    (``condconv.py:145-167``) done by the compiler.
    """

    features: int
    kernel_size: int
    num_experts: int
    stride: int = 1
    depthwise: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, routing_weights):
        x = x.astype(self.dtype)
        routing_weights = routing_weights.astype(self.dtype)
        in_ch = x.shape[-1]
        groups = in_ch if self.depthwise else 1
        kshape = (self.kernel_size, self.kernel_size, in_ch // groups, self.features)
        # the reference's CondConv uses torch-style SYMMETRIC padding
        # ((s-1)+(k-1))//2 (condconv.py:30-33, default padding=''), NOT
        # TF SAME like its other convs — match it for checkpoint parity
        pad = ((self.stride - 1) + (self.kernel_size - 1)) // 2
        padding = [(pad, pad), (pad, pad)]
        def init_experts(key, _shape):
            # each expert initialized independently (condconv.py:129-139)
            return jnp.stack(
                [conv_tf_init(k, kshape, jnp.float32)
                 for k in jax.random.split(key, self.num_experts)]
            )

        experts = self.param("experts", init_experts, (self.num_experts,) + kshape)
        # per-sample kernels: [B, kh, kw, cin/g, cout]
        kernels = jnp.einsum("be,ehwio->bhwio", routing_weights,
                             experts.astype(self.dtype))

        def conv_one(xi, ki):
            return jax.lax.conv_general_dilated(
                xi[None],
                ki,
                window_strides=(self.stride, self.stride),
                padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=groups,
            )[0]

        return jax.vmap(conv_one)(x, kernels)


class MBConvBlock(nn.Module):
    """Mobile inverted bottleneck with SE (``model.py:22-123``)."""

    args: BlockArgs
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool, drop_connect_rate: float = 0.0):
        a = self.args
        inputs = x
        expanded = a.input_filters * a.expand_ratio
        is_condconv = a.condconv_num_expert > 1

        if is_condconv:
            # routing: sigmoid(Linear(GAP(x))) (model.py:89-96)
            feat = x.mean(axis=(1, 2))
            routing = nn.sigmoid(
                nn.Dense(
                    a.condconv_num_expert,
                    kernel_init=routing_init,
                    bias_init=nn.initializers.zeros,
                    name="routing_fn",
                )(feat)
            )

            def conv(features, kernel, stride=1, depthwise=False, name=None):
                return lambda h: CondConv(
                    features, kernel, a.condconv_num_expert, stride, depthwise,
                    dtype=self.dtype, name=name
                )(h, routing)
        else:
            def conv(features, kernel, stride=1, depthwise=False, name=None):
                return _conv_same(
                    features, kernel, stride,
                    groups=expanded if depthwise else 1, dtype=self.dtype, name=name,
                )

        if a.expand_ratio != 1:
            x = conv(expanded, 1, name="expand_conv")(x)
            x = BatchNorm(momentum=_BN_MOMENTUM_TORCH, epsilon=_BN_EPS, name="bn0")(x, train)
            x = nn.silu(x)

        x = conv(expanded, a.kernel_size, a.stride, depthwise=True, name="depthwise_conv")(x)
        x = BatchNorm(momentum=_BN_MOMENTUM_TORCH, epsilon=_BN_EPS, name="bn1")(x, train)
        x = nn.silu(x)

        if a.se_ratio is not None and 0 < a.se_ratio <= 1:
            squeezed = max(1, int(a.input_filters * a.se_ratio))
            se = x.mean(axis=(1, 2), keepdims=True)
            se = _conv_same(squeezed, 1, bias=True, dtype=self.dtype,
                            name="se_reduce")(se)
            se = nn.silu(se)
            se = _conv_same(expanded, 1, bias=True, dtype=self.dtype,
                            name="se_expand")(se)
            x = nn.sigmoid(se) * x

        x = conv(a.output_filters, 1, name="project_conv")(x)
        x = BatchNorm(momentum=_BN_MOMENTUM_TORCH, epsilon=_BN_EPS, name="bn2")(x, train)

        if a.id_skip and a.stride == 1 and a.input_filters == a.output_filters:
            if drop_connect_rate and train:
                x = drop_connect(x, self.make_rng("shake"), drop_connect_rate, train)
            elif drop_connect_rate:
                x = drop_connect(x, None, drop_connect_rate, train)
            x = x + inputs
        return x


class EfficientNet(nn.Module):
    """EfficientNet backbone + head (``model.py:130-257``)."""

    blocks_args: Sequence[BlockArgs]
    width_coefficient: float
    depth_coefficient: float
    dropout_rate: float
    num_classes: int
    drop_connect_rate: float = 0.2
    dtype: Any = jnp.float32

    @classmethod
    def from_name(cls, model_name: str, num_classes: int = 1000,
                  condconv_num_expert: int = 0, dtype=jnp.float32) -> "EfficientNet":
        width, depth, _res, dropout = efficientnet_params(model_name)
        blocks = [decode_block_string(s) for s in _BLOCK_STRINGS]
        if condconv_num_expert > 1:
            # CondConv on the last 3 block groups (utils.py:275-279)
            blocks = blocks[:-3] + [
                replace(b, condconv_num_expert=condconv_num_expert) for b in blocks[-3:]
            ]
        return cls(
            blocks_args=tuple(blocks),
            width_coefficient=width,
            depth_coefficient=depth,
            dropout_rate=dropout,
            num_classes=num_classes,
            dtype=dtype,
        )

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.width_coefficient
        x = x.astype(self.dtype)
        x = _conv_same(round_filters(32, w), 3, 2, dtype=self.dtype,
                       name="conv_stem")(x)
        x = BatchNorm(momentum=_BN_MOMENTUM_TORCH, epsilon=_BN_EPS, name="bn0")(x, train)
        x = nn.silu(x)

        expanded = expand_blocks(self.blocks_args, w, self.depth_coefficient)
        total = len(expanded)
        for idx, args in enumerate(expanded):
            rate = self.drop_connect_rate * float(idx) / total
            x = MBConvBlock(args, dtype=self.dtype,
                            name=f"block{idx}")(x, train, drop_connect_rate=rate)

        x = _conv_same(round_filters(1280, w), 1, dtype=self.dtype,
                       name="conv_head")(x)
        x = BatchNorm(momentum=_BN_MOMENTUM_TORCH, epsilon=_BN_EPS, name="bn1")(x, train)
        x = nn.silu(x)
        x = x.mean(axis=(1, 2)).astype(jnp.float32)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(
            self.num_classes,
            kernel_init=dense_tf_init,
            bias_init=nn.initializers.zeros,
            name="fc",
        )(x)
