"""Composable game-day scenario specs (docs/GAMEDAYS.md).

A :class:`Scenario` composes four orthogonal axes:

- a :class:`Traffic` shape — the OFFERED load schedule (open loop: the
  schedule never slows down because the plane did; that is the point);
- a :class:`Plane` topology — replicas / router / autoscaler /
  controller, plus the shedding knobs a broken-config drill disables;
- fault verbs — ``FAA_FAULT`` (process faults, armed in the replicas)
  and ``FAA_FSFAULT`` (shared-FS faults, armed in the router) strings,
  plus an optional :class:`Kill` (SIGKILL a live replica mid-scenario);
- verdict predicates — ``(name, params)`` pairs resolved against
  ``gameday/verdict.py``'s catalog at evaluation time.

Everything here is a frozen dataclass and host-only (no jax, no
subprocess): specs must be constructible and hashable from a unit test
or ``faa_status`` without touching an accelerator.  The runner
(``gameday/runner.py``) is the only layer that turns a spec into
processes.

``expect`` records what the verdict engine is SUPPOSED to say:
``"pass"`` for the real plane, ``"fail"`` for deliberately broken
configurations kept in the suite to prove the engine has teeth (a
verdict harness that cannot fail is not a harness).
"""

from __future__ import annotations

import dataclasses
import math

__all__ = ["Traffic", "Plane", "Kill", "Scenario", "SCENARIOS",
           "scaled", "suite_names"]


@dataclasses.dataclass(frozen=True)
class Traffic:
    """Offered-load shape.

    ``kind``:

    - ``constant`` — ``base_rps`` for ``duration_s``;
    - ``flash`` — ``base_rps``, then a ramp to ``peak_rps`` over
      ``ramp_s`` starting at ``flash_at_frac * duration_s``, held to
      the end (the 10x-in-seconds flash crowd);
    - ``diurnal`` — a raised-cosine swing between ``base_rps`` and
      ``peak_rps`` with period ``period_s`` (a compressed day).

    ``tenants`` > 1 rotates the cohort mix: the active cohort advances
    every ``rotate_s`` seconds and gets ~80% of the traffic, the rest
    spread uniformly (the LRU-thrash shape).  ``lanes`` weights the
    raw / npz / shm wire-lane mix.
    """

    kind: str = "constant"
    duration_s: float = 20.0
    base_rps: float = 10.0
    peak_rps: float = 100.0
    flash_at_frac: float = 0.4
    ramp_s: float = 2.0
    period_s: float = 10.0
    imgs_per_request: int = 4
    lanes: tuple = (("raw", 0.6), ("npz", 0.2), ("shm", 0.2))
    tenants: int = 1
    rotate_s: float = 5.0

    def rate_at(self, t: float) -> float:
        """Offered requests/second at offset ``t`` (deterministic)."""
        if t < 0 or t >= self.duration_s:
            return 0.0
        if self.kind == "constant":
            return self.base_rps
        if self.kind == "flash":
            t0 = self.flash_at_frac * self.duration_s
            if t < t0:
                return self.base_rps
            frac = min(1.0, (t - t0) / max(self.ramp_s, 1e-9))
            return self.base_rps + frac * (self.peak_rps - self.base_rps)
        if self.kind == "diurnal":
            mid = 0.5 * (self.base_rps + self.peak_rps)
            amp = 0.5 * (self.peak_rps - self.base_rps)
            return mid - amp * math.cos(2 * math.pi * t / self.period_s)
        raise ValueError(f"unknown traffic kind: {self.kind!r}")

    @property
    def peak_rate(self) -> float:
        if self.kind == "constant":
            return self.base_rps
        return max(self.base_rps, self.peak_rps)


@dataclasses.dataclass(frozen=True)
class Plane:
    """Topology + the serve-side robustness knobs.

    ``shedding=False`` is the deliberately-broken configuration: the
    replica queue becomes effectively unbounded and deadlines are
    dropped, so overload turns into hang instead of fast rejection —
    the configuration the ``shed_not_hang`` predicate must catch.
    """

    replicas: int = 2
    router: bool = True
    autoscaler: bool = False
    min_replicas: int = 1
    max_replicas: int = 3
    controller: bool = False
    shedding: bool = True
    queue_depth: int = 16
    deadline_ms: float = 2000.0
    tenant_capacity: int = 0
    policies: int = 1
    shm_ingest: bool = True
    image: int = 8
    shapes: str = "1,4,8"
    max_wait_ms: float = 2.0
    # per-dispatch service-time floor (serve_cli --dispatch-floor-ms):
    # emulates a heavy model so flash-crowd scenarios reach REAL
    # overload on a 1-core CI host deterministically.  Capacity per
    # replica ~= (max AOT shape / imgs_per_request) / floor req/s.
    dispatch_floor_ms: float = 0.0
    # autoscaler watermarks (only read when autoscaler=True)
    high_queue: float = 3.0
    high_shed_rate: float = 2.0
    up_polls: int = 2
    down_polls: int = 8
    cooldown_s: float = 4.0
    poll_interval_s: float = 0.3


@dataclasses.dataclass(frozen=True)
class Kill:
    """SIGKILL a live replica mid-scenario.

    ``target`` is a replica tag (``replica0``) or ``"canary"`` — the
    replica named by the first journaled ``canary`` rollout event (the
    armed-split victim).  The kill fires ``delay_s`` after the trigger:
    the named journal event when ``after_event`` is set, else
    ``at_frac`` of the traffic duration.
    """

    target: str = "replica0"
    after_event: str = ""
    after_action: str = ""
    at_frac: float = 0.5
    delay_s: float = 0.5


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    summary: str
    traffic: Traffic
    plane: Plane
    predicates: tuple = ()
    faults: str = ""
    fsfaults: str = ""
    kill: Kill | None = None
    expect: str = "pass"
    seed: int = 20
    # post-traffic settle: how long the runner keeps the plane up after
    # the last offered request (control-plane decisions land here)
    settle_s: float = 2.0
    # when a controller runs, wait (bounded) for its terminal decision
    decision_timeout_s: float = 90.0


def scaled(scn: Scenario, factor: float) -> Scenario:
    """A time/load-shrunk copy for smoke runs: durations and offered
    rates scale by ``factor`` (< 1), topology / faults / predicates
    that are rate-independent stay put.  Goodput-style predicates are
    ratios, so they survive the shrink unchanged."""
    t = scn.traffic
    traffic = dataclasses.replace(
        t, duration_s=max(3.0, t.duration_s * factor),
        base_rps=max(2.0, t.base_rps * factor),
        peak_rps=max(4.0, t.peak_rps * factor),
        rotate_s=max(1.0, t.rotate_s * factor))
    plane = scn.plane
    if plane.dispatch_floor_ms > 0:
        # offered rates shrank by `factor`, so capacity must shrink
        # with them (floor grows by 1/factor) or the overload the
        # scenario exists to drill never materializes in smoke runs
        plane = dataclasses.replace(
            plane, dispatch_floor_ms=min(
                400.0, plane.dispatch_floor_ms / max(factor, 1e-9)))
    return dataclasses.replace(scn, traffic=traffic, plane=plane,
                               settle_s=min(scn.settle_s, 2.0))


# --------------------------------------------------------------------------
# the named game days (ISSUE 20 / ROADMAP "Million-user scenario
# harness").  Offered rates are sized for the 1-core CI host: the
# client, every replica, the router and the controller all time-slice
# one core, so a "10x flash" here drills the CONTROL structure
# (shed/scale/failover decisions), not datacenter throughput.
# --------------------------------------------------------------------------

_COMMON_SAFETY = (
    ("no_shm_leak", {}),
)

SCENARIOS: dict[str, Scenario] = {}


def _register(scn: Scenario) -> Scenario:
    SCENARIOS[scn.name] = scn
    return scn


_register(Scenario(
    name="flash-crowd-10x",
    summary="10x offered-load ramp in seconds against an autoscaled "
            "fleet: shedding keeps answers fast while the autoscaler "
            "grows the fleet with journaled evidence",
    traffic=Traffic(kind="flash", duration_s=30.0, base_rps=8.0,
                    peak_rps=80.0, flash_at_frac=0.4, ramp_s=2.0,
                    imgs_per_request=4),
    plane=Plane(replicas=1, router=True, autoscaler=True,
                min_replicas=1, max_replicas=3, shedding=True,
                dispatch_floor_ms=50.0),
    predicates=(
        ("goodput_floor", {"floor": 0.30}),
        ("shed_not_hang", {"max_hung": 0}),
        ("autoscaler_bounds", {"min_replicas": 1, "max_replicas": 3,
                               "require_scale_up": True}),
    ) + _COMMON_SAFETY,
))

_register(Scenario(
    name="cohort-rotation-lru-thrash",
    summary="cohort mix rotating across more tenant policy digests "
            "than the residency LRU holds: cold digests shed as "
            "structured 503s, background warms land, every cohort is "
            "eventually served",
    traffic=Traffic(kind="constant", duration_s=50.0, base_rps=10.0,
                    imgs_per_request=4, tenants=3, rotate_s=6.0,
                    lanes=(("raw", 0.7), ("npz", 0.3))),
    # sized for the 1-core host: a background tenant warm AOT-compiles
    # the policy (per-policy HLO), so the scenario keeps the compile
    # bill bounded — ONE replica, ONE padded shape (every request is
    # exactly imgs_per_request images), and an LRU of ONE beside the
    # pinned default tenant.  The two cold digests then evict each
    # other on every rotation (real churn), while a re-warm after
    # eviction is a compile-cache HIT, so the thrash costs seconds,
    # not a fresh XLA compile per admit.
    plane=Plane(replicas=1, router=True, tenant_capacity=1,
                policies=3, shedding=True, shapes="4"),
    predicates=(
        ("goodput_floor", {"floor": 0.25}),
        ("shed_not_hang", {"max_hung": 0}),
        ("tenant_churn", {"min_admits": 3, "min_evicts": 1}),
        ("all_cohorts_served", {}),
    ) + _COMMON_SAFETY,
))

_register(Scenario(
    name="replica-loss-mid-canary",
    summary="SIGKILL the canary replica during an armed split: the "
            "router ejects it, failover keeps clients whole, and the "
            "crash-resumable control loop still reaches a terminal "
            "promote/rollback in order",
    traffic=Traffic(kind="constant", duration_s=35.0, base_rps=10.0,
                    imgs_per_request=4,
                    lanes=(("raw", 0.7), ("npz", 0.3))),
    plane=Plane(replicas=3, router=True, controller=True,
                shedding=True),
    faults="drift@dispatch=30,shift=60",
    kill=Kill(target="canary", after_event="canary",
              after_action="rollout", delay_s=1.0),
    predicates=(
        ("goodput_floor", {"floor": 0.50}),
        ("max_transport_errors", {"max_errors": 0}),
        ("control_decision", {"require_terminal": True}),
        ("rotation_ejected", {}),
    ) + _COMMON_SAFETY,
))

_register(Scenario(
    name="drift-during-flash-crowd",
    summary="distribution drift arriving inside a flash crowd: the "
            "control loop must detect, canary and decide while the "
            "plane sheds overload",
    traffic=Traffic(kind="flash", duration_s=35.0, base_rps=8.0,
                    peak_rps=48.0, flash_at_frac=0.3, ramp_s=2.0,
                    imgs_per_request=4,
                    lanes=(("raw", 0.7), ("npz", 0.3))),
    plane=Plane(replicas=2, router=True, controller=True,
                shedding=True),
    faults="drift@dispatch=40,shift=60",
    predicates=(
        ("goodput_floor", {"floor": 0.30}),
        ("shed_not_hang", {"max_hung": 0}),
        ("control_decision", {"require_terminal": True}),
    ) + _COMMON_SAFETY,
))

_register(Scenario(
    name="stale-fs-under-load",
    summary="shared-FS lag + seeded transient read errors under the "
            "router's replica discovery while live traffic flows: "
            "hysteresis rides through the flaps, goodput holds",
    traffic=Traffic(kind="diurnal", duration_s=30.0, base_rps=6.0,
                    peak_rps=18.0, period_s=10.0, imgs_per_request=4),
    plane=Plane(replicas=2, router=True, shedding=True),
    fsfaults="lag@dir=replicas,secs=0.4;eio@p=0.05,seed=7",
    predicates=(
        ("goodput_floor", {"floor": 0.80}),
        ("max_transport_errors", {"max_errors": 0}),
        ("fsfault_observed", {"min_injections": 1}),
    ) + _COMMON_SAFETY,
))

# the teeth-proof: the same flash crowd against a replica whose
# shedding is disabled (quasi-unbounded queue, no deadlines, no
# autoscaler rescue).  Overload turns into hang; the verdict engine
# MUST fail it — expect="fail" keeps it in the suite as a standing
# demonstration that the predicates can reject a broken plane.
_register(Scenario(
    name="flash-crowd-10x-noshed",
    summary="BROKEN CONFIG (expected FAIL): the flash crowd against a "
            "single replica with shedding disabled — overload hangs "
            "clients instead of shedding, and the verdict engine "
            "must say so",
    traffic=Traffic(kind="flash", duration_s=24.0, base_rps=8.0,
                    peak_rps=80.0, flash_at_frac=0.3, ramp_s=2.0,
                    imgs_per_request=4),
    # heavier floor than the healthy flash scenario: with no shedding,
    # no deadline and no autoscaler rescue the queue wait must blow
    # PAST the client's socket timeout (not hover under it) so the
    # hang is unambiguous in both full and smoke runs
    plane=Plane(replicas=1, router=True, autoscaler=False,
                shedding=False, dispatch_floor_ms=80.0),
    predicates=(
        ("goodput_floor", {"floor": 0.30}),
        ("shed_not_hang", {"max_hung": 0}),
    ) + _COMMON_SAFETY,
    expect="fail",
))


def suite_names() -> list[str]:
    """The full suite, broken-config demonstrations included, in a
    stable order."""
    return list(SCENARIOS)
