"""Journal-driven pass/fail verdicts for game-day scenarios.

Every predicate is a pure function over an **evidence** dict — the
workload replay report, the run's telemetry journal records, and the
router's scraped stats — and returns a :class:`VerdictRow` with the
observed values INLINE so a failing verdict is self-explaining.
Nothing here talks to a live process: verdicts are recomputable after
the fact from the journal dir alone (the same files ``make status``
and ``make trace`` read), which is what makes them evidence rather
than vibes.

Evidence keys (the runner assembles them; synthetic dicts work too —
the unit tests exercise every predicate without a plane):

- ``report`` — ``WorkloadReport.to_dict()`` (client-side truth);
- ``journal`` — list of telemetry journal records (dicts) from the
  scenario's run dir, time-ordered;
- ``router_stats`` — the router's ``/stats`` JSON, or None;
- ``killed`` — the SIGKILLed replica tag, or None;
- ``tenants`` — cohort digest count offered by the workload.

The catalog (``PREDICATES``) is the extension point documented in
docs/GAMEDAYS.md: a new game day composes existing predicates or
registers a new name here.
"""

from __future__ import annotations

import dataclasses

__all__ = ["VerdictRow", "PREDICATES", "evaluate", "render_table"]


@dataclasses.dataclass
class VerdictRow:
    predicate: str
    ok: bool
    observed: dict
    bound: dict
    detail: str = ""

    def to_dict(self) -> dict:
        return {"predicate": self.predicate, "ok": self.ok,
                "observed": self.observed, "bound": self.bound,
                "detail": self.detail}


def _events(evidence: dict, etype: str, **match) -> list[dict]:
    out = []
    for rec in evidence.get("journal") or []:
        if rec.get("type") != etype:
            continue
        if all(rec.get(k) == v for k, v in match.items()):
            out.append(rec)
    out.sort(key=lambda r: r.get("t_wall") or 0)
    return out


# ------------------------------------------------------------ catalog


def goodput_floor(evidence: dict, *, floor: float) -> VerdictRow:
    """Served-OK over offered stays at or above ``floor`` — the
    load-shaped SLO.  Offered is the SCHEDULE's count (open loop), so
    a hung plane cannot pass by suppressing its own denominator."""
    rep = evidence["report"]
    goodput = (rep["ok"] / rep["offered"]) if rep["offered"] else 0.0
    return VerdictRow(
        "goodput_floor", goodput >= floor,
        {"goodput": round(goodput, 4), "ok": rep["ok"],
         "offered": rep["offered"]},
        {"floor": floor})


def shed_not_hang(evidence: dict, *, max_hung: int = 0,
                  p99_ms_ok: float | None = None) -> VerdictRow:
    """Overload must answer FAST NOs, never silence: every non-served
    request is an explicit structured rejection; transport errors and
    client timeouts (= hangs) stay within ``max_hung``; optionally the
    admitted p99 stays under ``p99_ms_ok``."""
    rep = evidence["report"]
    hung = rep["transport_errors"]
    unexpected = rep["unexpected_status"]
    ok = hung <= max_hung and unexpected == 0
    observed = {"hung": hung, "unexpected_status": unexpected,
                "shed": rep["shed"], "p99_ms_ok": rep.get("p99_ms_ok")}
    bound = {"max_hung": max_hung}
    if p99_ms_ok is not None:
        bound["p99_ms_ok"] = p99_ms_ok
        ok = ok and (rep.get("p99_ms_ok") or 0.0) <= p99_ms_ok
    return VerdictRow("shed_not_hang", ok, observed, bound,
                      detail="" if ok else
                      "requests hung or died instead of shedding")


def max_transport_errors(evidence: dict, *,
                         max_errors: int = 0) -> VerdictRow:
    """Zero dropped in-flight: every offered request got an HTTP
    answer (200 or a structured rejection) — failover and graceful
    drain must hide replica churn from clients."""
    rep = evidence["report"]
    n = rep["transport_errors"]
    return VerdictRow(
        "max_transport_errors", n <= max_errors,
        {"transport_errors": n,
         "errors_sample": rep.get("errors_sample") or []},
        {"max_errors": max_errors})


def affinity_floor(evidence: dict, *, floor: float) -> VerdictRow:
    """Router digest-affinity hit rate stays at or above ``floor``
    (from the router's own ``/stats`` accounting)."""
    stats = evidence.get("router_stats") or {}
    aff = (stats.get("affinity") or {})
    rate = aff.get("hit_rate")
    ok = rate is not None and rate >= floor
    return VerdictRow("affinity_floor", ok,
                      {"hit_rate": rate, "hits": aff.get("hits"),
                       "misses": aff.get("misses")},
                      {"floor": floor})


def autoscaler_bounds(evidence: dict, *, min_replicas: int,
                      max_replicas: int,
                      require_scale_up: bool = False,
                      max_actions: int = 8) -> VerdictRow:
    """Every journaled scale decision lands inside the configured
    fleet bounds, the loop does not flap past ``max_actions``, and —
    for flash scenarios — at least one ``scale_up`` actually fired."""
    ups = _events(evidence, "scale_up")
    downs = _events(evidence, "scale_down")
    after = [e.get("replicas_after") for e in ups + downs
             if e.get("replicas_after") is not None]
    in_bounds = all(min_replicas <= int(n) <= max_replicas
                    for n in after)
    ok = in_bounds and len(ups) + len(downs) <= max_actions
    if require_scale_up:
        ok = ok and len(ups) >= 1
    return VerdictRow(
        "autoscaler_bounds", ok,
        {"scale_ups": len(ups), "scale_downs": len(downs),
         "replicas_after": after},
        {"min_replicas": min_replicas, "max_replicas": max_replicas,
         "require_scale_up": require_scale_up,
         "max_actions": max_actions})


def control_decision(evidence: dict, *,
                     require_terminal: bool = True) -> VerdictRow:
    """The control loop's causal order holds: drift detected before
    the canary rollout, rollout before the terminal promote/rollback,
    and (when required) a terminal decision exists at all."""
    drifts = _events(evidence, "drift")
    rollouts = _events(evidence, "canary", action="rollout")
    terminals = (_events(evidence, "promote")
                 + _events(evidence, "rollback"))
    terminals.sort(key=lambda r: r.get("t_wall") or 0)
    ordered = True
    if drifts and rollouts:
        ordered &= drifts[0]["t_wall"] <= rollouts[0]["t_wall"]
    if rollouts and terminals:
        ordered &= rollouts[0]["t_wall"] <= terminals[-1]["t_wall"]
    ok = ordered and bool(drifts)
    if require_terminal:
        ok = ok and bool(terminals) and bool(rollouts)
    decision = terminals[-1]["type"] if terminals else None
    return VerdictRow(
        "control_decision", ok,
        {"drifts": len(drifts), "rollouts": len(rollouts),
         "decision": decision, "ordered": ordered},
        {"require_terminal": require_terminal},
        detail="" if ok else "missing or out-of-order control events")


def rotation_ejected(evidence: dict, *, tag: str | None = None
                     ) -> VerdictRow:
    """The router journaled an eject for the killed replica (the
    membership evidence must not vanish with the process)."""
    tag = tag or evidence.get("killed")
    ejects = [e for e in _events(evidence, "rotation")
              if e.get("action") == "eject"
              and (tag is None or str(e.get("replica")) == str(tag))]
    return VerdictRow("rotation_ejected", bool(ejects),
                      {"ejects": len(ejects), "replica": tag},
                      {"min_ejects": 1})


def tenant_churn(evidence: dict, *, min_admits: int,
                 min_evicts: int) -> VerdictRow:
    """The residency LRU actually thrashed: cohort rotation produced
    at least ``min_admits`` tenant admits and ``min_evicts`` evicts."""
    admits = [e for e in _events(evidence, "tenant")
              if e.get("action") == "admit"]
    evicts = [e for e in _events(evidence, "tenant")
              if e.get("action") == "evict"]
    ok = len(admits) >= min_admits and len(evicts) >= min_evicts
    return VerdictRow("tenant_churn", ok,
                      {"admits": len(admits), "evicts": len(evicts)},
                      {"min_admits": min_admits,
                       "min_evicts": min_evicts})


def all_cohorts_served(evidence: dict, *, min_ok: int = 1) -> VerdictRow:
    """Every offered cohort eventually got served — cold-tenant 503s
    are allowed (they are sheds), starvation of a whole cohort is
    not."""
    rep = evidence["report"]
    tenants = int(evidence.get("tenants") or 1)
    by_tenant = rep.get("ok_by_tenant") or {}
    starved = [t for t in range(tenants)
               if by_tenant.get(str(t), 0) < min_ok]
    return VerdictRow("all_cohorts_served", not starved,
                      {"ok_by_tenant": by_tenant, "starved": starved},
                      {"tenants": tenants, "min_ok": min_ok})


def fsfault_observed(evidence: dict, *,
                     min_injections: int = 1) -> VerdictRow:
    """The FSFAULT seam actually injected (proof the scenario drilled
    what it claims: surviving faults that never fired proves
    nothing)."""
    n = len(_events(evidence, "fsfault"))
    return VerdictRow("fsfault_observed", n >= min_injections,
                      {"injections": n},
                      {"min_injections": min_injections})


def no_shm_leak(evidence: dict) -> VerdictRow:
    """Every shm region the workload created is gone from /dev/shm by
    scenario end — a flash crowd must not leak segments."""
    rep = evidence["report"]
    leftover = rep.get("shm_leftover") or []
    return VerdictRow("no_shm_leak", not leftover,
                      {"created": rep.get("shm_created", 0),
                       "leftover": leftover},
                      {"max_leftover": 0})


PREDICATES = {
    "goodput_floor": goodput_floor,
    "shed_not_hang": shed_not_hang,
    "max_transport_errors": max_transport_errors,
    "affinity_floor": affinity_floor,
    "autoscaler_bounds": autoscaler_bounds,
    "control_decision": control_decision,
    "rotation_ejected": rotation_ejected,
    "tenant_churn": tenant_churn,
    "all_cohorts_served": all_cohorts_served,
    "fsfault_observed": fsfault_observed,
    "no_shm_leak": no_shm_leak,
}


def evaluate(scenario, evidence: dict, *,
             schedule_digest: str | None = None) -> dict:
    """All of one scenario's predicates over one run's evidence.

    Returns the verdict record: per-predicate rows, the scenario-level
    ``pass`` (every predicate ok), and ``ok_as_expected`` — whether
    the verdict matches the spec's ``expect`` (a broken-config
    scenario is SUPPOSED to fail; the suite is green only when every
    verdict matches its expectation)."""
    rows = []
    for name, params in scenario.predicates:
        fn = PREDICATES.get(name)
        if fn is None:
            rows.append(VerdictRow(name, False, {},
                                   {"error": "unknown predicate"}))
            continue
        try:
            rows.append(fn(evidence, **params))
        except (KeyError, TypeError, ValueError) as e:
            rows.append(VerdictRow(
                name, False, {"error": f"{type(e).__name__}: {e}"},
                dict(params), detail="predicate crashed"))
    passed = all(r.ok for r in rows)
    return {
        "scenario": scenario.name,
        "seed": scenario.seed,
        "schedule_digest": schedule_digest,
        "predicates": [r.to_dict() for r in rows],
        "pass": passed,
        "expect": scenario.expect,
        "ok_as_expected": passed == (scenario.expect == "pass"),
        "report": evidence.get("report"),
    }


def render_table(records: list[dict]) -> str:
    """The human verdict table (one line per scenario + per-predicate
    detail lines for anything that failed unexpectedly)."""
    rows = [["scenario", "verdict", "expected", "goodput", "hung",
             "digest"]]
    for rec in records:
        rep = rec.get("report") or {}
        verdict = "PASS" if rec["pass"] else "FAIL"
        if rec["expect"] == "fail":
            verdict += " (expected-fail)" if not rec["pass"] \
                else " (!! expected FAIL)"
        elif not rec["pass"]:
            verdict += " (!!)"
        rows.append([
            rec["scenario"], verdict, rec["expect"],
            str(rep.get("goodput")), str(rep.get("transport_errors")),
            str(rec.get("schedule_digest"))])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    for rec in records:
        for row in rec["predicates"]:
            mark = "ok" if row["ok"] else "FAIL"
            if not row["ok"] or rec["expect"] == "fail":
                lines.append(
                    f"  {rec['scenario']} :: {row['predicate']}: "
                    f"{mark}  observed={row['observed']} "
                    f"bound={row['bound']}")
    suite_ok = all(r["ok_as_expected"] for r in records)
    lines.append(f"suite: {'GREEN' if suite_ok else 'RED'} "
                 f"({sum(1 for r in records if r['ok_as_expected'])}"
                 f"/{len(records)} verdicts as expected)")
    return "\n".join(lines)
