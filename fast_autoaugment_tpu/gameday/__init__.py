"""Trace-driven game days: deterministic scenario drills with
journaled verdicts (docs/GAMEDAYS.md).

- ``scenario``: composable frozen specs + the named-scenario registry;
- ``workload``: the seeded open-loop schedule and its replay driver;
- ``runner``: spec -> live plane -> workload -> verdict record;
- ``verdict``: the journal-driven predicate catalog.

``make gameday`` / ``make gameday-smoke`` front it via
``launch/gameday_cli.py``.
"""

from .scenario import (Kill, Plane, SCENARIOS, Scenario, Traffic,
                       scaled, suite_names)
from .verdict import PREDICATES, evaluate, render_table
from .workload import Offered, build_schedule, schedule_digest

__all__ = [
    "Kill", "Plane", "SCENARIOS", "Scenario", "Traffic", "scaled",
    "suite_names", "PREDICATES", "evaluate", "render_table",
    "Offered", "build_schedule", "schedule_digest",
]
