"""The game-day runner: spec -> live plane -> workload -> verdicts.

One :func:`run_scenario` call owns a full drill lifecycle:

1. **bring-up** — spawn the plane a :class:`~.scenario.Plane` describes
   (serve_cli replicas directly, or autoscaler-owned; router_cli as the
   front door; control_cli in drill mode) into a throwaway workdir,
   every process journaling into ONE telemetry dir;
2. **traffic** — replay the deterministic ``(scenario, seed)`` schedule
   (``gameday/workload.py``) through the router, journaling rolling
   ``scenario`` progress events; an armed :class:`~.scenario.Kill`
   watches the journal and SIGKILLs its victim on cue; when a
   controller is running, a low-rate sustain trickle keeps traffic
   flowing (deterministically seeded chunks) until the terminal
   promote/rollback lands — a quality gate cannot measure a canary
   nobody is sending requests through;
3. **teardown** — SIGTERM newest-first with a shared deadline, SIGKILL
   stragglers, collect exit codes;
4. **verdict** — assemble the evidence (client report + journal +
   router ``/stats`` scrape), run ``gameday/verdict.py``, and journal
   one ``verdict`` event per predicate plus the ``scenario`` end mark —
   so ``make status`` and ``make trace`` can replay the whole drill
   from the journal alone.

:func:`run_suite` runs a list of named scenarios back to back sharing
one AOT compile cache (the first scenario pays the warm; the rest ride
it) and renders the verdict table docs/BENCHMARKS.md pins.

This module owns every filesystem touch of the game-day stack — the
``launch/gameday_cli.py`` front end stays FS-free (faalint F1).
"""

from __future__ import annotations

import dataclasses
import glob
import http.client
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

from fast_autoaugment_tpu.core.telemetry import (
    emit, enable_telemetry, journal_flush, mono)
from fast_autoaugment_tpu.utils.logging import get_logger

from .scenario import SCENARIOS, Scenario, Traffic, scaled, suite_names
from .verdict import evaluate, render_table
from .workload import WorkloadReport, build_schedule, run_workload
from .workload import schedule_digest as _schedule_digest

__all__ = ["run_scenario", "run_suite"]

logger = get_logger("faa_tpu.gameday.runner")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: bring-up budget: the FIRST scenario pays the AOT compile (the shared
#: cache makes every later replica spawn a cache hit)
READY_TIMEOUT_S = 300.0
ROUTER_READY_S = 90.0
TEARDOWN_S = 45.0

#: ops pool for generated tenant/candidate policies — names from the
#: repo's op table, mirroring the bench tools' POLICY_A/POLICY_B style
_OPS = ("Rotate", "Invert", "ShearX", "Solarize")


def _policy_spec(i: int) -> list:
    """Deterministic, pairwise-distinct single-sub policy specs."""
    a = _OPS[i % len(_OPS)]
    b = _OPS[(i + 1) % len(_OPS)]
    return [[[a, 0.5 + 0.1 * (i % 3), 0.4],
             [b, 0.3, 0.15 + 0.1 * (i % 4)]]]


def _write_policies(pol_dir: str, n: int) -> list[str]:
    os.makedirs(pol_dir, exist_ok=True)
    paths = []
    for i in range(n):
        path = os.path.join(pol_dir, f"policy{i}.json")
        with open(path, "w") as fh:
            json.dump(_policy_spec(i), fh)
        paths.append(path)
    return paths


def _policy_digests(paths: list[str]) -> list[str]:
    # lazy: pulls in jax (AOT machinery) — only actual runs pay it,
    # spec/verdict units never do
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fast_autoaugment_tpu.serve.policy_server import policy_digest
    from fast_autoaugment_tpu.serve.serve_cli import build_policy_tensor
    return [policy_digest(build_policy_tensor(p)) for p in paths]


# ------------------------------------------------------------ plumbing


def _http_get(host: str, port: int, path: str,
              timeout_s: float = 3.0) -> tuple[int, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            rec = json.load(fh)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def _read_journal(tel_dir: str, types: set[str] | None = None
                  ) -> list[dict]:
    """Every journal record under ``tel_dir`` (all hosts' segments),
    time-ordered — the same files ``make trace`` reads."""
    out: list[dict] = []
    pattern = os.path.join(tel_dir, "**", "journal-*.jsonl")
    for path in sorted(glob.glob(pattern, recursive=True)):
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail mid-write: next read wins
                    if types is None or rec.get("type") in types:
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: (r.get("t_wall") or 0, r.get("seq") or 0))
    return out


def _wait(predicate, timeout_s: float, interval_s: float = 0.25,
          what: str = "condition"):
    deadline = mono() + timeout_s
    while mono() < deadline:
        val = predicate()
        if val:
            return val
        time.sleep(interval_s)
    raise TimeoutError(f"gameday: timed out waiting for {what} "
                       f"({timeout_s:.0f}s)")


class _PlaneHandle:
    """Live-plane state: spawned processes + where to find them."""

    def __init__(self, workdir: str, tel_dir: str, port_dir: str):
        self.workdir = workdir
        self.tel_dir = tel_dir
        self.port_dir = port_dir
        self.procs: list[tuple[str, subprocess.Popen]] = []
        self.router_port: int | None = None
        self.killed: str | None = None

    def alive(self, name: str) -> bool:
        return any(n == name and p.poll() is None for n, p in self.procs)


def _base_env() -> dict:
    env = dict(os.environ)
    # children get EXPLICIT --telemetry flags and scenario-scoped fault
    # plans; ambient config from the harness must not leak in
    for var in ("FAA_TELEMETRY", "FAA_FAULT", "FAA_FSFAULT",
                "FAA_HOST_ID"):
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _replica_cmd(scn: Scenario, policy_path: str, tel_dir: str,
                 cc_dir: str, pol_dir: str | None) -> list[str]:
    pl = scn.plane
    cmd = [sys.executable, "-m", "fast_autoaugment_tpu.serve.serve_cli",
           "--policy", policy_path,
           "--image", str(pl.image), "--shapes", pl.shapes,
           "--max-wait-ms", str(pl.max_wait_ms),
           "--telemetry", tel_dir, "--compile-cache", cc_dir,
           "--traffic-stats", "--drain-timeout", "8"]
    if pl.dispatch_floor_ms > 0:
        cmd += ["--dispatch-floor-ms", str(pl.dispatch_floor_ms)]
    if pl.shedding:
        cmd += ["--queue-depth", str(pl.queue_depth),
                "--default-deadline-ms", str(pl.deadline_ms)]
    else:
        # the deliberately-broken configuration: a queue nobody can
        # fill and no deadlines — overload becomes hang, not a fast no
        cmd += ["--queue-depth", "1000000"]
    if pl.shm_ingest:
        cmd += ["--shm-ingest"]
    if pl.tenant_capacity > 0 and pol_dir:
        cmd += ["--tenant-capacity", str(pl.tenant_capacity),
                "--policy-dir", pol_dir]
    return cmd


def _bring_up(scn: Scenario, workdir: str, cc_dir: str,
              policies: list[str]) -> _PlaneHandle:
    """Spawn the plane and block until it answers: every replica (or
    the autoscaler's minimum fleet) proves ``/readyz``, then the router
    proves it with >= 1 replica in rotation."""
    tel_dir = os.path.join(workdir, "telemetry")
    port_dir = os.path.join(workdir, "replicas")
    os.makedirs(tel_dir, exist_ok=True)
    os.makedirs(port_dir, exist_ok=True)
    handle = _PlaneHandle(workdir, tel_dir, port_dir)
    try:
        return _bring_up_inner(scn, handle, cc_dir, policies)
    except BaseException:
        _teardown(handle)  # no orphans on a failed bring-up
        raise


def _bring_up_inner(scn: Scenario, handle: _PlaneHandle, cc_dir: str,
                    policies: list[str]) -> _PlaneHandle:
    pl = scn.plane
    tel_dir, port_dir = handle.tel_dir, handle.port_dir
    workdir = handle.workdir
    pol_dir = os.path.dirname(policies[0])
    env = _base_env()
    rep_cmd = _replica_cmd(scn, policies[0], tel_dir, cc_dir,
                           pol_dir if pl.tenant_capacity > 0 else None)

    expected = []
    if pl.autoscaler:
        as_env = dict(env)
        if scn.faults:
            as_env["FAA_FAULT"] = scn.faults  # fleet children inherit
        auto = subprocess.Popen([
            sys.executable, "-m", "fast_autoaugment_tpu.serve.autoscaler",
            "--port-dir", port_dir,
            "--min-replicas", str(pl.min_replicas),
            "--max-replicas", str(pl.max_replicas),
            "--high-queue", str(pl.high_queue),
            "--high-shed-rate", str(pl.high_shed_rate),
            "--up-polls", str(pl.up_polls),
            "--down-polls", str(pl.down_polls),
            "--cooldown", str(pl.cooldown_s),
            "--poll-interval", str(pl.poll_interval_s),
            "--telemetry", tel_dir,
            "--", *rep_cmd], env=as_env, cwd=_REPO)
        handle.procs.append(("autoscaler", auto))
        expected = [f"replica{i}" for i in range(pl.min_replicas)]
    else:
        for i in range(pl.replicas):
            rep_env = dict(env, FAA_HOST_ID=str(i))
            if scn.faults:
                rep_env["FAA_FAULT"] = scn.faults
            tag = f"replica{i}"
            proc = subprocess.Popen(
                rep_cmd + ["--port", "0", "--port-dir", port_dir,
                           "--host-tag", tag],
                env=rep_env, cwd=_REPO)
            handle.procs.append((tag, proc))
            expected.append(tag)

    def _replicas_ready():
        recs = [_read_json(os.path.join(port_dir, f"{t}.json"))
                for t in expected]
        if any(r is None or "port" not in r for r in recs):
            return None
        for rec in recs:
            try:
                status, _ = _http_get(rec.get("host", "127.0.0.1"),
                                      int(rec["port"]), "/readyz")
            except OSError:
                return None
            if status != 200:
                return None
        return recs

    _wait(_replicas_ready, READY_TIMEOUT_S,
          what=f"{len(expected)} replica(s) ready")

    router_file = os.path.join(workdir, "router.port")
    rt_env = dict(env)
    if scn.fsfaults:
        rt_env["FAA_FSFAULT"] = scn.fsfaults  # armed on the ROUTER
    router = subprocess.Popen([
        sys.executable, "-m", "fast_autoaugment_tpu.serve.router_cli",
        "--port-dir", port_dir, "--port", "0",
        "--port-file", router_file,
        "--poll-interval", "0.3",
        "--telemetry", tel_dir], env=rt_env, cwd=_REPO)
    handle.procs.append(("router", router))

    def _router_ready():
        if router.poll() is not None:
            raise RuntimeError("gameday: router died during bring-up")
        try:
            with open(router_file) as fh:
                port = int(fh.read().strip())
        except (OSError, ValueError):
            return None
        try:
            status, _ = _http_get("127.0.0.1", port, "/readyz")
        except OSError:
            return None
        return port if status == 200 else None

    handle.router_port = _wait(_router_ready, ROUTER_READY_S,
                               what="router ready (>=1 in rotation)")

    if pl.controller:
        candidate = policies[-1]  # one past the tenant set: pre-built
        ctl = subprocess.Popen([
            sys.executable, "-m", "fast_autoaugment_tpu.launch.control_cli",
            "--telemetry", tel_dir, "--port-dir", port_dir,
            "--router-url", f"http://127.0.0.1:{handle.router_port}",
            "--baseline-policy", policies[0],
            "--candidate-policy", candidate,
            "--baseline-samples", "10",
            "--cusum-h", "4", "--gate-polls", "2",
            "--quality-margin", "1.0",
            "--poll-interval", "0.2",
            "--reload-timeout", str(int(READY_TIMEOUT_S)),
            "--stats-file", os.path.join(workdir, "control_stats.json"),
        ], env=env, cwd=_REPO)  # fault plans are serve-side only
        handle.procs.append(("controller", ctl))
    return handle


def _teardown(handle: _PlaneHandle) -> dict:
    """SIGTERM newest-first (controller before router before fleet) so
    supervisors stop reacting before their wards leave; SIGKILL past
    the shared deadline.  Returns ``{name: exit_code}``."""
    for _name, proc in reversed(handle.procs):
        if proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
    deadline = mono() + TEARDOWN_S
    codes: dict[str, int | None] = {}
    for name, proc in reversed(handle.procs):
        budget = max(0.5, deadline - mono())
        try:
            codes[name] = proc.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                codes[name] = proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                codes[name] = None
    return codes


def _scrape_router_stats(handle: _PlaneHandle) -> dict | None:
    if handle.router_port is None:
        return None
    try:
        status, body = _http_get("127.0.0.1", handle.router_port,
                                 "/stats", timeout_s=5.0)
        if status == 200:
            return json.loads(body.decode())
    except (OSError, ValueError):
        pass
    return None


class _KillWatcher(threading.Thread):
    """SIGKILL the scenario's victim on cue.

    ``target="canary"`` resolves the victim from the first journaled
    canary rollout event — the replica the armed split just promoted —
    and the pid comes from the victim's own port record (SIGKILL means
    no graceful record removal, so the record outlives the process;
    that is exactly what makes the kill addressable)."""

    def __init__(self, scn: Scenario, handle: _PlaneHandle):
        super().__init__(name="gameday-kill", daemon=True)
        self.scn = scn
        self.handle = handle
        self.stop_evt = threading.Event()

    def run(self) -> None:
        k = self.scn.kill
        tag = k.target
        if k.after_event:
            deadline = (mono() + self.scn.traffic.duration_s
                        + self.scn.decision_timeout_s)
            while mono() < deadline:
                if self.stop_evt.is_set():
                    return
                evs = [e for e in _read_journal(self.handle.tel_dir,
                                                types={k.after_event})
                       if not k.after_action
                       or e.get("action") == k.after_action]
                if evs:
                    if tag == "canary":
                        tag = str(evs[0].get("replica") or tag)
                    break
                self.stop_evt.wait(0.3)
            else:
                return  # trigger never fired: nothing to kill
        else:
            if self.stop_evt.wait(
                    k.at_frac * self.scn.traffic.duration_s):
                return
        if self.stop_evt.wait(k.delay_s):
            return
        rec = _read_json(os.path.join(self.handle.port_dir,
                                      f"{tag}.json"))
        if rec is None or "pid" not in rec:
            logger.warning("gameday: kill target %s has no port "
                           "record; skipping", tag)
            return
        try:
            os.kill(int(rec["pid"]), signal.SIGKILL)
        except (OSError, ValueError) as e:
            logger.warning("gameday: SIGKILL %s failed: %s", tag, e)
            return
        self.handle.killed = tag
        # NOT `pid=` — the journal record schema reserves that field
        # for the emitting process
        emit("scenario", self.scn.name, action="kill", replica=tag,
             victim_pid=int(rec["pid"]))
        logger.warning("gameday: SIGKILLed %s (pid %d)", tag,
                       int(rec["pid"]))


def _merge_report(into: WorkloadReport, other: WorkloadReport) -> None:
    into.offered += other.offered
    into.completed += other.completed
    into.ok += other.ok
    into.shed += other.shed
    into.unexpected_status += other.unexpected_status
    into.transport_errors += other.transport_errors
    into.cancelled += other.cancelled
    into.too_late += other.too_late
    for k, v in other.ok_by_tenant.items():
        into.ok_by_tenant[k] = into.ok_by_tenant.get(k, 0) + v
    for k, v in other.shed_by_status.items():
        into.shed_by_status[k] = into.shed_by_status.get(k, 0) + v
    into.latencies_ok_s.extend(other.latencies_ok_s)
    into.max_lateness_s = max(into.max_lateness_s, other.max_lateness_s)
    into.elapsed_s += other.elapsed_s
    into.shm_created += other.shm_created
    into.shm_leftover.extend(other.shm_leftover)
    into.errors_sample.extend(other.errors_sample)


def _has_terminal(tel_dir: str) -> bool:
    return bool(_read_journal(tel_dir, types={"promote", "rollback"}))


def run_scenario(scn: Scenario, *, workdir: str,
                 compile_cache: str) -> dict:
    """One full drill: bring-up -> traffic (+ kill + sustain) ->
    teardown -> verdict record (see module docstring)."""
    os.makedirs(workdir, exist_ok=True)
    tel_dir = os.path.join(workdir, "telemetry")
    os.makedirs(tel_dir, exist_ok=True)
    # the runner journals INTO the scenario's own dir: scenario marks,
    # progress and verdicts live next to the plane's decision events
    enable_telemetry(tel_dir)

    n_policies = max(scn.plane.policies, 1) + (
        1 if scn.plane.controller else 0)
    policies = _write_policies(os.path.join(workdir, "policies"),
                               n_policies)
    digests = (_policy_digests(policies[:scn.traffic.tenants])
               if scn.traffic.tenants > 1 else None)

    schedule = build_schedule(scn.traffic, scn.seed)
    digest = _schedule_digest(schedule)
    t0 = mono()
    emit("scenario", scn.name, action="start", seed=scn.seed,
         schedule_digest=digest, requests=len(schedule),
         traffic=scn.traffic.kind, expect=scn.expect)
    logger.info("gameday %s: %d requests over %.0fs (digest %s)",
                scn.name, len(schedule), scn.traffic.duration_s, digest)

    handle = _bring_up(scn, workdir, compile_cache, policies)
    watcher = None
    router_stats = None
    report = None
    try:
        if scn.kill is not None:
            watcher = _KillWatcher(scn, handle)
            watcher.start()
        emit("scenario", scn.name, action="phase", phase="traffic")

        def _progress(offered, completed, ok):
            emit("scenario", scn.name, action="progress",
                 offered=offered, completed=completed, ok=ok)

        report = run_workload(
            schedule, "127.0.0.1", handle.router_port,
            image=scn.plane.image, digests=digests,
            progress_cb=_progress)

        if scn.plane.controller and not _has_terminal(tel_dir):
            # the quality gate cannot measure a canary nobody sends
            # traffic through: trickle deterministic sustain chunks
            # until the terminal decision (or the bounded timeout)
            emit("scenario", scn.name, action="phase",
                 phase="decision-wait")
            deadline = mono() + scn.decision_timeout_s
            chunk_i = 0
            while mono() < deadline and not _has_terminal(tel_dir) \
                    and handle.alive("controller"):
                chunk_i += 1
                sustain = Traffic(
                    kind="constant", duration_s=4.0,
                    base_rps=scn.traffic.base_rps,
                    imgs_per_request=scn.traffic.imgs_per_request,
                    lanes=scn.traffic.lanes,
                    tenants=scn.traffic.tenants,
                    rotate_s=scn.traffic.rotate_s)
                chunk = build_schedule(sustain,
                                       scn.seed + 7919 * chunk_i)
                _merge_report(report, run_workload(
                    chunk, "127.0.0.1", handle.router_port,
                    image=scn.plane.image, digests=digests,
                    drain_s=10.0))

        time.sleep(scn.settle_s)
        router_stats = _scrape_router_stats(handle)
    finally:
        if watcher is not None:
            watcher.stop_evt.set()
        emit("scenario", scn.name, action="phase", phase="teardown")
        exit_codes = _teardown(handle)

    evidence = {
        "report": report.to_dict() if report is not None else {
            "offered": len(schedule), "ok": 0, "shed": 0,
            "unexpected_status": 0, "transport_errors": len(schedule),
            "cancelled": 0, "completed": 0},
        "journal": _read_journal(tel_dir),
        "router_stats": router_stats,
        "killed": handle.killed,
        "tenants": scn.traffic.tenants,
    }
    record = evaluate(scn, evidence, schedule_digest=digest)
    record["killed"] = handle.killed
    record["exit_codes"] = exit_codes
    record["elapsed_s"] = round(mono() - t0, 1)
    for row in record["predicates"]:
        emit("verdict", scn.name, predicate=row["predicate"],
             ok=row["ok"], observed=row["observed"],
             bound=row["bound"], detail=row.get("detail") or "")
    emit("scenario", scn.name, action="end", passed=record["pass"],
         expect=scn.expect, ok_as_expected=record["ok_as_expected"],
         schedule_digest=digest, elapsed_s=record["elapsed_s"])
    journal_flush()
    logger.info("gameday %s: %s (expected %s) in %.0fs",
                scn.name, "PASS" if record["pass"] else "FAIL",
                scn.expect, record["elapsed_s"])
    return record


def run_suite(names: list[str] | None = None, *, smoke: bool = False,
              smoke_factor: float = 0.4, seed: int | None = None,
              out: str | None = None, keep: bool = False,
              root: str | None = None, extra: dict | None = None
              ) -> dict:
    """Run scenarios back to back, render the verdict table, optionally
    write the suite JSON (``make gameday``).  ``smoke`` runs every
    scenario through :func:`~.scenario.scaled` — same topology, same
    predicates, shrunk load."""
    names = list(names) if names else suite_names()
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise KeyError(f"unknown scenario(s): {', '.join(unknown)} "
                       f"(known: {', '.join(suite_names())})")
    root = root or tempfile.mkdtemp(prefix="faa-gameday-")
    compile_cache = os.path.join(root, "compile-cache")
    os.makedirs(compile_cache, exist_ok=True)
    records = []
    try:
        for name in names:
            scn = SCENARIOS[name]
            if seed is not None:
                scn = dataclasses.replace(scn, seed=int(seed))
            if smoke:
                scn = scaled(scn, smoke_factor)
            try:
                records.append(run_scenario(
                    scn, workdir=os.path.join(root, name),
                    compile_cache=compile_cache))
            except Exception as e:  # noqa: BLE001 — one crashed drill
                # must not take the rest of the suite (or its verdict
                # table) down with it; a harness crash is NEVER "as
                # expected", even for an expect=fail scenario
                logger.exception("gameday %s: harness crashed", name)
                records.append({
                    "scenario": name, "seed": scn.seed,
                    "schedule_digest": None, "predicates": [],
                    "pass": False, "expect": scn.expect,
                    "ok_as_expected": False,
                    "error": f"{type(e).__name__}: {e}",
                    "report": None,
                })
    finally:
        if not keep:
            shutil.rmtree(root, ignore_errors=True)
    table = render_table(records)
    result = {
        "suite": names,
        "smoke": bool(smoke),
        "smoke_factor": smoke_factor if smoke else None,
        "seed": seed,
        "suite_green": all(r["ok_as_expected"] for r in records),
        "records": records,
        "table": table,
    }
    if extra:
        result.update(extra)
    if out:
        tmp = f"{out}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(result, fh, indent=2, default=str)
        os.replace(tmp, out)
        logger.info("gameday: suite JSON -> %s", out)
    return result
