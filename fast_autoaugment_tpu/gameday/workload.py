"""Deterministic open-loop workload generation + replay.

The offered schedule is a pure function of ``(scenario, seed)``:
arrival times come from a seeded non-homogeneous Poisson thinning of
the scenario's rate curve, and every accepted request's fields (wire
lane, cohort, batch, body seed) derive from
``numpy.random.default_rng([seed, request_index])`` — so two runs of
the same ``(scenario, seed)`` offer byte-identical schedules AND
byte-identical request bodies, and :func:`schedule_digest` stamps that
identity into the verdict record.

Replay is OPEN LOOP: the driver fires requests on the schedule's
clock, not the plane's.  When the plane slows down the schedule does
not — backpressure shows up as shed responses, latency, or hangs, all
of which are the verdict engine's evidence, never as a quietly
throttled offered rate.  Requests ride the PR-16 pooled keep-alive
wire client across the raw / npz / shm lanes.

Host-only: numpy + stdlib (no jax).  PRNG keys for the raw lane are
built as ``[0, seed]`` uint32 pairs — bit-identical to
``jax.random.PRNGKey(seed)`` for 32-bit seeds, without importing jax
into the load generator.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import hashlib
import io
import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from fast_autoaugment_tpu.core.telemetry import mono
from fast_autoaugment_tpu.serve import wire
from fast_autoaugment_tpu.utils.logging import get_logger

from .scenario import Traffic

__all__ = ["Offered", "build_schedule", "schedule_digest",
           "request_body", "WorkloadReport", "run_workload"]

logger = get_logger("faa_tpu.gameday.workload")

#: statuses that count as an EXPLICIT structured rejection (shedding,
#: overload, cold tenant, bad request) — the "fast no" the plane is
#: allowed to answer under stress.  Anything else non-200 is a plane
#: bug; a transport error / timeout is a hang.
SHED_STATUSES = frozenset({400, 408, 413, 429, 503})


@dataclasses.dataclass(frozen=True)
class Offered:
    """One scheduled request (pure data, serializable)."""

    index: int
    t_s: float          # offset from scenario start, seconds
    lane: str           # raw | npz | shm
    tenant: int         # cohort index into the digest list
    batch: int
    body_seed: int      # base seed for the request's image bytes


def _per_request_rng(seed: int, index: int) -> np.random.Generator:
    return np.random.default_rng([seed & 0x7FFFFFFF, index])


def build_schedule(traffic: Traffic, seed: int) -> list[Offered]:
    """The full offered schedule for one scenario run (deterministic).

    Arrivals: Poisson thinning at ``traffic.peak_rate`` using the
    ``[seed, 0]`` stream; accepted request ``i`` then draws its lane /
    cohort / body seed from the ``[seed, i+1]`` stream (the
    ``(scenario_seed, request_index)`` contract — request ``i``'s
    fields never depend on how many candidates were thinned away
    before it)."""
    arrivals = np.random.default_rng([seed & 0x7FFFFFFF, 0])
    peak = max(traffic.peak_rate, 1e-6)
    lanes = [name for name, _w in traffic.lanes]
    weights = np.asarray([w for _n, w in traffic.lanes], np.float64)
    weights = weights / weights.sum()
    out: list[Offered] = []
    t = 0.0
    while True:
        t += float(arrivals.exponential(1.0 / peak))
        if t >= traffic.duration_s:
            break
        if float(arrivals.uniform()) * peak > traffic.rate_at(t):
            continue  # thinned: instantaneous rate below peak
        i = len(out)
        rng = _per_request_rng(seed, i + 1)
        lane = lanes[int(rng.choice(len(lanes), p=weights))]
        if traffic.tenants > 1:
            active = int(t // max(traffic.rotate_s, 1e-9)) \
                % traffic.tenants
            if float(rng.uniform()) < 0.8:
                tenant = active  # the rotating cohort's 80% share
            else:
                tenant = int(rng.integers(0, traffic.tenants))
        else:
            tenant = 0
        out.append(Offered(
            index=i, t_s=round(t, 6), lane=lane, tenant=tenant,
            batch=int(traffic.imgs_per_request),
            body_seed=int(rng.integers(0, 2**31 - 1))))
    return out


def _canonical_rows(schedule: list[Offered]) -> list[list]:
    return [[o.index, o.t_s, o.lane, o.tenant, o.batch, o.body_seed]
            for o in schedule]


def schedule_digest(schedule: list[Offered]) -> str:
    """sha256 over the canonical schedule serialization — the byte
    identity the determinism acceptance criterion pins (bodies derive
    from the serialized seeds, so the digest covers them too)."""
    blob = json.dumps(_canonical_rows(schedule),
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def request_body(offered: Offered, image: int
                 ) -> tuple[bytes, dict, np.ndarray | None]:
    """``(body, headers, shm_images)`` for one scheduled request.

    Deterministic in ``offered`` alone.  For the shm lane the images
    come back instead of a body — the caller owns the region lifecycle
    (create / write / request / unlink), because region names are
    process-unique and must not leak into the schedule identity."""
    rng = np.random.default_rng([offered.body_seed, offered.index])
    imgs = rng.integers(0, 256, (offered.batch, image, image, 3),
                        dtype=np.uint8)
    seeds = (np.uint32(offered.body_seed & 0xFFFFFFF)
             + np.arange(offered.batch, dtype=np.uint32))
    if offered.lane == "raw":
        keys = np.stack([np.zeros_like(seeds), seeds], axis=1)
        return (wire.encode_raw(imgs, seeds=keys),
                {"Content-Type": wire.RAW_CONTENT_TYPE}, None)
    if offered.lane == "npz":
        buf = io.BytesIO()
        np.savez(buf, images=imgs, seeds=seeds.astype(np.int64))
        return (buf.getvalue(),
                {"Content-Type": "application/octet-stream"}, None)
    if offered.lane == "shm":
        keys = np.stack([np.zeros_like(seeds), seeds], axis=1)
        return b"", {"Content-Type": wire.SHM_CONTENT_TYPE,
                     "_keys": keys}, imgs.astype(np.float32)
    raise ValueError(f"unknown lane: {offered.lane!r}")


@dataclasses.dataclass
class WorkloadReport:
    """Aggregated replay evidence (one scenario run's client view)."""

    offered: int = 0
    completed: int = 0
    ok: int = 0
    shed: int = 0                 # explicit structured rejections
    unexpected_status: int = 0    # non-200 outside SHED_STATUSES
    transport_errors: int = 0     # raised / timed out = a hang
    cancelled: int = 0            # never fired: plane too far behind
    too_late: int = 0             # client slot freed > timeout_s late
    ok_by_tenant: dict = dataclasses.field(default_factory=dict)
    shed_by_status: dict = dataclasses.field(default_factory=dict)
    latencies_ok_s: list = dataclasses.field(default_factory=list)
    max_lateness_s: float = 0.0
    elapsed_s: float = 0.0
    shm_created: int = 0
    shm_leftover: list = dataclasses.field(default_factory=list)
    errors_sample: list = dataclasses.field(default_factory=list)

    def _pctile(self, q: float) -> float | None:
        if not self.latencies_ok_s:
            return None
        xs = sorted(self.latencies_ok_s)
        idx = min(len(xs) - 1, int(q * (len(xs) - 1)))
        return round(xs[idx] * 1e3, 3)

    def to_dict(self) -> dict:
        return {
            "offered": self.offered,
            "completed": self.completed,
            "ok": self.ok,
            "shed": self.shed,
            "unexpected_status": self.unexpected_status,
            "transport_errors": self.transport_errors,
            "cancelled": self.cancelled,
            "too_late": self.too_late,
            "goodput": round(self.ok / self.offered, 4)
            if self.offered else None,
            "ok_by_tenant": dict(self.ok_by_tenant),
            "shed_by_status": dict(self.shed_by_status),
            "p50_ms_ok": self._pctile(0.50),
            "p99_ms_ok": self._pctile(0.99),
            "max_lateness_s": round(self.max_lateness_s, 3),
            "elapsed_s": round(self.elapsed_s, 2),
            "offered_rps": round(self.offered / self.elapsed_s, 2)
            if self.elapsed_s else None,
            "served_rps": round(self.ok / self.elapsed_s, 2)
            if self.elapsed_s else None,
            "shm_created": self.shm_created,
            "shm_leftover": list(self.shm_leftover),
            "errors_sample": list(self.errors_sample[:5]),
        }


def _shm_leftovers(names: list[str]) -> list[str]:
    return [n for n in names
            if os.path.exists(os.path.join("/dev/shm", n))]


def _auto_concurrency(schedule: list[Offered],
                      timeout_s: float) -> int:
    """Client worker-slot budget: the densest ``timeout_s`` window of
    scheduled arrivals, with margin.  Open loop only stays open while
    every request that can legally be in flight at once has a slot —
    size it from the plane's behavior instead and a hung plane
    quietly throttles its own offered load back to whatever it can
    absorb (and then passes its verdict).  Bounded: workers are
    lazily spawned and socket-bound, but 1-core hosts still pay per
    thread."""
    times = [o.t_s for o in schedule]
    lo, densest = 0, 0
    for hi, t in enumerate(times):
        while t - times[lo] > timeout_s:
            lo += 1
        densest = max(densest, hi - lo + 1)
    return max(16, min(256, int(densest * 1.25) + 8))


def run_workload(schedule: list[Offered], host: str, port: int, *,
                 image: int, digests: list[str] | None = None,
                 timeout_s: float = 5.0, concurrency: int | None = None,
                 drain_s: float = 20.0,
                 progress_cb=None, progress_every_s: float = 2.0
                 ) -> WorkloadReport:
    """Replay ``schedule`` against ``host:port`` open loop.

    ``digests`` maps cohort index -> policy digest header (cohort 0
    with no digest list rides headerless on the replica's default
    tenant).  ``progress_cb(offered, completed, ok)`` fires about
    every ``progress_every_s`` from the dispatcher thread — the hook
    the runner uses to journal rolling ``scenario`` progress events.

    The drain is BOUNDED: ``drain_s`` after the last scheduled arrival
    the driver cancels every request that has not even started and
    counts it as hung (``transport_errors``) — a plane so far behind
    that the harness gives up IS a hang, and an unbounded drain would
    let a broken plane stall the verdict instead of failing it.
    """
    if concurrency is None:
        concurrency = _auto_concurrency(schedule, timeout_s)
    pool = wire.ConnectionPool(timeout_s=timeout_s,
                               max_idle_per_key=concurrency)
    report = WorkloadReport(offered=len(schedule))
    lock = threading.Lock()
    shm_names: list[str] = []

    def _fire(offered: Offered, t_sched_mono: float) -> None:
        lateness = max(0.0, mono() - t_sched_mono)
        if lateness > timeout_s:
            # the worker slot for this request only freed up after the
            # request's own timeout budget: every client slot was stuck
            # waiting on the plane.  Firing it late would quietly turn
            # the open loop into a closed loop (a hung plane throttling
            # its own offered load back to whatever it can absorb), so
            # it counts as a hang instead — the evidence the
            # shed_not_hang predicate exists to catch.
            with lock:
                report.completed += 1
                report.too_late += 1
                report.transport_errors += 1
                report.max_lateness_s = max(report.max_lateness_s,
                                            lateness)
                if len(report.errors_sample) < 16:
                    report.errors_sample.append(
                        f"gave up: client slot freed {lateness:.1f}s "
                        f"after schedule (plane hanging)")
            return
        body, headers, shm_imgs = request_body(offered, image)
        headers = dict(headers)
        keys = headers.pop("_keys", None)
        if digests and offered.tenant < len(digests) \
                and digests[offered.tenant]:
            headers["X-FAA-Policy-Digest"] = digests[offered.tenant]
        region = None
        status, err = None, None
        t0 = mono()
        try:
            if shm_imgs is not None:
                region = wire.ShmRegion(shm_imgs.shape, np.float32)
                with lock:
                    shm_names.append(region.name)
                    report.shm_created += 1
                region.write(shm_imgs)
                body = region.request_body(seeds=keys)
            status, _h, _payload = pool.request(
                host, port, "POST", "/augment", body, headers)
        except OSError as e:
            err = f"{type(e).__name__}: {e}"
        finally:
            if region is not None:
                region.close()
        latency = mono() - t0
        with lock:
            report.completed += 1
            report.max_lateness_s = max(report.max_lateness_s, lateness)
            if err is not None:
                report.transport_errors += 1
                if len(report.errors_sample) < 16:
                    report.errors_sample.append(err)
            elif status == 200:
                report.ok += 1
                report.latencies_ok_s.append(latency)
                key = str(offered.tenant)
                report.ok_by_tenant[key] = \
                    report.ok_by_tenant.get(key, 0) + 1
            elif status in SHED_STATUSES:
                report.shed += 1
                key = str(status)
                report.shed_by_status[key] = \
                    report.shed_by_status.get(key, 0) + 1
            else:
                report.unexpected_status += 1
                if len(report.errors_sample) < 16:
                    report.errors_sample.append(f"status {status}")

    t_start = mono()
    next_progress = t_start + progress_every_s
    pacer = threading.Event()
    futures = []
    ex = ThreadPoolExecutor(max_workers=concurrency)
    for offered in schedule:
        t_sched = t_start + offered.t_s
        while True:
            now = mono()
            if now >= t_sched:
                break
            # short sleeps keep the dispatcher responsive to the
            # progress cadence without busy-waiting
            pacer.wait(min(0.05, t_sched - now))
        futures.append(ex.submit(_fire, offered, t_sched))
        if progress_cb is not None and mono() >= next_progress:
            next_progress = mono() + progress_every_s
            with lock:
                progress_cb(offered.index + 1, report.completed,
                            report.ok)
    concurrent.futures.wait(futures, timeout=drain_s)
    n_cancelled = sum(1 for f in futures if f.cancel())
    if n_cancelled:
        with lock:
            report.cancelled = n_cancelled
            report.transport_errors += n_cancelled
            if len(report.errors_sample) < 16:
                report.errors_sample.append(
                    f"cancelled: {n_cancelled} requests never started "
                    f"within drain_s={drain_s}")
    # in-flight stragglers are each bounded by the socket timeout —
    # wait them out so the shm-leftover census below is not racing a
    # live worker that still owns a region
    concurrent.futures.wait(futures, timeout=timeout_s + 5.0)
    ex.shutdown(wait=False)
    report.elapsed_s = max(mono() - t_start, 1e-9)
    pool.close_all()
    report.shm_leftover = _shm_leftovers(shm_names)
    if progress_cb is not None:
        progress_cb(report.offered, report.completed, report.ok)
    return report
