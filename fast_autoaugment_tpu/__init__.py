"""fast-autoaugment-tpu: a TPU-native Fast AutoAugment framework.

Brand-new JAX/XLA/Flax implementation of the capabilities of
kakaobrain/fast-autoaugment (NeurIPS 2019): augmentation-policy search
by density matching, plus full training of WideResNet / ResNet /
Shake-Shake / PyramidNet+ShakeDrop / EfficientNet(+CondConv) on
CIFAR-10/100, SVHN and ImageNet — re-designed TPU-first rather than
translated from the PyTorch/CUDA/Ray reference.

Layering (see SURVEY.md section 7):

- ``core``     config, metrics, checkpointing
- ``ops``      on-device augmentation kernels, stochastic shake ops,
               optimizers, LR schedules
- ``policies`` found-policy archives (data) + codec
- ``data``     host input pipeline (native dataset readers, folds,
               device prefetch)
- ``models``   Flax model zoo + registry
- ``parallel`` mesh / sharding / collective helpers
- ``train``    jitted train/eval steps + epoch driver
- ``search``   density-matching policy search (in-tree TPE + batched
               TTA evaluation)
- ``launch``   CLI entry points and multi-host launching
"""

__version__ = "0.1.0"
