"""In-tree Tree-structured Parzen Estimator (TPE) optimizer.

The reference drives its policy search with HyperOpt's TPE through Ray
Tune (``search.py:230-245``): 200 samples over a space of
{op-choice x prob x level} x (5 policies x 2 ops), maximizing
``top1_valid``.  Ray + HyperOpt + the gorilla monkey-patch
(``search.py:32-50``) are a heavyweight control plane for what is, on a
TPU pod, a simple proposal loop around one compiled evaluation step —
so the optimizer lives in-tree:

- mixed space: categorical ('choice') and box ('uniform') dimensions;
- startup phase of pure random sampling (n_startup, hyperopt default 20);
- after startup, observations are split into good/bad by the gamma
  quantile of the objective (hyperopt's adaptive
  ``min(ceil(0.25 * sqrt(n)), 25)`` rule);
- uniform dims: 1-D Parzen mixtures over good/bad with
  Silverman-style bandwidths; candidates drawn from the good mixture
  and ranked by the density ratio l(x)/g(x);
- choice dims: smoothed categorical counts, same ratio ranking;
- n_ei_candidates (default 24) proposals scored per suggestion.

Deterministic given the seed.  Ask-tell interface so the caller owns
the evaluation loop (and can batch/shard it across hosts):
``suggest``/``tell`` for the sequential loop, ``ask(n)``/``tell_batch``
for synchronous batches of n concurrent proposals (constant-liar
posterior; ``ask(1)`` is bit-for-bit ``suggest``), which the driver
evaluates in ONE vmapped TTA program per batch (``--trial-batch``).

The ASYNC pipeline (``search/pipeline.py``, ``--async-pipeline on``)
uses the PROPOSAL LEDGER instead: :meth:`ask_tagged` assigns each
proposal a monotonically increasing trial id and keeps it PENDING until
:meth:`tell` is called with that id.  Pending proposals contribute the
constant-liar placeholder to every posterior, and the posterior is
always materialized in CANONICAL trial-id order — so tells arriving out
of order (a later actor finishing first) produce bit-identical state to
in-order tells, and a resume can replay the exact ask/tell interleaving
from the id-ordered trial log (the RNG stream advances by re-running
the asks, which the legacy ``tell``-only replay never did).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

__all__ = ["Dim", "choice", "uniform", "TPE"]


@dataclass(frozen=True)
class Dim:
    name: str
    kind: str  # 'choice' | 'uniform'
    n: int = 0
    low: float = 0.0
    high: float = 1.0


def choice(name: str, n: int) -> Dim:
    return Dim(name, "choice", n=n)


def uniform(name: str, low: float = 0.0, high: float = 1.0) -> Dim:
    return Dim(name, "uniform", low=low, high=high)


@dataclass
class TPE:
    space: Sequence[Dim]
    seed: int = 0
    n_startup: int = 20
    n_ei_candidates: int = 24
    observations: list = field(default_factory=list)  # (x: dict, reward: float)

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)
        # proposal ledger (async pipeline): trial_id -> proposal for
        # asked-but-untold trials, trial_id -> (proposal, reward) once
        # told.  Ledger state is disjoint from `observations` — the
        # sequential/batched paths never touch it, so their streams
        # stay bit-for-bit.
        self._pending: dict[int, dict] = {}
        self._told: dict[int, tuple[dict, float]] = {}
        self._next_trial_id = 0
        #: tells that arrived while an earlier-asked trial was still
        #: pending (the out-of-order count the driver stamps)
        self.tell_reorders = 0

    # ------------------------------------------------------------------
    def _random_sample(self) -> dict:
        out = {}
        for d in self.space:
            if d.kind == "choice":
                out[d.name] = int(self._rng.integers(0, d.n))
            else:
                out[d.name] = float(self._rng.uniform(d.low, d.high))
        return out

    def _split(self):
        """Good/bad split by the hyperopt gamma rule (maximization)."""
        n = len(self.observations)
        n_good = min(int(math.ceil(0.25 * math.sqrt(n))), 25)
        order = sorted(range(n), key=lambda i: -self.observations[i][1])
        good = [self.observations[i][0] for i in order[:n_good]]
        bad = [self.observations[i][0] for i in order[n_good:]]
        return good, bad

    @staticmethod
    def _parzen_logpdf(x: np.ndarray, points: np.ndarray, low: float, high: float):
        """Log density of a 1-D Parzen mixture with a uniform prior component."""
        span = high - low
        if len(points) == 0:
            return np.full_like(x, -np.log(span))
        sigma = max(span * 1.06 * len(points) ** -0.2 / 4.0, 1e-3 * span)
        diff = (x[:, None] - points[None, :]) / sigma
        comp = -0.5 * diff**2 - 0.5 * np.log(2 * np.pi) - np.log(sigma)
        # include the uniform prior as one extra mixture component
        prior = np.full((x.shape[0], 1), -np.log(span))
        comp = np.concatenate([comp, prior], axis=1)
        return np.logaddexp.reduce(comp, axis=1) - np.log(comp.shape[1])

    @staticmethod
    def _categorical_probs(values: list[int], n: int) -> np.ndarray:
        counts = np.ones(n)  # +1 smoothing (hyperopt's prior)
        for v in values:
            counts[v] += 1.0
        return counts / counts.sum()

    # ------------------------------------------------------------------
    def suggest(self) -> dict:
        if len(self.observations) < self.n_startup:
            return self._random_sample()

        good, bad = self._split()
        proposal: dict = {}
        for d in self.space:
            gvals = [g[d.name] for g in good]
            bvals = [b[d.name] for b in bad]
            if d.kind == "choice":
                pg = self._categorical_probs(gvals, d.n)
                pb = self._categorical_probs(bvals, d.n)
                cands = self._rng.choice(d.n, size=self.n_ei_candidates, p=pg)
                scores = np.log(pg[cands]) - np.log(pb[cands])
                proposal[d.name] = int(cands[int(np.argmax(scores))])
            else:
                gp = np.asarray(gvals, np.float64)
                span = d.high - d.low
                sigma = max(span * 1.06 * max(len(gp), 1) ** -0.2 / 4.0, 1e-3 * span)
                if len(gp):
                    centers = self._rng.choice(gp, size=self.n_ei_candidates)
                    cands = np.clip(
                        centers + self._rng.normal(0, sigma, self.n_ei_candidates),
                        d.low, d.high,
                    )
                else:
                    cands = self._rng.uniform(d.low, d.high, self.n_ei_candidates)
                lg = self._parzen_logpdf(cands, gp, d.low, d.high)
                lb = self._parzen_logpdf(
                    cands, np.asarray(bvals, np.float64), d.low, d.high
                )
                proposal[d.name] = float(cands[int(np.argmax(lg - lb))])
        return proposal

    # ------------------------------------------------------------------
    def ask(self, n: int = 1) -> list[dict]:
        """Propose `n` candidates for CONCURRENT evaluation.

        ``ask(1)`` is exactly one :meth:`suggest` call — same RNG
        stream, same proposal — so a batch-1 ask/tell loop reproduces
        the sequential loop bit-for-bit.  For ``n > 1`` the proposals
        are generated by the CONSTANT-LIAR strategy (Ginsbourger et
        al.'s kriging-believer family, the standard synchronous-batch
        adaptation of sequential model-based search): after each
        proposal a pessimistic placeholder reward — the worst
        observation so far — is told to a TEMPORARY copy of the
        posterior, pushing the next proposal away from the still-
        pending point; the lies are discarded before returning.  The
        liar value is the conservative choice for maximization: an
        optimistic lie would cluster the whole batch on one mode.

        While the batch stays inside the random-startup phase the lies
        change nothing (the proposals are prior draws), matching
        batched random search; a batch that CROSSES the startup
        boundary switches to the (liar-informed) posterior mid-batch,
        exactly as the sequential loop would switch at that count.
        """
        if n <= 1:
            return [self.suggest()]
        lie = (min(r for _, r in self.observations)
               if self.observations else 0.0)
        proposals: list[dict] = []
        n_real = len(self.observations)
        try:
            for _ in range(n):
                p = self.suggest()
                proposals.append(p)
                self.observations.append((dict(p), lie))
        finally:
            # drop the lies, never the real observations
            del self.observations[n_real:]
        return proposals

    # ---------------------------------------------- proposal ledger
    def _lie(self) -> float:
        """The constant-liar placeholder for pending ledger trials:
        the worst reward told so far (0.0 before any tell) — the same
        pessimistic value :meth:`ask` uses within a batch."""
        return (min(r for _, r in self._told.values())
                if self._told else 0.0)

    def _materialized(self, lie: float) -> list:
        """The ledger's posterior view in CANONICAL trial-id order:
        told trials carry their true reward, pending ones the liar
        placeholder.  A pure function of the (id -> reward) SET, so
        the posterior is invariant to tell arrival order."""
        out = []
        for t in range(self._next_trial_id):
            if t in self._told:
                p, r = self._told[t]
                out.append((p, r))
            else:
                out.append((self._pending[t], lie))
        return out

    def ask_tagged(self, n: int = 1) -> list[tuple[int, dict]]:
        """Propose `n` candidates tagged with monotonically increasing
        trial ids, registering each as PENDING in the ledger until its
        :meth:`tell` arrives (in any order).

        The posterior for each proposal is the canonical-order
        materialization above: real rewards for told trials, the
        constant-liar placeholder for every pending one (in-flight
        rounds of the async pipeline).  With NO pending trials this
        consumes exactly the RNG stream of :meth:`ask` — the property
        that makes a one-actor in-order pipeline reproduce the serial
        trial log bit-for-bit, and that lets a resume replay the exact
        ask/tell interleaving by re-asking the logged rounds."""
        if n < 1:
            raise ValueError(f"ask_tagged needs n >= 1, got {n}")
        lie = self._lie()
        saved = self.observations
        work = self._materialized(lie)
        self.observations = work
        tagged: list[tuple[int, dict]] = []
        try:
            for _ in range(n):
                p = self.suggest()
                tid = self._next_trial_id
                self._next_trial_id += 1
                self._pending[tid] = dict(p)
                tagged.append((tid, p))
                # within-batch constant liar, exactly like ask()
                work.append((dict(p), lie))
        finally:
            self.observations = saved
        return tagged

    def _tell_id(self, trial_id: int, reward: float):
        if trial_id not in self._pending:
            state = "already told" if trial_id in self._told else "never asked"
            raise KeyError(f"ledger tell for trial {trial_id}: {state}")
        if any(t < trial_id for t in self._pending):
            self.tell_reorders += 1
        self._told[trial_id] = (self._pending.pop(trial_id), float(reward))

    @property
    def num_told(self) -> int:
        return len(self._told)

    @property
    def pending_ids(self) -> list[int]:
        return sorted(self._pending)

    def pending_proposal(self, trial_id: int) -> dict:
        return dict(self._pending[trial_id])

    def pending_rounds(self, trial_batch: int) -> list[list[int]]:
        """Group the pending trial ids back into their original ask
        ROUNDS (round ``r`` covers ids ``[r*K, (r+1)*K)``), in id
        order — the unit the async/fleet schedulers re-dispatch after
        a resume replay (:func:`~fast_autoaugment_tpu.search.pipeline.
        replay_trial_log` reconstructed them as ledger-pending)."""
        K = max(1, int(trial_batch))
        rounds: list[list[int]] = []
        for tid in self.pending_ids:
            if rounds and tid // K == rounds[-1][0] // K:
                rounds[-1].append(tid)
            else:
                rounds.append([tid])
        return rounds

    def round_payload(self, ids: Sequence[int]) -> list[dict]:
        """JSON-safe proposal dicts for a round of PENDING ids — the
        ledger's wire form for the cross-host round transport.  Python's
        ``json`` round-trips floats exactly (repr-based), so a decoded
        payload reproduces ``policy_decoder`` output bit for bit."""
        return [self.pending_proposal(int(t)) for t in ids]

    def worst_told(self) -> float:
        """Worst real reward in the ledger (the quarantine placeholder
        value); 0.0 before any tell — mirrors the driver's serial
        ``_quarantine`` semantics."""
        return self._lie()

    @property
    def best_told(self):
        """Ledger counterpart of :attr:`best`: the (proposal, reward)
        with the highest TOLD reward, in canonical id order."""
        if not self._told:
            return None
        tid = max(sorted(self._told), key=lambda t: self._told[t][1])
        return self._told[tid]

    # ------------------------------------------------------------------
    def tell(self, x, reward: float):
        """Record one result.  ``x`` is either the proposal dict
        (sequential/batched path — appends to ``observations``) or an
        int trial id from :meth:`ask_tagged` (ledger path — resolves
        the pending proposal, in any completion order)."""
        if isinstance(x, (int, np.integer)):
            return self._tell_id(int(x), float(reward))
        self.observations.append((dict(x), float(reward)))

    def tell_batch(self, xs: Sequence[dict], rewards: Sequence[float]):
        """Record the true rewards for a completed :meth:`ask` batch."""
        xs, rewards = list(xs), list(rewards)
        if len(xs) != len(rewards):
            raise ValueError(
                f"tell_batch: {len(xs)} proposals vs {len(rewards)} rewards")
        for x, r in zip(xs, rewards):
            self.tell(x, r)

    @property
    def best(self):
        if not self.observations:
            return None
        return max(self.observations, key=lambda o: o[1])
