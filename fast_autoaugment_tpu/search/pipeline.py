"""Async actor/learner search pipeline — overlap TPE math with device TTA.

The serial phase-2 scheduler (``search/driver.py``) alternates host-side
TPE math (ask, decode, tensor build, fsync persistence) and device TTA
dispatches strictly back to back: the device idles through every host
step and the host idles through every dispatch.  Density-matching search
never trains inside the loop, so its cost is PURE evaluation throughput
— the dispatch gaps are the whole remaining overhead (PRs 1-4 made the
dispatches themselves fast).

This module restructures one fold's trial budget as a streaming
ask-tell service in the Podracer actor/learner mold (arXiv:2104.06272):

- a bounded CANDIDATE QUEUE of ready-to-dispatch rounds (policy tensors
  + per-trial PRNG keys, built on the host while the device is busy);
- device ACTOR threads that pull rounds and run the existing
  ``_FoldEval`` TTA dispatches (the jitted steps are shared — actors
  reuse one executable, and the watchdog's label state is lock-guarded
  for exactly this concurrency);
- the TPE LEARNER (the calling thread) digests completed results and
  refills proposals concurrently, applying tells strictly in TRIAL-ID
  ORDER through the proposal ledger (``tpe.ask_tagged`` /
  ``tell(trial_id, ...)``) with a reorder buffer for rounds that finish
  out of order.

DETERMINISM is the design constraint that makes async mode testable and
resumable: the learner asks round ``r`` immediately after processing
round ``r - max_inflight`` (``max_inflight = actors + queue_depth``),
so the posterior behind every proposal is a pure function of
``(seed, K, actors, queue_depth)`` — real rewards for processed rounds,
constant-liar placeholders for the in-flight window — REGARDLESS of
completion timing.  Rewards are per-trial-id keyed, the trial log is
appended in id order, and a resume replays the exact ask/tell
interleaving from that log (:func:`replay_trial_log`), so an
interrupted async search completes to the same ``final_policy.json``
as an uninterrupted one.  With ``actors=1, queue_depth=0`` the
in-flight window is one round and the pipeline reproduces the serial
scheduler's trial log bit-for-bit.

:func:`run_overlapped_phases` is the second overlap axis — the
single-host seed of the fleet-as-pipeline direction (MPMD pipeline
parallelism, arXiv:2412.14374): phase-1 fold training runs on a trainer
thread and each fold is handed to phase-2 evaluation the moment its
training (and quality gate) completes, while the remaining folds still
train.

:class:`DispatchTrace` records per-dispatch start/end timestamps so
``tools/bench_pipeline.py`` can report the dispatch-gap histogram
(p50/p99 inter-dispatch idle, device busy fraction) for serial vs async
runs; ``search_result.json`` stamps the summary under ``pipeline``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

import numpy as np

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.resilience import (
    DispatchHungError,
    PreemptedError,
    preemption_requested,
)
from fast_autoaugment_tpu.core.telemetry import wall
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["DispatchTrace", "replay_trial_log", "run_fold_pipeline",
           "run_overlapped_phases", "resolve_async_pipeline"]

logger = get_logger("faa_tpu.pipeline")

#: learner poll quantum for the results queue — every blocking wait in
#: this module is bounded (lint R7), so preemption and actor failures
#: are noticed within this window
_POLL_SEC = 0.2
#: actor poll quantum for the candidate queue
_ACTOR_POLL_SEC = 0.2
#: bounded-join budget when shutting the actor fleet down (daemon
#: threads: a genuinely wedged dispatch cannot block process exit)
_JOIN_SEC = 5.0
#: on preemption, the overlapped phase-1 trainer gets this long to
#: reach its next dispatch boundary and checkpoint before the process
#: exits 77 — losing that checkpoint is still CORRECT (the resume
#: retrains deterministically) but wastes the fold's progress
_PREEMPT_DRAIN_SEC = 30.0

#: dispatch-gap histogram bucket edges (seconds)
_GAP_BUCKETS = (0.001, 0.01, 0.1, 1.0)


def resolve_async_pipeline(spec) -> bool:
    """``--async-pipeline {off,on}`` (or a bool) to a bool.  Anything
    unrecognized raises — a typo must not silently fall back to the
    serial scheduler."""
    if isinstance(spec, bool):
        return spec
    if spec is None:
        return False
    s = str(spec).strip().lower()
    if s in ("off", "0", "false", ""):
        return False
    if s in ("on", "1", "true"):
        return True
    raise ValueError(f"async_pipeline must be 'off' or 'on', got {spec!r}")


class DispatchTrace:
    """Thread-safe per-dispatch ``(start, end)`` recorder with named
    segments (one per fold's phase-2 trial loop).

    Actors record concurrently, so busy time is the UNION of the
    recorded windows per segment and a "gap" is an idle interval
    between merged windows — the quantity the async pipeline exists to
    drive to ~0.  :meth:`summary` pools gaps across segments into
    p50/p99 plus a log-bucket histogram and reports the device busy
    fraction sum(busy)/sum(span)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._segments: dict[str, list[tuple[float, float]]] = {}
        self._current: str | None = None

    def begin_segment(self, name: str) -> None:
        with self._lock:
            self._current = name
            self._segments.setdefault(name, [])

    def end_segment(self) -> None:
        with self._lock:
            self._current = None

    def record(self, t0: float, t1: float) -> None:
        """One dispatch window (monotonic seconds).  Ignored outside an
        open segment — phase-1 gate baselines and the audit share the
        evaluator but are not phase-2 dispatch-gap evidence."""
        with self._lock:
            if self._current is not None:
                self._segments[self._current].append((float(t0), float(t1)))

    @staticmethod
    def _merge(windows: list[tuple[float, float]]):
        merged: list[list[float]] = []
        for t0, t1 in sorted(windows):
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        return merged

    def summary(self) -> dict | None:
        """Aggregate dispatch-gap statistics, or None when nothing was
        recorded."""
        with self._lock:
            segments = {k: list(v) for k, v in self._segments.items() if v}
        if not segments:
            return None
        busy = span = 0.0
        gaps: list[float] = []
        n = 0
        for windows in segments.values():
            merged = self._merge(windows)
            busy += sum(t1 - t0 for t0, t1 in merged)
            span += merged[-1][1] - merged[0][0]
            gaps.extend(b[0] - a[1] for a, b in zip(merged, merged[1:]))
            n += len(windows)
        gaps_arr = np.asarray(gaps, np.float64)
        hist = {}
        if len(gaps_arr):
            edges = (0.0,) + _GAP_BUCKETS + (float("inf"),)
            for lo, hi in zip(edges, edges[1:]):
                label = (f"<{hi * 1000:g}ms" if hi != float("inf")
                         else f">={lo * 1000:g}ms")
                hist[label] = int(((gaps_arr >= lo) & (gaps_arr < hi)).sum())
        return {
            "num_dispatches": n,
            "num_segments": len(segments),
            "busy_secs": round(busy, 6),
            "span_secs": round(span, 6),
            "device_busy_frac": round(busy / span, 6) if span > 0 else None,
            "num_gaps": len(gaps),
            "gap_p50_ms": (round(float(np.percentile(gaps_arr, 50)) * 1e3, 3)
                           if len(gaps_arr) else None),
            "gap_p99_ms": (round(float(np.percentile(gaps_arr, 99)) * 1e3, 3)
                           if len(gaps_arr) else None),
            "gap_total_secs": round(float(gaps_arr.sum()), 6),
            "gap_hist": hist,
        }


def replay_trial_log(tpe, fold_trials: list, trial_batch: int,
                     num_search: int, max_inflight: int = 1) -> None:
    """Replay a (trial-id-ordered) trial log through the proposal
    ledger so a resumed async search continues EXACTLY where the
    uninterrupted one would be.

    The canonical pipeline schedule asks round ``r`` immediately after
    telling round ``r - max_inflight`` — so the replay re-runs that
    exact ask/tell interleaving: rounds are re-asked (advancing the
    TPE's RNG stream precisely as the original run did — the legacy
    tell-only replay leaves the stream at its seed position, so a
    resumed serial run proposes a DIFFERENT future than an
    uninterrupted one) and told their logged rewards in id order, with
    the in-flight window held at `max_inflight` rounds.  The logged
    proposals are authoritative: they overwrite the regenerated ones
    in the ledger, so a log written under different flags degrades
    gracefully instead of silently diverging.  On return the ledger's
    PENDING trials are the rounds the uninterrupted run had in flight
    at this log state; :func:`run_fold_pipeline` dispatches those
    first (per-trial keys are id-derived, so their rewards are
    bit-identical to the uninterrupted run's)."""
    K = max(1, int(trial_batch))
    M = max(1, int(max_inflight))
    n = len(fold_trials)
    rounds: list[tuple[int, list]] = []
    t = 0
    while t < n:
        k_eff = min(K, num_search - t)
        if k_eff <= 0:  # over-full log (stale num_search): stop
            break
        rounds.append((t, fold_trials[t:t + k_eff]))
        t += k_eff

    def _ask_one_round() -> bool:
        t_base = tpe._next_trial_id
        if t_base >= num_search:
            return False
        tpe.ask_tagged(min(K, num_search - t_base))
        return True

    asked = 0
    for told, (t_base, entries) in enumerate(rounds):
        while asked < told + M and _ask_one_round():
            asked += 1
        for i, entry in enumerate(entries):
            tid = t_base + i
            tpe._pending[tid] = dict(entry[0])
            tpe.tell(tid, float(entry[1]))


class _Round:
    """One ask round, built host-side and ready to dispatch: its trial
    ``ids``, the padded policy tensor (K lanes for the compiled
    candidate axis), and the [K] key stack (lane i's key is
    ``fold_in(key_fold, ids[i])`` — identical to the serial
    scheduler's, so rewards are schedule-invariant)."""

    __slots__ = ("idx", "ids", "proposals", "policies_t", "keys")

    def __init__(self, idx, ids, proposals, policies_t, keys):
        self.idx = idx
        self.ids = ids
        self.proposals = proposals
        self.policies_t = policies_t
        self.keys = keys

    @property
    def t_base(self) -> int:
        return self.ids[0]

    @property
    def k_eff(self) -> int:
        return len(self.ids)


def _build_round(idx, ids, proposals, *, trial_batch, num_policy, num_op,
                 key_fold) -> _Round:
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.policies.archive import (
        policy_decoder,
        policy_to_tensor,
    )

    k_eff = len(proposals)
    if trial_batch <= 1:
        policies_t = jnp.asarray(policy_to_tensor(
            policy_decoder(proposals[0], num_policy, num_op)))
        keys = jax.random.fold_in(key_fold, ids[0])
    else:
        padded = proposals + [proposals[-1]] * (trial_batch - k_eff)
        # padded lanes reuse the last real id's key stream continuation
        # (their results are dropped, exactly like the serial pad)
        key_ids = list(ids) + [ids[-1] + 1 + i
                               for i in range(trial_batch - k_eff)]
        policies_t = jnp.asarray(np.stack([
            np.asarray(policy_to_tensor(
                policy_decoder(p, num_policy, num_op)), np.float32)
            for p in padded
        ]))
        keys = jnp.stack([jax.random.fold_in(key_fold, t) for t in key_ids])
    return _Round(idx, list(ids), list(proposals), policies_t, keys)


def run_fold_pipeline(
    evaluator,
    fold: int,
    params,
    batch_stats,
    tpe,
    key_fold,
    fold_trials: list,
    *,
    num_search: int,
    trial_batch: int = 1,
    actors: int = 1,
    queue_depth: int = 1,
    num_policy: int,
    num_op: int,
    persist: Callable[[], None],
    record_quarantine: Callable[[int, int, BaseException, float], None],
    on_first_ok: Callable[[], None] | None = None,
    should_stop: Callable[[], BaseException | None] | None = None,
    heartbeat: Callable[[], None] | None = None,
) -> dict:
    """One fold's full trial budget through the actor/learner pipeline.

    The caller (``search/driver.py``) has already replayed the resumed
    prefix of `fold_trials` through :func:`replay_trial_log`; this
    function evaluates every remaining trial, appends ``(proposal,
    reward)`` entries (plus the serial scheduler's quarantine-marker
    third element on failed rounds) to `fold_trials` IN TRIAL-ID ORDER,
    and calls `persist` after each processed round — the same
    crash-loses-at-most-the-in-flight-work contract as the serial
    scheduler, except the fsync now overlaps device work.

    `record_quarantine(trial_lo, trial_hi, exc, worst)` mirrors the
    serial ``_quarantine`` bookkeeping (the learner computes `worst` —
    the min reward told so far, in id order, so it is deterministic);
    ``PreemptedError``/``DispatchHungError`` from an actor stop the
    fleet and re-raise in the calling thread (exit-77 restart path,
    never quarantined).  `should_stop` is polled every learner
    iteration and may return an exception to raise at the next round
    boundary (the phase-overlap scheduler routes trainer-thread
    failures through it); SIGTERM/SIGUSR1 preemption is polled
    directly.

    Returns accounting: rounds processed, trials appended, tell
    reorders observed, and the actor/queue geometry."""
    trial_batch = max(1, int(trial_batch))
    actors = max(1, int(actors))
    queue_depth = max(0, int(queue_depth))
    max_inflight = actors + queue_depth

    from fast_autoaugment_tpu.utils import faultinject

    fi = faultinject.active_plan()

    cand_q: queue.Queue = queue.Queue(maxsize=max_inflight)
    res_q: queue.Queue = queue.Queue()
    stop_event = threading.Event()

    def _evaluate(rnd: _Round) -> list[float]:
        if fi is not None:
            for t in rnd.ids:
                if fi.trial_error_at(t):
                    raise RuntimeError(f"injected trial_error at trial {t}")
        if trial_batch <= 1:
            metrics = evaluator.evaluate(
                fold, params, batch_stats, rnd.policies_t, rnd.keys)
            return [metrics["top1_valid"]]
        metrics_list = evaluator.evaluate_batch(
            fold, params, batch_stats, rnd.policies_t, rnd.keys)[:rnd.k_eff]
        return [m["top1_valid"] for m in metrics_list]

    def _actor(idx: int) -> None:
        while not stop_event.is_set():
            try:
                rnd = cand_q.get(timeout=_ACTOR_POLL_SEC)
            except queue.Empty:
                continue
            try:
                rewards = _evaluate(rnd)
                # res_q is unbounded: block=False documents (and the
                # lint enforces) that no actor can park here
                res_q.put(("ok", rnd, rewards), block=False)
            except (PreemptedError, DispatchHungError) as e:
                # graceful shutdown / wedged backend: the whole fleet
                # stops and the error takes the exit-77 restart path
                res_q.put(("fatal", rnd, e), block=False)
                stop_event.set()
                return
            except (ArithmeticError, RuntimeError, ValueError, OSError) as e:
                res_q.put(("err", rnd, e), block=False)

    threads = [
        threading.Thread(target=_actor, args=(i,), daemon=True,
                         name=f"pipeline-actor-{fold}-{i}")
        for i in range(actors)
    ]
    for th in threads:
        th.start()

    # ---------------- learner (the calling thread) --------------------
    # replayed-pending trials (the rounds the uninterrupted run had in
    # flight at the resume point) dispatch FIRST, grouped back into
    # their original rounds (round r covers ids [r*K, (r+1)*K))
    initial_rounds: list[list[int]] = []
    for tid in tpe.pending_ids:
        if initial_rounds and tid // trial_batch \
                == initial_rounds[-1][0] // trial_batch:
            initial_rounds[-1].append(tid)
        else:
            initial_rounds.append([tid])
    next_round = 0
    inflight = 0
    buffered: dict[int, tuple[str, _Round, object]] = {}
    next_to_process = 0
    rounds_processed = 0
    trials_appended = 0
    # completions that arrived before an earlier round finished: they
    # buffer here and apply in id order, so the TPE itself never sees
    # a reorder — this counter is the stamped out-of-order evidence
    tell_reorders = 0
    first_ok_seen = False
    fatal: BaseException | None = None

    def _ask_next() -> _Round | None:
        """Ask (or adopt the next replayed-pending) round, in strict
        round order — called exactly once per freed in-flight slot, so
        every ask sees the deterministic told/pending horizon."""
        nonlocal next_round
        if initial_rounds:
            ids = initial_rounds.pop(0)
            proposals = [tpe.pending_proposal(t) for t in ids]
        else:
            t_base = tpe._next_trial_id
            if t_base >= num_search:
                return None
            k_eff = min(trial_batch, num_search - t_base)
            tagged = tpe.ask_tagged(k_eff)
            ids = [tid for tid, _p in tagged]
            proposals = [p for _tid, p in tagged]
        rnd = _build_round(
            next_round, ids, proposals, trial_batch=trial_batch,
            num_policy=num_policy, num_op=num_op, key_fold=key_fold)
        next_round += 1
        return rnd

    def _submit_one() -> bool:
        nonlocal inflight
        if inflight >= max_inflight:
            return False
        rnd = _ask_next()
        if rnd is None:
            return False
        # capacity is accounted above, so this put cannot block; the
        # timeout is a belt-and-braces bound, never a wait we expect
        cand_q.put(rnd, timeout=60.0)
        inflight += 1
        return True

    def _process(kind: str, rnd: _Round, payload) -> None:
        """Apply one completed round: tells in id order, log append,
        persist, heartbeat — then immediately refill ONE slot so every
        ask sees the canonical horizon."""
        nonlocal rounds_processed, trials_appended, first_ok_seen
        if kind == "ok":
            rewards = list(payload)
            failure = None
        else:
            worst = tpe.worst_told()
            record_quarantine(
                rnd.t_base, rnd.t_base + rnd.k_eff, payload, worst)
            rewards = [worst] * rnd.k_eff
            failure = {"quarantined": True,
                       "error": f"{type(payload).__name__}: {payload}"}
        for tid, r in zip(rnd.ids, rewards):
            tpe.tell(tid, r)
            # journal evidence (no-op with telemetry off): one typed
            # event per trial told, in trial-id order like the log
            telemetry.emit("trial", f"fold{fold}", fold=fold, trial=tid,
                           reward=float(r),
                           quarantined=failure is not None)
        fold_trials.extend(
            (p, r) if failure is None else (p, r, failure)
            for p, r in zip(rnd.proposals, rewards))
        trials_appended += rnd.k_eff
        rounds_processed += 1
        persist()
        if heartbeat is not None:
            heartbeat()
        if kind == "ok" and not first_ok_seen:
            first_ok_seen = True
            if on_first_ok is not None:
                on_first_ok()
        best = tpe.best_told
        logger.info(
            "phase2 fold %d trials %d-%d/%d (async round %d, %d in flight):"
            " best_in_round=%.4f best=%.4f",
            fold, rnd.t_base, rnd.t_base + rnd.k_eff - 1, num_search,
            rnd.idx, inflight, max(rewards), best[1] if best else 0.0)

    def _check_stop() -> None:
        nonlocal fatal
        if fatal is None and preemption_requested():
            fatal = PreemptedError(
                f"preempted mid-pipeline (fold {fold}): processed rounds "
                "are persisted; resume replays the trial log")
        if fatal is None and should_stop is not None:
            fatal = should_stop()
        if fatal is not None:
            raise fatal

    try:
        while True:
            _check_stop()
            # keep the in-flight window full (initial fill; afterwards
            # _process refills one slot per completed round)
            while _submit_one():
                pass
            if inflight == 0:
                break  # budget exhausted and everything processed
            try:
                kind, rnd, payload = res_q.get(timeout=_POLL_SEC)
            except queue.Empty:
                continue
            if kind == "fatal":
                fatal = payload
                raise fatal
            if rnd.idx != next_to_process:
                tell_reorders += 1
            buffered[rnd.idx] = (kind, rnd, payload)
            # strict in-order processing with one refill per round:
            # the ask horizon stays a pure function of the geometry
            while next_to_process in buffered:
                k, r, p = buffered.pop(next_to_process)
                inflight -= 1
                _process(k, r, p)
                next_to_process += 1
                _submit_one()
    finally:
        stop_event.set()
        # graceful preemption waits out the in-flight dispatches
        # (exiting the process mid-XLA-dispatch aborts the runtime with
        # std::terminate instead of the contract's exit 77); a hung
        # dispatch keeps the short budget — the watchdog already
        # declared that thread unrecoverable and exit must not block
        budget = (_PREEMPT_DRAIN_SEC if isinstance(fatal, PreemptedError)
                  else _JOIN_SEC)
        deadline = time.monotonic() + budget
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        alive = [th.name for th in threads if th.is_alive()]
        if alive:
            logger.warning(
                "pipeline fold %d: %d actor thread(s) still running at "
                "shutdown (%s) — daemon threads, in-flight dispatch "
                "results are discarded", fold, len(alive), ", ".join(alive))

    return {
        "actors": actors,
        "queue_depth": queue_depth,
        "max_inflight": max_inflight,
        "rounds": rounds_processed,
        "trials": trials_appended,
        "tell_reorders": tell_reorders + tpe.tell_reorders,
    }


def run_overlapped_phases(
    fold_list: list[int],
    phase1_fn: Callable[[int], None],
    phase2_fn: Callable[[int], object],
    *,
    poll_sec: float = 0.5,
) -> dict:
    """Overlap phase-1 fold training with phase-2 search: a trainer
    thread runs ``phase1_fn(fold)`` (train + quality gate) fold by
    fold, and the calling thread runs ``phase2_fn(fold)`` the moment
    that fold is ready — fold k's TPE trials dispatch while fold k+1's
    training is still in flight (the MPMD fleet-as-pipeline seed,
    arXiv:2412.14374, on one host).

    Phase-2 folds still run in fold order, so every artifact (trial
    logs, final policy set) is identical to the sequential schedule —
    only the wall-clock interleaving changes.  A trainer-thread
    exception (including ``PreemptedError`` from a SIGTERM mid-train)
    re-raises HERE, with its original type, at the next poll boundary;
    a phase-2 exception stops the trainer between folds (mid-fold
    training still honors the global preemption flag at dispatch
    boundaries).

    Returns the overlap timeline: per-fold phase-1/phase-2 start/end
    wall times plus the measured overlap seconds — the evidence the
    phase-overlap e2e test asserts on."""
    cond = threading.Condition()
    ready: dict[int, float] = {}
    trainer_error: list[BaseException] = []
    stop = threading.Event()
    timeline: dict = {
        "phase1": {}, "phase2": {},
        "folds": [int(f) for f in fold_list],
    }

    def _trainer():
        for f in fold_list:
            if stop.is_set():
                return
            t0 = wall()
            t0m = telemetry.mono()
            try:
                phase1_fn(f)
            except BaseException as e:
                with cond:
                    trainer_error.append(e)
                    cond.notify_all()
                return
            telemetry.phase_event(f"phase1-fold{f}", t0m, telemetry.mono(),
                                  fold=int(f), lane="phase1")
            with cond:
                timeline["phase1"][str(f)] = {"start": t0,
                                              "end": wall()}
                ready[f] = wall()
                cond.notify_all()
        with cond:
            cond.notify_all()

    th = threading.Thread(target=_trainer, daemon=True,
                          name="phase1-trainer")
    th.start()
    try:
        for f in fold_list:
            with cond:
                while f not in ready and not trainer_error:
                    cond.wait(timeout=poll_sec)
                if trainer_error:
                    raise trainer_error[0]
            t0 = wall()
            t0m = telemetry.mono()
            phase2_fn(f)
            telemetry.phase_event(f"phase2-fold{f}", t0m, telemetry.mono(),
                                  fold=int(f), lane="phase2")
            timeline["phase2"][str(f)] = {"start": t0, "end": wall()}
    except BaseException as e:
        stop.set()
        if isinstance(e, PreemptedError):
            # the trainer polls the same global preemption flag at its
            # dispatch boundaries: give it a bounded window to
            # checkpoint the in-flight fold before exit 77 (its own
            # PreemptedError lands in trainer_error, already raised)
            th.join(timeout=_PREEMPT_DRAIN_SEC)
        raise
    deadline = time.monotonic() + _JOIN_SEC
    th.join(timeout=max(0.0, deadline - time.monotonic()))

    # overlap evidence: seconds during which some fold's phase-2 ran
    # while a LATER fold's phase-1 was still training
    overlap = 0.0
    for f in fold_list:
        p2 = timeline["phase2"].get(str(f))
        if not p2:
            continue
        for g in fold_list:
            if g <= f:
                continue
            p1 = timeline["phase1"].get(str(g))
            if not p1:
                continue
            overlap += max(0.0, min(p2["end"], p1["end"])
                           - max(p2["start"], p1["start"]))
    timeline["overlap_secs"] = round(overlap, 6)
    return timeline
