"""Async actor/learner search pipeline — overlap TPE math with device TTA.

The serial phase-2 scheduler (``search/driver.py``) alternates host-side
TPE math (ask, decode, tensor build, fsync persistence) and device TTA
dispatches strictly back to back: the device idles through every host
step and the host idles through every dispatch.  Density-matching search
never trains inside the loop, so its cost is PURE evaluation throughput
— the dispatch gaps are the whole remaining overhead (PRs 1-4 made the
dispatches themselves fast).

This module restructures one fold's trial budget as a streaming
ask-tell service in the Podracer actor/learner mold (arXiv:2104.06272):

- a bounded CANDIDATE QUEUE of ready-to-dispatch rounds (policy tensors
  + per-trial PRNG keys, built on the host while the device is busy);
- device ACTOR threads that pull rounds and run the existing
  ``_FoldEval`` TTA dispatches (the jitted steps are shared — actors
  reuse one executable, and the watchdog's label state is lock-guarded
  for exactly this concurrency);
- the TPE LEARNER (the calling thread) digests completed results and
  refills proposals concurrently, applying tells strictly in TRIAL-ID
  ORDER through the proposal ledger (``tpe.ask_tagged`` /
  ``tell(trial_id, ...)``) with a reorder buffer for rounds that finish
  out of order.

DETERMINISM is the design constraint that makes async mode testable and
resumable: the learner asks round ``r`` immediately after processing
round ``r - max_inflight`` (``max_inflight = actors + queue_depth``),
so the posterior behind every proposal is a pure function of
``(seed, K, actors, queue_depth)`` — real rewards for processed rounds,
constant-liar placeholders for the in-flight window — REGARDLESS of
completion timing.  Rewards are per-trial-id keyed, the trial log is
appended in id order, and a resume replays the exact ask/tell
interleaving from that log (:func:`replay_trial_log`), so an
interrupted async search completes to the same ``final_policy.json``
as an uninterrupted one.  With ``actors=1, queue_depth=0`` the
in-flight window is one round and the pipeline reproduces the serial
scheduler's trial log bit-for-bit.

:func:`run_overlapped_phases` is the second overlap axis — the
single-host seed of the fleet-as-pipeline direction (MPMD pipeline
parallelism, arXiv:2412.14374): phase-1 fold training runs on a trainer
thread and each fold is handed to phase-2 evaluation the moment its
training (and quality gate) completes, while the remaining folds still
train.

:class:`DispatchTrace` records per-dispatch start/end timestamps so
``tools/bench_pipeline.py`` can report the dispatch-gap histogram
(p50/p99 inter-dispatch idle, device busy fraction) for serial vs async
runs; ``search_result.json`` stamps the summary under ``pipeline``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable

import numpy as np

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.resilience import (
    DispatchHungError,
    PreemptedError,
    preemption_requested,
)
from fast_autoaugment_tpu.core.telemetry import wall
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["DispatchTrace", "replay_trial_log", "run_fold_pipeline",
           "run_overlapped_phases", "resolve_async_pipeline",
           "FleetTransport", "RemoteEvalError", "run_fleet_actor",
           "resolve_search_role", "SEARCH_ROLE_ENV_VAR",
           "FLEET_TRANSPORT_ENV_VAR"]

logger = get_logger("faa_tpu.pipeline")

#: learner poll quantum for the results queue — every blocking wait in
#: this module is bounded (lint R7), so preemption and actor failures
#: are noticed within this window
_POLL_SEC = 0.2
#: actor poll quantum for the candidate queue
_ACTOR_POLL_SEC = 0.2
#: bounded-join budget when shutting the actor fleet down (daemon
#: threads: a genuinely wedged dispatch cannot block process exit)
_JOIN_SEC = 5.0
#: on preemption, the overlapped phase-1 trainer gets this long to
#: reach its next dispatch boundary and checkpoint before the process
#: exits 77 — losing that checkpoint is still CORRECT (the resume
#: retrains deterministically) but wastes the fold's progress
_PREEMPT_DRAIN_SEC = 30.0

#: dispatch-gap histogram bucket edges (seconds)
_GAP_BUCKETS = (0.001, 0.01, 0.1, 1.0)


def resolve_async_pipeline(spec) -> bool:
    """``--async-pipeline {off,on}`` (or a bool) to a bool.  Anything
    unrecognized raises — a typo must not silently fall back to the
    serial scheduler."""
    if isinstance(spec, bool):
        return spec
    if spec is None:
        return False
    s = str(spec).strip().lower()
    if s in ("off", "0", "false", ""):
        return False
    if s in ("on", "1", "true"):
        return True
    raise ValueError(f"async_pipeline must be 'off' or 'on', got {spec!r}")


#: per-host role export for fleet-search launches (the fleet launcher's
#: ``--roles`` writes it, ``search_cli --search-role auto`` reads it —
#: the same launcher/worker env handoff as FAA_HOST_ID/FAA_ATTEMPT)
SEARCH_ROLE_ENV_VAR = "FAA_SEARCH_ROLE"
#: shared transport-dir handoff (the fleet launcher's
#: ``--fleet-transport`` exports it, mirroring FAA_COMPILE_CACHE /
#: FAA_TELEMETRY — every host launch AND retry inherits it)
FLEET_TRANSPORT_ENV_VAR = "FAA_FLEET_TRANSPORT"

_SEARCH_ROLES = ("learner", "actor")


def resolve_search_role(spec: str | None) -> str:
    """``--search-role {auto,learner,actor}`` to a concrete role.
    ``auto`` (or None) reads :data:`SEARCH_ROLE_ENV_VAR` and defaults
    to ``learner`` — a plain single-host launch is a learner.  Unknown
    roles raise: a typo'd role must not silently train."""
    s = ("auto" if spec is None else str(spec)).strip().lower()
    if s == "auto":
        s = os.environ.get(SEARCH_ROLE_ENV_VAR, "").strip().lower() \
            or "learner"
    if s not in _SEARCH_ROLES:
        raise ValueError(
            f"search role must be one of {('auto',) + _SEARCH_ROLES}, "
            f"got {spec!r} (env {SEARCH_ROLE_ENV_VAR}="
            f"{os.environ.get(SEARCH_ROLE_ENV_VAR)!r})")
    return s


class DispatchTrace:
    """Thread-safe per-dispatch ``(start, end)`` recorder with named
    segments (one per fold's phase-2 trial loop).

    Actors record concurrently, so busy time is the UNION of the
    recorded windows per segment and a "gap" is an idle interval
    between merged windows — the quantity the async pipeline exists to
    drive to ~0.  :meth:`summary` pools gaps across segments into
    p50/p99 plus a log-bucket histogram and reports the device busy
    fraction sum(busy)/sum(span)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._segments: dict[str, list[tuple[float, float]]] = {}
        self._current: str | None = None

    def begin_segment(self, name: str) -> None:
        with self._lock:
            self._current = name
            self._segments.setdefault(name, [])

    def end_segment(self) -> None:
        with self._lock:
            self._current = None

    def record(self, t0: float, t1: float) -> None:
        """One dispatch window (monotonic seconds).  Ignored outside an
        open segment — phase-1 gate baselines and the audit share the
        evaluator but are not phase-2 dispatch-gap evidence."""
        with self._lock:
            if self._current is not None:
                self._segments[self._current].append((float(t0), float(t1)))

    @staticmethod
    def _merge(windows: list[tuple[float, float]]):
        merged: list[list[float]] = []
        for t0, t1 in sorted(windows):
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        return merged

    def summary(self) -> dict | None:
        """Aggregate dispatch-gap statistics, or None when nothing was
        recorded."""
        with self._lock:
            segments = {k: list(v) for k, v in self._segments.items() if v}
        if not segments:
            return None
        busy = span = 0.0
        gaps: list[float] = []
        n = 0
        for windows in segments.values():
            merged = self._merge(windows)
            busy += sum(t1 - t0 for t0, t1 in merged)
            span += merged[-1][1] - merged[0][0]
            gaps.extend(b[0] - a[1] for a, b in zip(merged, merged[1:]))
            n += len(windows)
        gaps_arr = np.asarray(gaps, np.float64)
        hist = {}
        if len(gaps_arr):
            edges = (0.0,) + _GAP_BUCKETS + (float("inf"),)
            for lo, hi in zip(edges, edges[1:]):
                label = (f"<{hi * 1000:g}ms" if hi != float("inf")
                         else f">={lo * 1000:g}ms")
                hist[label] = int(((gaps_arr >= lo) & (gaps_arr < hi)).sum())
        return {
            "num_dispatches": n,
            "num_segments": len(segments),
            "busy_secs": round(busy, 6),
            "span_secs": round(span, 6),
            "device_busy_frac": round(busy / span, 6) if span > 0 else None,
            "num_gaps": len(gaps),
            "gap_p50_ms": (round(float(np.percentile(gaps_arr, 50)) * 1e3, 3)
                           if len(gaps_arr) else None),
            "gap_p99_ms": (round(float(np.percentile(gaps_arr, 99)) * 1e3, 3)
                           if len(gaps_arr) else None),
            "gap_total_secs": round(float(gaps_arr.sum()), 6),
            "gap_hist": hist,
        }


def replay_trial_log(tpe, fold_trials: list, trial_batch: int,
                     num_search: int, max_inflight: int = 1) -> None:
    """Replay a (trial-id-ordered) trial log through the proposal
    ledger so a resumed async search continues EXACTLY where the
    uninterrupted one would be.

    The canonical pipeline schedule asks round ``r`` immediately after
    telling round ``r - max_inflight`` — so the replay re-runs that
    exact ask/tell interleaving: rounds are re-asked (advancing the
    TPE's RNG stream precisely as the original run did — the legacy
    tell-only replay leaves the stream at its seed position, so a
    resumed serial run proposes a DIFFERENT future than an
    uninterrupted one) and told their logged rewards in id order, with
    the in-flight window held at `max_inflight` rounds.  The logged
    proposals are authoritative: they overwrite the regenerated ones
    in the ledger, so a log written under different flags degrades
    gracefully instead of silently diverging.  On return the ledger's
    PENDING trials are the rounds the uninterrupted run had in flight
    at this log state; :func:`run_fold_pipeline` dispatches those
    first (per-trial keys are id-derived, so their rewards are
    bit-identical to the uninterrupted run's)."""
    K = max(1, int(trial_batch))
    M = max(1, int(max_inflight))
    n = len(fold_trials)
    rounds: list[tuple[int, list]] = []
    t = 0
    while t < n:
        k_eff = min(K, num_search - t)
        if k_eff <= 0:  # over-full log (stale num_search): stop
            break
        rounds.append((t, fold_trials[t:t + k_eff]))
        t += k_eff

    def _ask_one_round() -> bool:
        t_base = tpe._next_trial_id
        if t_base >= num_search:
            return False
        tpe.ask_tagged(min(K, num_search - t_base))
        return True

    asked = 0
    for told, (t_base, entries) in enumerate(rounds):
        while asked < told + M and _ask_one_round():
            asked += 1
        for i, entry in enumerate(entries):
            tid = t_base + i
            tpe._pending[tid] = dict(entry[0])
            tpe.tell(tid, float(entry[1]))


class _Round:
    """One ask round, built host-side and ready to dispatch: its trial
    ``ids``, the padded policy tensor (K lanes for the compiled
    candidate axis), and the [K] key stack (lane i's key is
    ``fold_in(key_fold, ids[i])`` — identical to the serial
    scheduler's, so rewards are schedule-invariant)."""

    __slots__ = ("idx", "ids", "proposals", "policies_t", "keys")

    def __init__(self, idx, ids, proposals, policies_t, keys):
        self.idx = idx
        self.ids = ids
        self.proposals = proposals
        self.policies_t = policies_t
        self.keys = keys

    @property
    def t_base(self) -> int:
        return self.ids[0]

    @property
    def k_eff(self) -> int:
        return len(self.ids)


def _build_round(idx, ids, proposals, *, trial_batch, num_policy, num_op,
                 key_fold) -> _Round:
    import jax
    import jax.numpy as jnp

    from fast_autoaugment_tpu.policies.archive import (
        policy_decoder,
        policy_to_tensor,
    )

    k_eff = len(proposals)
    if trial_batch <= 1:
        policies_t = jnp.asarray(policy_to_tensor(
            policy_decoder(proposals[0], num_policy, num_op)))
        keys = jax.random.fold_in(key_fold, ids[0])
    else:
        padded = proposals + [proposals[-1]] * (trial_batch - k_eff)
        # padded lanes reuse the last real id's key stream continuation
        # (their results are dropped, exactly like the serial pad)
        key_ids = list(ids) + [ids[-1] + 1 + i
                               for i in range(trial_batch - k_eff)]
        policies_t = jnp.asarray(np.stack([
            np.asarray(policy_to_tensor(
                policy_decoder(p, num_policy, num_op)), np.float32)
            for p in padded
        ]))
        keys = jnp.stack([jax.random.fold_in(key_fold, t) for t in key_ids])
    return _Round(idx, list(ids), list(proposals), policies_t, keys)


class RemoteEvalError(RuntimeError):
    """A fleet ACTOR host's TTA evaluation failed; the learner rebuilds
    the failure from the reward-return payload.  ``str()`` carries the
    actor's already-formatted ``"Type: message"`` text, so quarantine
    records match the in-process scheduler's byte for byte."""


def _failure_text(exc: BaseException) -> str:
    """The trial log's quarantine error text for a failed evaluation —
    remote failures arrive pre-formatted by the actor host."""
    if isinstance(exc, RemoteEvalError):
        return str(exc)
    return f"{type(exc).__name__}: {exc}"


def _eval_round(evaluator, fold: int, params, batch_stats, rnd: _Round,
                trial_batch: int, fi=None, kill_check: bool = False):
    """One round's rewards through the shared ``_FoldEval`` machinery —
    the SAME call whether an in-process actor thread or a fleet actor
    host makes it, so a round's rewards are a pure function of
    (checkpoint, proposals, id-derived keys) wherever it runs."""
    if fi is not None:
        for t in rnd.ids:
            if kill_check:
                fi.maybe_kill_trial(t)
            if fi.trial_error_at(t):
                raise RuntimeError(f"injected trial_error at trial {t}")
    if trial_batch <= 1:
        metrics = evaluator.evaluate(
            fold, params, batch_stats, rnd.policies_t, rnd.keys)
        return [metrics["top1_valid"]]
    metrics_list = evaluator.evaluate_batch(
        fold, params, batch_stats, rnd.policies_t, rnd.keys)[:rnd.k_eff]
    return [m["top1_valid"] for m in metrics_list]


class _ThreadActorBackend:
    """In-process device actor threads + bounded candidate queue — the
    PR-9 single-host pipeline, now one of two interchangeable dispatch
    backends behind the learner loop (the other is
    :class:`_FleetRoundBackend`, the cross-host transport).

    ``submit`` builds the round's device tensors host-side (while the
    device is busy) and enqueues; actor threads pull, evaluate through
    :func:`_eval_round`, and push ``(kind, round, payload)`` results
    for ``poll``."""

    def __init__(self, evaluator, fold: int, params, batch_stats, *,
                 actors: int, trial_batch: int, max_inflight: int,
                 num_policy: int, num_op: int, key_fold):
        from fast_autoaugment_tpu.utils import faultinject

        self._evaluator = evaluator
        self._fold = fold
        self._params, self._batch_stats = params, batch_stats
        self._trial_batch = trial_batch
        self._num_policy, self._num_op = num_policy, num_op
        self._key_fold = key_fold
        self._fi = faultinject.active_plan()
        self._cand_q: queue.Queue = queue.Queue(maxsize=max_inflight)
        self._res_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._actor, daemon=True,
                             name=f"pipeline-actor-{fold}-{i}")
            for i in range(actors)
        ]
        for th in self._threads:
            th.start()

    def _actor(self) -> None:
        while not self._stop.is_set():
            try:
                rnd = self._cand_q.get(timeout=_ACTOR_POLL_SEC)
            except queue.Empty:
                continue
            try:
                rewards = _eval_round(
                    self._evaluator, self._fold, self._params,
                    self._batch_stats, rnd, self._trial_batch, self._fi)
                # res_q is unbounded: block=False documents (and the
                # lint enforces) that no actor can park here
                self._res_q.put(("ok", rnd, rewards), block=False)
            except (PreemptedError, DispatchHungError) as e:
                # graceful shutdown / wedged backend: the whole fleet
                # stops and the error takes the exit-77 restart path
                self._res_q.put(("fatal", rnd, e), block=False)
                self._stop.set()
                return
            except (ArithmeticError, RuntimeError, ValueError, OSError) as e:
                self._res_q.put(("err", rnd, e), block=False)

    def submit(self, rnd: _Round) -> None:
        rnd = _build_round(
            rnd.idx, rnd.ids, rnd.proposals, trial_batch=self._trial_batch,
            num_policy=self._num_policy, num_op=self._num_op,
            key_fold=self._key_fold)
        # capacity is accounted by the learner loop, so this put cannot
        # block; the timeout is a belt-and-braces bound, never a wait
        # we expect
        self._cand_q.put(rnd, timeout=60.0)

    def poll(self, timeout: float):
        try:
            return self._res_q.get(timeout=timeout)
        except queue.Empty:
            return None

    def shutdown(self, fatal: BaseException | None) -> None:
        self._stop.set()
        # graceful preemption waits out the in-flight dispatches
        # (exiting the process mid-XLA-dispatch aborts the runtime with
        # std::terminate instead of the contract's exit 77); a hung
        # dispatch keeps the short budget — the watchdog already
        # declared that thread unrecoverable and exit must not block
        budget = (_PREEMPT_DRAIN_SEC if isinstance(fatal, PreemptedError)
                  else _JOIN_SEC)
        deadline = time.monotonic() + budget
        for th in self._threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        alive = [th.name for th in self._threads if th.is_alive()]
        if alive:
            logger.warning(
                "pipeline fold %d: %d actor thread(s) still running at "
                "shutdown (%s) — daemon threads, in-flight dispatch "
                "results are discarded", self._fold, len(alive),
                ", ".join(alive))


def run_fold_pipeline(
    evaluator,
    fold: int,
    params,
    batch_stats,
    tpe,
    key_fold,
    fold_trials: list,
    *,
    num_search: int,
    trial_batch: int = 1,
    actors: int = 1,
    queue_depth: int = 1,
    num_policy: int,
    num_op: int,
    persist: Callable[[], None],
    record_quarantine: Callable[[int, int, BaseException, float], None],
    on_first_ok: Callable[[], None] | None = None,
    should_stop: Callable[[], BaseException | None] | None = None,
    heartbeat: Callable[[], None] | None = None,
    backend=None,
) -> dict:
    """One fold's full trial budget through the actor/learner pipeline.

    The caller (``search/driver.py``) has already replayed the resumed
    prefix of `fold_trials` through :func:`replay_trial_log`; this
    function evaluates every remaining trial, appends ``(proposal,
    reward)`` entries (plus the serial scheduler's quarantine-marker
    third element on failed rounds) to `fold_trials` IN TRIAL-ID ORDER,
    and calls `persist` after each processed round — the same
    crash-loses-at-most-the-in-flight-work contract as the serial
    scheduler, except the fsync now overlaps device work.

    `record_quarantine(trial_lo, trial_hi, exc, worst)` mirrors the
    serial ``_quarantine`` bookkeeping (the learner computes `worst` —
    the min reward told so far, in id order, so it is deterministic);
    ``PreemptedError``/``DispatchHungError`` from an actor stop the
    fleet and re-raise in the calling thread (exit-77 restart path,
    never quarantined).  `should_stop` is polled every learner
    iteration and may return an exception to raise at the next round
    boundary (the phase-overlap scheduler routes trainer-thread
    failures through it); SIGTERM/SIGUSR1 preemption is polled
    directly.

    `backend` selects the dispatch plane: None (default) builds the
    in-process :class:`_ThreadActorBackend` over `actors` device
    threads; a :class:`_FleetRoundBackend` routes the same rounds to
    ACTOR HOSTS over the shared-directory transport instead.  The
    learner loop — ask horizon, reorder buffer, id-order tells,
    persistence — is identical either way, which is why an N-host
    fleet reproduces the single-host trial log bit for bit.

    Returns accounting: rounds processed, trials appended, tell
    reorders observed, and the actor/queue geometry."""
    trial_batch = max(1, int(trial_batch))
    actors = max(1, int(actors))
    queue_depth = max(0, int(queue_depth))
    max_inflight = actors + queue_depth

    if backend is None:
        backend = _ThreadActorBackend(
            evaluator, fold, params, batch_stats, actors=actors,
            trial_batch=trial_batch, max_inflight=max_inflight,
            num_policy=num_policy, num_op=num_op, key_fold=key_fold)

    # ---------------- learner (the calling thread) --------------------
    # replayed-pending trials (the rounds the uninterrupted run had in
    # flight at the resume point) dispatch FIRST, grouped back into
    # their original rounds (round r covers ids [r*K, (r+1)*K))
    initial_rounds: list[list[int]] = tpe.pending_rounds(trial_batch)
    next_round = 0
    inflight = 0
    buffered: dict[int, tuple[str, _Round, object]] = {}
    next_to_process = 0
    rounds_processed = 0
    trials_appended = 0
    # completions that arrived before an earlier round finished: they
    # buffer here and apply in id order, so the TPE itself never sees
    # a reorder — this counter is the stamped out-of-order evidence
    tell_reorders = 0
    first_ok_seen = False
    fatal: BaseException | None = None

    def _ask_next() -> _Round | None:
        """Ask (or adopt the next replayed-pending) round, in strict
        round order — called exactly once per freed in-flight slot, so
        every ask sees the deterministic told/pending horizon.  The
        round is LIGHT (ids + proposals only): the backend decides
        where and when the device tensors get built."""
        nonlocal next_round
        if initial_rounds:
            ids = initial_rounds.pop(0)
            proposals = tpe.round_payload(ids)
        else:
            t_base = tpe._next_trial_id
            if t_base >= num_search:
                return None
            k_eff = min(trial_batch, num_search - t_base)
            tagged = tpe.ask_tagged(k_eff)
            ids = [tid for tid, _p in tagged]
            proposals = [p for _tid, p in tagged]
        rnd = _Round(next_round, list(ids), list(proposals), None, None)
        next_round += 1
        return rnd

    def _submit_one() -> bool:
        nonlocal inflight
        if inflight >= max_inflight:
            return False
        rnd = _ask_next()
        if rnd is None:
            return False
        backend.submit(rnd)
        inflight += 1
        return True

    def _process(kind: str, rnd: _Round, payload) -> None:
        """Apply one completed round: tells in id order, log append,
        persist, heartbeat — then immediately refill ONE slot so every
        ask sees the canonical horizon."""
        nonlocal rounds_processed, trials_appended, first_ok_seen
        if kind == "ok":
            rewards = list(payload)
            failure = None
        else:
            worst = tpe.worst_told()
            record_quarantine(
                rnd.t_base, rnd.t_base + rnd.k_eff, payload, worst)
            rewards = [worst] * rnd.k_eff
            failure = {"quarantined": True,
                       "error": _failure_text(payload)}
        for tid, r in zip(rnd.ids, rewards):
            tpe.tell(tid, r)
            # journal evidence (no-op with telemetry off): one typed
            # event per trial told, in trial-id order like the log
            telemetry.emit("trial", f"fold{fold}", fold=fold, trial=tid,
                           reward=float(r),
                           quarantined=failure is not None)
        fold_trials.extend(
            (p, r) if failure is None else (p, r, failure)
            for p, r in zip(rnd.proposals, rewards))
        trials_appended += rnd.k_eff
        rounds_processed += 1
        persist()
        if heartbeat is not None:
            heartbeat()
        if kind == "ok" and not first_ok_seen:
            first_ok_seen = True
            if on_first_ok is not None:
                on_first_ok()
        best = tpe.best_told
        logger.info(
            "phase2 fold %d trials %d-%d/%d (async round %d, %d in flight):"
            " best_in_round=%.4f best=%.4f",
            fold, rnd.t_base, rnd.t_base + rnd.k_eff - 1, num_search,
            rnd.idx, inflight, max(rewards), best[1] if best else 0.0)

    def _check_stop() -> None:
        nonlocal fatal
        if fatal is None and preemption_requested():
            fatal = PreemptedError(
                f"preempted mid-pipeline (fold {fold}): processed rounds "
                "are persisted; resume replays the trial log")
        if fatal is None and should_stop is not None:
            fatal = should_stop()
        if fatal is not None:
            raise fatal

    try:
        while True:
            _check_stop()
            # keep the in-flight window full (initial fill; afterwards
            # _process refills one slot per completed round)
            while _submit_one():
                pass
            if inflight == 0:
                break  # budget exhausted and everything processed
            item = backend.poll(_POLL_SEC)
            if item is None:
                continue
            kind, rnd, payload = item
            if kind == "fatal":
                fatal = payload
                raise fatal
            if rnd.idx != next_to_process:
                tell_reorders += 1
            buffered[rnd.idx] = (kind, rnd, payload)
            # strict in-order processing with one refill per round:
            # the ask horizon stays a pure function of the geometry
            while next_to_process in buffered:
                k, r, p = buffered.pop(next_to_process)
                inflight -= 1
                _process(k, r, p)
                next_to_process += 1
                _submit_one()
    finally:
        backend.shutdown(fatal)

    return {
        "actors": actors,
        "queue_depth": queue_depth,
        "max_inflight": max_inflight,
        "rounds": rounds_processed,
        "trials": trials_appended,
        "tell_reorders": tell_reorders + tpe.tell_reorders,
    }


class FleetTransport:
    """Cross-host round transport for the fleet search — the promotion
    of the in-process candidate queue to shared-directory MPMD plumbing
    (the Podracer/MPMD shape from PAPERS.md: a learner host drives the
    proposal ledger while dedicated actor hosts stream TTA dispatches).

    The LEARNER host publishes each ask round as a leased work unit
    (trial ids + proposals — a few hundred bytes of JSON); ACTOR hosts
    claim rounds through the PR-6 lease protocol, rebuild the policy
    tensors and id-derived PRNG keys locally (:func:`_build_round` is a
    pure function of the payload), run the shared ``_FoldEval`` TTA
    dispatches against the published gate-cleared fold checkpoint, and
    post rewards back as done-marker ``info`` payloads.  Because every
    reward is a pure function of (checkpoint digest, proposals,
    id-derived keys), ANY actor computes the same answer — which is
    what lets the lease TTL + steal fence reclaim a SIGKILLed actor's
    round and still reproduce the single-host artifacts bit for bit.

    Layout under ``root`` (a directory every host mounts — the same
    assumption the shared ``save_dir`` scatter already makes)::

        work/p2r-f<fold>-t<t_base>.json   round payloads (the claim menu)
        leases/ done/ hosts/              the PR-6 lease protocol
        ckpt/fold<k>.json                 checkpoint-published markers
        search_done.json                  the learner's terminal marker

    Round units are keyed by ``t_base`` (the round's first trial id),
    which is stable across learner resumes — a resumed learner
    republishes byte-identical payloads onto the same units and adopts
    any results actors posted while it was down.  Journal evidence:
    typed ``round`` events (``publish``/``claim``/``return``/``apply``)
    carry the transport latencies ``tools/bench_fleet_search.py``
    reports."""

    UNIT_PREFIX = "p2r-"

    def __init__(self, root: str, owner: str, *,
                 lease_ttl: float | None = None, role: str | None = None):
        from fast_autoaugment_tpu.launch.workqueue import (
            DEFAULT_LEASE_TTL_SEC,
            WorkQueue,
        )

        self.wq = WorkQueue(
            root, owner,
            lease_ttl=DEFAULT_LEASE_TTL_SEC if lease_ttl is None
            else float(lease_ttl))
        self.root = self.wq.root
        self.owner = self.wq.owner
        self.role = role
        self._ckpt_dir = os.path.join(self.root, "ckpt")
        os.makedirs(self._ckpt_dir, exist_ok=True)

    # ------------------------------------------------ identity/liveness
    def beat(self, extra: dict | None = None) -> None:
        """Host liveness beat, stamped with this host's fleet-search
        role (the status tool renders the topology from these)."""
        rec = dict(extra or {})
        if self.role:
            rec.setdefault("role", self.role)
        self.wq.beat_host(rec)

    def mark_host_done(self, info: dict | None = None) -> None:
        rec = dict(info or {})
        if self.role:
            rec.setdefault("role", self.role)
        self.wq.mark_host_done(rec)

    def accounting(self) -> dict:
        return self.wq.accounting()

    # ------------------------------------------------------- round units
    @classmethod
    def round_unit(cls, fold: int, t_base: int) -> str:
        """Unit id for the round whose first trial id is `t_base` —
        trial-id keyed, so resumes can never collide two different
        rounds onto one unit (round indices restart at 0 per process;
        trial ids never do)."""
        return f"{cls.UNIT_PREFIX}f{int(fold)}-t{int(t_base):06d}"

    def publish_round(self, fold: int, rnd: _Round, *, key_seed: int,
                      trial_batch: int, num_policy: int,
                      num_op: int) -> str:
        """Mint the round's work unit (atomic payload write) — the
        learner-side cost of handing a round to the fleet is this one
        write, measured into the ``publish`` journal event."""
        unit = self.round_unit(fold, rnd.t_base)
        t0 = telemetry.mono()
        self.wq.publish_unit(unit, {
            "fold": int(fold), "round_idx": int(rnd.idx),
            "t_base": int(rnd.t_base),
            "ids": [int(t) for t in rnd.ids],
            "proposals": rnd.proposals,
            "trial_batch": int(trial_batch),
            "num_policy": int(num_policy), "num_op": int(num_op),
            "key_seed": int(key_seed),
        })
        telemetry.emit("round", unit, action="publish", fold=int(fold),
                       round_idx=int(rnd.idx), t_base=int(rnd.t_base),
                       k=rnd.k_eff,
                       publish_secs=round(telemetry.mono() - t0, 6))
        return unit

    def open_rounds(self) -> list[str]:
        """Published round units with no posted result yet (sorted by
        fold then t_base — zero-padded ids keep the lexicographic order
        numeric)."""
        return self.wq.open_units(self.UNIT_PREFIX)

    def poll_round(self, fold: int, t_base: int):
        """Learner-side result check: ``None`` while the round is in
        flight, else ``("ok", rewards)`` or ``("err", RemoteEvalError)``
        from the done marker an actor posted.  Emits the ``apply``
        journal event with the return->apply latency and the evaluating
        host's identity."""
        unit = self.round_unit(fold, t_base)
        t0 = telemetry.mono()
        rec = self.wq.done_record(unit)
        if rec is None:
            return None
        info = rec.get("info") or {}
        completed = rec.get("completed_at")
        lat_ms = (round((wall() - float(completed)) * 1e3, 3)
                  if isinstance(completed, (int, float)) else None)
        telemetry.emit("round", unit, action="apply", fold=int(fold),
                       t_base=int(t_base),
                       poll_secs=round(telemetry.mono() - t0, 6),
                       return_to_apply_ms=lat_ms,
                       evaluated_by=rec.get("owner"),
                       lease_attempt=int(rec.get("attempt", 1)))
        if "rewards" in info:
            return ("ok", [float(r) for r in info["rewards"]])
        return ("err", RemoteEvalError(
            str(info.get("error")
                or "actor host evaluation failed (no detail posted)")))

    def post_result(self, unit: str, payload: dict, result: dict) -> None:
        """Actor-side reward return: release the unit with the rewards
        (or the failure text) riding the done marker."""
        self.wq.release(unit, info=result)
        telemetry.emit("round", unit, action="return",
                       fold=int(payload.get("fold", -1)),
                       t_base=int(payload.get("t_base", -1)),
                       ok="rewards" in result,
                       eval_secs=result.get("eval_secs"))

    def learner_backend(self, fold: int, *, key_seed: int,
                        trial_batch: int, num_policy: int, num_op: int):
        """The dispatch backend :func:`run_fold_pipeline` plugs in to
        route this fold's rounds over the fleet instead of in-process
        actor threads."""
        return _FleetRoundBackend(
            self, fold, key_seed=key_seed, trial_batch=trial_batch,
            num_policy=num_policy, num_op=num_op)

    # ------------------------------------------- checkpoint publication
    def _ckpt_marker(self, fold: int) -> str:
        return os.path.join(self._ckpt_dir, f"fold{int(fold)}.json")

    def publish_checkpoint(self, fold: int, path: str) -> dict:
        """Announce a gate-cleared fold checkpoint to the fleet: the
        trainer host writes the marker (name + sha256 digest from the
        PR-5 sidecar) the moment the quality gate clears —
        ``run_overlapped_phases`` generalized across processes.  The
        payload itself already lives in the shared ``save_dir``."""
        from fast_autoaugment_tpu.core.checkpoint import read_metadata
        from fast_autoaugment_tpu.search.driver import write_json_atomic

        meta = read_metadata(path) or {}
        rec = {"fold": int(fold), "name": os.path.basename(path),
               "digest": meta.get("digest"), "epoch": meta.get("epoch")}
        write_json_atomic(self._ckpt_marker(fold), rec)
        telemetry.emit("checkpoint", f"fold{int(fold)}", action="publish",
                       fold=int(fold), digest=rec["digest"])
        return rec

    def checkpoint_record(self, fold: int) -> dict | None:
        from fast_autoaugment_tpu.launch.workqueue import _read_json

        return _read_json(self._ckpt_marker(fold))

    def wait_checkpoint(self, fold: int, local_path: str, *,
                        timeout: float = 900.0, poll_sec: float = 0.5,
                        should_stop=None) -> dict:
        """Actor-side: block until the fold's marker exists AND the
        locally visible sidecar digest matches it (a lagging shared
        filesystem must never evaluate against a half-synced
        checkpoint).  Raises ``TimeoutError`` past `timeout` — the
        actor exits nonzero and its lease-stale rounds go to a
        survivor with a fresher view."""
        from fast_autoaugment_tpu.core.checkpoint import read_metadata

        deadline = time.monotonic() + float(timeout)
        while True:
            rec = self.checkpoint_record(fold)
            if rec is not None:
                meta = read_metadata(local_path) or {}
                if not rec.get("digest") \
                        or meta.get("digest") == rec.get("digest"):
                    return rec
            if preemption_requested():
                raise PreemptedError(
                    f"preempted while waiting for fold {fold}'s published "
                    "checkpoint")
            if should_stop is not None:
                err = should_stop()
                if err is not None:
                    raise err
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fold {fold} checkpoint was not published (or never "
                    f"matched digest {rec and rec.get('digest')!r} "
                    f"locally) within {timeout:.0f}s of claiming its round")
            time.sleep(poll_sec)  # robust: allow — deadline-bounded, preemption-polled publish wait

    # --------------------------------------------------- terminal marker
    @property
    def _search_done_path(self) -> str:
        return os.path.join(self.root, "search_done.json")

    def mark_search_done(self, info: dict | None = None) -> None:
        """The learner's terminal marker: actor hosts drain their idle
        poll and exit 0 once it exists and no open rounds remain."""
        from fast_autoaugment_tpu.search.driver import write_json_atomic

        write_json_atomic(self._search_done_path,
                          dict(info or {}, done=True))
        telemetry.emit("mark", "fleet-search", kind="search_done")

    def search_done(self) -> bool:
        from fast_autoaugment_tpu.launch.workqueue import _read_json

        return _read_json(self._search_done_path) is not None


class _FleetRoundBackend:
    """Learner-side dispatch backend over :class:`FleetTransport`:
    ``submit`` publishes the round as a leased work unit, ``poll``
    scans the outstanding rounds' done markers for posted rewards.
    The learner loop upstream is byte-identical to the thread-backend
    path — same ask horizon, same reorder buffer, same id-order tells
    — so the fleet reproduces the single-host trial log bit for bit
    when launched with the same ``actors + queue_depth`` window."""

    def __init__(self, transport: FleetTransport, fold: int, *,
                 key_seed: int, trial_batch: int, num_policy: int,
                 num_op: int, poll_quantum: float = 0.05):
        self._transport = transport
        self._fold = int(fold)
        self._key_seed = int(key_seed)
        self._trial_batch = int(trial_batch)
        self._num_policy, self._num_op = int(num_policy), int(num_op)
        self._poll_quantum = float(poll_quantum)
        self._outstanding: dict[int, _Round] = {}

    def submit(self, rnd: _Round) -> None:
        self._transport.publish_round(
            self._fold, rnd, key_seed=self._key_seed,
            trial_batch=self._trial_batch, num_policy=self._num_policy,
            num_op=self._num_op)
        self._outstanding[rnd.idx] = rnd

    def poll(self, timeout: float):
        for idx in sorted(self._outstanding):
            rnd = self._outstanding[idx]
            res = self._transport.poll_round(self._fold, rnd.t_base)
            if res is not None:
                kind, payload = res
                return (kind, self._outstanding.pop(idx), payload)
        # one bounded nap per empty scan (the learner loop re-polls);
        # the scan itself is a handful of stat/read calls, so the
        # learner-side cost per round stays far under the ask() wall
        time.sleep(min(float(timeout), self._poll_quantum))
        return None

    def shutdown(self, fatal: BaseException | None) -> None:
        # nothing to tear down: published rounds STAY in the queue — a
        # resumed learner republishes identical payloads onto the same
        # t_base-keyed units and adopts whatever results actors posted
        # while it was down
        return None


def _load_fold_resilient(evaluator, fold: int, path: str, *,
                         budget_s: float = 60.0):
    """Digest-verified checkpoint read with bounded backoff: on a
    lagging shared filesystem the published marker can match the
    sidecar while the PAYLOAD is still half-synced (or a read returns
    transient EIO/stale bytes), so the digest check inside
    ``load_checkpoint`` raises — treat that as not-yet-visible and
    retry until the budget, then raise a typed ``TimeoutError`` (the
    actor's loud-exit contract; its rounds go to a survivor with a
    fresher view)."""
    from fast_autoaugment_tpu.core.resilience import CheckpointCorruptError

    deadline = time.monotonic() + float(budget_s)
    delay = 0.1
    while True:
        try:
            return evaluator.load_fold(path)
        except (CheckpointCorruptError, OSError) as e:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fold {fold} checkpoint at {path} never became "
                    f"readable/digest-clean within {budget_s:.0f}s "
                    f"(last error: {type(e).__name__}: {e}) — "
                    "half-synced shared filesystem?") from e
            logger.warning(
                "fleet actor: fold %d checkpoint read failed (%s: %s) "
                "— retrying in %.2fs (visibility lag)", fold,
                type(e).__name__, e, delay)
            time.sleep(delay)  # robust: allow — deadline-bounded visibility-lag retry
            delay = min(1.0, delay * 2)


def run_fleet_actor(evaluator, transport: FleetTransport,
                    fold_ckpt_path: Callable[[int], str], *,
                    trial_batch: int = 1, num_policy: int = 5,
                    num_op: int = 2, poll_sec: float = 0.5,
                    ckpt_timeout: float = 900.0,
                    should_stop: Callable[[], BaseException | None] | None
                    = None) -> dict:
    """One ACTOR host's service loop: claim published rounds off the
    transport, evaluate them with the shared ``_FoldEval`` machinery
    against the published fold checkpoints, post rewards back, repeat
    until the learner marks the search done.

    Failure contract (docs/RESILIENCE.md "Fleet search"): a trial-level
    evaluation failure posts the formatted error (the learner
    quarantines the round exactly as the in-process scheduler would);
    ``PreemptedError``/``DispatchHungError`` re-raise — the CLI maps
    them to exit 77, the claimed lease goes stale, and a surviving
    actor reclaims the round; a ``LeaseLostError`` mid-round abandons
    the unit to its new owner (this host was presumed dead; duplicate
    evaluation is safe — rewards are deterministic).  A geometry
    mismatch against the published payload (trial_batch/num_policy/
    num_op) raises ``ValueError`` immediately: that is a launch
    configuration error, not a quarantinable trial failure."""
    import jax

    from fast_autoaugment_tpu.launch.workqueue import LeaseLostError
    from fast_autoaugment_tpu.utils import faultinject

    trial_batch = max(1, int(trial_batch))
    fi = faultinject.active_plan()
    loaded: dict[int, tuple] = {}
    folds_seen: set[int] = set()
    stats = {"rounds_ok": 0, "rounds_err": 0, "lease_lost": 0}
    transport.beat()
    while True:
        if preemption_requested():
            raise PreemptedError(
                "fleet actor preempted — claimed leases go stale and "
                "surviving actors reclaim the in-flight rounds")
        if should_stop is not None:
            err = should_stop()
            if err is not None:
                raise err
        unit = payload = None
        for u in transport.open_rounds():
            p = transport.wq.unit_payload(u)
            if p is not None and transport.wq.claim(u):
                unit, payload = u, p
                break
        if unit is None:
            transport.beat()
            if transport.search_done():
                break
            # TTL-fraction claim poll (the _workqueue_phase discipline):
            # the loop's exit is the learner's search_done marker, and
            # each nap stays well under the lease TTL so stale-round
            # reclaims are never starved
            time.sleep(max(0.1, min(poll_sec, transport.wq.lease_ttl / 4.0)))  # robust: allow
            continue
        fold = int(payload["fold"])
        if (int(payload.get("trial_batch", 1)) != trial_batch
                or int(payload.get("num_policy", num_policy)) != num_policy
                or int(payload.get("num_op", num_op)) != num_op):
            raise ValueError(
                f"fleet-actor geometry mismatch on {unit}: learner "
                f"published trial_batch={payload.get('trial_batch')} "
                f"num_policy={payload.get('num_policy')} "
                f"num_op={payload.get('num_op')}; this actor compiled "
                f"{trial_batch}/{num_policy}/{num_op} — launch actors "
                "with the learner's search flags")
        lease = transport.wq.read_lease(unit) or {}
        telemetry.emit("round", unit, action="claim", fold=fold,
                       t_base=int(payload.get("t_base", -1)),
                       lease_attempt=int(lease.get("attempt", 1)))
        try:
            path = fold_ckpt_path(fold)
            transport.wait_checkpoint(fold, path, timeout=ckpt_timeout,
                                      should_stop=should_stop)
            if fold not in loaded:
                loaded[fold] = _load_fold_resilient(
                    evaluator, fold, path,
                    budget_s=min(60.0, float(ckpt_timeout)))
            params, batch_stats = loaded[fold]
            rnd = _build_round(
                int(payload.get("round_idx", 0)),
                [int(t) for t in payload["ids"]],
                [dict(p) for p in payload["proposals"]],
                trial_batch=trial_batch, num_policy=num_policy,
                num_op=num_op,
                key_fold=jax.random.PRNGKey(int(payload["key_seed"])))
            transport.wq.renew(unit)
            t0m = telemetry.mono()
            rewards = _eval_round(evaluator, fold, params, batch_stats,
                                  rnd, trial_batch, fi, kill_check=True)
            t1m = telemetry.mono()
            transport.wq.renew(unit)
            # the phase-2 lane evidence with THIS host's identity — the
            # cross-host overlap `make status` renders
            telemetry.phase_event(f"phase2-fold{fold}", t0m, t1m,
                                  fold=fold, lane="phase2",
                                  t_base=int(rnd.t_base))
            result = {"rewards": [float(r) for r in rewards],
                      "eval_secs": round(t1m - t0m, 6)}
        except (PreemptedError, DispatchHungError, TimeoutError):
            # exit-77 / loud-exit path: the lease goes stale and a
            # survivor reclaims the round (TimeoutError FIRST — it IS
            # an OSError subclass and must not read as a trial failure)
            raise
        except LeaseLostError as e:
            stats["lease_lost"] += 1
            logger.warning(
                "fleet actor: lost the lease on %s mid-round (%s) — "
                "abandoning it to its new owner", unit, e)
            continue
        except (ArithmeticError, RuntimeError, ValueError, OSError) as e:
            result = {"error": f"{type(e).__name__}: {e}"}
        try:
            transport.post_result(unit, payload, result)
        except LeaseLostError as e:
            # the done-marker post was FENCED (epoch/owner moved): this
            # host was presumed dead and the round reclaimed — the
            # reclaimer posts the same bytes, so abandon, never clobber
            stats["lease_lost"] += 1
            logger.warning(
                "fleet actor: done-marker post for %s fenced off (%s) "
                "— abandoning the round to its reclaimer", unit, e)
            continue
        folds_seen.add(fold)
        ok = "rewards" in result
        stats["rounds_ok" if ok else "rounds_err"] += 1
        transport.beat()
        logger.info(
            "fleet actor %s: %s round %s (fold %d, trials %s)%s",
            transport.owner, "evaluated" if ok else "FAILED", unit, fold,
            payload.get("ids"),
            "" if ok else f" — posted {result['error']!r}")
    return dict(stats, folds=sorted(folds_seen),
                reclaimed_units=list(transport.wq.reclaimed_units))


def run_overlapped_phases(
    fold_list: list[int],
    phase1_fn: Callable[[int], None],
    phase2_fn: Callable[[int], object],
    *,
    poll_sec: float = 0.5,
) -> dict:
    """Overlap phase-1 fold training with phase-2 search: a trainer
    thread runs ``phase1_fn(fold)`` (train + quality gate) fold by
    fold, and the calling thread runs ``phase2_fn(fold)`` the moment
    that fold is ready — fold k's TPE trials dispatch while fold k+1's
    training is still in flight (the MPMD fleet-as-pipeline seed,
    arXiv:2412.14374, on one host).

    Phase-2 folds still run in fold order, so every artifact (trial
    logs, final policy set) is identical to the sequential schedule —
    only the wall-clock interleaving changes.  A trainer-thread
    exception (including ``PreemptedError`` from a SIGTERM mid-train)
    re-raises HERE, with its original type, at the next poll boundary;
    a phase-2 exception stops the trainer between folds (mid-fold
    training still honors the global preemption flag at dispatch
    boundaries).

    Returns the overlap timeline: per-fold phase-1/phase-2 start/end
    wall times plus the measured overlap seconds — the evidence the
    phase-overlap e2e test asserts on."""
    cond = threading.Condition()
    ready: dict[int, float] = {}
    trainer_error: list[BaseException] = []
    stop = threading.Event()
    timeline: dict = {
        "phase1": {}, "phase2": {},
        "folds": [int(f) for f in fold_list],
    }

    def _trainer():
        for f in fold_list:
            if stop.is_set():
                return
            t0 = wall()
            t0m = telemetry.mono()
            try:
                phase1_fn(f)
            except BaseException as e:
                with cond:
                    trainer_error.append(e)
                    cond.notify_all()
                return
            telemetry.phase_event(f"phase1-fold{f}", t0m, telemetry.mono(),
                                  fold=int(f), lane="phase1")
            with cond:
                timeline["phase1"][str(f)] = {"start": t0,
                                              "end": wall()}
                ready[f] = wall()
                cond.notify_all()
        with cond:
            cond.notify_all()

    th = threading.Thread(target=_trainer, daemon=True,
                          name="phase1-trainer")
    th.start()
    try:
        for f in fold_list:
            with cond:
                while f not in ready and not trainer_error:
                    cond.wait(timeout=poll_sec)
                if trainer_error:
                    raise trainer_error[0]
            t0 = wall()
            t0m = telemetry.mono()
            phase2_fn(f)
            telemetry.phase_event(f"phase2-fold{f}", t0m, telemetry.mono(),
                                  fold=int(f), lane="phase2")
            timeline["phase2"][str(f)] = {"start": t0, "end": wall()}
    except BaseException as e:
        stop.set()
        if isinstance(e, PreemptedError):
            # the trainer polls the same global preemption flag at its
            # dispatch boundaries: give it a bounded window to
            # checkpoint the in-flight fold before exit 77 (its own
            # PreemptedError lands in trainer_error, already raised)
            th.join(timeout=_PREEMPT_DRAIN_SEC)
        raise
    deadline = time.monotonic() + _JOIN_SEC
    th.join(timeout=max(0.0, deadline - time.monotonic()))

    # overlap evidence: seconds during which some fold's phase-2 ran
    # while a LATER fold's phase-1 was still training
    overlap = 0.0
    for f in fold_list:
        p2 = timeline["phase2"].get(str(f))
        if not p2:
            continue
        for g in fold_list:
            if g <= f:
                continue
            p1 = timeline["phase1"].get(str(g))
            if not p1:
                continue
            overlap += max(0.0, min(p2["end"], p1["end"])
                           - max(p2["start"], p1["start"]))
    timeline["overlap_secs"] = round(overlap, 6)
    return timeline
