"""Density-matching TTA evaluation — the search's inner loop.

The reference's ``eval_tta`` (``search.py:70-134``) loads a fold
checkpoint, builds ``num_policy`` independently-augmented copies of the
held-out fold loader (all applying the SAME candidate policy set, each
with fresh randomness), and per batch records:

- ``minus_loss``: minus the MINIMUM loss over all (policy-draw, sample)
  pairs of the batch — a batch-global scalar, not per-sample
  (SURVEY.md errata 2), and
- ``correct``: per-sample max of top-1 correctness across the draws,

normalized by sample count at the end.

Here that whole inner loop is ONE jitted step: the candidate policy is
a TENSOR argument, the P augmentation draws are a vmap, and the P*B
forward runs as a single batch on the mesh.  Because nothing about the
policy is baked into the compilation, every TPE sample reuses the same
executable — the property that makes search cheap on TPU (SURVEY.md
hard-part 3; the reference pays a fresh loader build per trial
instead, ``search.py:87-91``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fast_autoaugment_tpu.core import telemetry
from fast_autoaugment_tpu.core.compilecache import seam_jit
from fast_autoaugment_tpu.core.metrics import Accumulator
from fast_autoaugment_tpu.core.watchdog import dispatch_enqueue_guard
from fast_autoaugment_tpu.ops.preprocess import cifar_train_batch

__all__ = ["make_tta_step", "make_audit_step", "eval_tta", "eval_tta_batched"]


def _jit_with_trace_counter(fn, label: str):
    """jit `fn` (through the compile seam) with an explicit trace-event
    counter attached.

    Each retrace of a jitted function corresponds to one new executable
    in its compile cache (a cache hit never re-traces), so counting
    trace events is a public-API-only census of compiles — the fallback
    :func:`search.census.executable_census` uses when jit's private
    ``_cache_size`` disappears in a jax upgrade.  The counter fires at
    trace time only; it costs nothing on the steady-state call path.
    The seam (``core/compilecache.py``) times the first-call lowering
    and classifies it against the persistent compile cache; `label`
    matches the watchdog's dispatch label for the same entry point."""
    events: list = []

    def counted(*args, **kwargs):
        events.append(1)  # trace-time side effect: once per (re)lowering
        return fn(*args, **kwargs)

    jitted = seam_jit(counted, label=label)
    jitted._faa_trace_count = lambda: len(events)
    return jitted


def _default_augment_fn(cutout_length: int, aug_dispatch: str = "exact",
                        aug_groups: int = 8) -> Callable:
    """CIFAR-family train stack (crop/flip/normalize + policy + cutout)."""
    def augment_fn(images, policy, key):
        return cifar_train_batch(images, key, policy=policy,
                                 cutout_length=cutout_length,
                                 aug_dispatch=aug_dispatch,
                                 aug_groups=aug_groups)
    return augment_fn


def make_tta_step(model, *, num_policy: int = 5, cutout_length: int = 16,
                  augment_fn: Callable | None = None,
                  num_candidates: int | None = None,
                  aug_dispatch: str = "exact", aug_groups: int = 8):
    """Build the jitted TTA evaluation step.

    With ``num_candidates=None`` (default) returns
    ``fn(params, batch_stats, images_u8, labels, mask, policy, key) ->
    {"minus_loss_sum", "correct_sum", "cnt"}`` where `policy` is a
    [num_sub, num_op, 3] tensor applied `num_policy` times with
    independent randomness.

    With ``num_candidates=K`` the step gains a LEADING CANDIDATE AXIS:
    `policy` becomes a [K, num_sub, num_op, 3] tensor of K independent
    TPE proposals and `key` a [K]-stack of per-candidate PRNG keys; the
    candidate axis is a vmap over the exact single-candidate
    computation, so the K*P*B forwards run as ONE device program and
    every returned field carries a leading [K] (including the
    batch-global min-loss errata, which stays global PER CANDIDATE).
    Candidate k's results are bit-identical to evaluating its
    (policy[k], key[k]) through the single-candidate step — the Podracer
    fan-out (arXiv:2104.06272): homogeneous trials feed the device as
    one batch.  For either variant, one fixed argument shape = one
    executable for the whole search (the zero-recompile invariant;
    census via ``search.census.executable_census``).

    ``aug_dispatch="grouped"`` switches the augmentation to the
    scalar-dispatch kernels (``ops/augment.py``): the P draw axis (and
    for ``num_candidates=K`` the candidate axis) is traversed with
    ``lax.map`` instead of ``vmap`` so the per-chunk sub-policy indices
    stay SCALAR — a vmapped axis would re-batch them and XLA would fall
    back to executing all 19 op branches.  The model forward still runs
    on the full flattened batch either way.  A custom `augment_fn`
    combined with grouped dispatch owns its own internal dispatch; this
    function only serializes the outer axes for it.
    """
    from fast_autoaugment_tpu.ops.augment import check_aug_dispatch

    check_aug_dispatch(aug_dispatch)
    grouped = aug_dispatch == "grouped"
    if augment_fn is None:
        augment_fn = _default_augment_fn(cutout_length, aug_dispatch,
                                         aug_groups)

    def augment_draws(images, policy, key):
        keys = jax.random.split(key, num_policy)

        def one_draw(k):
            return augment_fn(images, policy, k)

        if grouped:
            # scan over draws: each draw's grouped dispatch keeps its
            # scalar switch index (a draw vmap would batch it)
            return jax.lax.map(one_draw, keys)  # [P, B, H, W, C]
        return jax.vmap(one_draw)(keys)  # [P, B, H, W, C]

    def score_augmented(params, batch_stats, augmented, labels, mask):
        p, b = augmented.shape[0], augmented.shape[1]
        flat = augmented.reshape((p * b,) + augmented.shape[2:])
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, flat, train=False
        )
        logits = logits.reshape(p, b, -1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[None, :, None], axis=-1)[..., 0]  # [P, B]
        correct = (jnp.argmax(logits, axis=-1) == labels[None, :])  # [P, B]

        # batch-global min loss over every (draw, sample) pair, masked
        nll_masked = jnp.where(mask[None, :] > 0, nll, jnp.inf)
        minus_loss = -jnp.min(nll_masked)
        # per-sample best across draws (the reference's reward,
        # search.py:116-125) — NOTE this is an optimistic reduction: a
        # destructive sub-policy hides behind one benign draw
        correct_max = correct.any(axis=0) * (mask > 0)
        # per-sample MEAN across draws: the pessimistic counterpart the
        # sub-policy audit ranks by (what training-time application of
        # the policy actually costs; round-2 post-mortem,
        # docs/search_postmortem_r2.md)
        correct_mean = correct.mean(axis=0) * (mask > 0)
        return {
            "minus_loss_sum": minus_loss,
            "correct_sum": correct_max.sum().astype(jnp.float32),
            "correct_mean_sum": correct_mean.sum().astype(jnp.float32),
            "cnt": mask.sum().astype(jnp.float32),
        }

    def one_candidate(params, batch_stats, images, labels, mask, policy, key):
        augmented = augment_draws(images, policy, key)
        return score_augmented(params, batch_stats, augmented, labels, mask)

    if num_candidates is None:
        return _jit_with_trace_counter(one_candidate, "tta")

    def tta_step_batched(params, batch_stats, images, labels, mask,
                         policies, keys):
        if grouped:
            # candidate axis: augment under lax.map (scalar dispatch
            # preserved), then vmap only the forward/metrics over the
            # pre-augmented [K, P, B, ...] tensor
            augmented = jax.lax.map(
                lambda pk: augment_draws(images, pk[0], pk[1]),
                (policies, keys))
            return jax.vmap(
                lambda aug: score_augmented(params, batch_stats, aug,
                                            labels, mask)
            )(augmented)
        return jax.vmap(
            lambda pol, k: one_candidate(
                params, batch_stats, images, labels, mask, pol, k)
        )(policies, keys)

    return _jit_with_trace_counter(tta_step_batched, "tta_batched")


def make_audit_step(model, *, num_policy: int = 5, cutout_length: int = 16,
                    augment_fn: Callable | None = None,
                    aug_dispatch: str = "exact", aug_groups: int = 8):
    """Batched sub-policy audit step: evaluates S candidate sub-policies
    against one batch in ONE compiled call.

    The per-sub-policy audit (``search/driver.py:audit_sub_policies``)
    needs mean-over-draws accuracy for EVERY selected sub-policy alone;
    done with :func:`make_tta_step` that is one tiny dispatch per
    (sub-policy, batch) — thousands of launches that starve the MXU.
    Here the sub-policy axis is a vmap: ``subs`` is [S, num_op, 3] and
    the model forward runs on the S*P*B flattened batch.  Returns
    ``fn(params, batch_stats, images, labels, mask, subs, key) ->
    {"correct_mean_sum": [S], "cnt": scalar}``.  NOTE peak memory is S x
    the TTA step's (the [S, P, B, H, W, C] augmented tensor) — callers
    size S by image resolution (``audit_sub_policies``).

    ``aug_dispatch="grouped"``: the S axis already fixes the sub-policy
    per lane, so scalar dispatch needs NO distribution change — each
    lane's ops are known per lane, and the grouped single-sub path is
    bitwise identical to the exact one.  The S and draw axes are
    traversed with ``lax.map`` (a vmap would re-batch the op indices
    and lower back to all-branches execution); the forward stays one
    flattened S*P*B batch.
    """
    from fast_autoaugment_tpu.ops.augment import check_aug_dispatch

    check_aug_dispatch(aug_dispatch)
    grouped = aug_dispatch == "grouped"
    if augment_fn is None:
        augment_fn = _default_augment_fn(cutout_length, aug_dispatch,
                                         aug_groups)

    def audit_step(params, batch_stats, images, labels, mask, subs, key):
        s = subs.shape[0]
        keys = jax.random.split(key, s * num_policy).reshape(s, num_policy, 2)

        def per_sub(sub, ks):
            # a [1, num_op, 3] policy: every draw applies this sub-policy
            return jax.vmap(lambda k: augment_fn(images, sub[None], k))(ks)

        if grouped:
            augmented = jax.lax.map(
                lambda sk: jax.lax.map(
                    lambda k: augment_fn(images, sk[0][None], k), sk[1]),
                (subs, keys))  # [S, P, B, H, W, C]
        else:
            augmented = jax.vmap(per_sub)(subs, keys)  # [S, P, B, H, W, C]
        p, b = augmented.shape[1], augmented.shape[2]
        flat = augmented.reshape((s * p * b,) + augmented.shape[3:])
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, flat, train=False
        ).reshape(s, p, b, -1)
        correct = (jnp.argmax(logits, axis=-1) == labels[None, None, :])
        correct_mean = correct.mean(axis=1) * (mask[None, :] > 0)  # [S, B]
        return {
            "correct_mean_sum": correct_mean.sum(axis=1).astype(jnp.float32),
            "cnt": mask.sum().astype(jnp.float32),
        }

    return _jit_with_trace_counter(audit_step, "audit")


def eval_tta(tta_step, params, batch_stats, batches, policy, key,
             trace=None) -> dict:
    """Run the TTA step over a fold's batches; returns
    {'minus_loss', 'top1_valid'} normalized by sample count
    (reference ``search.py:117-133``).

    `batches` yields mesh-placed ``{"x", "y", "m"}`` dicts
    (`parallel.mesh.shard_transform` maps `eval_batches` tuples to
    this shape) — the driver uploads each fold ONCE and replays the
    device-resident batches across all trials (the fold data is
    identical for every TPE sample; only the policy tensor changes),
    or streams them through a prefetch worker for lazy datasets.

    `trace(t0, t1)` (optional) receives each dispatch's start/end
    monotonic timestamps — the per-dispatch evidence behind the
    pipeline bench's gap histogram.  Tracing forces a per-batch
    ``block_until_ready`` (the tiny output scalars are pulled to the
    host right after anyway), so it never changes values.  Every
    dispatch window also feeds the telemetry span seam
    (``core/telemetry.py::record_dispatch``, label ``tta``) — registry
    histogram always, journal event when ``--telemetry`` is armed."""
    acc = Accumulator()
    for i, batch in enumerate(batches):
        t0 = telemetry.mono()
        with dispatch_enqueue_guard():  # async pipeline: one enqueue
            out = tta_step(             # order on every device queue
                params, batch_stats, batch["x"], batch["y"], batch["m"],
                policy, jax.random.fold_in(key, i),
            )
        if trace is not None:
            out = jax.block_until_ready(out)
            t1 = telemetry.mono()
            trace(t0, t1)
        else:
            t1 = telemetry.mono()
        telemetry.record_dispatch("tta", t0, t1,
                                  blocking=trace is not None)
        acc.add_dict(out)
    cnt = acc["cnt"]
    return {
        "minus_loss": acc["minus_loss_sum"] / cnt if cnt else 0.0,
        "top1_valid": acc["correct_sum"] / cnt if cnt else 0.0,
        "top1_mean": acc["correct_mean_sum"] / cnt if cnt else 0.0,
        "cnt": cnt,
    }


def eval_tta_batched(tta_step_k, params, batch_stats, batches, policies,
                     keys, trace=None) -> list[dict]:
    """Batched counterpart of :func:`eval_tta`: K candidate policies
    through a ``make_tta_step(num_candidates=K)`` step in one device
    program per batch.

    `policies` is [K, num_sub, num_op, 3]; `keys` is a [K]-stack of
    per-candidate TRIAL keys.  Candidate k's per-batch key is
    ``fold_in(keys[k], batch_idx)`` — exactly what a sequential
    :func:`eval_tta` call with ``key=keys[k]`` derives — so each entry
    of the returned list is numerically identical to evaluating that
    candidate alone.  One host sync per batch serves all K candidates
    (the sequential loop pays it K times).  `trace(t0, t1)` (optional)
    records each dispatch's start/end monotonic timestamps (the
    per-batch host sync already bounds the dispatch, so tracing adds
    two clock reads and nothing else).  Each dispatch window also feeds
    the telemetry span seam (label ``tta_batched``)."""
    sums: dict[str, np.ndarray] | None = None
    for i, batch in enumerate(batches):
        t0 = telemetry.mono()
        batch_keys = jax.vmap(lambda kk: jax.random.fold_in(kk, i))(keys)
        with dispatch_enqueue_guard():
            out = tta_step_k(
                params, batch_stats, batch["x"], batch["y"], batch["m"],
                policies, batch_keys,
            )
        # accumulate at native f32 on the host: the same sequential
        # f32 additions eval_tta's Accumulator performs on device, so
        # batched == sequential holds bit-for-bit across batches too
        out = {k: np.asarray(v) for k, v in out.items()}
        t1 = telemetry.mono()
        if trace is not None:
            trace(t0, t1)
        telemetry.record_dispatch("tta_batched", t0, t1, blocking=True)
        sums = out if sums is None else {
            k: sums[k] + out[k] for k in sums
        }
    if sums is None:
        k_dim = int(policies.shape[0])
        sums = {f: np.zeros(k_dim) for f in
                ("minus_loss_sum", "correct_sum", "correct_mean_sum", "cnt")}
    results = []
    for k in range(int(sums["cnt"].shape[0])):
        cnt = float(sums["cnt"][k])
        results.append({
            "minus_loss": float(sums["minus_loss_sum"][k]) / cnt if cnt else 0.0,
            "top1_valid": float(sums["correct_sum"][k]) / cnt if cnt else 0.0,
            "top1_mean": float(sums["correct_mean_sum"][k]) / cnt if cnt else 0.0,
            "cnt": cnt,
        })
    return results
