"""Executable-census helpers for the compiled search steps.

The policy-as-tensor TTA design promises ONE executable per argument
shape for the whole search (SURVEY.md hard-part 3); the census is how
the driver PROVES it in every `search_result.json` instead of claiming
it.  The probe used to be a bare call to jit's private
``_cache_size()``, silently recording ``None`` whenever a jax upgrade
moved the attribute (VERDICT r5 weak 6) — which would have turned the
zero-recompile gate into a no-op without anyone noticing.

:func:`executable_census` is the version-guarded replacement:

1. prefer ``_cache_size()`` (private, exact — counts cache entries);
2. fall back to the explicit trace-event counter the step factories in
   ``search/tta.py`` attach (``_faa_trace_count``: a retrace happens
   exactly once per new cache entry, so the count is equivalent), with
   a WARNING that the private API is gone;
3. warn loudly and return ``None`` only when neither probe exists —
   never a silent no-op.
"""

from __future__ import annotations

from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["executable_census"]

logger = get_logger("faa_tpu.census")


def executable_census(step) -> int | None:
    """Best-effort count of compiled executables held by a jitted step.

    Returns an int from jit's private cache probe when available, else
    from the trace-event counter attached by the ``search/tta.py``
    factories, else ``None`` (after warning).  A return of ``None``
    means the zero-recompile invariant CANNOT be asserted — callers
    must treat it as "unknown", not "one".
    """
    cache_probe = getattr(step, "_cache_size", None)
    if callable(cache_probe):
        try:
            return int(cache_probe())
        except Exception as e:  # noqa: BLE001 — private, version-dependent
            logger.warning(
                "jit _cache_size() probe failed (%s: %s) — falling back to "
                "the trace-event counter", type(e).__name__, e,
            )
    trace_probe = getattr(step, "_faa_trace_count", None)
    if callable(trace_probe):
        if not callable(cache_probe):
            logger.warning(
                "jit no longer exposes _cache_size (jax upgrade?) — "
                "executable census now counts explicit trace events; "
                "the zero-recompile assertion still holds, but consider "
                "updating search/census.py for the new jax version"
            )
        return int(trace_probe())
    logger.warning(
        "executable census UNAVAILABLE for %r: neither jit._cache_size nor "
        "the _faa_trace_count counter exists — the zero-recompile invariant "
        "is NOT being verified for this step", step,
    )
    return None
