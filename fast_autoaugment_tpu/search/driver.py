"""Three-phase policy search by density matching.

The reference's ``search.py:137-312``: (1) pretrain K=5 models on CV
resamples WITHOUT augmentation, (2) per fold run HyperOpt-TPE over
{op, prob, level}^(num_policy x num_op) with test-time-augmentation
reward against the held-out fold, keep each fold's top-10 samples,
decode + dedup into ``final_policy_set``, (3) retrain on the full data
with and without the found policies and compare.

Differences by design:
- Ray remotes + Redis + checkpoint-polling progress threads become a
  plain in-process loop around ONE compiled TTA step per fold; trial
  state is a JSON file, resumable (`--resume` parity) and readable by
  the launcher for multi-host fold sharding (fold k -> host k % n).
- TPE is in-tree (``search/tpe.py``).
- "GPU-hours" accounting (``search.py:132-133,251``) becomes
  TPU-seconds = wall x device_count, reported per phase.

Additions beyond the reference (round-2 post-mortem,
``docs/search_postmortem_r2.md`` — the reference has neither and its
pipeline silently selected accuracy-destroying policies in our round-2
validation run):
- a **fold-oracle quality gate**: after phase 1 each fold model's
  no-candidate-policy baseline accuracy is measured with the compiled
  TTA step; folds below ``fold_quality_floor`` are retrained with a
  fresh seed up to ``fold_retrain_tries`` times and excluded from
  ranking if still weak (a 0.37-accuracy oracle cannot rank policies);
- a **per-sub-policy audit**: every sub-policy surviving the
  reference's top-N selection is evaluated ALONE under the
  *mean*-over-draws reduction (training-time semantics) and dropped
  when it degrades fold accuracy below ``audit_floor`` x baseline —
  the reference's max-over-draws reward (``search.py:116-125``) lets a
  destructive sub-policy hide behind one benign draw of its trial
  siblings.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fast_autoaugment_tpu.core import fsfault, telemetry
from fast_autoaugment_tpu.core.checkpoint import load_checkpoint, read_metadata
from fast_autoaugment_tpu.core.compilecache import (
    compile_cache_stats,
    configure_compile_cache,
)
from fast_autoaugment_tpu.core.resilience import (
    DispatchHungError,
    PreemptedError,
)
from fast_autoaugment_tpu.core.telemetry import wall
from fast_autoaugment_tpu.core.watchdog import resolve_watchdog
from fast_autoaugment_tpu.data.datasets import cv_split, load_dataset
from fast_autoaugment_tpu.models import get_model, num_class
from fast_autoaugment_tpu.ops.augment import SEARCH_OP_NAMES
from fast_autoaugment_tpu.parallel.mesh import make_mesh
from fast_autoaugment_tpu.policies.archive import (
    policy_decoder,
    policy_to_tensor,
    remove_duplicates,
)
from fast_autoaugment_tpu.search.census import executable_census
from fast_autoaugment_tpu.search.tpe import TPE, choice, uniform
from fast_autoaugment_tpu.search.tta import (
    eval_tta,
    eval_tta_batched,
    make_audit_step,
    make_tta_step,
)
from fast_autoaugment_tpu.train.trainer import train_and_eval, train_folds_stacked
from fast_autoaugment_tpu.utils.logging import get_logger

__all__ = ["search_policies", "search_actor", "make_search_space",
           "SearchResult", "resolve_quality_floor", "resolve_fold_stack",
           "write_json_atomic", "draw_random_policy_set"]

logger = get_logger("faa_tpu.search")


def phase1_device_seconds_attribution(sw, fold_list, stack_groups) -> dict:
    """Per-fold device-seconds from the phase-1 stopwatch ledger.

    ``phase1_fold<k>`` phases (initial train + gate retrains, the same
    accumulating name) credit fold k directly; each ``phase1_stack<i>``
    phase (one measured wall for a whole fold-stacked group) splits
    evenly over `stack_groups[i]`.  The ``device_secs_phase1_per_fold``
    stamp in ``search_result.json`` is THIS function over THIS stopwatch
    — one ledger (mirrored into the telemetry registry as
    ``faa_phase_device_seconds`` gauges), so the stamp cannot drift from
    the measurement; equality is pinned by tests/test_telemetry.py."""
    attr = {int(f): sw.device_seconds(f"phase1_fold{f}") for f in fold_list}
    for i, group in enumerate(stack_groups):
        share = sw.device_seconds(f"phase1_stack{i}") / len(group)
        for f in group:
            attr[int(f)] = attr.get(int(f), 0.0) + share
    return attr


def resolve_quality_floor(floor, num_classes: int) -> float | None:
    """Resolve the fold-oracle quality floor.

    ``"auto"`` (the CLI default since round 4) is chance-relative: the
    fold baseline must close at least 35% of the chance-to-perfect gap,
    ``chance + 0.35 * (1 - chance)`` — 0.415 on a 10-class task, in line
    with the validated 0.45 recipe (docs/search_postmortem_r2.md) while
    scaling to any class count.  Floats pass through; ``None``/``"off"``
    or a non-positive value disables the gate (the pre-round-4
    behavior, which ships the round-2 failure mode — see VERDICT r3)."""
    if floor is None:
        return None
    if isinstance(floor, str):
        if floor == "auto":
            chance = 1.0 / num_classes
            return chance + 0.35 * (1.0 - chance)
        if floor.lower() in ("off", "none"):
            return None
        floor = float(floor)
    return floor if floor > 0 else None


def resolve_fold_stack(fold_stack, num_pending: int) -> int:
    """Resolve the ``--fold-stack`` knob to a stack width.

    ``0`` (default) keeps the sequential per-fold loop bit-for-bit;
    ``"auto"`` stacks every fold that needs training; an int K caps the
    stack at K folds per program.  Widths below 2 degrade to
    sequential (a 1-fold stack buys nothing over the plain path)."""
    if fold_stack in (None, 0, "0"):
        return 0
    if isinstance(fold_stack, str):
        if fold_stack == "auto":
            return num_pending if num_pending >= 2 else 0
        fold_stack = int(fold_stack)
    if fold_stack < 0:
        raise ValueError(f"fold_stack must be >= 0, got {fold_stack}")
    k = min(int(fold_stack), num_pending)
    return k if k >= 2 else 0


def write_json_atomic(path: str, obj) -> None:
    """fsync-then-rename write: a crash mid-write can never tear the
    file, and a crash right after loses nothing (VERDICT r3, weak 4).
    Public: the search CLI persists its result files through this too."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


_write_json_atomic = write_json_atomic  # internal call sites


def make_search_space(num_policy: int, num_op: int):
    """The reference's search space (``search.py:214-220``): per (i, j)
    an op choice over the 15 searchable ops, prob ~ U(0,1), level ~ U(0,1)."""
    space = []
    for i in range(num_policy):
        for j in range(num_op):
            space.append(choice(f"policy_{i}_{j}", len(SEARCH_OP_NAMES)))
            space.append(uniform(f"prob_{i}_{j}", 0, 1))
            space.append(uniform(f"level_{i}_{j}", 0, 1))
    return space


class SearchResult(dict):
    @property
    def final_policy_set(self):
        return self["final_policy_set"]


def draw_random_policy_set(num_subs: int, num_policy: int, num_op: int,
                           seed: int) -> list:
    """Uniform draws from the same (op, prob, level) space as
    :func:`make_search_space`, decoded through the same
    ``policy_decoder`` path as TPE proposals.

    The phase-3 control arm (VERDICT r4, next-step 4): density
    matching's actual claim is that SEARCHED policies beat *random*
    ones from the same space — not merely no-augmentation.  Matching
    the searched set's PRE-audit size and auditing identically keeps
    the two arms' selection pipelines aligned except for the ranking
    step under test."""
    rng = np.random.RandomState(seed)
    out: list = []
    stalled = 0
    while len(out) < num_subs:
        proposal = {}
        for i in range(num_policy):
            for j in range(num_op):
                proposal[f"policy_{i}_{j}"] = int(
                    rng.randint(len(SEARCH_OP_NAMES)))
                proposal[f"prob_{i}_{j}"] = float(rng.rand())
                proposal[f"level_{i}_{j}"] = float(rng.rand())
        before = len(out)
        out = remove_duplicates(
            out + policy_decoder(proposal, num_policy, num_op))
        # dedup is by op-name sequence, a space of only
        # len(SEARCH_OP_NAMES)**num_op sequences: demanding more subs
        # than that can never finish — fail instead of spinning
        stalled = stalled + 1 if len(out) == before else 0
        if stalled >= 50:
            raise ValueError(
                f"cannot draw {num_subs} distinct sub-policies: the op-"
                f"sequence space holds only {len(SEARCH_OP_NAMES) ** num_op}"
                f" and {len(out)} are already drawn")
    return out[:num_subs]


def _fold_ckpt_path(save_dir: str, conf, fold: int, cv_ratio: float) -> str:
    tag = f"{conf['model']['type']}_{conf['dataset']}_fold{fold}_ratio{cv_ratio:.2f}"
    return os.path.join(save_dir, f"{tag}.msgpack")


# every per-checkpoint artifact train_and_eval emits: the msgpack, the
# cheap-metadata sidecar, the rollback-chain link (+ its sidecar —
# default --ckpt-keep depth; a stale chain link from a REJECTED retry
# must never survive as rollback material for the promoted fold), and
# the ScalarWriter logs — retry promotion must move/remove all of them
# or the promoted fold keeps the rejected run's training curves
_CKPT_SUFFIXES = ("", ".meta.json", ".prev", ".prev.meta.json",
                  "_train.jsonl", "_valid.jsonl", "_test.jsonl")


def _replace_ckpt(src: str, dst: str):
    """Promote a retrained fold checkpoint (+ all sidecars)."""
    for suffix in _CKPT_SUFFIXES:
        if os.path.exists(dst + suffix):
            os.remove(dst + suffix)
        if os.path.exists(src + suffix):
            shutil.move(src + suffix, dst + suffix)


def _remove_ckpt(path: str):
    for suffix in _CKPT_SUFFIXES:
        if os.path.exists(path + suffix):
            os.remove(path + suffix)


def _call_train_fold_fn(fn: Callable, conf, fold: int, path: str, seed: int):
    """Invoke a phase-1 training override with an explicit seed.

    The hook protocol is ``fn(conf, fold, save_path, seed=...)``;
    legacy three-argument overrides still work — they get the seed
    riding on ``conf['seed']`` (ADVICE r4: a thin wrapper around
    ``train_and_eval(conf, fold, path)`` ignored conf-level seed, so
    quality-gate retries deterministically reproduced the same weak
    oracle)."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
        takes_seed = "seed" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):  # builtins / C callables
        takes_seed = False
    conf = conf.replace(**{"seed": seed})
    if takes_seed:
        return fn(conf, fold, path, seed=seed)
    return fn(conf, fold, path)


class _FoldEval:
    """Lazily-built TTA machinery shared by the fold-quality gate,
    phase 2 and the sub-policy audit: one compiled step, per-fold
    device-resident batch caches, a checkpoint template."""

    def __init__(self, conf, dataroot, mesh, *, num_policy, num_op, cv_ratio,
                 seed, trial_batch: int = 1, aug_dispatch: str = "exact",
                 aug_groups: int = 8, watchdog=None, trace=None):
        from fast_autoaugment_tpu.ops.augment import check_aug_dispatch

        self.conf, self.dataroot, self.mesh = conf, dataroot, mesh
        self.num_policy, self.num_op = num_policy, num_op
        self.cv_ratio, self.seed = cv_ratio, seed
        self.trial_batch = max(1, int(trial_batch))
        self.aug_dispatch = check_aug_dispatch(aug_dispatch)
        self.aug_groups = max(1, int(aug_groups))
        self.watchdog = resolve_watchdog(watchdog)
        # optional DispatchTrace (search/pipeline.py): per-dispatch
        # start/end timestamps for the dispatch-gap evidence
        self.trace = trace
        self._built = False
        # the async pipeline evaluates from several actor threads (and
        # the overlapped phase-1 gate from the trainer thread): build
        # and per-fold batch-cache population are lock-guarded
        self._lock = threading.RLock()
        self._batches: dict[int, Callable] = {}
        # distinct leading policy-tensor shapes fed to the compiled TTA
        # step; the executable-count invariant is exactly one compile
        # per shape (the gate's identity baseline is [1, num_op, 3],
        # trials are [num_policy, num_op, 3])
        self.policy_shapes: set[int] = set()
        # candidate-axis sizes fed to the BATCHED step (trial_batch > 1):
        # its invariant is one executable for the single fixed K
        self.batch_policy_shapes: set[int] = set()

    def _build(self):
        with self._lock:
            self._build_locked()

    def _build_locked(self):
        if self._built:
            return
        conf, mesh = self.conf, self.mesh
        dataset_name = conf["dataset"]
        num_classes = num_class(dataset_name)
        self.num_classes = num_classes
        self.total_train, _test = load_dataset(dataset_name, self.dataroot)
        model_conf = dict(conf["model"], dataset=dataset_name)
        model_conf.setdefault("precision", conf.get("precision", "f32"))
        model = get_model(model_conf, num_classes)
        cutout_length = int(conf.get("cutout", 0) or 0)

        # the TTA loaders use the TRAIN transform stack (the reference's
        # validloader shares the train dataset's transforms, data.py:88-112)
        from fast_autoaugment_tpu.models import input_image_size

        # same conf['imgsize'] override as train_and_eval — phase 2 must
        # evaluate the phase-1 checkpoints at the resolution they trained at
        image = int(conf.get("imgsize", 0) or 0) or input_image_size(
            dataset_name, conf["model"]["type"]
        )
        self.image = image
        if dataset_name.endswith("imagenet"):
            from fast_autoaugment_tpu.ops.preprocess_imagenet import (
                imagenet_train_batch,
                random_crop_box,
            )

            tta_augment_fn = lambda images, pol, key: imagenet_train_batch(  # noqa: E731
                images, key, pol, cutout_length=cutout_length,
                aug_dispatch=self.aug_dispatch, aug_groups=self.aug_groups,
            )
            self._box_fn = lambda rng, w, h: random_crop_box(rng, w, h, image)  # noqa: E731
        else:
            tta_augment_fn = None
            self._box_fn = None
        dispatch_kw = dict(aug_dispatch=self.aug_dispatch,
                           aug_groups=self.aug_groups)
        self.tta_step = make_tta_step(
            model, num_policy=self.num_policy, cutout_length=cutout_length,
            augment_fn=tta_augment_fn, **dispatch_kw,
        )
        # jit wrapping is free; XLA compiles at the first audit_eval call
        self.audit_step = make_audit_step(
            model, num_policy=self.num_policy, cutout_length=cutout_length,
            augment_fn=tta_augment_fn, **dispatch_kw,
        )
        # trial-parallel TTA: K candidate policies per device program
        # (jit wrapping free here too; compiles at the first batch)
        self.tta_step_batch = None
        if self.trial_batch > 1:
            self.tta_step_batch = make_tta_step(
                model, num_policy=self.num_policy,
                cutout_length=cutout_length, augment_fn=tta_augment_fn,
                num_candidates=self.trial_batch, **dispatch_kw,
            )

        # checkpoint template, built once (models are input-size-polymorphic
        # after init, but use the real resolution for clarity)
        from fast_autoaugment_tpu.ops.optim import build_optimizer
        from fast_autoaugment_tpu.train.steps import create_train_state

        sample = jnp.zeros((2, image, image, 3), jnp.float32)
        optimizer = build_optimizer(dict(conf["optimizer"]), lambda s: 0.0)
        self.template = create_train_state(
            model, optimizer, jax.random.PRNGKey(0), sample,
            use_ema=bool(conf.get("optimizer", {}).get("ema", 0)),
        )
        self._built = True

    def load_fold(self, path: str):
        self._build()
        state = load_checkpoint(path, self.template)
        return state.params, state.batch_stats

    def batches_fn(self, fold: int) -> Callable:
        """Batch source for a fold's held-out split.  In-memory datasets
        upload the fold ONCE and replay the device-resident batches for
        every trial (the data never changes between TPE samples — only
        the policy tensor does); lazy on-disk datasets (ImageNet) stream
        through a prefetch worker."""
        self._build()
        with self._lock:
            return self._batches_locked(fold)

    def _batches_locked(self, fold: int) -> Callable:
        if fold in self._batches:
            return self._batches[fold]
        from fast_autoaugment_tpu.data.pipeline import BatchIterator
        from fast_autoaugment_tpu.parallel.mesh import shard_transform

        _train_idx, valid_idx = cv_split(self.total_train.labels, self.cv_ratio, fold)
        batch = int(self.conf["batch"]) * self.mesh.size
        fold_it = BatchIterator(
            self.total_train, valid_idx,
            eval_box_fn=self._box_fn, train_box_fn=self._box_fn,
            imgsize=self.image,
        )

        def _stream():
            # pad the final batch to FULL size (not just the mesh
            # multiple): every batch then has one static shape, so the
            # TTA/audit executables never fork on the remainder batch —
            # one compile serves the entire search (the masks already
            # carry correctness; the waste is <1 batch per fold epoch)
            return fold_it.eval_epoch(
                batch, process_index=jax.process_index(),
                process_count=jax.process_count(), pad_multiple=batch,
            )

        _to_device = shard_transform(self.mesh, ("x", "y", "m"))
        if not self.total_train.lazy:
            cached = [_to_device(t) for t in _stream()]
            fn = lambda: iter(cached)  # noqa: E731
        else:
            from fast_autoaugment_tpu.data.pipeline import prefetch

            fn = lambda: prefetch(_stream(), transform=_to_device)  # noqa: E731
        self._batches[fold] = fn
        return fn

    def _guarded(self, label: str, fn, *args):
        """TTA/audit evaluations through the watchdog seam (one
        monitored window per whole-fold evaluation; the per-label EMA
        tracks the full replay wall).  Off = the direct call."""
        if not self.watchdog.enabled:
            return fn(*args)
        return self.watchdog.run(label, fn, *args)

    def _trace_cb(self):
        return self.trace.record if self.trace is not None else None

    def evaluate(self, fold: int, params, batch_stats, policy_t, key) -> dict:
        self.policy_shapes.add(int(policy_t.shape[0]))
        return self._guarded(
            "tta", eval_tta,
            self.tta_step, params, batch_stats, self.batches_fn(fold)(),
            policy_t, key, self._trace_cb(),
        )

    def evaluate_batch(self, fold: int, params, batch_stats, policies_t,
                       keys) -> list[dict]:
        """K candidate policies against the fold in one vmapped program
        per batch.  `policies_t` is [K, num_sub, num_op, 3] with
        K == trial_batch (the compiled candidate-axis size); `keys` is
        the [K]-stack of per-candidate trial keys."""
        self._build()
        if self.tta_step_batch is None:
            raise RuntimeError("evaluate_batch requires trial_batch > 1")
        if int(policies_t.shape[0]) != self.trial_batch:
            raise ValueError(
                f"candidate axis {int(policies_t.shape[0])} != compiled "
                f"trial_batch {self.trial_batch}")
        self.batch_policy_shapes.add(int(policies_t.shape[0]))
        return self._guarded(
            "tta_batched", eval_tta_batched,
            self.tta_step_batch, params, batch_stats,
            self.batches_fn(fold)(), policies_t, keys, self._trace_cb(),
        )

    def audit_eval(self, params, batch_stats, batch, subs, key) -> dict:
        """Batched audit: S sub-policies against one mesh-placed batch
        in a single compiled call (``make_audit_step``)."""
        from fast_autoaugment_tpu.core.watchdog import (
            dispatch_enqueue_guard,
        )

        self._build()

        def _dispatch(*args):  # serialized enqueue (async pipeline only)
            with dispatch_enqueue_guard():
                return self.audit_step(*args)

        return self._guarded(
            "audit", _dispatch, params, batch_stats, batch["x"],
            batch["y"], batch["m"], subs, key)

    def baseline(self, fold: int, path: str) -> float:
        """No-candidate-policy fold accuracy: the identity policy (one
        all-zero sub-policy row: op 0 gated at prob 0) through the same
        compiled step — i.e. fold accuracy under the default transform
        stack alone.  The oracle-quality measure the gate and audit
        normalize against."""
        params, batch_stats = self.load_fold(path)
        ident = jnp.zeros((1, self.num_op, 3), jnp.float32)
        out = self.evaluate(fold, params, batch_stats, ident,
                            jax.random.PRNGKey(17))
        return float(out["top1_mean"])


def search_policies(
    conf,
    dataroot: str,
    save_dir: str,
    *,
    cv_num: int = 5,
    cv_ratio: float = 0.4,
    num_policy: int = 5,
    num_op: int = 2,
    num_search: int = 200,
    num_top: int = 10,
    smoke_test: bool = False,
    resume: bool = True,
    train_fold_fn: Callable | None = None,
    until: int = 2,
    folds: list[int] | None = None,
    seed: int = 0,
    fold_quality_floor: float | None = None,
    fold_retrain_tries: int = 2,
    phase1_epochs: int | None = None,
    audit_floor: float | None = None,
    random_control: bool = False,
    trial_batch: int = 1,
    fold_stack: int | str = 0,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
    device_cache: str = "auto",
    steps_per_dispatch: int = 1,
    divergence_retries: int = 0,
    ckpt_keep: int = 2,
    watchdog="off",
    work_queue=None,
    compile_cache: str = "off",
    async_pipeline: str | bool = "off",
    pipeline_actors: int = 1,
    pipeline_queue_depth: int = 1,
    telemetry_spec: str = "off",
    fleet_transport=None,
    topup_trials: int = 0,
) -> SearchResult:
    """Run phases 1 and 2; returns the final policy set plus accounting.

    `train_fold_fn(conf, fold, save_path, seed=...)` overrides phase-1
    training (the launcher passes a multi-host scatter; default trains
    in-process sequentially, the single-host analog of the reference's
    Ray scatter, ``search.py:170-206``).  Quality-gate retrains route
    through the same override with a fresh explicit ``seed``; legacy
    three-argument hooks receive it as ``conf['seed']`` instead.

    `folds` restricts BOTH phases to a subset of fold indices — the
    scatter unit for running the search across machines (host k runs
    ``--folds k``, then one host merges the per-fold trial JSONs by
    rerunning with all folds, which resumes instantly from the merged
    trial state).

    `fold_quality_floor` enables the fold-oracle quality gate: folds
    whose no-policy baseline accuracy stays below the floor after
    `fold_retrain_tries` fresh-seed retrains are excluded from ranking.
    `phase1_epochs` overrides conf['epoch'] for phase-1 fold pretraining
    only (weak oracles on small folds are usually under-trained, not
    under-parameterized).  `audit_floor` (None disables) drops any
    selected sub-policy whose standalone mean-over-draws fold accuracy
    falls below ``audit_floor x fold_baseline`` averaged over the folds
    that pass the gate.  All three are additions over the reference —
    see the module docstring and docs/search_postmortem_r2.md.

    `trial_batch` (K, default 1) makes phase 2 TRIAL-PARALLEL ON ONE
    HOST: the TPE proposes K candidates per round (constant-liar
    ``ask(K)``), all K are evaluated by ONE vmapped TTA program per
    batch (K x num_policy x batch forwards filling the device — the
    Podracer batching pattern, arXiv:2104.06272, with the fan-out as a
    mapped primitive in the DrJAX style, arXiv:2403.07128), and the K
    true rewards are told back together.  K=1 takes the sequential code
    path bit-for-bit.  This is the single-host answer to the
    reference's 80 concurrent Ray trials (``search.py:230``); it
    composes with the ``--folds`` multi-host scatter below.  Trial-log
    persistence/resume is per ROUND of K (a crash loses at most the
    in-flight batch).

    `fold_stack` (0, "auto", or K >= 2; default 0) makes phase 1
    FOLD-PARALLEL: every fold needing fresh training advances through
    ONE vmapped K-model program per step (``train_folds_stacked`` — the
    Podracer learner-replica stacking, arXiv:2104.06272), fed by a
    multiplexed iterator that gathers the K per-fold shuffled index
    streams out of the one shared dataset.  0 keeps today's sequential
    loop bit-for-bit; stacked per-fold training reproduces the
    sequential per-fold data and key streams exactly and deviates only
    by the documented ~1 f32 ULP/step batched-kernel bound
    (train_folds_stacked docstring; tests/test_stacked_phase1.py).
    The stacked path only covers the default in-process trainer on
    in-memory datasets: a `train_fold_fn` override, lazy (ImageNet)
    datasets, and every quality-gate retrain take the sequential path
    unchanged.

    `device_cache` ("auto"/"on"/"off") and `steps_per_dispatch` (N)
    select the device-resident data path for every phase-1 training run
    (sequential folds, the fold stack, and quality-gate retrains): the
    dataset is uploaded once, per-epoch index matrices replace the image
    feed, and one dispatch advances N steps (x K folds when stacked) —
    ``train.trainer.train_and_eval`` docstring.  Defaults ("auto", 1)
    are bit-for-bit with the host-fed path on eager datasets; lazy
    (ImageNet) datasets keep the prefetch path under "auto".  Both are
    stamped into ``search_result.json``.  Phase-2 TTA already replays
    device-resident fold batches (``_FoldEval``), so the knob does not
    touch it.

    `aug_dispatch` ("exact" default / "grouped") selects the policy
    application kernel for phase-2 TTA evaluation and the sub-policy
    audit; `aug_groups` is the grouped chunk count.  "exact" reproduces
    the historical vmapped-switch path bit-for-bit; "grouped" keeps the
    ``lax.switch`` op index scalar inside the compiled programs
    (single-branch execution; stratified per-chunk sub-policy draws in
    the multi-sub TTA step, bitwise-identical single-sub lanes in the
    audit and the quality-gate baseline — see docs/BENCHMARKS.md
    "Augmentation dispatch").  Both settings are stamped into
    ``search_result.json``.  Phase-1 pretraining is policy-free, so the
    knob does not touch it.

    Resilience (docs/RESILIENCE.md): `divergence_retries` and
    `ckpt_keep` thread into every phase-1/retry training run (rollback
    chains + NaN-epoch replay); a phase-2 trial whose TTA evaluation
    raises is QUARANTINED — told to the TPE as the worst observed
    reward (the constant-liar value) and recorded with its failure in
    the trial log and ``search_result.json['quarantined_trials']`` —
    instead of killing the search.  A preemption request
    (:class:`PreemptedError`) always propagates: per-fold checkpoints
    and the per-trial log make the rerun resume where it stopped.

    `watchdog` ("off" default / "auto" / seconds) deadline-guards every
    device dispatch this search issues — phase-1 train dispatches, TTA
    evaluations, the audit — raising the typed ``DispatchHungError``
    (exit-77 process-restart recovery) when one wedges; fire counts
    and per-label deadlines are stamped into
    ``search_result.json['resilience']['watchdog']``.

    `work_queue` (a :class:`~fast_autoaugment_tpu.launch.workqueue.
    WorkQueue` over a shared directory, or None) makes the multi-host
    scatter ELASTIC: instead of the static ``--folds`` assignment,
    hosts claim phase-1 fold trainings (with their gate retrains) and
    per-fold phase-2 trial searches off a lease queue, renew the lease
    at dispatch/round boundaries, and RECLAIM units whose lease went
    stale — a dead host's fold is finished by a survivor from the PR-5
    checkpoint chain + per-fold trial log, and the search completes
    with any >= 1 live host.  Trial logs are per-fold files
    (``search_trials.fold<k>.json``) in this mode so concurrent hosts
    never clobber one shared file; the accounting (``degraded``,
    ``lost_hosts``, ``reclaimed_units``) is stamped into the result.
    Fold stacking is forced off (work units are per fold).

    `async_pipeline` ("off" default / "on") restructures the search as
    the streaming actor/learner pipeline (``search/pipeline.py``, the
    Podracer decomposition, arXiv:2104.06272): device ACTOR threads
    (`pipeline_actors`) pull ready-built candidate rounds from a
    bounded queue (`pipeline_queue_depth` rounds proposed ahead) and
    run the usual ``_FoldEval`` TTA dispatches, while the TPE LEARNER
    digests completed rounds and refills proposals concurrently through
    the proposal ledger (``tpe.ask_tagged``/``tell(trial_id, ...)`` —
    out-of-order completions apply in canonical trial-id order, so the
    whole schedule is deterministic given the geometry).  On top, a
    PHASE-OVERLAP scheduler starts fold k's phase-2 trials the moment
    fold k's phase-1 training and quality gate complete, while the
    remaining folds still train (the single-host MPMD pipeline seed,
    arXiv:2412.14374).  "off" (default) is bit-for-bit the historical
    serial driver; "on" with ``pipeline_actors=1, pipeline_queue_depth
    =0`` reproduces the serial trial log exactly (in-flight window of
    one round = no constant-liar horizon), and deeper geometries
    deviate only the way a larger `trial_batch` does — pessimistic
    placeholder posteriors for in-flight rounds.  Accounting lands in
    ``search_result.json['pipeline']`` (mode, actors, queue_depth,
    tell_reorders, device_busy_frac + the dispatch-gap histogram) —
    ``tools/bench_pipeline.py`` / ``make bench-pipeline`` is the
    measured serial-vs-async evidence.  Async mode is single-host:
    `work_queue` forces it off (work units already scatter folds).

    `fleet_transport` (a :class:`~fast_autoaugment_tpu.search.pipeline.
    FleetTransport` over a shared directory, or None) promotes the
    async pipeline's candidate queue to a CROSS-HOST transport: this
    process becomes the LEARNER host — it trains phase-1 folds,
    publishes each gate-cleared fold checkpoint to the fleet the moment
    the gate clears, and publishes ask rounds as leased work units that
    dedicated ACTOR hosts (``search_cli --search-role actor``) claim,
    evaluate, and answer with posted rewards.  The learner buffers
    out-of-order completions and applies them in trial-id order exactly
    as the in-process pipeline does, so an N-host fleet reproduces the
    single-host ``--async-pipeline`` artifacts BIT FOR BIT when
    launched with the same ``pipeline_actors + pipeline_queue_depth``
    in-flight window; dead or preempted actor hosts are reclaimed for
    free by the lease TTL + the fleet ``--elastic`` stack.  Implies
    ``async_pipeline=on`` (the learner schedule IS the pipeline
    schedule) and is mutually exclusive with `work_queue` (which
    scatters whole folds instead of rounds).

    `compile_cache` ("off" default / a directory) wires JAX's
    persistent compilation cache through every compile this search
    pays — phase-1 training, TTA, audit, retrains — so a fresh process
    (exit-77 resume, fleet retry, reclaimed unit) deserializes its
    executables instead of re-lowering them; hit/miss counts and
    per-label first-call seconds are stamped into
    ``search_result.json['compile_cache']`` (``core/compilecache.py``;
    "off" still honors an inherited ``FAA_COMPILE_CACHE``).

    `topup_trials` (0 default) is the WARM-START entry point the
    control plane's incremental re-search uses (``control/research.py``,
    docs/CONTROL.md): a completed search's per-fold budget extends by
    this many trials — resume replays the persisted trial log (through
    the PR-9 ``replay_trial_log`` ledger under ``async_pipeline``), only
    the top-up dispatches, and the artifact stamps ``warm_start``.  A
    top-up of 0 is a plain resume: `final_policy.json` reproduces the
    one-shot run byte-identically.

    PHASE ordering stays sequential (VERDICT round 1, next-step 9):
    phase-1 fold training and phase-2 TTA evaluation are both
    device-bound on the same chip, so overlapping PHASES cannot shorten
    the critical path.  The reference's concurrent fold trains
    (``search.py:170-206``) exploit a multi-GPU Ray cluster; the
    equivalent concurrency here is `trial_batch` within a fold plus the
    ``--folds`` multi-host scatter across folds (each host pretrains
    AND searches its own folds in parallel with the others), merged by
    ``tools/merge_trials.py``.  Per-fold checkpoint + trial-log resume
    means an interrupted run loses at most the in-flight work.
    """
    if smoke_test:  # reference --smoke-test (search.py:153, 235)
        num_search = 4

    # warm-started incremental re-search (the control plane's entry
    # point, control/research.py + docs/CONTROL.md): `topup_trials` > 0
    # EXTENDS a completed search's per-fold trial budget by that many
    # trials — resume replays the persisted trial log (the async
    # pipeline routes it through the PR-9 replay_trial_log ledger, so
    # the TPE's RNG stream sits exactly where the original run left
    # it), then only the top-up trials dispatch.  0 (default) leaves
    # the historical budget — and the artifact stream — untouched;
    # topup with an EMPTY save_dir is just a longer fresh search.
    topup_trials = max(0, int(topup_trials))
    if topup_trials:
        base_num_search = num_search
        num_search += topup_trials

    # persistent compile cache (core/compilecache.py): "off" (default,
    # bit-for-bit historical) still honors an inherited
    # FAA_COMPILE_CACHE, which is how fleet retries and reclaimed work
    # units warm-start; every compile this search pays is classified
    # hit/miss and stamped into search_result.json['compile_cache']
    configure_compile_cache(compile_cache)
    # flight-recorder journal (core/telemetry.py): "off" (default,
    # bit-for-bit — no file I/O, no new artifact keys) still honors an
    # inherited FAA_TELEMETRY, the fleet/relaunch handoff
    telemetry.configure_telemetry(telemetry_spec)
    fold_quality_floor = resolve_quality_floor(
        fold_quality_floor, num_class(conf["dataset"])
    )
    os.makedirs(save_dir, exist_ok=True)
    mesh = make_mesh()
    watch = {"start": wall()}
    result = SearchResult()
    # device-hours ledger provenance (VERDICT r4 weak 5): the ``tpu_
    # secs_*`` fields are wall x device_count on WHATEVER backend ran —
    # a CPU dev-box run must not read as TPU-hours.  Every consumer can
    # now tell from the artifact alone.
    dev0 = jax.devices()[0]
    result["backend"] = dev0.platform
    result["device_kind"] = getattr(dev0, "device_kind", dev0.platform)
    result["device_count"] = mesh.size
    # the guard settings this run actually used — the defaults-safety
    # regression test reads these back from the committed artifact
    result["guards"] = {
        "fold_quality_floor": fold_quality_floor,
        "fold_retrain_tries": fold_retrain_tries,
        "audit_floor": audit_floor,
        "phase1_epochs": phase1_epochs,
    }
    fold_list = list(folds) if folds is not None else list(range(cv_num))
    bad = [f for f in fold_list if not 0 <= f < cv_num]
    if bad:
        raise ValueError(f"fold indices {bad} out of range [0, {cv_num})")

    trials_path = os.path.join(save_dir, "search_trials.json")
    trials_log: dict = {}
    if resume and os.path.exists(trials_path):
        trials_log = fsfault.load_json(trials_path)

    def _fold_trials_path(fold: int) -> str:
        """Per-fold trial log (work-queue mode): one writer per lease,
        so concurrent hosts can never clobber each other's folds."""
        return os.path.join(save_dir, f"search_trials.fold{fold}.json")

    def _load_fold_trials(fold: int) -> list:
        if work_queue is not None and os.path.exists(_fold_trials_path(fold)):
            return fsfault.load_json(_fold_trials_path(fold))
        return trials_log.get(str(fold), [])

    def _fold_searched(fold: int) -> bool:
        return len(_load_fold_trials(fold)) >= num_search

    trial_batch = max(1, int(trial_batch))
    result["trial_batch"] = trial_batch
    if topup_trials:
        # stamped ONLY on warm-started runs: a default run's artifact
        # carries no new keys (the defaults-bit-for-bit contract)
        result["warm_start"] = {
            "base_num_search": base_num_search,
            "topup_trials": topup_trials,
            "num_search": num_search,
            "resumed_trials_per_fold": {
                str(f): len(_load_fold_trials(f)) for f in fold_list},
        }
    wd = resolve_watchdog(watchdog)
    # async actor/learner pipeline (search/pipeline.py): resolved here
    # so a typo fails loudly before any training; the dispatch trace is
    # armed for async runs and (FAA_PIPELINE_TRACE=1) serial baselines
    # so the pipeline bench can compare gap histograms
    from fast_autoaugment_tpu.search.pipeline import (
        DispatchTrace,
        resolve_async_pipeline,
    )

    pipeline_on = resolve_async_pipeline(async_pipeline)
    pipeline_actors = max(1, int(pipeline_actors))
    pipeline_queue_depth = max(0, int(pipeline_queue_depth))
    if fleet_transport is not None and work_queue is not None:
        raise ValueError(
            "fleet_transport and work_queue are mutually exclusive: the "
            "round transport scatters ask ROUNDS across actor hosts, the "
            "lease workqueue scatters whole FOLDS across peer searches")
    if fleet_transport is not None and not pipeline_on:
        # the learner schedule IS the pipeline schedule (ask horizon,
        # reorder buffer, id-order tells) — rounds just dispatch to
        # actor hosts instead of actor threads
        logger.info("fleet transport: async pipeline forced ON (the "
                    "learner's round schedule is the pipeline schedule)")
        pipeline_on = True
    if pipeline_on and work_queue is not None:
        logger.warning("workqueue: async pipeline forced off — the lease "
                       "queue already scatters folds across hosts")
        pipeline_on = False
    # async mode dispatches compiled programs from several threads:
    # serialize their ENQUEUE so every device queue sees one global
    # program order (the cross-thread collective rendezvous deadlock —
    # core/watchdog.py docstring).  Explicitly disarmed for serial runs
    # so one process can alternate modes.
    from fast_autoaugment_tpu.core.watchdog import arm_dispatch_serializer

    arm_dispatch_serializer(pipeline_on)
    trace = None
    if pipeline_on or os.environ.get("FAA_PIPELINE_TRACE"):
        trace = DispatchTrace()
    evaluator = _FoldEval(
        conf, dataroot, mesh,
        num_policy=num_policy, num_op=num_op, cv_ratio=cv_ratio, seed=seed,
        trial_batch=trial_batch, aug_dispatch=aug_dispatch,
        aug_groups=aug_groups, watchdog=wd, trace=trace,
    )
    # dispatch-mode stamping: the artifact must say which augmentation
    # kernel scored these trials (grouped deviates distributionally)
    result["aug_dispatch"] = evaluator.aug_dispatch
    result["aug_groups"] = evaluator.aug_groups
    # feed-path stamping: which data path trained the phase-1 oracles
    # (steps_per_dispatch>1 deviates by the documented scan ULP bound)
    steps_per_dispatch = max(1, int(steps_per_dispatch))
    result["device_cache"] = device_cache
    result["steps_per_dispatch"] = steps_per_dispatch
    divergence_retries = max(0, int(divergence_retries))
    ckpt_keep = max(1, int(ckpt_keep))
    result["resilience"] = {"divergence_retries": divergence_retries,
                            "ckpt_keep": ckpt_keep,
                            "watchdog": wd.stats()}
    # quarantined phase-2 trials (TTA evaluation raised): recorded, told
    # to TPE as the worst observed reward, never ranked
    quarantined: list[dict] = []
    # shared by the sequential trainer AND the fold stack; the
    # divergence-retry knob is sequential-only (train_and_eval); the
    # ONE watchdog instance threads through so fire counts aggregate
    train_feed_kw = dict(device_cache=device_cache,
                         steps_per_dispatch=steps_per_dispatch,
                         ckpt_keep=ckpt_keep, watchdog=wd)
    seq_train_kw = dict(train_feed_kw, divergence_retries=divergence_retries)

    def _lease_heartbeat(unit: str):
        """Dispatch-boundary callback for the trainer / trial loop:
        renew the unit's lease + this host's liveness beat."""
        if work_queue is None:
            return None

        def beat():
            work_queue.renew(unit)
            work_queue.beat_host()
        return beat
    fold_baselines: dict[int, float] = {}
    excluded_folds: list[int] = []

    # ---------------- phase 1: pretrain without augmentation ----------
    t0 = wall()
    no_aug_conf = conf.replace(aug="default")
    if phase1_epochs:
        no_aug_conf = no_aug_conf.replace(epoch=int(phase1_epochs))
    fold_paths = [_fold_ckpt_path(save_dir, conf, f, cv_ratio)
                  for f in range(cv_num)]
    phase1_epochs_eff = int(no_aug_conf["epoch"])
    # per-fold device-seconds attribution: every phase-1 training wall
    # is measured on ONE PhaseStopwatch ledger (utils/profiling.py,
    # mirrored into the telemetry registry) and attributed per fold by
    # phase1_device_seconds_attribution — stacked groups split their one
    # measured wall evenly; device_secs_phase1 stays the once-recorded
    # phase total and the attribution must sum to (at most) it
    from fast_autoaugment_tpu.utils.profiling import PhaseStopwatch

    phase1_sw = PhaseStopwatch(device_count=mesh.size,
                               registry=telemetry.registry())
    stack_groups: list[list[int]] = []

    def _needs_training(fold: int) -> bool:
        meta = read_metadata(fold_paths[fold])
        return not (resume and meta
                    and meta.get("epoch", 0) >= phase1_epochs_eff)

    # fold-stacked phase 1 (the tentpole): all pending folds advance in
    # one vmapped program; the per-fold loop below then finds their
    # checkpoints complete and only runs the quality gate / accounting.
    stack_trained: set[int] = set()
    pending = [f for f in fold_list
               if not _fold_searched(f) and _needs_training(f)]
    if work_queue is not None and fold_stack not in (None, 0, "0"):
        # lease units are per fold: a stacked group would advance folds
        # this host does not own
        logger.warning("workqueue: fold stacking forced off — work "
                       "units are per fold")
        fold_stack = 0
    stack_k = resolve_fold_stack(fold_stack, len(pending))
    if stack_k and train_fold_fn is not None:
        logger.warning(
            "fold-stack: a train_fold_fn override is set — the stacked "
            "trainer only covers the in-process default; falling back "
            "to the sequential per-fold path")
        stack_k = 0
    if stack_k and conf["dataset"].endswith("imagenet"):
        logger.warning(
            "fold-stack: %s is a lazy on-disk dataset — per-fold host "
            "decode streams cannot be multiplexed bit-for-bit; falling "
            "back to the sequential per-fold path", conf["dataset"])
        stack_k = 0
    result["fold_stack"] = stack_k
    if stack_k:
        for lo in range(0, len(pending), stack_k):
            group = pending[lo:lo + stack_k]
            logger.info("phase1: training folds %s fold-stacked (K=%d)",
                        group, len(group))
            with phase1_sw.phase(f"phase1_stack{len(stack_groups)}"):
                train_folds_stacked(
                    no_aug_conf, dataroot, cv_ratio=cv_ratio, folds=group,
                    save_paths=[fold_paths[f] for f in group], seed=seed,
                    resume=resume, **train_feed_kw,
                )
            stack_groups.append([int(f) for f in group])
            stack_trained.update(group)

    def _phase1_fold(fold: int, heartbeat=None) -> None:
        """The full per-fold phase-1 body: train if needed, then the
        fold-oracle quality gate (+fresh-seed retrains).  `heartbeat`
        (work-queue mode) renews the fold's lease at every trainer
        dispatch boundary."""
        path = fold_paths[fold]
        if _fold_searched(fold):
            # merged trial state from another host: nothing left to train,
            # but the quality gate still applies — a resumed weak oracle
            # must not rank policies (its trial budget is spent, so no
            # retrain: measure and exclude only)
            logger.info("phase1: fold %d already searched (merged trials)", fold)
            if fold_quality_floor is not None:
                if os.path.exists(path):
                    acc = evaluator.baseline(fold, path)
                    fold_baselines[fold] = acc
                    if acc < fold_quality_floor:
                        logger.warning(
                            "phase1: resumed fold %d baseline %.3f below "
                            "floor %.3f — EXCLUDED from ranking", fold, acc,
                            fold_quality_floor,
                        )
                        excluded_folds.append(fold)
                else:
                    logger.warning(
                        "phase1: fold %d searched elsewhere and its "
                        "checkpoint is not on this host — quality gate "
                        "cannot assess it; trials rank ungated", fold,
                    )
            return
        meta = read_metadata(path)
        if fold in stack_trained:
            logger.info("phase1: fold %d trained in the stacked program", fold)
        elif not (resume and meta and meta.get("epoch", 0) >= phase1_epochs_eff):
            logger.info("phase1: training fold %d -> %s", fold, path)
            with phase1_sw.phase(f"phase1_fold{fold}"):
                if train_fold_fn is not None:
                    _call_train_fold_fn(train_fold_fn, no_aug_conf, fold,
                                        path, seed)
                else:
                    train_and_eval(
                        no_aug_conf, dataroot,
                        test_ratio=cv_ratio, cv_fold=fold,
                        save_path=path, metric="last", seed=seed,
                        heartbeat=heartbeat, **seq_train_kw,
                    )
        else:
            logger.info("phase1: fold %d already trained (epoch %d)", fold, meta["epoch"])

        # fold-oracle quality gate (round-2 post-mortem: fold baselines
        # of 0.37-0.65 produced a reward signal that ranked destructive
        # policies on top)
        if fold_quality_floor is None:
            return
        acc = evaluator.baseline(fold, path)
        tries = 0
        while acc < fold_quality_floor and tries < fold_retrain_tries:
            tries += 1
            alt = f"{path}.retry{tries}"
            logger.warning(
                "phase1: fold %d baseline %.3f < floor %.3f — retraining "
                "with a fresh seed (try %d/%d)",
                fold, acc, fold_quality_floor, tries, fold_retrain_tries,
            )
            _remove_ckpt(alt)
            retry_seed = seed + 1009 * tries + fold
            with phase1_sw.phase(f"phase1_fold{fold}"):
                if train_fold_fn is not None:
                    # same mechanism as the initial training (a caller's
                    # scatter/trainer override applies to retries too);
                    # the fresh seed is passed explicitly when the hook
                    # accepts it, and rides on conf['seed'] either way
                    _call_train_fold_fn(
                        train_fold_fn, no_aug_conf, fold, alt, retry_seed
                    )
                else:
                    train_and_eval(
                        no_aug_conf, dataroot, test_ratio=cv_ratio,
                        cv_fold=fold,
                        save_path=alt, metric="last", seed=retry_seed,
                        heartbeat=heartbeat, **seq_train_kw,
                    )
            alt_acc = evaluator.baseline(fold, alt)
            if alt_acc > acc:
                _replace_ckpt(alt, path)
                acc = alt_acc
            else:
                _remove_ckpt(alt)
        fold_baselines[fold] = acc
        if acc < fold_quality_floor:
            logger.warning(
                "phase1: fold %d baseline %.3f still below floor %.3f after "
                "%d retrains — EXCLUDED from policy ranking",
                fold, acc, fold_quality_floor, fold_retrain_tries,
            )
            excluded_folds.append(fold)
        else:
            logger.info("phase1: fold %d baseline %.3f (floor %.3f) ok",
                        fold, acc, fold_quality_floor)

    def _workqueue_phase(units: dict[int, str], run) -> None:
        """Claim-and-run `units` ({fold: unit_id}) until EVERY unit is
        done (by this host or any other).  Passes that find nothing
        claimable wait out a fraction of the TTL — a stale lease (dead
        or wedged owner) is then reclaimed and the unit finished here,
        resuming from the shared checkpoint chain / trial log.  A
        LeaseLostError mid-work abandons the unit to its new owner
        (this host was presumed dead; its writes stay safe — same
        seeds, same atomic chain)."""
        from fast_autoaugment_tpu.launch.workqueue import LeaseLostError

        pending = dict(units)
        while pending:
            progress = False
            for fold, unit in sorted(pending.items()):
                if work_queue.is_done(unit):
                    del pending[fold]
                    progress = True
                    continue
                if not work_queue.claim(unit):
                    continue
                work_queue.beat_host()
                try:
                    info = run(fold, unit)
                    # release() verifies the fencing token at post
                    # time — a robbed host raises here instead of
                    # clobbering the reclaimer's completion record
                    work_queue.release(unit, info=info)
                except LeaseLostError as e:
                    logger.warning(
                        "workqueue: lost the lease on %s mid-work (%s) — "
                        "abandoning it to its new owner", unit, e)
                    continue
                del pending[fold]
                progress = True
            if pending and not progress:
                work_queue.beat_host()
                # TTL-bounded claim poll: the loop's exit is queue
                # completion by ANY host, and each wait is capped well
                # under the lease TTL so reclaims are never starved
                time.sleep(max(0.2, min(5.0, work_queue.lease_ttl / 4.0)))  # robust: allow
        work_queue.beat_host()

    # phase overlap (async pipeline): phase-1 fold training moves onto
    # a trainer thread inside the phase-2 section below — fold k's TPE
    # trials start the moment its gate clears, while fold k+1 still
    # trains.  Stacked groups (if any) already trained above, in the
    # main thread; the overlapped per-fold body then only runs gates.
    overlap_mode = pipeline_on and work_queue is None and until >= 2
    if overlap_mode:
        logger.info(
            "async pipeline: overlapping phase-1 fold training with "
            "phase-2 search (each fold hands over at gate completion)")
    elif work_queue is None:
        for fold in range(cv_num):
            if fold not in fold_list:
                continue
            _phase1_fold(fold)
    else:
        work_queue.beat_host()

        def _run_p1(fold, unit):
            _phase1_fold(fold, heartbeat=_lease_heartbeat(unit))
            return {"baseline": fold_baselines.get(fold),
                    "excluded": fold in excluded_folds}

        _workqueue_phase({f: f"p1-fold{f}" for f in fold_list}, _run_p1)
        # folds finished by other hosts: adopt their gate verdicts from
        # the done markers (the ranking below must honor every
        # exclusion, wherever the gate ran)
        for fold in fold_list:
            info = work_queue.done_info(f"p1-fold{fold}") or {}
            if info.get("baseline") is not None and fold not in fold_baselines:
                fold_baselines[fold] = float(info["baseline"])
            if info.get("excluded") and fold not in excluded_folds:
                excluded_folds.append(fold)
    phase1_t0 = t0

    def _stamp_phase1(end_time: float | None = None):
        # device_secs_* is the honest name; tpu_secs_* stays as a
        # compatibility alias for committed-artifact readers (same value)
        end = wall() if end_time is None else end_time
        result["device_secs_phase1"] = result["tpu_secs_phase1"] = (
            (end - phase1_t0) * mesh.size)
        # per-fold attribution of the phase total, sourced from the ONE
        # stopwatch ledger every phase-1 training ran under (stacked
        # groups record ONE wall measurement and split it evenly — the
        # phase total is never double-counted); the gap between
        # sum(per_fold) and device_secs_phase1 is the gate's baseline
        # evals plus setup, which belong to no single fold
        attr = phase1_device_seconds_attribution(
            phase1_sw, fold_list, stack_groups)
        result["device_secs_phase1_per_fold"] = {
            str(f): attr[f] for f in sorted(attr)}
        result["fold_baselines"] = {
            str(k): v for k, v in fold_baselines.items()}
        result["excluded_folds"] = list(excluded_folds)

    if not overlap_mode:  # overlap re-stamps after the trainer finishes
        _stamp_phase1()
    if until < 2:
        result["final_policy_set"] = []
        result["compile_cache"] = compile_cache_stats()
        result["elapsed_total"] = wall() - watch["start"]
        if fleet_transport is not None:
            # no rounds will ever be published: let actor hosts drain
            fleet_transport.mark_search_done({"until": until})
        return result

    # ---------------- phase 2: TPE search per fold --------------------
    t0 = wall()
    space = make_search_space(num_policy, num_op)
    final_policy_set = []
    # async-pipeline accounting + the cross-thread stop channel: the
    # overlapped trainer pushes its failure here so the in-flight
    # learner stops at the next round boundary instead of finishing
    # the fold against a dying run
    pipeline_fold_stats: list[dict] = []
    pipeline_stop_cell: list[BaseException] = []
    pipeline_overlap_timeline: dict = {}

    def _pipeline_should_stop():
        return pipeline_stop_cell[0] if pipeline_stop_cell else None

    def _phase2_fold_async(fold, params, batch_stats, tpe, key_fold,
                           fold_trials, heartbeat=None) -> dict:
        """One fold's trial budget through the actor/learner pipeline
        (``search/pipeline.py``).  Persistence, quarantine and census
        bookkeeping mirror the serial schedulers; the trial log is
        appended in trial-id order so the artifact stream is
        schedule-invariant."""
        from fast_autoaugment_tpu.search.pipeline import (
            replay_trial_log,
            run_fold_pipeline,
        )

        replay_trial_log(
            tpe, fold_trials, trial_batch, num_search,
            max_inflight=pipeline_actors + pipeline_queue_depth)

        def _persist():
            trials_log[str(fold)] = fold_trials
            if work_queue is not None:
                _write_json_atomic(_fold_trials_path(fold), fold_trials)
            else:
                _write_json_atomic(trials_path, trials_log)

        def _record_quarantine(lo, hi, exc, worst):
            from fast_autoaugment_tpu.search.pipeline import _failure_text

            text = _failure_text(exc)
            logger.warning(
                "phase2 fold %d trial(s) %d-%d: TTA evaluation FAILED "
                "(%s) — QUARANTINED with worst-observed reward %.4f; "
                "the search continues", fold, lo, hi - 1, text, worst)
            for t in range(lo, hi):
                quarantined.append({
                    "fold": fold, "trial": t, "error": text})

        def _on_first_ok():
            if trial_batch > 1:
                if "tta_batched_executables_first" not in result:
                    result["tta_batched_executables_first"] = (
                        executable_census(evaluator.tta_step_batch))
            elif "tta_executables_first" not in result:
                result["tta_executables_first"] = executable_census(
                    evaluator.tta_step)

        backend = None
        on_first_ok = _on_first_ok
        if fleet_transport is not None:
            # rounds dispatch to ACTOR HOSTS: publish instead of
            # enqueue, poll done markers instead of a results queue.
            # key_seed reproduces this fold's key stream on any host
            # (key_fold IS PRNGKey(seed * 77 + fold) — see above)
            backend = fleet_transport.learner_backend(
                fold, key_seed=seed * 77 + fold, trial_batch=trial_batch,
                num_policy=num_policy, num_op=num_op)
            heartbeat = fleet_transport.beat
            # no local TTA dispatches on the learner: the executable
            # census belongs to the actor hosts
            on_first_ok = None

        if trace is not None:
            trace.begin_segment(f"p2-fold{fold}")
        try:
            stats = run_fold_pipeline(
                evaluator, fold, params, batch_stats, tpe, key_fold,
                fold_trials,
                num_search=num_search, trial_batch=trial_batch,
                actors=pipeline_actors, queue_depth=pipeline_queue_depth,
                num_policy=num_policy, num_op=num_op,
                persist=_persist, record_quarantine=_record_quarantine,
                on_first_ok=on_first_ok,
                should_stop=_pipeline_should_stop, heartbeat=heartbeat,
                backend=backend,
            )
        finally:
            if trace is not None:
                trace.end_segment()
        pipeline_fold_stats.append(dict(stats, fold=fold))
        return {"num_trials": len(fold_trials)}

    def _phase2_fold(fold: int, heartbeat=None) -> dict | None:
        """One fold's full TPE trial budget (sequential or batched
        scheduler).  `heartbeat` (work-queue mode) renews the fold's
        lease after every persisted trial/round."""
        if fold in excluded_folds:
            logger.info("phase2: fold %d excluded by the quality gate", fold)
            return None
        if _fold_searched(fold):
            logger.info("phase2: fold %d trials already complete", fold)
            trials_log[str(fold)] = _load_fold_trials(fold)
            return None
        params, batch_stats = evaluator.load_fold(fold_paths[fold])

        # small budgets keep some TPE engagement: the hyperopt default
        # n_startup=20 leaves a 60-trial run barely out of the random
        # phase (round-2 run; docs/tpe_benchmark.md)
        tpe = TPE(space, seed=seed * 1000 + fold,
                  n_startup=min(20, max(5, num_search // 4)))
        key_fold = jax.random.PRNGKey(seed * 77 + fold)
        fold_trials = _load_fold_trials(fold)
        if pipeline_on:
            # async actor/learner scheduler: resume replay goes through
            # the proposal ledger (exact ask/tell interleaving) inside
            return _phase2_fold_async(fold, params, batch_stats, tpe,
                                      key_fold, fold_trials, heartbeat)
        for entry in fold_trials:  # resume previous trials (a third
            # element marks a quarantined trial's failure record)
            tpe.tell(entry[0], entry[1])

        def _persist_trials():
            trials_log[str(fold)] = fold_trials
            if work_queue is not None:
                # one writer per lease: the fold file, not the shared log
                _write_json_atomic(_fold_trials_path(fold), fold_trials)
            else:
                _write_json_atomic(trials_path, trials_log)
            if heartbeat is not None:
                heartbeat()

        def _quarantine(trial_lo: int, trial_hi: int, exc: BaseException,
                        fold=fold) -> float:
            """Record failed trial(s) and return the pessimistic reward
            told to the TPE — the worst observed value, mirroring the
            constant-liar placeholder (search/tpe.py::ask)."""
            worst = (min(r for _, r in tpe.observations)
                     if tpe.observations else 0.0)
            logger.warning(
                "phase2 fold %d trial(s) %d-%d: TTA evaluation FAILED "
                "(%s: %s) — QUARANTINED with worst-observed reward %.4f; "
                "the search continues", fold, trial_lo, trial_hi - 1,
                type(exc).__name__, exc, worst)
            for t in range(trial_lo, trial_hi):
                quarantined.append({
                    "fold": fold, "trial": t,
                    "error": f"{type(exc).__name__}: {exc}"})
            return worst

        fi = None

        def _injected_trial_error(trial_idx: int):
            nonlocal fi
            from fast_autoaugment_tpu.utils import faultinject

            fi = faultinject.active_plan()
            if fi is not None and fi.trial_error_at(trial_idx):
                raise RuntimeError(
                    f"injected trial_error at trial {trial_idx}")

        if trace is not None:  # serial dispatch-gap baseline
            trace.begin_segment(f"p2-fold{fold}")
        while trial_batch <= 1 and len(tpe.observations) < num_search:
            trial_idx = len(tpe.observations)
            proposal = tpe.suggest()
            policies = policy_decoder(proposal, num_policy, num_op)
            policy_t = jnp.asarray(policy_to_tensor(policies))
            failure = None
            try:
                _injected_trial_error(trial_idx)
                metrics = evaluator.evaluate(
                    fold, params, batch_stats, policy_t,
                    jax.random.fold_in(key_fold, trial_idx),
                )
                reward = metrics["top1_valid"]
            except (PreemptedError, DispatchHungError):
                # graceful shutdown is NOT a trial failure, and a hung
                # dispatch means the backend is wedged — quarantining it
                # would keep dispatching into the wedge; both take the
                # exit-77 restart path
                raise
            except (ArithmeticError, RuntimeError, ValueError, OSError) as e:
                reward = _quarantine(trial_idx, trial_idx + 1, e)
                failure = {"quarantined": True,
                           "error": f"{type(e).__name__}: {e}"}
            if failure is None and "tta_executables_first" not in result:
                # snapshot after the very first evaluation: the
                # zero-recompile assertion is final == first
                result["tta_executables_first"] = executable_census(
                    evaluator.tta_step)
            tpe.tell(proposal, reward)
            telemetry.emit("trial", f"fold{fold}", fold=fold,
                           trial=trial_idx, reward=float(reward),
                           quarantined=failure is not None)
            fold_trials.append(
                (proposal, reward) if failure is None
                else (proposal, reward, failure))
            # persist EVERY trial (fsync + atomic rename): a crash loses
            # at most the in-flight evaluation (VERDICT r3, weak 4); the
            # JSON is small and the write is trivially cheap next to a
            # compiled TTA evaluation.  Trial persistence is also the
            # lease-renewal boundary in work-queue mode.
            _persist_trials()
            if trial_idx % 10 == 0 or trial_idx == num_search - 1:
                logger.info(
                    "phase2 fold %d trial %d/%d: top1_valid=%.4f best=%.4f",
                    fold, trial_idx, num_search, reward, tpe.best[1],
                )

        # trial-parallel scheduler (trial_batch = K > 1): ask K
        # constant-liar proposals, evaluate all K in one vmapped TTA
        # program per batch, tell the K true rewards back together.
        # Persistence/resume is per ROUND: a crash loses at most the
        # in-flight K evaluations.
        while trial_batch > 1 and len(tpe.observations) < num_search:
            t_base = len(tpe.observations)
            k_eff = min(trial_batch, num_search - t_base)
            proposals = tpe.ask(k_eff)
            # pad the candidate axis to the compiled K on a short final
            # round (one executable per K — never recompile); padded
            # lanes repeat the last proposal, their results are dropped
            padded = proposals + [proposals[-1]] * (trial_batch - k_eff)
            policies_t = jnp.asarray(np.stack([
                np.asarray(policy_to_tensor(
                    policy_decoder(p, num_policy, num_op)), np.float32)
                for p in padded
            ]))
            # candidate i's trial key is EXACTLY the sequential trial
            # (t_base + i)'s key, so a K-batched evaluation is
            # numerically identical to K sequential ones
            keys = jnp.stack([
                jax.random.fold_in(key_fold, t_base + i)
                for i in range(trial_batch)
            ])
            round_failure = None
            try:
                for i in range(k_eff):
                    _injected_trial_error(t_base + i)
                metrics_list = evaluator.evaluate_batch(
                    fold, params, batch_stats, policies_t, keys)[:k_eff]
                rewards = [m["top1_valid"] for m in metrics_list]
            except (PreemptedError, DispatchHungError):
                raise  # shutdown / wedged backend: restart, not quarantine
            except (ArithmeticError, RuntimeError, ValueError, OSError) as e:
                # one vmapped program evaluates the whole round: a raise
                # cannot be attributed to a single candidate, so the
                # ROUND is quarantined (K x the sequential policy)
                worst = _quarantine(t_base, t_base + k_eff, e)
                rewards = [worst] * k_eff
                round_failure = {"quarantined": True,
                                 "error": f"{type(e).__name__}: {e}"}
            if round_failure is None and \
                    "tta_batched_executables_first" not in result:
                result["tta_batched_executables_first"] = executable_census(
                    evaluator.tta_step_batch)
            tpe.tell_batch(proposals, rewards)
            for i, r in enumerate(rewards):
                telemetry.emit("trial", f"fold{fold}", fold=fold,
                               trial=t_base + i, reward=float(r),
                               quarantined=round_failure is not None)
            fold_trials.extend(
                (p, r) if round_failure is None else (p, r, round_failure)
                for p, r in zip(proposals, rewards))
            _persist_trials()
            logger.info(
                "phase2 fold %d trials %d-%d/%d (batch of %d): "
                "best_in_batch=%.4f best=%.4f",
                fold, t_base, t_base + k_eff - 1, num_search, k_eff,
                max(rewards), tpe.best[1],
            )
        if trace is not None:
            trace.end_segment()
        return {"num_trials": len(fold_trials)}

    if overlap_mode:
        from fast_autoaugment_tpu.search.pipeline import (
            run_overlapped_phases,
        )

        def _p1_overlap(f):
            try:
                _phase1_fold(
                    f, heartbeat=(fleet_transport.beat
                                  if fleet_transport is not None else None))
            except BaseException as e:
                # the in-flight learner must stop at its next round
                # boundary, not finish the fold against a dying run
                pipeline_stop_cell.append(e)
                raise
            if fleet_transport is not None and f not in excluded_folds \
                    and os.path.exists(fold_paths[f]):
                # stream the gate-cleared checkpoint to the fleet the
                # moment the gate clears — fold f's rounds dispatch to
                # actor hosts while fold f+1 still trains HERE
                fleet_transport.publish_checkpoint(f, fold_paths[f])

        timeline = run_overlapped_phases(fold_list, _p1_overlap,
                                         _phase2_fold)
        pipeline_overlap_timeline.update(timeline)
        p1_ends = [v["end"] for v in timeline["phase1"].values()]
        _stamp_phase1(max(p1_ends) if p1_ends else None)
    elif work_queue is None:
        for fold in fold_list:
            _phase2_fold(fold)
    else:
        def _run_p2(fold, unit):
            return _phase2_fold(fold, heartbeat=_lease_heartbeat(unit)) or {}

        _workqueue_phase(
            {f: f"p2-fold{f}" for f in fold_list if f not in excluded_folds},
            _run_p2)
        # every fold's trials (finished here or by other hosts) merge
        # into the in-memory log so the ranking below sees all of them
        for fold in fold_list:
            ft = _load_fold_trials(fold)
            if ft:
                trials_log[str(fold)] = ft

    # top-N per fold from the trial log (covers folds run here, folds
    # merged from other hosts, and folds resumed from disk alike,
    # search.py:253-259); only in-range folds with COMPLETE searches count
    for fold_key in sorted(trials_log, key=int):
        fold_trials = trials_log[fold_key]
        if not 0 <= int(fold_key) < cv_num:
            logger.warning("ignoring stale fold %s in trial log", fold_key)
            continue
        if int(fold_key) in excluded_folds:
            logger.warning("fold %s excluded by the quality gate — its "
                           "trials do not rank", fold_key)
            continue
        if len(fold_trials) < num_search:
            logger.warning(
                "fold %s has %d/%d trials — incomplete, excluded from the "
                "final policy set", fold_key, len(fold_trials), num_search,
            )
            continue
        # quarantined trials (3rd element = failure record) carry the
        # worst-observed placeholder reward; they never rank — a failed
        # evaluation must not nominate policies even in a tiny run
        scored = [t for t in fold_trials
                  if len(t) < 3 or not (t[2] or {}).get("quarantined")]
        ranked = sorted(scored, key=lambda o: -o[1])[:num_top]
        for entry in ranked:
            final_policy_set.extend(
                policy_decoder(entry[0], num_policy, num_op))

    final_policy_set = remove_duplicates(final_policy_set)
    result["num_sub_policies_selected"] = len(final_policy_set)
    # canonical quarantine stamp from the PERSISTED trial log: covers
    # trials failed in this process and ones resumed from disk alike
    quarantined = [
        {"fold": int(fk), "trial": i,
         "error": (t[2] or {}).get("error", "unknown")}
        for fk, trs in sorted(trials_log.items())
        if fk.lstrip("-").isdigit()
        for i, t in enumerate(trs)
        if len(t) >= 3 and (t[2] or {}).get("quarantined")
    ]
    result["quarantined_trials"] = quarantined
    result["num_quarantined_trials"] = len(quarantined)
    if quarantined:
        logger.warning(
            "phase2: %d trial(s) quarantined after failed TTA "
            "evaluations — see search_result.json['quarantined_trials']",
            len(quarantined))
    result["device_secs_phase2"] = result["tpu_secs_phase2"] = (
        (wall() - t0) * mesh.size)
    # async-pipeline accounting (+ the dispatch-gap evidence whenever
    # the trace is armed — FAA_PIPELINE_TRACE=1 captures the serial
    # baseline the pipeline bench compares against).  In overlap mode
    # device_secs_phase2 spans the whole overlapped region; the
    # timeline below carries the per-fold interleaving.
    if pipeline_on or trace is not None:
        gaps = trace.summary() if trace is not None else None
        result["pipeline"] = {
            "mode": "on" if pipeline_on else "off",
            "actors": pipeline_actors if pipeline_on else None,
            "queue_depth": pipeline_queue_depth if pipeline_on else None,
            "max_inflight": (pipeline_actors + pipeline_queue_depth
                             if pipeline_on else None),
            "tell_reorders": sum(
                s["tell_reorders"] for s in pipeline_fold_stats),
            "rounds": sum(s["rounds"] for s in pipeline_fold_stats),
            "per_fold": pipeline_fold_stats,
            "device_busy_frac": (gaps or {}).get("device_busy_frac"),
            "dispatch_gaps": gaps,
            "overlap": pipeline_overlap_timeline or None,
        }
    # compile-cache census: the whole point of policy-as-tensor TTA is
    # that EVERY trial reuses one executable (SURVEY.md hard-part 3) —
    # record it so the search-cost artifact can assert zero recompiles
    # across all num_search x folds evaluations.  executable_census is
    # the version-guarded probe (jit private _cache_size, else the
    # explicit trace-event counter, else a loud warning + None).
    # a fully-resumed run never builds the TTA machinery — there were
    # no evaluations in this process, so there is nothing to census
    result["tta_executables"] = (
        executable_census(evaluator.tta_step) if evaluator._built else None)
    # the expected ABSOLUTE count is one executable per distinct
    # policy-tensor shape actually evaluated: [num_policy, num_op, 3]
    # for every trial, plus [1, num_op, 3] once when the quality gate
    # measured identity baselines — 2 with the gate on, 1 without
    # (VERDICT r4 weak 6: growth-only checking would not catch
    # compiling 2x per shape up front)
    result["tta_executables_expected"] = len(evaluator.policy_shapes)
    census_failures = []
    if (result["tta_executables"] is not None
            and result["tta_executables"] > result["tta_executables_expected"]):
        census_failures.append(
            f"{result['tta_executables']} TTA executables for "
            f"{result['tta_executables_expected']} distinct policy shapes "
            f"{sorted(evaluator.policy_shapes)}")
    if trial_batch > 1:
        # the batched step has its own jit cache: one fixed candidate-
        # axis size K -> exactly one executable for every trial round
        result["tta_batched_executables"] = (
            executable_census(evaluator.tta_step_batch)
            if evaluator._built else None)
        result["tta_batched_executables_expected"] = len(
            evaluator.batch_policy_shapes)
        if (result["tta_batched_executables"] is not None
                and result["tta_batched_executables"]
                > result["tta_batched_executables_expected"]):
            census_failures.append(
                f"{result['tta_batched_executables']} batched-TTA "
                f"executables for {result['tta_batched_executables_expected']}"
                f" candidate-axis shapes "
                f"{sorted(evaluator.batch_policy_shapes)}")
    if census_failures:
        msg = ("phase2: " + "; ".join(census_failures)
               + " — recompilation is leaking into the trial loop "
                 "(policy-as-tensor contract broken)")
        # persist the partial result WITH a failure marker before
        # raising: the trial compute is already spent, and without this
        # write the run would leave no search_result.json to diagnose
        # or resume from (ADVICE r5, driver.py:682)
        result["failure"] = {"stage": "tta_executable_census", "error": msg}
        result["resilience"]["watchdog"] = wd.stats()
        result["compile_cache"] = compile_cache_stats()
        result["final_policy_set_pre_audit_size"] = len(final_policy_set)
        result["elapsed_total"] = wall() - watch["start"]
        _write_json_atomic(
            os.path.join(save_dir, "search_result.json"),
            {k: v for k, v in result.items()
             if k not in ("final_policy_set", "random_policy_set")})
        raise RuntimeError(msg)

    # one audit pipeline for both arms: cached-score reuse (the cache
    # validates its own fold set + baselines inside audit_sub_policies),
    # identical candidate folds/floors, per-arm timing + record file —
    # the searched-vs-random comparison stays fair by construction
    def _audited(policy_set, cache_name: str, secs_key: str):
        t0 = wall()
        apath = os.path.join(save_dir, cache_name)
        cached = None
        if resume and os.path.exists(apath):
            cached = fsfault.read_json(apath)
        kept, audit = audit_sub_policies(
            evaluator, policy_set, fold_paths,
            fold_baselines=fold_baselines,
            candidate_folds=[f for f in range(cv_num)
                             if f not in excluded_folds],
            audit_floor=audit_floor,
            quality_floor=fold_quality_floor,
            cached_audit=cached,
        )
        result[f"device_secs_{secs_key}"] = (wall() - t0) * mesh.size
        result[f"tpu_secs_{secs_key}"] = result[f"device_secs_{secs_key}"]
        _write_json_atomic(apath, audit)
        return kept, audit

    # ---------------- phase 2.5: per-sub-policy audit -----------------
    if audit_floor is not None and final_policy_set:
        final_policy_set, audit = _audited(
            final_policy_set, "audit.json", "audit")
        result["num_sub_policies_dropped"] = len(audit["dropped"])

    # ---------------- random control arm ------------------------------
    # An equal-size uniform draw from the same search space, pushed
    # through the SAME audit: phase 3 can then compare searched vs
    # random vs default instead of searched vs default only.
    if random_control:
        rand_path = os.path.join(save_dir, "random_policy.json")
        n_rand = max(int(result.get("num_sub_policies_selected", 0)), 1)
        if resume and os.path.exists(rand_path):
            # JSON turns the decoder's (op, prob, level) tuples into
            # lists — normalize back so resumed and fresh runs are
            # indistinguishable to callers
            random_set = [[tuple(op) for op in sub]
                          for sub in fsfault.load_json(rand_path)]
            logger.info("random control: resumed %d drawn sub-policies",
                        len(random_set))
        else:
            random_set = draw_random_policy_set(
                n_rand, num_policy, num_op, seed=seed * 31 + 7)
            _write_json_atomic(rand_path, random_set)
            logger.info("random control: drew %d sub-policies (matching the "
                        "searched arm's pre-audit size)", len(random_set))
        result["num_sub_policies_random_drawn"] = len(random_set)
        if audit_floor is not None and random_set:
            random_set, audit_r = _audited(
                random_set, "audit_random.json", "audit_random")
            result["num_sub_policies_random_dropped"] = len(audit_r["dropped"])
        result["random_policy_set"] = random_set
        result["num_sub_policies_random"] = len(random_set)
        _write_json_atomic(os.path.join(save_dir, "random_final_policy.json"),
                           random_set)

    # self-healing accounting, refreshed AFTER all device work so the
    # stamps cover the whole run: watchdog fire counts + (work-queue
    # mode) the degraded-completion evidence any surviving host can
    # reconstruct from the shared queue state
    result["resilience"]["watchdog"] = wd.stats()
    result["watchdog_fires"] = wd.fires
    # compile-tax evidence covering the whole run: a resumed/retried
    # process proves here (hits > 0, first_step_secs in the seconds)
    # that it warm-started instead of re-paying the 23-55 s compile
    result["compile_cache"] = compile_cache_stats()
    if work_queue is not None:
        work_queue.beat_host()  # the census must not see a stale self
        acct = work_queue.accounting()
        result["resilience"]["fleet"] = acct
        result["degraded"] = acct["degraded"]
        result["lost_hosts"] = acct["lost_hosts"]
        result["reclaimed_units"] = [r["unit"]
                                     for r in acct["reclaimed_units"]]
        if acct["degraded"]:
            logger.warning(
                "search completed DEGRADED: lost_hosts=%s, %d unit(s) "
                "reclaimed and finished by survivors",
                acct["lost_hosts"], acct["num_reclaimed_units"])

    if fleet_transport is not None:
        # fleet-search accounting, mirrored from the work-queue stamp:
        # any round finished at lease attempt > 1 was reclaimed from a
        # dead/preempted actor host; stale non-done host beats are the
        # lost hosts.  The trial log itself is already byte-identical
        # to the single-host run — this stamp is the evidence of HOW it
        # got there.
        fleet_transport.beat()
        acct = fleet_transport.accounting()
        result["resilience"]["fleet"] = acct
        result["degraded"] = acct["degraded"]
        result["lost_hosts"] = acct["lost_hosts"]
        result["reclaimed_units"] = [r["unit"]
                                     for r in acct["reclaimed_units"]]
        result["fleet_transport"] = {
            "root": fleet_transport.root,
            "owner": fleet_transport.owner,
            "window": pipeline_actors + pipeline_queue_depth,
        }
        if acct["degraded"]:
            logger.warning(
                "fleet search completed DEGRADED: lost_hosts=%s, %d "
                "round unit(s) reclaimed and finished by surviving "
                "actors", acct["lost_hosts"], acct["num_reclaimed_units"])

    result["final_policy_set"] = final_policy_set
    result["num_sub_policies"] = len(final_policy_set)

    _write_json_atomic(os.path.join(save_dir, "final_policy.json"),
                       final_policy_set)
    if fleet_transport is not None:
        # terminal marker AFTER the final artifacts land: actor hosts
        # drain their claim poll and exit 0
        fleet_transport.mark_search_done(
            {"num_sub_policies": len(final_policy_set)})
    logger.info(
        "search done: %d sub-policies; phase1 %.1f TPU-s, phase2 %.1f TPU-s",
        len(final_policy_set), result["tpu_secs_phase1"], result["tpu_secs_phase2"],
    )
    result["elapsed_total"] = wall() - watch["start"]
    return result


def search_actor(
    conf,
    dataroot: str,
    save_dir: str,
    fleet_transport,
    *,
    cv_num: int = 5,
    cv_ratio: float = 0.4,
    num_policy: int = 5,
    num_op: int = 2,
    trial_batch: int = 1,
    seed: int = 0,
    aug_dispatch: str = "exact",
    aug_groups: int = 8,
    watchdog="off",
    compile_cache: str = "off",
    telemetry_spec: str = "off",
    poll_sec: float = 0.5,
    ckpt_timeout: float = 900.0,
) -> dict:
    """ACTOR-host entry point for the multi-host fleet search: no
    training, no TPE — just the shared ``_FoldEval`` TTA machinery in
    a claim/evaluate/post loop against the learner's published rounds
    (``search_cli --search-role actor``; docs/RESILIENCE.md "Fleet
    search").

    The geometry flags (`trial_batch`, `num_policy`, `num_op`,
    `aug_dispatch`, ...) must match the learner's — they shape the
    compiled TTA step, and a payload mismatch raises loudly instead of
    quarantining every round.  `save_dir` is the SHARED artifact
    directory the learner writes fold checkpoints into; the transport's
    published digests gate loading.  Returns the actor's accounting
    (rounds evaluated/failed, leases lost, units reclaimed from dead
    peers) once the learner marks the search done."""
    from fast_autoaugment_tpu.search.pipeline import run_fleet_actor

    configure_compile_cache(compile_cache)
    telemetry.configure_telemetry(telemetry_spec)
    mesh = make_mesh()
    wd = resolve_watchdog(watchdog)
    evaluator = _FoldEval(
        conf, dataroot, mesh,
        num_policy=num_policy, num_op=num_op, cv_ratio=cv_ratio,
        seed=seed, trial_batch=max(1, int(trial_batch)),
        aug_dispatch=aug_dispatch, aug_groups=aug_groups, watchdog=wd,
    )

    def _fold_path(fold: int) -> str:
        if not 0 <= int(fold) < cv_num:
            raise ValueError(
                f"published round names fold {fold} outside this actor's "
                f"cv_num={cv_num} — launch actors with the learner's flags")
        return _fold_ckpt_path(save_dir, conf, int(fold), cv_ratio)

    logger.info("fleet actor %s: serving rounds from %s (save_dir %s)",
                fleet_transport.owner, fleet_transport.root, save_dir)
    stats = run_fleet_actor(
        evaluator, fleet_transport, _fold_path,
        trial_batch=max(1, int(trial_batch)), num_policy=num_policy,
        num_op=num_op, poll_sec=poll_sec, ckpt_timeout=ckpt_timeout,
    )
    stats["watchdog"] = wd.stats()
    stats["compile_cache"] = compile_cache_stats()
    logger.info(
        "fleet actor %s: done — %d round(s) evaluated, %d failed, "
        "%d lease(s) lost, %d reclaimed", fleet_transport.owner,
        stats["rounds_ok"], stats["rounds_err"], stats["lease_lost"],
        len(stats["reclaimed_units"]))
    return stats


def audit_sub_policies(
    evaluator: _FoldEval,
    policy_set: list,
    fold_paths: list[str],
    *,
    fold_baselines: dict[int, float],
    candidate_folds: list[int],
    audit_floor: float,
    quality_floor: float | None = None,
    num_draws_key: int = 23,
    cached_audit: dict | None = None,
    audit_chunk: int | None = None,
) -> tuple[list, dict]:
    """Drop sub-policies that standalone-degrade fold accuracy.

    Each surviving sub-policy is scored ``mean_f[acc_f(sp)/base_f]``
    over the audit folds, where ``acc_f(sp)`` uses the MEAN-over-draws
    reduction (training applies one sub-policy per image — there is no
    best-of-5 rescue at train time) and ``base_f`` is the fold's
    identity-policy baseline.  Scores below `audit_floor` drop the
    sub-policy.  The reference has no such step: its top-10 selection
    inherits every trial's 5 sub-policies wholesale
    (``search.py:255-259``), which is how round 2's destructive
    policies survived.

    Folds qualify for auditing when their checkpoint exists and their
    baseline clears max(quality_floor, 2x chance).  Returns the kept
    set and an audit record for ``audit.json``.
    """
    evaluator._build()
    chance = 2.0 / evaluator.num_classes
    floor = max(quality_floor or 0.0, chance)
    audit_folds = []
    for fold in candidate_folds:
        path = fold_paths[fold]
        if not os.path.exists(path):
            continue
        if fold not in fold_baselines:
            fold_baselines[fold] = evaluator.baseline(fold, path)
        if fold_baselines[fold] >= floor:
            audit_folds.append(fold)
    record: dict = {
        "audit_floor": audit_floor,
        "audit_folds": audit_folds,
        "fold_baselines": {str(k): v for k, v in fold_baselines.items()},
        "scores": [],
        "dropped": [],
    }
    if not audit_folds:
        logger.warning("audit: no fold passes the baseline floor %.3f — "
                       "audit SKIPPED, policy set unchanged", floor)
        return policy_set, record

    # cached-score validity: the old run must have audited the SAME fold
    # set with the SAME baselines — scores are means over audit folds,
    # so a changed fold set silently changes every score's meaning
    cached_scores: dict = {}
    if cached_audit:
        try:
            same_folds = list(cached_audit.get("audit_folds", [])) == audit_folds
            same_base = same_folds and all(
                abs(cached_audit["fold_baselines"].get(str(f), -1.0)
                    - fold_baselines[f]) < 1e-6
                for f in audit_folds
            )
            if same_base and cached_audit.get("scores"):
                cached_scores = {
                    json.dumps(s["sub_policy"]): s["score"]
                    for s in cached_audit["scores"]
                }
                logger.info("audit: reusing %d cached scores", len(cached_scores))
            else:
                logger.info("audit: cached scores stale (fold set or "
                            "baselines changed) — recomputing")
        except (KeyError, TypeError, ValueError):
            cached_scores = {}

    # evaluate the non-cached sub-policies in CHUNKS of `audit_chunk`
    # per compiled call (make_audit_step): the sub-policy axis is a
    # vmap, so one dispatch covers chunk x draws x batch images — the
    # MXU-shaped layout — instead of one tiny launch per (sub-policy,
    # batch).  The last chunk pads to the fixed size (no recompiles).
    idx_to_eval = [i for i, sub in enumerate(policy_set)
                   if json.dumps(sub) not in cached_scores]
    computed: dict[int, float] = {}
    if idx_to_eval and len(idx_to_eval) <= 4:
        # tiny audits (tests, smoke runs): the already-compiled TTA step
        # beats paying a fresh audit-step compile
        loaded = {f: evaluator.load_fold(fold_paths[f]) for f in audit_folds}
        for i in idx_to_eval:
            sp_t = jnp.asarray(policy_to_tensor([list(map(tuple, policy_set[i]))]))
            ratios = [
                evaluator.evaluate(
                    fold, *loaded[fold], sp_t,
                    jax.random.PRNGKey(num_draws_key * 1000 + i),
                )["top1_mean"] / max(fold_baselines[fold], 1e-6)
                for fold in audit_folds
            ]
            computed[i] = float(np.mean(ratios))
    elif idx_to_eval:
        loaded = {f: evaluator.load_fold(fold_paths[f]) for f in audit_folds}
        if audit_chunk is None:
            # peak memory scales with chunk x image^2: 8 at CIFAR
            # resolution, 1 at ImageNet's 224px (same footprint as the
            # TTA step either way)
            audit_chunk = max(1, (8 * 32 * 32) // (evaluator.image ** 2))
        chunk = max(1, int(audit_chunk))
        n = len(idx_to_eval)
        subs_np = np.stack([
            np.asarray(policy_to_tensor([list(map(tuple, policy_set[i]))]),
                       np.float32)[0]
            for i in idx_to_eval
        ])  # [n, num_op, 3]
        ratio_sums = np.zeros(n)
        for fold in audit_folds:
            params, batch_stats = loaded[fold]
            sums = np.zeros(n)
            cnt = 0.0
            for start in range(0, n, chunk):
                block = subs_np[start:start + chunk]
                real = len(block)
                if real < chunk:
                    block = np.concatenate(
                        [block,
                         np.zeros((chunk - real,) + block.shape[1:], np.float32)])
                bsum = np.zeros(chunk)
                bcnt = 0.0
                block_dev = jnp.asarray(block)  # one upload per chunk
                for bi, batch in enumerate(evaluator.batches_fn(fold)()):
                    # chained fold_in: collision-free for any batch
                    # count (a single mixed integer collides once a
                    # fold yields >131 batches, e.g. ImageNet folds)
                    k = jax.random.PRNGKey(num_draws_key)
                    for part in (fold, start, bi):
                        k = jax.random.fold_in(k, part)
                    out = evaluator.audit_eval(
                        params, batch_stats, batch, block_dev, k,
                    )
                    bsum += np.asarray(out["correct_mean_sum"])
                    bcnt += float(out["cnt"])
                sums[start:start + real] = bsum[:real]
                cnt = bcnt
            ratio_sums += (sums / max(cnt, 1e-6)) / max(fold_baselines[fold], 1e-6)
        for j, i in enumerate(idx_to_eval):
            computed[i] = float(ratio_sums[j] / len(audit_folds))

    kept = []
    for i, sub in enumerate(policy_set):
        cache_key = json.dumps(sub)
        score = (float(cached_scores[cache_key])
                 if cache_key in cached_scores else computed[i])
        record["scores"].append({"sub_policy": sub, "score": score})
        if score >= audit_floor:
            kept.append(sub)
        else:
            record["dropped"].append({"sub_policy": sub, "score": score})
    logger.info(
        "audit: %d/%d sub-policies kept (floor %.2f x baseline over folds %s)",
        len(kept), len(policy_set), audit_floor, audit_folds,
    )
    return kept, record
